"""Shared helpers for layer functions (ref: python/paddle/fluid/layers/
layer_function_generator.py) — generate a static-graph layer function straight
from a registered op."""
from __future__ import annotations

from ..framework import Variable, in_dygraph_mode
from ..layer_helper import LayerHelper
from ..ops.registry import get_op


def _var_name(x):
    return x.name if isinstance(x, Variable) else x


def apply_op_layer(op_type, inputs, attrs=None, name=None, n_outputs=None,
                   dtype=None):
    """Append `op_type` to the current program; returns output Variable(s).

    inputs: dict slot → Variable | [Variables]. In dygraph mode, dispatches
    eagerly through the tape instead (one code path for both modes, like the
    reference's `in_dygraph_mode()` branches in each layer).
    """
    if inputs.get('length', 'absent') is None:
        # lod_reset parity: a var carrying a `sequence_length` attribute
        # feeds it to any sequence op that wasn't given lengths explicitly
        for v in inputs.values():
            if isinstance(v, Variable) and hasattr(v, 'sequence_length'):
                inputs = dict(inputs, length=v.sequence_length)
                break
    if in_dygraph_mode():
        from ..dygraph.tape import dispatch_op
        return dispatch_op(op_type, inputs, attrs or {})
    opdef = get_op(op_type)
    helper = LayerHelper(op_type, name=name)
    in_names = {}
    first_dtype = dtype
    for slot, v in inputs.items():
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            in_names[slot] = [_var_name(x) for x in v]
            if first_dtype is None and v and isinstance(v[0], Variable):
                first_dtype = v[0].dtype
        else:
            in_names[slot] = _var_name(v)
            if first_dtype is None and isinstance(v, Variable):
                first_dtype = v.dtype
    outs = {}
    out_vars = []
    slots = opdef.output_slots
    for slot in slots:
        k = n_outputs.get(slot, 1) if isinstance(n_outputs, dict) else 1
        vs = [helper.create_variable_for_type_inference(first_dtype or 'float32')
              for _ in range(k)]
        outs[slot] = [v.name for v in vs]
        out_vars.append(vs if k > 1 else vs[0])
    helper.append_op(type=op_type, inputs=in_names, outputs=outs,
                     attrs=attrs or {})
    return out_vars[0] if len(out_vars) == 1 else tuple(out_vars)


def op_call(op_type, **inputs):
    """Keyword sugar over apply_op_layer: input slots as kwargs, op attrs
    under the reserved `attrs` kwarg."""
    attrs = inputs.pop('attrs', None)
    return apply_op_layer(op_type, inputs, attrs)


def generate_layer_fn(op_type, in_slots=None, doc=''):
    """Make a `fn(x, ..., name=None, **attrs) -> Variable` layer from an op."""
    opdef = get_op(op_type)
    slots = in_slots or opdef.input_slots

    def layer(*args, name=None, **kwargs):
        inputs = {}
        for slot, v in zip(slots, args):
            inputs[slot] = v
        for slot in slots[len(args):]:
            if slot in kwargs:
                inputs[slot] = kwargs.pop(slot)
        return apply_op_layer(op_type, inputs, kwargs, name=name)

    layer.__name__ = op_type
    layer.__doc__ = doc or f"Auto-generated layer for op `{op_type}` " \
                           f"(TPU-native jax functional)."
    return layer
