"""Default-scope helpers (ref: python/paddle/fluid/default_scope_funcs.py).

A thread-local stack of Scopes over core.Scope; `scoped_function` runs a
callable inside a fresh child scope and discards it after.
"""
import threading

from .core.scope import Scope, global_scope

__all__ = ['get_cur_scope', 'enter_local_scope', 'leave_local_scope',
           'var', 'find_var', 'scoped_function']

_local = threading.local()


def _stack():
    if not hasattr(_local, 'stack') or not _local.stack:
        _local.stack = [global_scope()]
    return _local.stack


def get_cur_scope():
    """Innermost scope of the current thread (ref :30)."""
    return _stack()[-1]


def enter_local_scope():
    """Push a child scope (ref :39)."""
    cur = get_cur_scope()
    _stack().append(cur.new_scope())


def leave_local_scope():
    """Pop the innermost scope (ref :46)."""
    stack = _stack()
    if len(stack) <= 1:
        raise RuntimeError('cannot leave the global scope')
    stack.pop()


def var(name):
    """Find-or-create `name` in the current scope (ref :53)."""
    return get_cur_scope().var(name)


def find_var(name):
    """Find `name` walking outward through parents (ref :60)."""
    return get_cur_scope().find(name)


def scoped_function(func):
    """Run func() inside a fresh local scope (ref :67)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
