"""Native (C++) runtime components, loaded via ctypes (SURVEY §2.12).

- DataPipeline: shuffle buffer + batcher + prefetch ring (the reference's
  C++ BufferedReader/shuffle stack, src/data_pipeline.cc)
- WordPieceTokenizer: BERT-path text preproc (src/wordpiece.cc)
- pack_padded / unpack_padded / bucket_by_length: LoD↔padded conversions
  (src/lod_pack.cc)

The shared library builds on first import (`make` in this directory); if no
toolchain is available every entry point falls back to a pure-Python
implementation with identical semantics, so the framework never hard-fails.
`is_native()` reports which path is active.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, 'libpaddle_tpu_native.so')
_lib = None
_tried = False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(['make', '-C', _DIR, '-s'], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.ptpu_pipeline_create.restype = ctypes.c_void_p
    lib.ptpu_pipeline_create.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int, ctypes.c_uint64]
    lib.ptpu_pipeline_push.restype = ctypes.c_int
    lib.ptpu_pipeline_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptpu_pipeline_finish.argtypes = [ctypes.c_void_p]
    lib.ptpu_pipeline_cancel.argtypes = [ctypes.c_void_p]
    lib.ptpu_pipeline_pop.restype = ctypes.c_int64
    lib.ptpu_pipeline_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptpu_pipeline_destroy.argtypes = [ctypes.c_void_p]
    lib.ptpu_wp_create.restype = ctypes.c_void_p
    lib.ptpu_wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.c_int, ctypes.c_char_p]
    lib.ptpu_wp_vocab_size.restype = ctypes.c_int64
    lib.ptpu_wp_vocab_size.argtypes = [ctypes.c_void_p]
    lib.ptpu_wp_lookup.restype = ctypes.c_int64
    lib.ptpu_wp_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_wp_tokenize.restype = ctypes.c_int64
    lib.ptpu_wp_tokenize.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_void_p,
                                     ctypes.c_int64]
    lib.ptpu_wp_destroy.argtypes = [ctypes.c_void_p]
    for name in ('ptpu_pack_f32', 'ptpu_pack_i64'):
        getattr(lib, name).restype = None
    lib.ptpu_unpack_f32.restype = ctypes.c_int64
    lib.ptpu_unpack_i64.restype = ctypes.c_int64
    lib.ptpu_bucket_by_length.restype = None
    _lib = lib
    return _lib


def is_native():
    return _load() is not None


def _start_feed(target, iterable):
    """Shared producer thread: push until the target cancels, route errors
    into the target so the consumer re-raises them from pop()."""
    def run():
        try:
            for s in iterable:
                if not target.push(s):
                    return          # consumer cancelled
        except BaseException as e:  # propagate to the consumer
            target._set_error(e)
        finally:
            target.finish()         # always unblock the consumer
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# DataPipeline
# ---------------------------------------------------------------------------


class DataPipeline:
    """Shuffle + batch + prefetch over fixed-shape samples.

    Samples are numpy arrays of one dtype/shape; `feed(iterable)` runs on a
    background thread; iterate the pipeline to pop ready batches."""

    def __init__(self, sample_shape, dtype='float32', batch_size=32,
                 shuffle_capacity=0, ring_capacity=4, drop_last=False,
                 seed=0):
        self.sample_shape = tuple(int(s) for s in sample_shape)
        self.dtype = np.dtype(dtype)
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self._nbytes = int(np.prod(self.sample_shape)) * self.dtype.itemsize
        self._lib = _load()
        self._thread = None
        self._error = None       # producer-thread exception, re-raised in pop
        if self._lib is not None:
            self._h = self._lib.ptpu_pipeline_create(
                self._nbytes, self.batch_size, int(shuffle_capacity),
                int(ring_capacity), int(drop_last), int(seed))
        else:                                    # python fallback
            self._h = None
            self._fb_rng = np.random.RandomState(seed)
            self._fb_buf = []
            self._fb_batches = []
            self._fb_cap = int(shuffle_capacity)
            self._fb_ring_cap = max(int(ring_capacity), 1)
            self._fb_partial = []
            self._fb_done = False
            self._fb_lock = threading.Lock()
            self._fb_cv = threading.Condition(self._fb_lock)

    # -- producer --
    def push(self, sample):
        """Returns False once the pipeline is finished/cancelled (producers
        should stop feeding)."""
        arr = np.asarray(sample)
        if arr.shape != self.sample_shape:
            raise ValueError(f"sample shape {arr.shape} != "
                             f"{self.sample_shape}")
        arr = np.ascontiguousarray(arr, self.dtype)
        if self._h is not None:
            return bool(self._lib.ptpu_pipeline_push(
                self._h, arr.ctypes.data_as(ctypes.c_void_p)))
        with self._fb_cv:
            # backpressure like the native ring: block while full
            self._fb_cv.wait_for(
                lambda: len(self._fb_batches) < self._fb_ring_cap
                or self._fb_done)
            if self._fb_done:
                return False
            if self._fb_cap > 0:
                if len(self._fb_buf) < self._fb_cap:
                    self._fb_buf.append(arr.copy())
                    return True
                j = self._fb_rng.randint(self._fb_cap)
                out, self._fb_buf[j] = self._fb_buf[j], arr.copy()
                self._fb_emit(out)
            else:
                self._fb_emit(arr.copy())
            return True

    def _fb_emit(self, arr):
        self._fb_partial.append(arr)
        if len(self._fb_partial) == self.batch_size:
            self._fb_batches.append(np.stack(self._fb_partial))
            self._fb_partial = []
            self._fb_cv.notify_all()

    def finish(self):
        if self._h is not None:
            self._lib.ptpu_pipeline_finish(self._h)
            return
        with self._fb_cv:
            if self._fb_cap > 0:
                self._fb_rng.shuffle(self._fb_buf)
                for a in self._fb_buf:
                    # honor the ring bound while draining; cancel breaks out
                    self._fb_cv.wait_for(
                        lambda: len(self._fb_batches) < self._fb_ring_cap
                        or self._fb_done)
                    if self._fb_done:
                        break
                    self._fb_emit(a)
                self._fb_buf = []
            if self._fb_partial and not self.drop_last and not self._fb_done:
                self._fb_batches.append(np.stack(self._fb_partial))
            self._fb_partial = []
            self._fb_done = True
            self._fb_cv.notify_all()

    def _set_error(self, e):
        self._error = e

    def cancel(self):
        """Consumer-side early exit: unblock the producer, drop the rest."""
        if self._h is not None:
            self._lib.ptpu_pipeline_cancel(self._h)
            return
        with self._fb_cv:
            self._fb_done = True
            self._fb_cv.notify_all()

    def feed(self, iterable):
        """Run the producer on a background thread (prefetch overlap).
        Producer exceptions are re-raised from pop() rather than dying
        silently in the thread."""
        self._thread = _start_feed(self, iterable)
        return self

    # -- consumer --
    def pop(self):
        """Next batch (n, *sample_shape) or None at end of stream."""
        if self._h is not None:
            out = np.empty((self.batch_size,) + self.sample_shape, self.dtype)
            n = self._lib.ptpu_pipeline_pop(
                self._h, out.ctypes.data_as(ctypes.c_void_p))
            if n == 0:
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                return None
            return out[:n]
        with self._fb_cv:
            self._fb_cv.wait_for(
                lambda: self._fb_batches or self._fb_done)
            if not self._fb_batches:
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                return None
            b = self._fb_batches.pop(0)
            self._fb_cv.notify_all()    # free producer backpressure
            return b

    def __iter__(self):
        try:
            while True:
                b = self.pop()
                if b is None:
                    return
                yield b
        finally:
            self.cancel()   # early break: unblock the producer

    def __del__(self):
        if getattr(self, '_h', None) is not None and self._lib is not None:
            # the feed thread may still hold the native handle: cancel and
            # join before freeing (avoids use-after-free on the C++ side)
            try:
                self._lib.ptpu_pipeline_cancel(self._h)
                t = getattr(self, '_thread', None)
                # the GC can run __del__ ON the feed thread (e.g. when the
                # last consumer reference dies inside it) — joining the
                # current thread raises
                import threading
                if (t is not None and t.is_alive()
                        and t is not threading.current_thread()):
                    t.join(timeout=5.0)
            finally:
                self._lib.ptpu_pipeline_destroy(self._h)
                self._h = None


# ---------------------------------------------------------------------------
# WordPiece tokenizer
# ---------------------------------------------------------------------------


class WordPieceTokenizer:
    def __init__(self, vocab, lowercase=True, unk_token='[UNK]'):
        """vocab: path to a vocab file, list of tokens, or dict token→id."""
        if isinstance(vocab, str):
            with open(vocab, 'rb') as f:
                blob = f.read()
            # BERT convention: id == line number. Blank lines stay in the
            # list as placeholders so subsequent ids don't shift.
            tokens = blob.decode('utf-8').split('\n')
            if tokens and tokens[-1] == '':
                tokens.pop()  # trailing newline is not a vocab line
        elif isinstance(vocab, dict):
            tokens = [t for t, _ in sorted(vocab.items(),
                                           key=lambda kv: kv[1])]
        else:
            tokens = list(vocab)
        self._tokens = tokens
        self._vocab = {t: i for i, t in enumerate(tokens) if t}
        self.lowercase = lowercase
        self.unk_token = unk_token
        self._lib = _load()
        if self._lib is not None:
            blob = '\n'.join(tokens).encode('utf-8')
            self._h = self._lib.ptpu_wp_create(blob, len(blob),
                                               int(lowercase),
                                               unk_token.encode())
        else:
            self._h = None

    @property
    def vocab_size(self):
        return len(self._tokens)

    def lookup(self, token):
        return self._vocab.get(token, -1)

    def tokenize(self, text, max_len=512):
        if self._h is not None:
            enc = text.encode('utf-8')
            out = np.empty(max_len, np.int64)
            n = self._lib.ptpu_wp_tokenize(
                self._h, enc, len(enc), out.ctypes.data_as(ctypes.c_void_p),
                max_len)
            return out[:n].tolist()
        return self._py_tokenize(text)[:max_len]

    @staticmethod
    def _is_cjk(cp):
        return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF or
                0xF900 <= cp <= 0xFAFF or 0x20000 <= cp <= 0x2A6DF or
                0x2A700 <= cp <= 0x2B73F or 0x2B740 <= cp <= 0x2B81F or
                0x2B820 <= cp <= 0x2CEAF or 0x2F800 <= cp <= 0x2FA1F)

    def _py_tokenize(self, text):
        """Matches the C++ tokenizer: ASCII space/punct split + ASCII-only
        lowercasing, non-ASCII chars kept intact, CJK ideographs split off as
        standalone words (BERT BasicTokenizer ranges), 100-byte word cap."""
        import string
        punct = set(string.punctuation)
        space = set(' \t\n\r\v\f')
        unk = self._vocab.get(self.unk_token, 0)
        words = []
        cur = []
        for ch in text:
            if ch in space:
                if cur:
                    words.append(''.join(cur))
                    cur = []
            elif ch in punct:
                if cur:
                    words.append(''.join(cur))
                    cur = []
                words.append(ch)
            elif ord(ch) >= 0x80 and self._is_cjk(ord(ch)):
                if cur:
                    words.append(''.join(cur))
                    cur = []
                words.append(ch)
            else:
                cur.append(ch.lower() if self.lowercase and 'A' <= ch <= 'Z'
                           else ch)
        if cur:
            words.append(''.join(cur))
        ids = []
        for w in words:
            if len(w.encode('utf-8')) > 100:
                ids.append(unk)
                continue
            start, sub, bad = 0, [], False
            while start < len(w):
                end = len(w)
                cur_id = None
                while start < end:
                    piece = ('##' if start > 0 else '') + w[start:end]
                    if piece in self._vocab:
                        cur_id = self._vocab[piece]
                        break
                    end -= 1
                if cur_id is None:
                    bad = True
                    break
                sub.append(cur_id)
                start = end
            ids.extend([unk] if bad else sub)
        return ids

    def __del__(self):
        if getattr(self, '_h', None) is not None and self._lib is not None:
            self._lib.ptpu_wp_destroy(self._h)
            self._h = None


# ---------------------------------------------------------------------------
# LoD / ragged packing
# ---------------------------------------------------------------------------


def pack_padded(flat, lengths, max_len=None, pad_value=0):
    """Concatenated rows (N, D...) + lengths (B,) → padded (B, T, D...)."""
    flat = np.ascontiguousarray(flat)
    lengths = np.ascontiguousarray(lengths, np.int64)
    B = lengths.shape[0]
    T = int(max_len if max_len is not None else lengths.max(initial=0))
    width = int(np.prod(flat.shape[1:])) if flat.ndim > 1 else 1
    lib = _load()
    kind = {np.dtype('float32'): 'f32', np.dtype('int64'): 'i64'}.get(
        flat.dtype)
    if lib is not None and kind is not None:
        out = np.empty((B, T) + flat.shape[1:], flat.dtype)
        fn = getattr(lib, f'ptpu_pack_{kind}')
        fn(flat.ctypes.data_as(ctypes.c_void_p),
           lengths.ctypes.data_as(ctypes.c_void_p),
           ctypes.c_int64(B), ctypes.c_int64(T), ctypes.c_int64(width),
           (ctypes.c_float if kind == 'f32' else ctypes.c_int64)(pad_value),
           out.ctypes.data_as(ctypes.c_void_p))
        return out
    out = np.full((B, T) + flat.shape[1:], pad_value, flat.dtype)
    off = 0
    for b in range(B):
        n = min(int(lengths[b]), T)
        out[b, :n] = flat[off:off + n]
        off += int(lengths[b])
    return out


def unpack_padded(padded, lengths):
    """Padded (B, T, D...) + lengths → concatenated (sum(min(len,T)), D...)."""
    padded = np.ascontiguousarray(padded)
    lengths = np.ascontiguousarray(lengths, np.int64)
    B, T = padded.shape[0], padded.shape[1]
    width = int(np.prod(padded.shape[2:])) if padded.ndim > 2 else 1
    total = int(np.minimum(lengths, T).sum())
    lib = _load()
    kind = {np.dtype('float32'): 'f32', np.dtype('int64'): 'i64'}.get(
        padded.dtype)
    if lib is not None and kind is not None:
        out = np.empty((total,) + padded.shape[2:], padded.dtype)
        fn = getattr(lib, f'ptpu_unpack_{kind}')
        fn(padded.ctypes.data_as(ctypes.c_void_p),
           lengths.ctypes.data_as(ctypes.c_void_p),
           ctypes.c_int64(B), ctypes.c_int64(T), ctypes.c_int64(width),
           out.ctypes.data_as(ctypes.c_void_p))
        return out
    parts = [padded[b, :min(int(lengths[b]), T)] for b in range(B)]
    return np.concatenate(parts, 0) if parts else \
        np.empty((0,) + padded.shape[2:], padded.dtype)


def bucket_by_length(lengths):
    """Stable argsort of lengths, descending (length-bucketed batching)."""
    lengths = np.ascontiguousarray(lengths, np.int64)
    lib = _load()
    if lib is not None:
        out = np.empty(lengths.shape[0], np.int64)
        lib.ptpu_bucket_by_length(
            lengths.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(lengths.shape[0]),
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    return np.argsort(-lengths, kind='stable').astype(np.int64)


class TupleDataPipeline:
    """DataPipeline over multi-field samples (img, label, ...): each sample's
    fields are packed into one contiguous byte record so shuffling keeps
    fields aligned; pop() splits batches back into per-field arrays."""

    def __init__(self, field_shapes, field_dtypes, batch_size,
                 shuffle_capacity=0, ring_capacity=4, drop_last=False,
                 seed=0):
        self.shapes = [tuple(int(d) for d in s) for s in field_shapes]
        self.dtypes = [np.dtype(d) for d in field_dtypes]
        self.nbytes = [int(np.prod(s)) * d.itemsize
                       for s, d in zip(self.shapes, self.dtypes)]
        self._pipe = DataPipeline((sum(self.nbytes),), 'uint8', batch_size,
                                  shuffle_capacity, ring_capacity, drop_last,
                                  seed)

    def push(self, fields):
        fields = fields if isinstance(fields, (list, tuple)) else (fields,)
        parts = []
        for i, (f, shape, d) in enumerate(zip(fields, self.shapes,
                                              self.dtypes)):
            a = np.asarray(f)
            if a.shape != shape:
                raise ValueError(
                    f"field {i}: sample shape {a.shape} != {shape} inferred "
                    f"from the first sample (variable-shape samples need "
                    f"padding before batching)")
            if a.dtype != d and a.dtype.kind != d.kind:
                raise TypeError(
                    f"field {i}: sample dtype {a.dtype} incompatible with "
                    f"{d} inferred from the first sample")
            parts.append(np.ascontiguousarray(a, d).view(np.uint8)
                         .reshape(-1))
        return self._pipe.push(np.concatenate(parts) if len(parts) > 1
                               else parts[0])

    def finish(self):
        self._pipe.finish()

    def cancel(self):
        self._pipe.cancel()

    def _set_error(self, e):
        self._pipe._set_error(e)

    def feed(self, iterable):
        self._thread = _start_feed(self, iterable)
        return self

    def pop(self):
        rec = self._pipe.pop()
        if rec is None:
            return None
        n = rec.shape[0]
        out = []
        off = 0
        for shape, dt, nb in zip(self.shapes, self.dtypes, self.nbytes):
            chunk = rec[:, off:off + nb]
            out.append(np.ascontiguousarray(chunk).view(dt).reshape(
                (n,) + shape))
            off += nb
        return tuple(out)

    def __iter__(self):
        try:
            while True:
                b = self.pop()
                if b is None:
                    return
                yield b
        finally:
            self.cancel()
