// WordPiece tokenizer for the BERT data path.
//
// Parity target: the reference models' Python wordpiece preprocessing
// (PaddlePaddle/models BERT tokenization) moved to native code so the host
// CPU can keep up with the TPU input pipeline. Greedy longest-match-first
// over a vocab hash map, basic whitespace+punctuation pre-split, lowercase
// option. Plain C ABI for ctypes.

#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Length of the UTF-8 sequence starting at lead byte `c` (invalid bytes → 1).
inline size_t U8Len(unsigned char c) {
  if (c < 0x80) return 1;
  if ((c >> 5) == 0x6) return 2;
  if ((c >> 4) == 0xE) return 3;
  if ((c >> 3) == 0x1E) return 4;
  return 1;
}

// Decode the codepoint at s[i] (length n). Returns 0 on malformed input.
inline uint32_t U8Decode(const std::string& s, size_t i, size_t n) {
  if (i + n > s.size()) return 0;
  unsigned char c0 = s[i];
  if (n == 1) return c0;
  uint32_t cp = c0 & (0x7F >> n);
  for (size_t k = 1; k < n; ++k) cp = (cp << 6) | ((unsigned char)s[i + k] & 0x3F);
  return cp;
}

// BERT BasicTokenizer._is_chinese_char ranges: CJK ideographs are split off
// as standalone single-char words.
inline bool IsCJK(uint32_t cp) {
  return (cp >= 0x4E00 && cp <= 0x9FFF) || (cp >= 0x3400 && cp <= 0x4DBF) ||
         (cp >= 0xF900 && cp <= 0xFAFF) || (cp >= 0x20000 && cp <= 0x2A6DF) ||
         (cp >= 0x2A700 && cp <= 0x2B73F) || (cp >= 0x2B740 && cp <= 0x2B81F) ||
         (cp >= 0x2B820 && cp <= 0x2CEAF) || (cp >= 0x2F800 && cp <= 0x2FA1F);
}

struct Tokenizer {
  std::unordered_map<std::string, int64_t> vocab;
  int64_t unk_id = 0;
  bool lowercase = true;
  int64_t max_chars_per_word = 100;

  std::vector<int64_t> tokenize(const std::string& text) const {
    std::vector<int64_t> ids;
    std::vector<std::string> words;
    std::string cur;
    // UTF-8 aware pre-split: ASCII space/punct split + optional ASCII
    // lowercase; multi-byte sequences are kept intact (no byte-wise
    // tolower/ispunct) and CJK ideographs become standalone words.
    // Non-ASCII lowercasing/accent-stripping is out of scope (documented).
    for (size_t i = 0; i < text.size();) {
      unsigned char ch = text[i];
      size_t n = U8Len(ch);
      if (n == 1) {
        if (std::isspace(ch)) {
          if (!cur.empty()) { words.push_back(cur); cur.clear(); }
        } else if (std::ispunct(ch)) {
          if (!cur.empty()) { words.push_back(cur); cur.clear(); }
          words.emplace_back(1, (char)ch);
        } else {
          cur.push_back(lowercase ? (char)std::tolower(ch) : (char)ch);
        }
      } else {
        uint32_t cp = U8Decode(text, i, n);
        if (IsCJK(cp)) {
          if (!cur.empty()) { words.push_back(cur); cur.clear(); }
          words.push_back(text.substr(i, n));
        } else {
          cur.append(text, i, n);
        }
      }
      i += n;
    }
    if (!cur.empty()) words.push_back(cur);

    for (const auto& w : words) {
      if ((int64_t)w.size() > max_chars_per_word) {
        ids.push_back(unk_id);
        continue;
      }
      size_t start = 0;
      std::vector<int64_t> sub;
      bool bad = false;
      while (start < w.size()) {
        size_t end = w.size();
        int64_t cur_id = -1;
        while (start < end) {
          std::string piece = (start > 0 ? "##" : "") +
                              w.substr(start, end - start);
          auto it = vocab.find(piece);
          if (it != vocab.end()) { cur_id = it->second; break; }
          // shrink to the previous UTF-8 char boundary, never mid-sequence
          do { --end; } while (end > start && ((unsigned char)w[end] & 0xC0) == 0x80);
        }
        if (cur_id < 0) { bad = true; break; }
        sub.push_back(cur_id);
        start = end;
      }
      if (bad) ids.push_back(unk_id);
      else ids.insert(ids.end(), sub.begin(), sub.end());
    }
    return ids;
  }
};

}  // namespace

extern "C" {

// vocab_blob: '\n'-separated tokens, line index = id
void* ptpu_wp_create(const char* vocab_blob, int64_t blob_len, int lowercase,
                     const char* unk_token) {
  auto* t = new Tokenizer();
  t->lowercase = lowercase != 0;
  std::string blob(vocab_blob, blob_len);
  size_t pos = 0;
  int64_t id = 0;
  while (pos <= blob.size()) {
    size_t nl = blob.find('\n', pos);
    bool last = (nl == std::string::npos);
    if (last) nl = blob.size();
    std::string tok = blob.substr(pos, nl - pos);
    if (last && tok.empty()) break;  // trailing newline is not a vocab line
    // BERT convention: id == line number, so blank lines still consume an id
    if (!tok.empty()) t->vocab[tok] = id;
    ++id;
    pos = nl + 1;
    if (nl == blob.size()) break;
  }
  auto it = t->vocab.find(unk_token ? unk_token : "[UNK]");
  t->unk_id = it != t->vocab.end() ? it->second : 0;
  return t;
}

int64_t ptpu_wp_vocab_size(void* h) {
  return (int64_t)static_cast<Tokenizer*>(h)->vocab.size();
}

int64_t ptpu_wp_lookup(void* h, const char* token) {
  auto* t = static_cast<Tokenizer*>(h);
  auto it = t->vocab.find(token);
  return it != t->vocab.end() ? it->second : -1;
}

// returns number of ids written (truncated to max_len)
int64_t ptpu_wp_tokenize(void* h, const char* text, int64_t text_len,
                         int64_t* out_ids, int64_t max_len) {
  auto ids = static_cast<Tokenizer*>(h)->tokenize(
      std::string(text, text_len));
  int64_t n = std::min<int64_t>((int64_t)ids.size(), max_len);
  std::memcpy(out_ids, ids.data(), n * sizeof(int64_t));
  return n;
}

void ptpu_wp_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

}  // extern "C"
