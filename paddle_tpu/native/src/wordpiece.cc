// WordPiece tokenizer for the BERT data path.
//
// Parity target: the reference models' Python wordpiece preprocessing
// (PaddlePaddle/models BERT tokenization) moved to native code so the host
// CPU can keep up with the TPU input pipeline. Greedy longest-match-first
// over a vocab hash map, basic whitespace+punctuation pre-split, lowercase
// option. Plain C ABI for ctypes.

#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::unordered_map<std::string, int64_t> vocab;
  int64_t unk_id = 0;
  bool lowercase = true;
  int64_t max_chars_per_word = 100;

  std::vector<int64_t> tokenize(const std::string& text) const {
    std::vector<int64_t> ids;
    std::vector<std::string> words;
    std::string cur;
    for (unsigned char ch : text) {
      if (std::isspace(ch)) {
        if (!cur.empty()) { words.push_back(cur); cur.clear(); }
      } else if (std::ispunct(ch)) {
        if (!cur.empty()) { words.push_back(cur); cur.clear(); }
        words.emplace_back(1, (char)ch);
      } else {
        cur.push_back(lowercase ? (char)std::tolower(ch) : (char)ch);
      }
    }
    if (!cur.empty()) words.push_back(cur);

    for (const auto& w : words) {
      if ((int64_t)w.size() > max_chars_per_word) {
        ids.push_back(unk_id);
        continue;
      }
      size_t start = 0;
      std::vector<int64_t> sub;
      bool bad = false;
      while (start < w.size()) {
        size_t end = w.size();
        int64_t cur_id = -1;
        while (start < end) {
          std::string piece = (start > 0 ? "##" : "") +
                              w.substr(start, end - start);
          auto it = vocab.find(piece);
          if (it != vocab.end()) { cur_id = it->second; break; }
          --end;
        }
        if (cur_id < 0) { bad = true; break; }
        sub.push_back(cur_id);
        start = end;
      }
      if (bad) ids.push_back(unk_id);
      else ids.insert(ids.end(), sub.begin(), sub.end());
    }
    return ids;
  }
};

}  // namespace

extern "C" {

// vocab_blob: '\n'-separated tokens, line index = id
void* ptpu_wp_create(const char* vocab_blob, int64_t blob_len, int lowercase,
                     const char* unk_token) {
  auto* t = new Tokenizer();
  t->lowercase = lowercase != 0;
  std::string blob(vocab_blob, blob_len);
  size_t pos = 0;
  int64_t id = 0;
  while (pos <= blob.size()) {
    size_t nl = blob.find('\n', pos);
    if (nl == std::string::npos) nl = blob.size();
    std::string tok = blob.substr(pos, nl - pos);
    if (!tok.empty()) t->vocab[tok] = id++;
    pos = nl + 1;
    if (nl == blob.size()) break;
  }
  auto it = t->vocab.find(unk_token ? unk_token : "[UNK]");
  t->unk_id = it != t->vocab.end() ? it->second : 0;
  return t;
}

int64_t ptpu_wp_vocab_size(void* h) {
  return (int64_t)static_cast<Tokenizer*>(h)->vocab.size();
}

int64_t ptpu_wp_lookup(void* h, const char* token) {
  auto* t = static_cast<Tokenizer*>(h);
  auto it = t->vocab.find(token);
  return it != t->vocab.end() ? it->second : -1;
}

// returns number of ids written (truncated to max_len)
int64_t ptpu_wp_tokenize(void* h, const char* text, int64_t text_len,
                         int64_t* out_ids, int64_t max_len) {
  auto ids = static_cast<Tokenizer*>(h)->tokenize(
      std::string(text, text_len));
  int64_t n = std::min<int64_t>((int64_t)ids.size(), max_len);
  std::memcpy(out_ids, ids.data(), n * sizeof(int64_t));
  return n;
}

void ptpu_wp_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

}  // extern "C"
