// LoD/ragged packing utilities.
//
// Parity target: the reference's LoDTensor host-side packing
// (/root/reference/paddle/fluid/framework/lod_tensor.cc) — the TPU framework
// represents ragged batches as padded (B, T, D) + lengths, and these
// routines do the hot host-side conversions without Python loops:
//   pack:   concatenated rows + per-seq lengths → padded batch (+ pad value)
//   unpack: padded batch + lengths → concatenated rows
//   bucket: argsort lengths descending (for length-bucketed batching)
// float32/int64 element types; plain C ABI for ctypes.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

namespace {

template <typename T>
void pack_impl(const T* flat, const int64_t* lengths, int64_t batch,
               int64_t max_len, int64_t width, T pad, T* out) {
  int64_t offset = 0;
  for (int64_t b = 0; b < batch; ++b) {
    int64_t n = std::min(lengths[b], max_len);
    T* row = out + b * max_len * width;
    std::memcpy(row, flat + offset * width, n * width * sizeof(T));
    std::fill(row + n * width, row + max_len * width, pad);
    offset += lengths[b];
  }
}

template <typename T>
int64_t unpack_impl(const T* padded, const int64_t* lengths, int64_t batch,
                    int64_t max_len, int64_t width, T* out) {
  int64_t offset = 0;
  for (int64_t b = 0; b < batch; ++b) {
    int64_t n = std::min(lengths[b], max_len);
    std::memcpy(out + offset * width, padded + b * max_len * width,
                n * width * sizeof(T));
    offset += n;
  }
  return offset;
}

}  // namespace

extern "C" {

void ptpu_pack_f32(const float* flat, const int64_t* lengths, int64_t batch,
                   int64_t max_len, int64_t width, float pad, float* out) {
  pack_impl(flat, lengths, batch, max_len, width, pad, out);
}

void ptpu_pack_i64(const int64_t* flat, const int64_t* lengths, int64_t batch,
                   int64_t max_len, int64_t width, int64_t pad, int64_t* out) {
  pack_impl(flat, lengths, batch, max_len, width, pad, out);
}

int64_t ptpu_unpack_f32(const float* padded, const int64_t* lengths,
                        int64_t batch, int64_t max_len, int64_t width,
                        float* out) {
  return unpack_impl(padded, lengths, batch, max_len, width, out);
}

int64_t ptpu_unpack_i64(const int64_t* padded, const int64_t* lengths,
                        int64_t batch, int64_t max_len, int64_t width,
                        int64_t* out) {
  return unpack_impl(padded, lengths, batch, max_len, width, out);
}

// indices of lengths sorted descending (stable) — length bucketing
void ptpu_bucket_by_length(const int64_t* lengths, int64_t n, int64_t* idx) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return lengths[a] > lengths[b];
  });
  std::memcpy(idx, order.data(), n * sizeof(int64_t));
}

}  // extern "C"
