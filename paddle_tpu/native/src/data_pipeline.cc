// Native data-pipeline core: shuffle buffer + batcher + prefetch ring.
//
// Parity target: the reference's C++ reader stack
// (/root/reference/paddle/fluid/operators/reader/buffered_reader.cc,
// python/paddle/reader/decorator.py lowered to C++). The Python DataLoader
// pushes raw samples (contiguous float/int rows) into this core; worker
// threads shuffle and assemble fixed-shape batch buffers; Python pops ready
// batches zero-copy (ctypes view) and ships them to HBM with device_put.
//
// Plain C ABI throughout — loaded with ctypes, no pybind11.

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <vector>

namespace {

struct Sample {
  std::vector<uint8_t> bytes;
};

struct Batch {
  std::vector<uint8_t> bytes;  // batch_size * sample_nbytes, contiguous
  int64_t count = 0;           // rows actually filled
};

struct Pipeline {
  int64_t sample_nbytes;    // fixed serialized sample size
  int64_t batch_size;
  int64_t shuffle_capacity; // 0 = no shuffling
  int64_t ring_capacity;    // max ready batches buffered ahead
  bool drop_last;
  std::mt19937_64 rng;

  std::mutex mu;
  std::condition_variable ready_cv;   // batches available / finished
  std::condition_variable space_cv;   // ring has space
  std::vector<Sample> reservoir;      // shuffle buffer
  std::vector<uint8_t> partial;       // current batch under assembly
  int64_t partial_count = 0;
  std::deque<Batch> ring;             // ready batches
  bool finished = false;              // producer called finish()

  Pipeline(int64_t nbytes, int64_t bs, int64_t shuf, int64_t ring_cap,
           bool drop, uint64_t seed)
      : sample_nbytes(nbytes), batch_size(bs), shuffle_capacity(shuf),
        ring_capacity(ring_cap < 1 ? 1 : ring_cap), drop_last(drop),
        rng(seed) {
    partial.resize(sample_nbytes * batch_size);
    if (shuffle_capacity > 0) reservoir.reserve(shuffle_capacity);
  }

  // -- producer side (Python feed thread) --
  void emit_locked(const uint8_t* data) {
    std::memcpy(partial.data() + partial_count * sample_nbytes, data,
                sample_nbytes);
    if (++partial_count == batch_size) flush_locked();
  }

  void flush_locked() {
    if (partial_count == 0) return;
    Batch b;
    b.bytes.assign(partial.begin(),
                   partial.begin() + partial_count * sample_nbytes);
    b.count = partial_count;
    partial_count = 0;
    ring.push_back(std::move(b));
    ready_cv.notify_all();
  }

  bool push(const uint8_t* data) {
    std::unique_lock<std::mutex> lk(mu);
    space_cv.wait(lk, [&] {
      return (int64_t)ring.size() < ring_capacity || finished;
    });
    if (finished) return false;
    if (shuffle_capacity > 0) {
      if ((int64_t)reservoir.size() < shuffle_capacity) {
        Sample s;
        s.bytes.assign(data, data + sample_nbytes);
        reservoir.push_back(std::move(s));
        return true;
      }
      // swap a random resident out, emit it, keep the newcomer
      std::uniform_int_distribution<int64_t> d(0, shuffle_capacity - 1);
      int64_t j = d(rng);
      Sample out = std::move(reservoir[j]);
      reservoir[j].bytes.assign(data, data + sample_nbytes);
      emit_locked(out.bytes.data());
    } else {
      emit_locked(data);
    }
    return true;
  }

  void finish() {
    std::unique_lock<std::mutex> lk(mu);
    if (shuffle_capacity > 0) {
      std::shuffle(reservoir.begin(), reservoir.end(), rng);
      for (auto& s : reservoir) {
        // honor the ring bound while draining (consumer pops concurrently);
        // a cancel() from the consumer side breaks the wait
        space_cv.wait(lk, [&] {
          return (int64_t)ring.size() < ring_capacity || finished;
        });
        if (finished) break;
        emit_locked(s.bytes.data());
      }
      reservoir.clear();
    }
    if (!finished && !drop_last) flush_locked();
    partial_count = 0;
    finished = true;
    ready_cv.notify_all();
    space_cv.notify_all();
  }

  // consumer-side early exit: unblock any producer without draining
  void cancel() {
    std::unique_lock<std::mutex> lk(mu);
    finished = true;
    ready_cv.notify_all();
    space_cv.notify_all();
  }

  // -- consumer side --
  // returns rows in the popped batch, 0 on end-of-stream
  int64_t pop(uint8_t* out) {
    std::unique_lock<std::mutex> lk(mu);
    ready_cv.wait(lk, [&] { return !ring.empty() || finished; });
    if (ring.empty()) return 0;
    Batch b = std::move(ring.front());
    ring.pop_front();
    space_cv.notify_all();
    std::memcpy(out, b.bytes.data(), b.bytes.size());
    return b.count;
  }
};

}  // namespace

extern "C" {

void* ptpu_pipeline_create(int64_t sample_nbytes, int64_t batch_size,
                           int64_t shuffle_capacity, int64_t ring_capacity,
                           int drop_last, uint64_t seed) {
  return new Pipeline(sample_nbytes, batch_size, shuffle_capacity,
                      ring_capacity, drop_last != 0, seed);
}

int ptpu_pipeline_push(void* h, const uint8_t* data) {
  return static_cast<Pipeline*>(h)->push(data) ? 1 : 0;
}

void ptpu_pipeline_finish(void* h) { static_cast<Pipeline*>(h)->finish(); }

void ptpu_pipeline_cancel(void* h) { static_cast<Pipeline*>(h)->cancel(); }

int64_t ptpu_pipeline_pop(void* h, uint8_t* out) {
  return static_cast<Pipeline*>(h)->pop(out);
}

void ptpu_pipeline_destroy(void* h) { delete static_cast<Pipeline*>(h); }

}  // extern "C"
