"""Automatic mixed precision (ref: python/paddle/fluid/contrib/
mixed_precision/decorator.py + fp16_lists.py).

TPU-first: the fast dtype is bfloat16 (no loss scaling needed — bf16 keeps
fp32's exponent range), but the reference's fp16 dynamic loss scaling
machinery is kept for API parity and for fp16 compat runs. Master weights
stay fp32; the cast list mirrors the ref's white/black lists.

Observability (docs/OBSERVABILITY.md): the dynamic loss scale is exported
as the ``amp_loss_scale`` gauge and overflow-skipped steps as the
``amp_overflow_skipped_steps`` counter, on BOTH paths — the dygraph wrapper
counts host-side at the skip, the static path accumulates an in-graph skip
counter var that an at-export registry collector drains. The process-wide
:func:`total_overflow_skips` / :meth:`OptimizerWithMixedPrecision.
overflow_steps` feed the training supervisor's benignity check
(resilience/supervisor.py): an AMP overflow skip is the optimizer
ABSORBING a transient, by design — it must never be mistaken for
divergence and trigger a rollback.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import observability as _obs
from ..framework import in_dygraph_mode

# host-visible overflow accounting, independent of PADDLE_TPU_TELEMETRY:
# the supervisor consults this every boundary, so it must be a plain
# attribute read, not a registry lookup
_overflow_skips_total = 0


def total_overflow_skips():
    """Process-wide count of optimizer updates skipped on gradient overflow
    (dygraph path host-observed; static-path skips are per-optimizer, see
    :meth:`OptimizerWithMixedPrecision.overflow_steps`)."""
    return _overflow_skips_total


def _record_overflow_skip(loss_scale):
    global _overflow_skips_total
    _overflow_skips_total += 1
    if _obs._ENABLED:
        _obs.inc('amp_overflow_skipped_steps',
                 help='optimizer updates skipped on non-finite gradients '
                      '(dynamic loss scaling)')
        _obs.set_gauge('amp_loss_scale', loss_scale,
                       help='current dynamic loss scale')

# ref: fp16_lists.py
white_list = {'conv2d', 'conv3d', 'matmul', 'mul', 'conv2d_transpose'}
black_list = {'exp', 'square', 'log', 'mean', 'sum', 'cos_sim',
              'softmax', 'softmax_with_cross_entropy', 'sigmoid_cross_entropy_with_logits',
              'cross_entropy', 'layer_norm', 'batch_norm', 'reduce_sum'}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list) | set(custom_white_list or ())
        self.black_list = set(black_list) | set(custom_black_list or ())


class OptimizerWithMixedPrecision:
    """Wraps an optimizer (ref decorator.py).

    Static mode: the full AMP pipeline — white/black-list cast rewrite at
    lowering, loss scaling/unscaling and the fused finite-check +
    update_loss_scaling fused into the jitted step.

    Dygraph mode: forward math stays fp32 on TPU (bf16 via
    TrainStep(amp_dtype=...) is the production path), so loss
    scaling would be a no-op numerically; the wrapper contributes the
    ONE fused all-finite gradient gate (skip step + decay scale on
    overflow, grow scale after incr_every good steps) so scripts using
    the fp16 recipe keep their semantics."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.**15,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
                 dtype='bfloat16'):
        self._inner = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scale = float(init_loss_scaling)
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dynamic = use_dynamic_loss_scaling
        self._dtype = dtype
        self._good_steps = 0
        self._bad_steps = 0
        self._skip_count = 0          # dygraph host-observed skips
        self._scale_var = None
        self._skip_var = None         # static in-graph skip counter
        self._exported_skips = 0      # collector high-water mark

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def get_loss_scaling(self, scope=None):
        if self._scale_var is not None:
            from ..core.scope import global_scope
            scope = scope if scope is not None else global_scope()
            val = scope.find(self._scale_var.name)
            if val is not None:
                import numpy as np
                return float(np.asarray(val).reshape(())[()])
        return self._loss_scale

    def overflow_steps(self, scope=None):
        """Cumulative optimizer updates this optimizer skipped on gradient
        overflow. Dygraph: host-counted at the skip. Static: reads the
        in-graph skip counter var from the scope — a device→host read, so
        callers (the supervisor's benignity check, the export collector)
        only consult it off the hot path."""
        if self._skip_var is not None:
            from ..core.scope import global_scope
            scope = scope if scope is not None else global_scope()
            val = scope.find(self._skip_var.name)
            if val is not None:
                import numpy as np
                return int(np.asarray(val).reshape(())[()])
        return self._skip_count

    def _register_export_collector(self):
        """Static path: surface the in-graph scale/skip state through the
        registry at export time (scrapes, dump_artifacts) — zero cost per
        step, one scope read per export."""
        from ..observability import registry

        def collect():
            registry.gauge(
                'amp_loss_scale',
                'current dynamic loss scale').set(self.get_loss_scaling())
            skips = self.overflow_steps()
            delta = skips - self._exported_skips
            if delta > 0:
                self._exported_skips = skips
                registry.counter(
                    'amp_overflow_skipped_steps',
                    'optimizer updates skipped on non-finite gradients '
                    '(dynamic loss scaling)').inc(delta)

        registry.register_collector(collect)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        # Static AMP graph rewrite (ref fp16_utils.py:156): record the cast
        # lists on the Program — the Executor's lowering casts white-list op
        # inputs to the AMP dtype and pins black-list ops to fp32. Master
        # params stay fp32 in the scope.
        program = loss.block.program
        program._amp_config = {
            'dtype': jnp.float16 if self._dtype == 'float16' else jnp.bfloat16,
            'white': frozenset(self._amp_lists.white_list),
            'black': frozenset(self._amp_lists.black_list)}
        program._bump_version()
        if self._dtype == 'float16':
            # fp16 always scales/unscales (constant scale when dynamic
            # scaling is off — ref decorator.py keeps the multiplier)
            return self._static_minimize_with_loss_scaling(loss,
                                                           parameter_list)
        # bf16 keeps fp32's exponent range — no loss scaling needed
        return self._inner.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)

    def _static_minimize_with_loss_scaling(self, loss, parameter_list):
        """Dynamic loss scaling fused INTO the jitted step (ref
        fp16_utils.py:283 update_loss_scaling): scale loss → backward →
        one fused check_finite_and_unscale over all grads → conditional
        optimizer apply (lax.cond) → loss-scale state update. Zero host
        round-trips."""
        from ..backward import append_backward
        from ..core import unique_name as un
        from ..layer_helper import LayerHelper
        from ..layers import control_flow as cf
        from ..layers import tensor as T
        from ..layers.common import apply_op_layer

        scale_var = T.create_global_var(
            [1], float(self._loss_scale), 'float32', persistable=True,
            name=un.generate('loss_scaling'))
        good = T.create_global_var([1], 0, 'int32', persistable=True,
                                   name=un.generate('loss_scaling_good'))
        bad = T.create_global_var([1], 0, 'int32', persistable=True,
                                  name=un.generate('loss_scaling_bad'))
        self._scale_var = scale_var
        scaled = apply_op_layer('elementwise_mul',
                                {'x': loss, 'y': scale_var})
        params_grads = append_backward(
            scaled, parameter_list or self._inner._parameter_names())

        helper = LayerHelper('amp')
        found = helper.create_variable_for_type_inference('bool')
        found.shape = (1,)
        gnames = [g.name for _, g in params_grads]
        helper.append_op(
            type='check_finite_and_unscale',
            inputs={'xs': gnames, 'scale': scale_var.name},
            outputs={'Out': gnames, 'FoundInfinite': found.name})
        # monotonic in-graph skip counter: `bad` decays to 0 on each scale
        # decrease, so observability needs its own accumulator. One cast +
        # add fused into the step; drained by the export collector and read
        # by the supervisor's benignity check (overflow_steps).
        skip_var = T.create_global_var(
            [1], 0, 'int32', persistable=True,
            name=un.generate('loss_scaling_skips'))
        self._skip_var = skip_var
        found_i32 = apply_op_layer('cast', {'x': found}, {'dtype': 'int32'})
        helper.append_op(
            type='elementwise_add',
            inputs={'x': skip_var.name, 'y': found_i32.name},
            outputs={'Out': skip_var.name})
        self._register_export_collector()
        if self._dynamic:
            helper.append_op(
                type='update_loss_scaling',
                inputs={'found_inf': found.name,
                        'prev_loss_scaling': scale_var.name,
                        'in_good_steps': good.name, 'in_bad_steps': bad.name},
                outputs={'LossScaling': scale_var.name,
                         'OutGoodSteps': good.name, 'OutBadSteps': bad.name},
                attrs={'incr_every_n_steps': self._incr_every,
                       'decr_every_n_nan_or_inf': self._decr_every,
                       'incr_ratio': self._incr_ratio,
                       'decr_ratio': self._decr_ratio})
        ok = apply_op_layer('logical_not', {'x': found})

        def apply_block():
            self._inner.apply_gradients(params_grads)

        cf.cond(ok, apply_block, None)
        return None, params_grads

    def _dygraph_minimize(self, loss, parameter_list):
        params = parameter_list or self._inner._parameter_list
        grads = [p.grad for p in params if p.grad is not None]
        # ONE fused all-finite reduction + one host sync (not per-param)
        grads_finite = bool(_all_finite(grads)) if grads else True
        if not grads_finite:
            # the skip gate is unconditional (matching the static path's
            # lax.cond guard); dynamic scaling only controls whether the
            # scale decays on overflow
            if self._dynamic:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every:
                    self._loss_scale = max(
                        self._loss_scale * self._decr_ratio, 1.0)
                    self._bad_steps = 0
            self._skip_count += 1
            _record_overflow_skip(self._loss_scale)
            for p in params:
                p.clear_gradient()
            return None, []
        self._good_steps += 1
        self._bad_steps = 0
        if self._dynamic and self._good_steps >= self._incr_every:
            self._loss_scale *= self._incr_ratio
            self._good_steps = 0
        if _obs._ENABLED:
            _obs.set_gauge('amp_loss_scale', self._loss_scale,
                           help='current dynamic loss scale')
        return self._inner.minimize(loss, parameter_list=params)


@jax.jit
def _all_finite(grads):
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(g)) for g in grads]))


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
             dtype='bfloat16'):
    """fluid.contrib.mixed_precision.decorate parity."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, incr_every_n_steps,
        decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        use_dynamic_loss_scaling, dtype)


def cast_model_to_bf16(layer):
    """Cast a dygraph model's float params to bfloat16 (inference)."""
    for p in layer.parameters():
        if jnp.issubdtype(p.value.dtype, jnp.floating):
            p.value = p.value.astype(jnp.bfloat16)
    return layer


def bf16_autocast_wrap(apply_fn):
    """Wrap a functional apply: params stay fp32, activations compute in bf16
    (matmul/conv inputs cast; XLA keeps accumulation fp32 on MXU)."""
    def wrapped(params, *args, **kw):
        cast_params = {k: (v.astype(jnp.bfloat16)
                           if jnp.issubdtype(v.dtype, jnp.floating) else v)
                       for k, v in params.items()}
        return apply_fn(cast_params, *args, **kw)
    return wrapped
