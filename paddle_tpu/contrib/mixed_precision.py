"""Automatic mixed precision (ref: python/paddle/fluid/contrib/
mixed_precision/decorator.py + fp16_lists.py).

TPU-first: the fast dtype is bfloat16 (no loss scaling needed — bf16 keeps
fp32's exponent range), but the reference's fp16 dynamic loss scaling
machinery is kept for API parity and for fp16 compat runs. Master weights
stay fp32; the cast list mirrors the ref's white/black lists.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import in_dygraph_mode

# ref: fp16_lists.py
white_list = {'conv2d', 'conv3d', 'matmul', 'mul', 'conv2d_transpose'}
black_list = {'exp', 'square', 'log', 'mean', 'sum', 'cos_sim',
              'softmax', 'softmax_with_cross_entropy', 'sigmoid_cross_entropy_with_logits',
              'cross_entropy', 'layer_norm', 'batch_norm', 'reduce_sum'}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list) | set(custom_white_list or ())
        self.black_list = set(black_list) | set(custom_black_list or ())


class OptimizerWithMixedPrecision:
    """Wraps an optimizer: scales the loss, unscales grads, skips steps on
    inf/nan (dynamic loss scaling, ref decorator.py)."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.**15,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
                 dtype='bfloat16'):
        self._inner = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scale = float(init_loss_scaling)
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dynamic = use_dynamic_loss_scaling
        self._dtype = dtype
        self._good_steps = 0
        self._bad_steps = 0

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def get_loss_scaling(self):
        return self._loss_scale

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        # static: bf16 scaling is a no-op numerically; scale loss for fp16
        # parity then let the optimizer unscale via lr (scale folded in grads)
        from ..layers.common import apply_op_layer
        if self._dtype == 'float16' and self._loss_scale != 1.0:
            scaled = apply_op_layer('scale', {'x': loss},
                                    {'scale': self._loss_scale})
            from ..backward import append_backward
            params_grads = append_backward(scaled, parameter_list)
            inv = 1.0 / self._loss_scale
            params_grads = [
                (p, apply_op_layer('scale', {'x': g}, {'scale': inv}))
                for p, g in params_grads]
            self._inner.apply_gradients(params_grads)
            return None, params_grads
        return self._inner.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)

    def _dygraph_minimize(self, loss, parameter_list):
        import numpy as np
        params = parameter_list or self._inner._parameter_list
        grads_finite = all(
            bool(jnp.all(jnp.isfinite(p.grad))) for p in params
            if p.grad is not None)
        if not grads_finite and self._dynamic:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._loss_scale = max(self._loss_scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
            for p in params:
                p.clear_gradient()
            return None, []
        self._good_steps += 1
        if self._dynamic and self._good_steps >= self._incr_every:
            self._loss_scale *= self._incr_ratio
            self._good_steps = 0
        return self._inner.minimize(loss, parameter_list=params)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
             dtype='bfloat16'):
    """fluid.contrib.mixed_precision.decorate parity."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, incr_every_n_steps,
        decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        use_dynamic_loss_scaling, dtype)


def cast_model_to_bf16(layer):
    """Cast a dygraph model's float params to bfloat16 (inference)."""
    for p in layer.parameters():
        if jnp.issubdtype(p.value.dtype, jnp.floating):
            p.value = p.value.astype(jnp.bfloat16)
    return layer


def bf16_autocast_wrap(apply_fn):
    """Wrap a functional apply: params stay fp32, activations compute in bf16
    (matmul/conv inputs cast; XLA keeps accumulation fp32 on MXU)."""
    def wrapped(params, *args, **kw):
        cast_params = {k: (v.astype(jnp.bfloat16)
                           if jnp.issubdtype(v.dtype, jnp.floating) else v)
                       for k, v in params.items()}
        return apply_fn(cast_params, *args, **kw)
    return wrapped
