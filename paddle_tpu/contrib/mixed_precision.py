"""Automatic mixed precision (ref: python/paddle/fluid/contrib/
mixed_precision/decorator.py + fp16_lists.py).

TPU-first: the fast dtype is bfloat16 (no loss scaling needed — bf16 keeps
fp32's exponent range), but the reference's fp16 dynamic loss scaling
machinery is kept for API parity and for fp16 compat runs. Master weights
stay fp32; the cast list mirrors the ref's white/black lists.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import in_dygraph_mode

# ref: fp16_lists.py
white_list = {'conv2d', 'conv3d', 'matmul', 'mul', 'conv2d_transpose'}
black_list = {'exp', 'square', 'log', 'mean', 'sum', 'cos_sim',
              'softmax', 'softmax_with_cross_entropy', 'sigmoid_cross_entropy_with_logits',
              'cross_entropy', 'layer_norm', 'batch_norm', 'reduce_sum'}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list) | set(custom_white_list or ())
        self.black_list = set(black_list) | set(custom_black_list or ())


class OptimizerWithMixedPrecision:
    """Wraps an optimizer (ref decorator.py).

    Static mode: the full AMP pipeline — white/black-list cast rewrite at
    lowering, loss scaling/unscaling and the fused finite-check +
    update_loss_scaling fused into the jitted step.

    Dygraph mode: forward math stays fp32 on TPU (bf16 via
    TrainStep(amp_dtype=...) is the production path), so loss
    scaling would be a no-op numerically; the wrapper contributes the
    ONE fused all-finite gradient gate (skip step + decay scale on
    overflow, grow scale after incr_every good steps) so scripts using
    the fp16 recipe keep their semantics."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.**15,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
                 dtype='bfloat16'):
        self._inner = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scale = float(init_loss_scaling)
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dynamic = use_dynamic_loss_scaling
        self._dtype = dtype
        self._good_steps = 0
        self._bad_steps = 0
        self._scale_var = None

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def get_loss_scaling(self):
        if self._scale_var is not None:
            from ..core.scope import global_scope
            val = global_scope().find(self._scale_var.name)
            if val is not None:
                import numpy as np
                return float(np.asarray(val).reshape(())[()])
        return self._loss_scale

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        # Static AMP graph rewrite (ref fp16_utils.py:156): record the cast
        # lists on the Program — the Executor's lowering casts white-list op
        # inputs to the AMP dtype and pins black-list ops to fp32. Master
        # params stay fp32 in the scope.
        program = loss.block.program
        program._amp_config = {
            'dtype': jnp.float16 if self._dtype == 'float16' else jnp.bfloat16,
            'white': frozenset(self._amp_lists.white_list),
            'black': frozenset(self._amp_lists.black_list)}
        program._bump_version()
        if self._dtype == 'float16':
            # fp16 always scales/unscales (constant scale when dynamic
            # scaling is off — ref decorator.py keeps the multiplier)
            return self._static_minimize_with_loss_scaling(loss,
                                                           parameter_list)
        # bf16 keeps fp32's exponent range — no loss scaling needed
        return self._inner.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)

    def _static_minimize_with_loss_scaling(self, loss, parameter_list):
        """Dynamic loss scaling fused INTO the jitted step (ref
        fp16_utils.py:283 update_loss_scaling): scale loss → backward →
        one fused check_finite_and_unscale over all grads → conditional
        optimizer apply (lax.cond) → loss-scale state update. Zero host
        round-trips."""
        from ..backward import append_backward
        from ..core import unique_name as un
        from ..layer_helper import LayerHelper
        from ..layers import control_flow as cf
        from ..layers import tensor as T
        from ..layers.common import apply_op_layer

        scale_var = T.create_global_var(
            [1], float(self._loss_scale), 'float32', persistable=True,
            name=un.generate('loss_scaling'))
        good = T.create_global_var([1], 0, 'int32', persistable=True,
                                   name=un.generate('loss_scaling_good'))
        bad = T.create_global_var([1], 0, 'int32', persistable=True,
                                  name=un.generate('loss_scaling_bad'))
        self._scale_var = scale_var
        scaled = apply_op_layer('elementwise_mul',
                                {'x': loss, 'y': scale_var})
        params_grads = append_backward(
            scaled, parameter_list or self._inner._parameter_names())

        helper = LayerHelper('amp')
        found = helper.create_variable_for_type_inference('bool')
        found.shape = (1,)
        gnames = [g.name for _, g in params_grads]
        helper.append_op(
            type='check_finite_and_unscale',
            inputs={'xs': gnames, 'scale': scale_var.name},
            outputs={'Out': gnames, 'FoundInfinite': found.name})
        if self._dynamic:
            helper.append_op(
                type='update_loss_scaling',
                inputs={'found_inf': found.name,
                        'prev_loss_scaling': scale_var.name,
                        'in_good_steps': good.name, 'in_bad_steps': bad.name},
                outputs={'LossScaling': scale_var.name,
                         'OutGoodSteps': good.name, 'OutBadSteps': bad.name},
                attrs={'incr_every_n_steps': self._incr_every,
                       'decr_every_n_nan_or_inf': self._decr_every,
                       'incr_ratio': self._incr_ratio,
                       'decr_ratio': self._decr_ratio})
        ok = apply_op_layer('logical_not', {'x': found})

        def apply_block():
            self._inner.apply_gradients(params_grads)

        cf.cond(ok, apply_block, None)
        return None, params_grads

    def _dygraph_minimize(self, loss, parameter_list):
        params = parameter_list or self._inner._parameter_list
        grads = [p.grad for p in params if p.grad is not None]
        # ONE fused all-finite reduction + one host sync (not per-param)
        grads_finite = bool(_all_finite(grads)) if grads else True
        if not grads_finite:
            # the skip gate is unconditional (matching the static path's
            # lax.cond guard); dynamic scaling only controls whether the
            # scale decays on overflow
            if self._dynamic:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every:
                    self._loss_scale = max(
                        self._loss_scale * self._decr_ratio, 1.0)
                    self._bad_steps = 0
            for p in params:
                p.clear_gradient()
            return None, []
        self._good_steps += 1
        self._bad_steps = 0
        if self._dynamic and self._good_steps >= self._incr_every:
            self._loss_scale *= self._incr_ratio
            self._good_steps = 0
        return self._inner.minimize(loss, parameter_list=params)


@jax.jit
def _all_finite(grads):
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(g)) for g in grads]))


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
             dtype='bfloat16'):
    """fluid.contrib.mixed_precision.decorate parity."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, incr_every_n_steps,
        decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        use_dynamic_loss_scaling, dtype)


def cast_model_to_bf16(layer):
    """Cast a dygraph model's float params to bfloat16 (inference)."""
    for p in layer.parameters():
        if jnp.issubdtype(p.value.dtype, jnp.floating):
            p.value = p.value.astype(jnp.bfloat16)
    return layer


def bf16_autocast_wrap(apply_fn):
    """Wrap a functional apply: params stay fp32, activations compute in bf16
    (matmul/conv inputs cast; XLA keeps accumulation fp32 on MXU)."""
    def wrapped(params, *args, **kw):
        cast_params = {k: (v.astype(jnp.bfloat16)
                           if jnp.issubdtype(v.dtype, jnp.floating) else v)
                       for k, v in params.items()}
        return apply_fn(cast_params, *args, **kw)
    return wrapped
