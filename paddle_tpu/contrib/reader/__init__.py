"""contrib.reader (ref: python/paddle/fluid/contrib/reader/)."""
from .distributed_reader import distributed_batch_reader

__all__ = ['distributed_batch_reader']
