"""Distributed batch reader (ref: python/paddle/fluid/contrib/reader/
distributed_reader.py:21) — each trainer keeps every
trainer_id-th batch, driven by the PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM env set by distributed.launch."""
import os

__all__ = ['distributed_batch_reader']


def distributed_batch_reader(batch_reader):
    """Wrap a batch reader so each worker consumes its 1/N batch shard."""
    trainer_id = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    trainer_num = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    if trainer_id >= trainer_num:
        raise ValueError(
            'trainer_id must be less than the number of trainers')

    def decorated():
        for i, batch in enumerate(batch_reader()):
            if i % trainer_num == trainer_id:
                yield batch
    return decorated
