"""Legacy high-level Trainer API (ref: python/paddle/fluid/contrib/
trainer.py) — train_func returns (loss, ...) built in a fresh program;
Trainer owns programs/executor, runs epochs from a reader, fires events,
and checkpoints via CheckpointConfig."""
import os

from .. import io as fluid_io
from ..core.scope import Scope, scope_guard
from ..data_feeder import DataFeeder
from ..executor import Executor
from ..framework import Program, program_guard

__all__ = ['BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'CheckpointConfig', 'Trainer']


class BeginEpochEvent:
    """ref trainer.py:BeginEpochEvent."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    """ref trainer.py:EndEpochEvent."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    """ref trainer.py:BeginStepEvent."""

    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    """ref trainer.py:EndStepEvent."""

    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """ref trainer.py:CheckpointConfig."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.getcwd()
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None
        self.pserver_id = None
        self.lookup_table_name = None


class Trainer:
    """ref trainer.py:Trainer(train_func, optimizer_func, place, ...).

    `train_func` builds the model and returns the loss Variable (or a
    [loss, metric...] list); `optimizer_func` returns the optimizer to
    minimize it. Everything lowers to ONE jitted step via the Executor.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.parallel = parallel
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.checkpoint_cfg = checkpoint_config
        if self.checkpoint_cfg is not None and \
                not isinstance(self.checkpoint_cfg, CheckpointConfig):
            raise TypeError(
                'checkpoint_config must be a CheckpointConfig instance')

        with program_guard(self.train_program, self.startup_program):
            out = train_func()
            if isinstance(out, (list, tuple)):
                self.train_func_outputs = list(out)
            else:
                self.train_func_outputs = [out]
            loss = self.train_func_outputs[0]
            optimizer = optimizer_func()
            optimizer.minimize(loss)
        self.loss = loss

        self.place = place
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            if param_path is not None:
                fluid_io.load_persistables(self.exe, param_path,
                                           self.train_program)

    def stop(self):
        """ref trainer.py:stop."""
        self.__stopped = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        """ref trainer.py:train — epoch/step loop with events."""
        self.__stopped = False
        feeder = DataFeeder(feed_list=feed_order,
                            program=self.train_program) \
            if feed_order else None
        with scope_guard(self.scope):
            for epoch_id in range(num_epochs):
                if self.__stopped:
                    break
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stopped:
                        break
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    feed = feeder.feed(data) if feeder else data
                    fetch = self.train_func_outputs \
                        if begin.fetch_metrics else []
                    metrics = self.exe.run(self.train_program, feed=feed,
                                           fetch_list=fetch)
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    cfg = self.checkpoint_cfg
                    if cfg and (step_id + 1) % cfg.step_interval == 0:
                        self._save_checkpoint(epoch_id, step_id)
                cfg = self.checkpoint_cfg
                if cfg and (epoch_id + 1) % cfg.epoch_interval == 0:
                    self._save_checkpoint(epoch_id, 'end')
                event_handler(EndEpochEvent(epoch_id))

    def test(self, reader, feed_order):
        """ref trainer.py:test — average the train_func metrics over a
        reader on the test-mode program."""
        import numpy as np
        test_program = self.train_program.clone(for_test=True)
        feeder = DataFeeder(feed_list=feed_order, program=test_program)
        totals, count = None, 0
        with scope_guard(self.scope):
            for data in reader():
                vals = self.exe.run(test_program, feed=feeder.feed(data),
                                    fetch_list=self.train_func_outputs)
                vals = [np.mean(v) for v in vals]
                totals = vals if totals is None else \
                    [a + b for a, b in zip(totals, vals)]
                count += 1
        if count == 0:
            return []
        return [t / count for t in totals]

    def save_params(self, param_path):
        """ref trainer.py:save_params."""
        with scope_guard(self.scope):
            fluid_io.save_persistables(self.exe, param_path,
                                       self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        """ref trainer.py:save_inference_model."""
        with scope_guard(self.scope):
            fluid_io.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                self.exe, self.train_program)

    def _save_checkpoint(self, epoch_id, step_id):
        cfg = self.checkpoint_cfg
        d = os.path.join(cfg.checkpoint_dir, f'checkpoint_{epoch_id}_{step_id}')
        fluid_io.save_persistables(self.exe, d, self.train_program)
        # GC old checkpoints beyond max_num_checkpoints
        kept = sorted(
            (p for p in os.listdir(cfg.checkpoint_dir)
             if p.startswith('checkpoint_')),
            key=lambda p: os.path.getmtime(os.path.join(cfg.checkpoint_dir,
                                                        p)))
        while len(kept) > cfg.max_num_checkpoints:
            victim = kept.pop(0)
            import shutil
            shutil.rmtree(os.path.join(cfg.checkpoint_dir, victim),
                          ignore_errors=True)
