"""Evolutionary search controllers (ref: python/paddle/fluid/contrib/slim/
searcher/controller.py): the simulated-annealing controller light NAS uses.
Own formulation of the standard SA accept rule — accept a worse solution
with probability exp(Δreward / T), T decaying geometrically per iteration.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ['EvolutionaryController', 'SAController']


class EvolutionaryController:
    def update(self, tokens, reward):
        raise NotImplementedError('Abstract method.')

    def reset(self, range_table, init_tokens, constrain_func=None):
        raise NotImplementedError('Abstract method.')

    def next_tokens(self):
        raise NotImplementedError('Abstract method.')


class SAController(EvolutionaryController):
    """Simulated-annealing token search. tokens[i] ∈ [0, range_table[i])."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=None):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._rng = np.random.RandomState(seed)
        self._constrain_func = None
        self._reward = -float('inf')
        self._tokens = None
        self._max_reward = -float('inf')
        self._best_tokens = None
        self._iter = 0

    def __getstate__(self):
        """Checkpointable state: `_constrain_func` is a closure over the
        SearchSpace (unpicklable), so the epoch-end strategy pickle would
        abort a latency-constrained LightNAS run (ADVICE r5). Drop it here;
        LightNASStrategy.restore_from_checkpoint rebuilds it from the
        context's search space."""
        state = dict(self.__dict__)
        state['_constrain_func'] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        """SA accept rule: always take improvements; take regressions with
        probability exp(Δ/T) at the current temperature."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        delta = reward - self._reward
        if delta > 0 or self._rng.random_sample() <= math.exp(
                min(0.0, delta) / max(temperature, 1e-12)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self, control_token=None):
        """Mutate one random position to a different value in its range."""
        tokens = list(control_token) if control_token else list(self._tokens)
        new_tokens = list(tokens)
        index = self._rng.randint(len(self._range_table))
        span = self._range_table[index]
        if span > 1:
            new_tokens[index] = (new_tokens[index] + 1 +
                                 self._rng.randint(span - 1)) % span
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_iter_number):
            if self._constrain_func(new_tokens):
                break
            index = self._rng.randint(len(self._range_table))
            new_tokens = list(tokens)
            new_tokens[index] = self._rng.randint(self._range_table[index])
        return new_tokens
