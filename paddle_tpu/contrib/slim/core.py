"""slim compression pipeline core: Strategy callbacks, Context, Compressor.

ref: python/paddle/fluid/contrib/slim/core/{strategy.py, compressor.py,
config.py}. The Compressor drives epoch-based training while strategies
(quantization / distillation / pruning / NAS) rewrite the train graph at
their scheduled epochs through the callback protocol. TPU-first notes: the
rewritten Program is re-lowered to one jitted XLA step on the next run call
(executor compile cache keys on program version), so a strategy swap costs
one recompile, not per-batch overhead.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ...framework import Program, program_guard
from ...executor import Executor
from .graph import GraphWrapper, SlimGraphExecutor


def _logger():
    import logging
    from ...log_helper import get_logger
    return get_logger(__name__, logging.INFO, fmt='%(message)s')

__all__ = ['Strategy', 'Context', 'Compressor', 'ConfigFactory']


class Strategy:
    """ref slim/core/strategy.py — epoch-scheduled compression callbacks."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass

    def restore_from_checkpoint(self, context):
        pass


class Context:
    """ref slim/core/compressor.py:Context — the mutable compression state
    the strategies communicate through."""

    def __init__(self, place=None, scope=None, train_graph=None,
                 train_reader=None, eval_graph=None, eval_reader=None,
                 teacher_graphs=None, train_optimizer=None,
                 distiller_optimizer=None, search_space=None):
        self.epoch = 0
        self.epoch_id = 0
        self.batch_id = 0
        self.k_v = {}
        self.place = place
        self.scope = scope
        self.train_graph = train_graph
        self.train_reader = train_reader
        self.eval_graph = eval_graph
        self.eval_reader = eval_reader
        self.executor = None
        self.teacher_graphs = teacher_graphs or []
        self.train_optimizer = train_optimizer
        self.distiller_optimizer = distiller_optimizer
        self.optimize_graph = None
        self.eval_results = {}
        self.skip_training = False
        self.search_space = search_space

    def put(self, key, value):
        self.k_v[key] = value

    def get(self, key):
        return self.k_v.get(key)

    def get_executor(self):
        """One SlimGraphExecutor per context: its Executor caches compiled
        XLA programs, so reusing it across epochs avoids re-tracing the
        identical train/eval step every epoch."""
        if self.executor is None:
            self.executor = SlimGraphExecutor(self.place)
        return self.executor

    def to_file(self, file_name):
        with open(file_name, 'wb') as f:
            pickle.dump({'epoch_id': self.epoch_id,
                         'eval_results': self.eval_results}, f)

    def from_file(self, file_name):
        with open(file_name, 'rb') as f:
            data = pickle.load(f)
        self.epoch_id = data['epoch_id']
        self.eval_results = data['eval_results']

    def eval_converged(self, metric_name, delta=0.001):
        if metric_name not in self.eval_results or \
                len(self.eval_results[metric_name]) < 2:
            return False
        a, b = self.eval_results[metric_name][-2:]
        return abs(b - a) / (abs(a) + 1e-12) < delta

    def _sampled_batches(self, sampled_rate, cached_id):
        """Reader subsampling for run_eval_graph (ref compressor.py
        _eval_graph → cached_reader): keep each batch with probability
        `sampled_rate`, deterministic per `cached_id` — repeated scans with
        the same id (SensitivePruneStrategy's per-ratio sweeps) evaluate
        the SAME subset, so sensitivity deltas compare like for like."""
        if not (0.0 < sampled_rate <= 1.0):
            raise ValueError(
                f"sampled_rate must be in (0, 1], got {sampled_rate}")
        rng = np.random.RandomState(int(cached_id))
        kept_any = False
        first = None
        have_first = False
        for data in self.eval_reader():
            if not have_first:
                first, have_first = data, True
            if rng.random_sample() < sampled_rate:
                kept_any = True
                yield data
        if not kept_any and have_first:
            yield first          # never evaluate on 0 batches

    def run_eval_graph(self, sampled_rate=None, cached_id=0):
        """Evaluate eval_graph over eval_reader; records and returns the
        mean of each eval out_node. `sampled_rate` evaluates a
        deterministic (per `cached_id`) subsample of the reader instead of
        the full dataset."""
        assert self.eval_graph is not None and self.eval_reader is not None
        executor = self.get_executor()
        # cache the for_test clone: cloning per call would defeat the
        # executor's compile cache (keyed on program identity+version)
        cached = self.k_v.get('_eval_clone')
        key = (id(self.eval_graph), self.eval_graph.program.num_ops())
        if cached is None or cached[0] != key:
            cached = (key, self.eval_graph.clone(for_test=True))
            self.k_v['_eval_clone'] = cached
        eval_graph = cached[1]
        batches_iter = (self.eval_reader() if sampled_rate is None
                        else self._sampled_batches(sampled_rate, cached_id))
        accum, names, batches = None, None, 0
        for data in batches_iter:
            feed = data if isinstance(data, dict) else None
            results, names = executor.run(eval_graph, scope=self.scope,
                                          data=None if feed else data,
                                          feed=feed)
            vals = [float(np.asarray(r).mean()) for r in results]
            accum = vals if accum is None else \
                [a + v for a, v in zip(accum, vals)]
            batches += 1
        assert batches, "eval_reader yielded no batches"
        # fleet-global eval (docs/DISTRIBUTED.md): on a multi-host fleet
        # each host evaluated its own shard of the eval stream; sum the
        # per-host metric accumulators AND batch counts so the reported
        # numbers are over the WHOLE eval set, identical on every host
        import jax as _jax
        if _jax.process_count() > 1:
            from ...fleet_runtime import fleet_allreduce_scalars
            reduced = fleet_allreduce_scalars(accum + [float(batches)])
            accum, batches = reduced[:-1], reduced[-1]
        result = {n: a / batches for n, a in zip(names, accum)}
        for n, v in result.items():
            self.eval_results.setdefault(n, []).append(v)
        return result


class Compressor:
    """ref slim/core/compressor.py:Compressor — config-driven strategy
    pipeline (quantization / distillation / pruning / NAS) around an
    epoch training loop."""

    def __init__(self, place=None, scope=None, train_program=None,
                 train_reader=None, train_feed_list=None,
                 train_fetch_list=None, eval_program=None, eval_reader=None,
                 eval_feed_list=None, eval_fetch_list=None,
                 teacher_programs=(), checkpoint_path=None,
                 train_optimizer=None, distiller_optimizer=None,
                 search_space=None, epoch=1, log_period=20,
                 init_model=None):
        def _graph(p, feeds, fetches):
            if p is None:
                return None
            if isinstance(p, GraphWrapper):
                return p
            in_nodes = {}
            for i, f in enumerate(feeds or []):
                in_nodes[f] = i
            out_nodes = {}
            for i, f in enumerate(fetches or []):
                name = f if isinstance(f, str) else f.name
                key = 'loss' if i == 0 and fetches is not None and \
                    p is train_program else name
                out_nodes[key] = name
            return GraphWrapper(p, in_nodes, out_nodes)

        self.place = place
        self.scope = scope
        self.train_graph = _graph(train_program, train_feed_list,
                                  train_fetch_list)
        self.eval_graph = _graph(eval_program, eval_feed_list,
                                 eval_fetch_list)
        self.train_reader = train_reader
        self.eval_reader = eval_reader
        self.teacher_graphs = [g if isinstance(g, GraphWrapper)
                               else GraphWrapper(g) for g in teacher_programs]
        self.checkpoint_path = checkpoint_path
        self.train_optimizer = train_optimizer
        self.distiller_optimizer = distiller_optimizer
        self.search_space = search_space
        self.epoch = epoch
        self.log_period = log_period
        self.strategies = []
        self.init_model = init_model

    def add_strategy(self, strategy):
        self.strategies.append(strategy)
        self.epoch = max(self.epoch, strategy.end_epoch)
        return self

    def config(self, config_file):
        """Load strategies from a slim YAML config (ref slim/core/config.py
        schema: a `strategies:` list naming registered strategy classes with
        kwargs, and a `compressor:` section with epoch/checkpoint)."""
        factory = ConfigFactory(config_file)
        for s in factory.strategies:
            self.add_strategy(s)
        if factory.compressor.get('epoch'):
            self.epoch = int(factory.compressor['epoch'])
        if factory.compressor.get('checkpoint_path'):
            self.checkpoint_path = factory.compressor['checkpoint_path']
        if factory.compressor.get('init_model'):
            self.init_model = factory.compressor['init_model']
        return self

    def _load_init_model(self, context):
        """ref compressor.py:_load_model — a configured `init_model` seeds
        the pretrained weights BEFORE checkpoint resume (a later checkpoint
        overrides it). Without this the pipeline silently compressed a
        randomly-initialized network (ADVICE r5)."""
        if not self.init_model:
            return
        if not os.path.isdir(self.init_model):
            raise ValueError(
                f"Compressor init_model directory {self.init_model!r} does "
                f"not exist")
        exe = Executor(self.place)
        from ... import io
        with self._scope_guard(context):
            io.load_persistables(exe, self.init_model,
                                 context.train_graph.program)
        _logger().info("[slim] loaded init model from %s", self.init_model)

    # ---- checkpoints (ref compressor.py:_load_checkpoint/_save_checkpoint)
    def _checkpoint_dir(self, epoch_id):
        return os.path.join(self.checkpoint_path, str(epoch_id))

    def _scope_guard(self, context):
        """io.save/load_persistables read the GLOBAL scope; training runs in
        context.scope — guard so checkpoints hit the scope that trained."""
        import contextlib
        from ...core.scope import scope_guard
        return scope_guard(context.scope) if context.scope is not None \
            else contextlib.nullcontext()

    def _save_checkpoint(self, context):
        if not self.checkpoint_path:
            return
        d = self._checkpoint_dir(context.epoch_id)
        os.makedirs(d, exist_ok=True)
        context.to_file(os.path.join(d, 'context'))
        with open(os.path.join(d, 'strategies'), 'wb') as f:
            pickle.dump(self.strategies, f)
        exe = Executor(self.place)
        from ... import io
        with self._scope_guard(context):
            io.save_persistables(exe, d, context.optimize_graph.program
                                 if context.optimize_graph else
                                 context.train_graph.program)

    def _load_checkpoint(self, context):
        if not self.checkpoint_path or not os.path.isdir(
                self.checkpoint_path):
            return context
        epochs = sorted(int(e) for e in os.listdir(self.checkpoint_path)
                        if e.isdigit())
        if not epochs:
            return context
        d = self._checkpoint_dir(epochs[-1])
        context.from_file(os.path.join(d, 'context'))
        context.epoch_id += 1
        spath = os.path.join(d, 'strategies')
        if os.path.exists(spath):
            # strategy STATE (prune masks/ratios, controller state) resumes
            # with the checkpoint, like the reference's pickled strategies
            with open(spath, 'rb') as f:
                self.strategies = pickle.load(f)
        exe = Executor(self.place)
        from ... import io
        with self._scope_guard(context):
            io.load_persistables(exe, d, context.train_graph.program)
        for s in self.strategies:
            s.restore_from_checkpoint(context)
        return context

    # ---- main loop ----
    def _train_one_epoch(self, context):
        if context.skip_training or context.train_reader is None:
            return
        graph = context.optimize_graph or context.train_graph
        executor = context.get_executor()
        for batch_id, data in enumerate(context.train_reader()):
            context.batch_id = batch_id
            for s in self.strategies:
                s.on_batch_begin(context)
            feed = data if isinstance(data, dict) else None
            executor.run(graph, scope=context.scope,
                         data=None if feed else data, feed=feed)
            for s in self.strategies:
                s.on_batch_end(context)

    def run(self):
        context = Context(
            place=self.place, scope=self.scope,
            train_graph=self.train_graph, train_reader=self.train_reader,
            eval_graph=self.eval_graph, eval_reader=self.eval_reader,
            teacher_graphs=self.teacher_graphs,
            train_optimizer=self.train_optimizer,
            distiller_optimizer=self.distiller_optimizer,
            search_space=self.search_space)
        context.epoch = self.epoch
        self.context = context
        if context.optimize_graph is None and self.train_optimizer is not None:
            context.optimize_graph = self.train_graph.get_optimize_graph(
                self.train_optimizer, self.place, self.scope)
        self._load_init_model(context)
        context = self._load_checkpoint(context)

        for s in self.strategies:
            s.on_compression_begin(context)
        start = context.epoch_id
        for epoch_id in range(start, self.epoch):
            context.epoch_id = epoch_id
            for s in self.strategies:
                s.on_epoch_begin(context)
            self._train_one_epoch(context)
            for s in self.strategies:
                s.on_epoch_end(context)
            if context.eval_graph is not None and \
                    context.eval_reader is not None:
                context.run_eval_graph()
            self._save_checkpoint(context)
        for s in self.strategies:
            s.on_compression_end(context)
        return context.eval_graph


class ConfigFactory:
    """ref slim/core/config.py — YAML strategy registry. Schema:

        version: 1.0
        strategies:
          quant_strategy:
            class: QuantizationStrategy
            start_epoch: 0
            end_epoch: 2
            weight_bits: 8
        compressor:
          epoch: 2
          checkpoint_path: ./ckpt
          strategies: [quant_strategy]
    """

    def __init__(self, config):
        import yaml
        if isinstance(config, str) and os.path.exists(config):
            with open(config) as f:
                spec = yaml.safe_load(f)
        elif isinstance(config, str):
            spec = yaml.safe_load(config)
        else:
            spec = config
        self.compressor = dict(spec.get('compressor', {}))
        wanted = self.compressor.get('strategies')
        self.strategies = []
        defs = spec.get('strategies', {}) or {}
        if wanted is None:
            ordered = list(defs)
        else:
            # callbacks fire in the compressor's LISTED order, not the
            # YAML-definition order (reference config.py resolves the
            # compressor's strategy list by name, preserving it)
            unknown = [n for n in wanted if n not in defs]
            if unknown:
                raise ValueError(
                    f"compressor.strategies names undefined strategies "
                    f"{unknown}; defined: {sorted(defs)}")
            ordered = list(wanted)
        for name in ordered:
            sdef = dict(defs[name])
            cls_name = sdef.pop('class')
            self.strategies.append(_strategy_class(cls_name)(**sdef))

    def instance(self, name):
        for s in self.strategies:
            if type(s).__name__ == name:
                return s
        return None


def _strategy_class(name):
    from . import distillation, prune, nas, quant_strategy
    registry = {
        'QuantizationStrategy': quant_strategy.QuantizationStrategy,
        'DistillationStrategy': distillation.DistillationStrategy,
        'UniformPruneStrategy': prune.UniformPruneStrategy,
        'SensitivePruneStrategy': prune.SensitivePruneStrategy,
        'PruneStrategy': prune.PruneStrategy,
        'LightNASStrategy': nas.LightNASStrategy,
    }
    if name not in registry:
        raise ValueError(f"unknown slim strategy class {name!r}; "
                         f"known: {sorted(registry)}")
    return registry[name]
