"""QuantizationStrategy for the Compressor pipeline (ref: python/paddle/
fluid/contrib/slim/quantization/quantization_strategy.py) — rewrites the
train graph with QAT fake-quant ops at start_epoch and freezes/saves the
int8 artifacts at end_epoch."""
from __future__ import annotations

import os

from .core import Strategy

__all__ = ['QuantizationStrategy']


class QuantizationStrategy(Strategy):
    def __init__(self, start_epoch=0, end_epoch=0, float_model_save_path=None,
                 int8_model_save_path=None, weight_bits=8,
                 activation_bits=8, activation_quantize_type='abs_max',
                 weight_quantize_type='abs_max', save_in_nodes=None,
                 save_out_nodes=None):
        super().__init__(start_epoch, end_epoch)
        self.float_model_save_path = float_model_save_path
        self.int8_model_save_path = int8_model_save_path
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.save_in_nodes = save_in_nodes
        self.save_out_nodes = save_out_nodes

    def __getstate__(self):
        # the transpiler holds program references — rebuilt on restore
        d = dict(self.__dict__)
        d.pop('_transpiler', None)
        return d

    def _transpile(self, context):
        from ..quantize import QuantizeTranspiler
        t = QuantizeTranspiler(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            activation_quantize_type=self.activation_quantize_type,
            weight_quantize_type=self.weight_quantize_type)
        graph = context.optimize_graph or context.train_graph
        t.training_transpile(graph.program)
        if context.eval_graph is not None:
            t.training_transpile(context.eval_graph.program)
        self._transpiler = t

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            self._transpile(context)

    def restore_from_checkpoint(self, context):
        # a resume past start_epoch must re-insert the fake-quant ops (the
        # checkpointed weights are float; the rewrite is not persisted)
        if context.epoch_id > self.start_epoch:
            self._transpile(context)

    def on_epoch_end(self, context):
        if context.epoch_id == self.end_epoch - 1 and \
                (self.float_model_save_path or self.int8_model_save_path):
            from ...executor import Executor
            from ... import io
            exe = Executor(context.place)
            graph = context.eval_graph or context.train_graph
            feeds = self.save_in_nodes or sorted(graph.in_nodes)
            fetches = self.save_out_nodes or \
                [graph.out_nodes[k] for k in sorted(graph.out_nodes)]
            if self.float_model_save_path:
                os.makedirs(self.float_model_save_path, exist_ok=True)
                io.save_inference_model(self.float_model_save_path, feeds,
                                        fetches, exe, graph.program)
            if self.int8_model_save_path:
                os.makedirs(self.int8_model_save_path, exist_ok=True)
                prog = graph.program.clone(for_test=True)
                self._transpiler.convert_to_int8(prog, context.place,
                                                 context.scope)
                io.save_inference_model(self.int8_model_save_path, feeds,
                                        fetches, exe, prog)
