"""Model compression suite (ref: python/paddle/fluid/contrib/slim/):
quantization (QAT/PTQ), knowledge distillation, filter pruning, light NAS,
and the config-driven Compressor strategy pipeline that composes them.
"""
from .core import Strategy, Context, Compressor, ConfigFactory
from .graph import GraphWrapper, VarWrapper, OpWrapper, SlimGraphExecutor
from .quantization import (FakeQuantWrapper, quant_aware, convert,
                           quant_post, PostTrainingQuantization,
                           WeightQuantization, QUANTIZABLE)
from .quant_strategy import QuantizationStrategy
from .distillation import (FSPDistiller, L2Distiller, SoftLabelDistiller,
                           DistillationStrategy)
from .prune import (Pruner, StructurePruner, PruneStrategy,
                    UniformPruneStrategy, SensitivePruneStrategy)
from .searcher import EvolutionaryController, SAController
from .nas import SearchSpace, LightNASStrategy
from . import core
from . import graph
from . import quantization
from . import distillation
from . import prune
from . import nas
from . import searcher

__all__ = [
    'Strategy', 'Context', 'Compressor', 'ConfigFactory', 'GraphWrapper',
    'SlimGraphExecutor', 'FakeQuantWrapper', 'quant_aware', 'convert',
    'quant_post', 'PostTrainingQuantization', 'WeightQuantization',
    'QuantizationStrategy', 'FSPDistiller', 'L2Distiller',
    'SoftLabelDistiller', 'DistillationStrategy', 'Pruner',
    'StructurePruner', 'PruneStrategy', 'UniformPruneStrategy',
    'SensitivePruneStrategy', 'EvolutionaryController', 'SAController',
    'SearchSpace', 'LightNASStrategy',
]
