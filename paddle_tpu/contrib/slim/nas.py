"""Light NAS (ref: python/paddle/fluid/contrib/slim/nas/{search_space.py,
light_nas_strategy.py}).

The strategy drives an SAController over a user SearchSpace: each round the
controller proposes tokens, the space builds train/eval programs for them,
the candidate trains for `retrain_epoch` passes and is scored on the eval
metric (optionally latency-constrained); the controller anneals toward the
best tokens. The reference's socket-based controller server / search agent
(nas/controller_server.py, distributed search workers) is replaced by the
in-process loop — multi-host search on TPU parallelizes over pods via the
fleet launch utilities instead of ad-hoc sockets.
"""
from __future__ import annotations

import numpy as np

from ...executor import Executor
from .core import Strategy
from .graph import GraphWrapper, SlimGraphExecutor
from .searcher import SAController

__all__ = ['SearchSpace', 'LightNASStrategy']


class SearchSpace:
    """ref nas/search_space.py — NAS problem definition."""

    def init_tokens(self):
        raise NotImplementedError('Abstract method.')

    def range_table(self):
        raise NotImplementedError('Abstract method.')

    def create_net(self, tokens):
        """tokens → (startup_program, train_program, eval_program,
        train_metrics(dict name→var-name), eval_metrics)."""
        raise NotImplementedError('Abstract method.')

    def get_model_latency(self, program):
        """Optional latency model for constrained search."""
        raise NotImplementedError('Abstract method.')


class LightNASStrategy(Strategy):
    """ref nas/light_nas_strategy.py — SA search over the space. Runs the
    whole search in on_compression_begin (search is a pre-training phase);
    the best tokens/programs are left on the context for the caller."""

    def __init__(self, controller=None, end_epoch=0, target_latency=None,
                 retrain_epoch=1, metric_name='acc', search_steps=10,
                 max_train_batches=None, start_epoch=0):
        super().__init__(start_epoch, max(end_epoch, start_epoch))
        self.controller = controller or SAController(seed=0)
        self.target_latency = target_latency
        self.retrain_epoch = retrain_epoch
        self.metric_name = metric_name
        self.search_steps = search_steps
        self.max_train_batches = max_train_batches

    def _constrain(self, space):
        if self.target_latency is None:
            return None

        def ok(tokens):
            _, train_p, _, _, _ = space.create_net(tokens)
            return space.get_model_latency(train_p) <= self.target_latency
        return ok

    def restore_from_checkpoint(self, context):
        """SAController.__getstate__ drops the latency-constraint closure
        (it captures the SearchSpace and cannot pickle); rebuild it from
        the live context so a resumed search keeps honoring
        target_latency."""
        if self.target_latency is not None and \
                context.search_space is not None:
            self.controller._constrain_func = \
                self._constrain(context.search_space)

    def _score(self, space, tokens, context):
        """Train the candidate briefly and return the eval metric."""
        startup, train_p, eval_p, train_m, eval_m = space.create_net(tokens)
        exe = Executor(context.place)
        exe.run(startup, scope=context.scope)
        sge = SlimGraphExecutor(context.place)
        train_g = GraphWrapper(train_p, out_nodes=train_m)
        for _ in range(self.retrain_epoch):
            for bi, data in enumerate(context.train_reader()):
                if self.max_train_batches is not None and \
                        bi >= self.max_train_batches:
                    break
                feed = data if isinstance(data, dict) else None
                sge.run(train_g, scope=context.scope,
                        data=None if feed else data, feed=feed)
        eval_g = GraphWrapper(eval_p, out_nodes=eval_m)
        vals, names = [], []
        batches = 0
        accum = None
        for data in context.eval_reader():
            feed = data if isinstance(data, dict) else None
            res, names = sge.run(eval_g, scope=context.scope,
                                 data=None if feed else data, feed=feed)
            vals = [float(np.asarray(r).mean()) for r in res]
            accum = vals if accum is None else \
                [a + v for a, v in zip(accum, vals)]
            batches += 1
        result = {n: a / batches for n, a in zip(names, accum)}
        return result[self.metric_name]

    def on_compression_begin(self, context):
        space = context.search_space
        assert space is not None, "LightNASStrategy needs a search_space"
        tokens = list(space.init_tokens())
        self.controller.reset(space.range_table(), tokens,
                              self._constrain(space))
        reward = self._score(space, tokens, context)
        self.controller.update(tokens, reward)
        for _ in range(self.search_steps):
            tokens = self.controller.next_tokens()
            reward = self._score(space, tokens, context)
            self.controller.update(tokens, reward)
        best = self.controller.best_tokens
        context.put('best_tokens', best)
        context.put('best_reward', self.controller.max_reward)
        context.put('best_net', space.create_net(best))
