"""Knowledge distillation (ref: python/paddle/fluid/contrib/slim/
distillation/{distiller.py, distillation_strategy.py}).

Distillers add a teacher-guidance loss to the merged student+teacher graph:
- L2Distiller: mean squared error between feature maps,
- FSPDistiller: L2 between FSP (flow of solution procedure) matrices of
  layer pairs (the `fsp` op — one einsum on TPU, ops/nn_ops.py:494),
- SoftLabelDistiller: soft cross-entropy between temperature-scaled logits.

DistillationStrategy merges the teacher program into a clone of the student
train graph at start_epoch, sums the distill losses onto the student loss,
appends the distiller optimizer, and swaps the result in as
context.optimize_graph until end_epoch.
"""
from __future__ import annotations

from ... import layers
from ...framework import Program, Variable, program_guard
from ...executor import Executor
from .core import Strategy

__all__ = ['FSPDistiller', 'L2Distiller', 'SoftLabelDistiller',
           'DistillationStrategy']


class L2Distiller:
    """ref distiller.py:L2Distiller — L2 loss between a student and a
    teacher feature map (same shape)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.distillation_loss_weight = distillation_loss_weight

    def distiller_loss(self, graph):
        with program_guard(graph.program):
            s = graph.var(self.student_feature_map)._var
            t = graph.var(self.teacher_feature_map)._var
            l2 = layers.reduce_mean(layers.square(s - t))
            dl = l2 * self.distillation_loss_weight
            loss = dl
            if 'loss' in graph.out_nodes:
                loss = dl + graph.var(graph.out_nodes['loss'])._var
            graph.out_nodes['loss'] = loss.name
            graph.out_nodes['l2loss_' + self.student_feature_map + '_' +
                            self.teacher_feature_map] = dl.name
        return graph


class FSPDistiller:
    """ref distiller.py:FSPDistiller — L2 between FSP matrices of
    (start, end) feature-map pairs from student and teacher."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.distillation_loss_weight = distillation_loss_weight

    def distiller_loss(self, graph):
        with program_guard(graph.program):
            losses = []
            for s_pair, t_pair in zip(self.student_pairs,
                                      self.teacher_pairs):
                s_fsp = layers.fsp_matrix(graph.var(s_pair[0])._var,
                                          graph.var(s_pair[1])._var)
                t_fsp = layers.fsp_matrix(graph.var(t_pair[0])._var,
                                          graph.var(t_pair[1])._var)
                losses.append(layers.reduce_mean(
                    layers.square(s_fsp - t_fsp)))
            dl = layers.sum(losses) * self.distillation_loss_weight
            loss = dl
            if 'loss' in graph.out_nodes:
                loss = dl + graph.var(graph.out_nodes['loss'])._var
            graph.out_nodes['loss'] = loss.name
            graph.out_nodes['fsp_distillation_loss'] = dl.name
        return graph


class SoftLabelDistiller:
    """ref distiller.py:SoftLabelDistiller — soft cross-entropy between
    temperature-scaled student logits and teacher soft labels."""

    def __init__(self, student_feature_map=None, teacher_feature_map=None,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.distillation_loss_weight = distillation_loss_weight

    def distiller_loss(self, graph):
        with program_guard(graph.program):
            s = graph.var(self.student_feature_map)._var
            t = graph.var(self.teacher_feature_map)._var
            s_scaled = s / self.student_temperature
            t_soft = layers.softmax(t / self.teacher_temperature)
            t_soft.stop_gradient = True
            ce = layers.softmax_with_cross_entropy(s_scaled, t_soft,
                                                   soft_label=True)
            dl = layers.reduce_mean(ce) * self.distillation_loss_weight
            loss = dl
            if 'loss' in graph.out_nodes:
                loss = dl + graph.var(graph.out_nodes['loss'])._var
            graph.out_nodes['loss'] = loss.name
            graph.out_nodes['soft_label_loss_' + self.student_feature_map +
                            '_' + self.teacher_feature_map] = dl.name
        return graph


class DistillationStrategy(Strategy):
    """ref distillation_strategy.py — swap in the merged distillation graph
    between start_epoch and end_epoch."""

    def __init__(self, distillers=None, start_epoch=0, end_epoch=0):
        super().__init__(start_epoch, end_epoch)
        self.distillers = distillers or []

    def restore_from_checkpoint(self, context):
        if self.start_epoch < context.epoch_id < self.end_epoch:
            self._create_distillation_graph(context)

    def on_epoch_begin(self, context):
        if self.start_epoch == context.epoch_id:
            self._create_distillation_graph(context)

    def _create_distillation_graph(self, context):
        teacher = context.teacher_graphs[0]
        for var in teacher.program.list_vars():
            var.stop_gradient = True
        graph = context.train_graph.clone()
        graph.merge(teacher)
        if 'loss' in graph.out_nodes:
            graph.out_nodes['student_loss'] = graph.out_nodes['loss']

        for distiller in self.distillers:
            graph = distiller.distiller_loss(graph)

        startup = Program()
        with program_guard(graph.program, startup):
            optimizer = context.distiller_optimizer
            # only student params update: teacher params came in through
            # merge() and are recorded in teacher_persistables
            students = [p._var for p in graph.all_parameters()
                        if p.name not in graph.teacher_persistables]
            optimizer.minimize(graph.var(graph.out_nodes['loss'])._var,
                               parameter_list=[p.name for p in students])
        exe = Executor(context.place)
        exe.run(startup, scope=context.scope)

        context.put('distillation_backup_optimize_graph',
                    context.optimize_graph)
        context.optimize_graph = graph

    def on_epoch_end(self, context):
        if context.epoch_id == (self.end_epoch - 1):
            context.optimize_graph = context.get(
                'distillation_backup_optimize_graph')
