"""Graph abstraction for the slim compression pipeline.

ref: python/paddle/fluid/contrib/slim/graph/graph_wrapper.py — the reference
wraps an IrGraph; here the Program op-list IR is already the graph, so
GraphWrapper is a thin shell holding the program plus the in/out node name
maps the strategies communicate through. SlimGraphExecutor
(ref: slim/graph/executor.py) delegates to the XLA-lowering Executor.
"""
from __future__ import annotations

import numpy as np

from ...framework import Program, Variable, program_guard
from ...executor import Executor


class VarWrapper:
    """ref graph_wrapper.VarWrapper — `._var` unwraps to the framework var."""

    def __init__(self, var, graph):
        self._var = var
        self._graph = graph

    @property
    def name(self):
        return self._var.name

    def shape(self):
        return list(self._var.shape) if self._var.shape else []

    def set_shape(self, shape):
        self._var.shape = tuple(int(s) for s in shape)


class OpWrapper:
    def __init__(self, op, graph):
        self._op = op
        self._graph = graph

    @property
    def type(self):
        return self._op.type

    def attr(self, name):
        return self._op.attrs.get(name)


class GraphWrapper:
    """Program + the in/out node registry the strategies share.

    ref: slim/graph/graph_wrapper.py:GraphWrapper. `out_nodes['loss']` names
    the training loss; distillers rebind it to the combined loss.
    """

    def __init__(self, program=None, in_nodes=None, out_nodes=None):
        self.program = program if program is not None else Program()
        self.in_nodes = dict(in_nodes or {})
        self.out_nodes = dict(out_nodes or {})
        self.teacher_persistables = {}

    # ---- queries ----
    def all_parameters(self):
        return [VarWrapper(p, self) for p in self.program.all_parameters()]

    def is_parameter(self, var):
        from ...framework import Parameter
        return isinstance(var._var if isinstance(var, VarWrapper) else var,
                          Parameter)

    def is_persistable(self, var):
        v = var._var if isinstance(var, VarWrapper) else var
        return bool(v.persistable)

    def var(self, name):
        return VarWrapper(self.program.global_block().var(name), self)

    def vars(self):
        return [VarWrapper(v, self) for v in self.program.list_vars()]

    def ops(self):
        return [OpWrapper(op, self)
                for b in self.program.blocks for op in b.ops]

    def numel_params(self):
        return sum(int(np.prod(p._var.shape)) for p in self.all_parameters()
                   if p._var.shape)

    # ---- transforms ----
    def clone(self, for_test=False):
        g = GraphWrapper(self.program.clone(for_test),
                         self.in_nodes, self.out_nodes)
        g.teacher_persistables = dict(self.teacher_persistables)
        return g

    def merge(self, other):
        """Append `other`'s vars + ops into this graph (ref merge semantics:
        same-named vars are SHARED — that is how teacher ops consume the
        student's feed vars; build teacher nets with distinct param names)."""
        from ...framework import Operator
        blk = self.program.global_block()
        for var in other.program.list_vars():
            if var.persistable:
                self.teacher_persistables[var.name] = var
            if var.name not in blk.vars:
                import copy
                nv = copy.copy(var)
                nv.block = blk
                blk.vars[var.name] = nv
        for b in other.program.blocks:
            for op in b.ops:
                blk.ops.append(Operator(
                    blk, op.type,
                    {k: list(v) for k, v in op.inputs.items()},
                    {k: list(v) for k, v in op.outputs.items()},
                    dict(op.attrs)))

    def program_guard(self, startup=None):
        return program_guard(self.program, startup)

    def get_optimize_graph(self, optimizer, place=None, scope=None):
        """Clone + append backward/optimize ops for `out_nodes['loss']` and
        run the resulting startup (ref graph_wrapper.get_optimize_graph)."""
        g = self.clone()
        startup = Program()
        with program_guard(g.program, startup):
            optimizer.minimize(g.var(g.out_nodes['loss'])._var)
        Executor(place).run(startup, scope=scope)
        return g

    def save_persistables(self, path, exe):
        from ... import io
        io.save_persistables(exe.exe if isinstance(exe, SlimGraphExecutor)
                             else exe, path, self.program)

    def load_persistables(self, path, exe):
        from ... import io
        io.load_persistables(exe.exe if isinstance(exe, SlimGraphExecutor)
                             else exe, path, self.program)


class SlimGraphExecutor:
    """ref: slim/graph/executor.py — runs a GraphWrapper with feeds."""

    def __init__(self, place=None):
        self.exe = Executor(place)
        self.place = place

    def run(self, graph, scope=None, data=None, feed=None):
        results = []
        fetch_list = [graph.out_nodes[n] for n in sorted(graph.out_nodes)]
        if data is not None and feed is None:
            feed = {}
            for name, idx in graph.in_nodes.items():
                feed[name] = np.asarray([d[idx] for d in data]) \
                    if isinstance(data, list) else data[idx]
        outs = self.exe.run(graph.program, feed=feed,
                            fetch_list=fetch_list, scope=scope)
        results.extend(outs)
        return results, sorted(graph.out_nodes)
