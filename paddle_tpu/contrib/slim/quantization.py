"""Model compression: QAT fake-quant + post-training quantization.

Parity target: the reference's slim quantization passes
(python/paddle/fluid/contrib/slim/quantization) — the reference rewrites the
Program graph inserting fake_quantize ops before every quantizable op; the
dygraph formulation wraps quantizable Layers (Conv2D/Linear) so their
weights and input activations pass through the STE quant-dequant ops
(ops/quant_ops.py), which is the same math fused into the jitted step.
"""
from __future__ import annotations

import numpy as np

from ...dygraph import Layer
from ...dygraph.nn import Conv2D, Linear
from ...dygraph.tape import dispatch_op, Tensor


class FakeQuantWrapper(Layer):
    """Wraps a Conv2D/Linear: channel-wise weight fake-quant + EMA
    activation fake-quant (training observers; exact QAT rule of the
    reference's QuantizationTransformPass)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = layer
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self._act_scale = np.ones(1, np.float32)
        self._act_state = np.ones(1, np.float32)
        self._act_accum = np.ones(1, np.float32)

    def forward(self, x, *args, **kwargs):
        out = dispatch_op(
            'fake_quantize_dequantize_moving_average_abs_max',
            {'x': x, 'in_scale': Tensor(self._act_scale, stop_gradient=True),
             'state': Tensor(self._act_state, stop_gradient=True),
             'accum': Tensor(self._act_accum, stop_gradient=True)},
            {'moving_rate': self.moving_rate,
             'bit_length': self.activation_bits,
             'is_test': not self.training})
        xq, scale, state, accum = out
        if self.training:
            self._act_scale = np.asarray(scale.numpy())
            self._act_state = np.asarray(state.numpy())
            self._act_accum = np.asarray(accum.numpy())
        w = self.inner.weight
        wq, _ = dispatch_op(
            'fake_channel_wise_quantize_dequantize_abs_max',
            {'x': w}, {'bit_length': self.weight_bits, 'quant_axis': 0})
        orig_value = w.value
        try:
            w.value = wq.value if hasattr(wq, 'value') else wq
            return self.inner(xq, *args, **kwargs)
        finally:
            w.value = orig_value

    @property
    def act_scale(self):
        return float(self._act_scale[0])


QUANTIZABLE = (Conv2D, Linear)


def quant_aware(model, weight_bits=8, activation_bits=8, moving_rate=0.9,
                quantizable_types=QUANTIZABLE):
    """In-place QAT transform: every quantizable sublayer is wrapped with
    fake-quant observers. Returns the model (ref: quant_aware API of
    paddleslim / the QuantizationTransformPass)."""

    def transform(layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, quantizable_types):
                layer._sub_layers[name] = FakeQuantWrapper(
                    sub, weight_bits, activation_bits, moving_rate)
            elif isinstance(sub, FakeQuantWrapper):
                continue
            else:
                transform(sub)
        return layer

    return transform(model)


def convert(model):
    """Strip QAT wrappers for deployment, returning (model, scales): the
    recorded activation scales + channel-wise weight scales per wrapped
    layer (ref: QuantizationFreezePass)."""
    scales = {}

    def strip(layer, prefix=''):
        for name, sub in list(layer._sub_layers.items()):
            full = f'{prefix}.{name}' if prefix else name
            if isinstance(sub, FakeQuantWrapper):
                w = np.asarray(sub.inner.weight.numpy())
                axes = tuple(range(1, w.ndim))
                scales[full] = {
                    'activation': sub.act_scale,
                    'weight': np.max(np.abs(w), axis=axes),
                }
                layer._sub_layers[name] = sub.inner
            else:
                strip(sub, full)
        return layer

    return strip(model), scales


def quant_post(model, calib_reader, num_batches=10, activation_bits=8,
               weight_bits=8):
    """Post-training quantization: run calibration batches through the
    float model recording per-layer abs-max activation scales, and compute
    channel-wise weight scales. Returns a scales dict usable with the
    quantize_linear/dequantize_linear ops (ref: quant_post / the
    PostTrainingQuantization pass)."""
    acts = {}
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, output):
            x = inputs[0]
            v = float(np.max(np.abs(np.asarray(x.numpy()))))
            acts[name] = max(acts.get(name, 0.0), v)
        return hook

    for name, sub in model.named_sublayers():
        if isinstance(sub, QUANTIZABLE):
            hooks.append(sub.register_forward_post_hook(make_hook(name)))
    model.eval()
    for i, batch in enumerate(calib_reader()):
        if i >= num_batches:
            break
        model(*[b if isinstance(b, Tensor) else Tensor(np.asarray(b))
                for b in (batch if isinstance(batch, (list, tuple))
                          else [batch])])
    for h in hooks:
        if h is not None and hasattr(h, 'remove'):
            h.remove()
    scales = {}
    for name, sub in model.named_sublayers():
        if isinstance(sub, QUANTIZABLE):
            w = np.asarray(sub.weight.numpy())
            axes = tuple(range(1, w.ndim))
            scales[name] = {
                'activation': acts.get(name, 1.0),
                'weight': np.max(np.abs(w), axis=axes),
            }
    return scales


class PostTrainingQuantization:
    """ref: contrib/slim/quantization/post_training_quantization.py —
    class-form wrapper over quant_post: calibrate a float model, return
    scales, and save_quantized_model persists the float state + scales for
    the int8 Predictor path (inference.py Config.enable_int8)."""

    def __init__(self, model=None, sample_generator=None, batch_nums=10,
                 activation_bits=8, weight_bits=8, algo='abs_max', **kw):
        if model is None or isinstance(model, str):
            raise ValueError(
                "PostTrainingQuantization needs a dygraph `model=` Layer; "
                "the reference's executor/model_dir loading form is not "
                "supported — load the model first (load_dygraph + "
                "set_dict), then pass it here"
                + (f" (got unsupported kwargs {sorted(kw)})" if kw else ""))
        self._model = model
        self._reader = sample_generator
        self._batches = batch_nums
        self._abits = activation_bits
        self._wbits = weight_bits
        self._scales = None

    def quantize(self):
        self._scales = quant_post(self._model, self._reader,
                                  num_batches=self._batches,
                                  activation_bits=self._abits,
                                  weight_bits=self._wbits)
        return self._scales

    @property
    def scales(self):
        return self._scales

    def save_quantized_model(self, save_model_path):
        """Persist the calibrated model: float state_dict (npz) + per-layer
        activation/weight scales, consumable by the int8 Predictor."""
        import os
        if self._scales is None:
            self.quantize()
        os.makedirs(save_model_path, exist_ok=True)
        from ...dygraph.checkpoint import save_dygraph
        save_dygraph(self._model.state_dict(),
                     os.path.join(save_model_path, 'model'))
        flat = {}
        for name, info in self._scales.items():
            flat[f'{name}.activation'] = np.asarray([info['activation']])
            flat[f'{name}.weight'] = np.asarray(info['weight'])
        # torn-write-proof like every other model artifact (PR 7): a crash
        # mid-save must not leave a half-written scales file beside a
        # fully-written model checkpoint
        from ...io import _atomic_savez
        _atomic_savez(os.path.join(save_model_path, 'quant_scales.npz'),
                      flat)
        return save_model_path


class WeightQuantization:
    """ref: contrib/slim/quantization/quantization_pass.py:
    WeightQuantization — channel-wise abs-max weight scales for a dygraph
    model (weight-only int8; raw abs-max, directly consumable by
    inference Config.enable_int8)."""

    def __init__(self, model=None, weight_bits=8, **kw):
        if model is None or isinstance(model, str):
            raise ValueError(
                "WeightQuantization needs a dygraph `model=` Layer; the "
                "reference's model_dir form is not supported — load the "
                "model first, then pass it here"
                + (f" (got unsupported kwargs {sorted(kw)})" if kw else ""))
        self._model = model
        self._bits = weight_bits

    def quantize_weight_to_int(self, quantizable_op_type=None):
        """Returns per-layer channel-wise abs-max scales (the SAME raw
        abs-max convention as quant_post and the int8 Predictor's
        calibration — inference.py Config.enable_int8)."""
        type_map = {'conv2d': Conv2D, 'mul': Linear, 'matmul': Linear,
                    'linear': Linear}
        wanted = (QUANTIZABLE if quantizable_op_type is None else
                  tuple({type_map[t] for t in quantizable_op_type
                         if t in type_map}))
        scales = {}
        for name, sub in self._model.named_sublayers():
            if isinstance(sub, wanted):
                w = np.asarray(sub.weight.numpy())
                axes = tuple(range(1, w.ndim))
                scales[name] = np.max(np.abs(w), axis=axes)
        return scales
