"""Filter/structure pruning (ref: python/paddle/fluid/contrib/slim/prune/
{pruner.py, prune_strategy.py}).

TPU-first formulation: pruning keeps STATIC shapes — pruned filter groups
are masked to zero and the masks are re-applied after each optimizer step
(`lazy` semantics of the reference's Pruner.prune_tensor), so the jitted
XLA step never recompiles and the dense MXU tiling is untouched. The
reference's shape-shrinking mode exists as `prune_tensor(lazy=False)` for
parity/export; on TPU the win comes at export (smaller deployed weights),
not in training, so the strategies train masked.
"""
from __future__ import annotations

import numpy as np

from .core import Strategy

__all__ = ['Pruner', 'StructurePruner', 'PruneStrategy',
           'UniformPruneStrategy', 'SensitivePruneStrategy']


class Pruner:
    """ref prune/pruner.py:Pruner — base class."""

    def prune(self, param):
        pass


class StructurePruner(Pruner):
    """ref prune/pruner.py:StructurePruner — group pruning along an axis
    ranked by a criterion (l1_norm)."""

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {'*': 0}
        self.criterions = criterions or {'*': 'l1_norm'}

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indices of the weakest `ratio` fraction of groups on `axis`."""
        criterion = self.criterions.get(name, self.criterions.get('*'))
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get('*'))
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        if criterion == 'l1_norm':
            scores = np.sum(np.abs(param), axis=reduce_dims)
        elif criterion == 'l2_norm':
            scores = np.sqrt(np.sum(param * param, axis=reduce_dims))
        else:
            raise ValueError(f"unsupported criterion {criterion!r}")
        return scores.argsort()[:prune_num]

    def prune_tensor(self, tensor, pruned_idx, pruned_axis, lazy=False):
        """lazy=True zeroes the pruned groups (shape-stable — the TPU
        training mode); lazy=False removes them (export mode)."""
        mask = np.zeros(tensor.shape[pruned_axis], dtype=bool)
        mask[np.asarray(pruned_idx, np.int64)] = True
        if lazy:
            keep = (~mask).astype(tensor.dtype)
            shape = [1] * tensor.ndim
            shape[pruned_axis] = -1
            return tensor * keep.reshape(shape)
        return np.take(tensor, np.flatnonzero(~mask), axis=pruned_axis)


class PruneStrategy(Strategy):
    """Base pruning strategy: applies masks to scope params at start_epoch
    and re-applies them after every batch so pruned groups stay zero through
    training (ref prune_strategy.py:PruneStrategy, masked formulation)."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 params=None, ratios=None):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or StructurePruner()
        self.params = params or []
        self.ratios = ratios or []
        self._masks = {}

    def _scope_get(self, context, name):
        return np.asarray(context.scope.find(name))

    def _scope_set(self, context, name, value):
        import jax.numpy as jnp
        context.scope.set(name, jnp.asarray(value))

    def _build_masks(self, context):
        self._masks = {}
        for name, ratio in zip(self.params, self.ratios):
            w = self._scope_get(context, name)
            idx = self.pruner.cal_pruned_idx(name, w, ratio)
            axis = self.pruner.pruning_axis.get(
                name, self.pruner.pruning_axis.get('*'))
            mask = np.ones(w.shape[axis], dtype=w.dtype)
            mask[idx] = 0
            shape = [1] * w.ndim
            shape[axis] = -1
            self._masks[name] = mask.reshape(shape)

    def _apply_masks(self, context):
        for name, mask in self._masks.items():
            self._scope_set(context, name,
                            self._scope_get(context, name) * mask)

    def sparsity(self, context, name):
        w = self._scope_get(context, name)
        return float((w == 0).mean())

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            self._build_masks(context)
            self._apply_masks(context)

    def on_batch_end(self, context):
        if self._masks and self.start_epoch <= context.epoch_id:
            self._apply_masks(context)

    def restore_from_checkpoint(self, context):
        """Strategy state (params/ratios/masks) rides the Compressor's
        pickled-strategies checkpoint; re-derive masks from the restored
        weights and re-apply so pruning survives the resume."""
        if context.epoch_id > self.start_epoch and self.params:
            if not self._masks:
                self._build_masks(context)
            self._apply_masks(context)


class UniformPruneStrategy(PruneStrategy):
    """ref prune_strategy.py:UniformPruneStrategy — one target ratio applied
    uniformly to every (or the named) conv filter params."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, params=None, pruning_axis=0,
                 criterion='l1_norm'):
        if pruner is None:  # YAML-config path: build from scalar kwargs
            pruner = StructurePruner({'*': pruning_axis}, {'*': criterion})
        super().__init__(pruner, start_epoch, end_epoch,
                         params=params or [], ratios=[])
        self.target_ratio = target_ratio

    def _ensure_params(self, context):
        if not self.params:
            # default: every conv-like (ndim==4) parameter
            self.params = [
                p.name for p in context.train_graph.all_parameters()
                if p._var.shape and len(p._var.shape) == 4]
        self.ratios = [self.target_ratio] * len(self.params)

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            self._ensure_params(context)
            self._build_masks(context)
            self._apply_masks(context)

    def restore_from_checkpoint(self, context):
        if context.epoch_id > self.start_epoch:
            self._ensure_params(context)
            if not self._masks:
                self._build_masks(context)
            self._apply_masks(context)


class SensitivePruneStrategy(PruneStrategy):
    """ref prune_strategy.py:SensitivePruneStrategy — per-param ratios from
    a sensitivity scan: each param is test-pruned at `delta_rate` steps and
    the eval-metric drop determines how much it tolerates within
    `sensitivities_tolerance`."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 delta_rate=0.2, target_ratio=0.5, metric_name=None,
                 sensitivities_tolerance=0.01, params=None):
        super().__init__(pruner, start_epoch, end_epoch,
                         params=params or [], ratios=[])
        self.delta_rate = delta_rate
        self.target_ratio = target_ratio
        self.metric_name = metric_name
        self.tolerance = sensitivities_tolerance

    def _sensitivity_scan(self, context):
        """For each param: the largest tested ratio whose eval drop stays
        within tolerance; baseline from the unpruned eval."""
        assert context.eval_graph is not None and \
            context.eval_reader is not None, \
            "SensitivePruneStrategy needs eval_graph + eval_reader"
        metric = self.metric_name or sorted(
            context.eval_graph.out_nodes)[0]
        base = context.run_eval_graph()[metric]
        chosen = []
        for name in self.params:
            orig = self._scope_get(context, name)
            best = 0.0
            ratio = self.delta_rate
            while ratio < min(1.0, self.target_ratio + 1e-9):
                idx = self.pruner.cal_pruned_idx(name, orig, ratio)
                axis = self.pruner.pruning_axis.get(
                    name, self.pruner.pruning_axis.get('*'))
                self._scope_set(context, name, self.pruner.prune_tensor(
                    orig, idx, axis, lazy=True))
                score = context.run_eval_graph()[metric]
                if abs(base - score) <= self.tolerance * (abs(base) + 1e-12):
                    best = ratio
                else:
                    break
                ratio += self.delta_rate
            self._scope_set(context, name, orig)
            chosen.append(best)
        return chosen

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            if not self.params:
                self.params = [
                    p.name for p in context.train_graph.all_parameters()
                    if p._var.shape and len(p._var.shape) == 4]
            self.ratios = self._sensitivity_scan(context)
            self._build_masks(context)
            self._apply_masks(context)
