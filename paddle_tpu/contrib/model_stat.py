"""Model PARAMs/FLOPs summary table (ref: python/paddle/fluid/contrib/
model_stat.py:summary). Covers the op families the reference counts
(conv2d, mul/fc, pool2d, norms, activations, elementwise) over the
op-list IR; prints and returns (rows, total_params, total_flops)."""
from collections import OrderedDict

import numpy as np

__all__ = ['summary']

_ACTS = {'relu', 'sigmoid', 'tanh', 'relu6', 'leaky_relu', 'prelu',
         'softmax', 'gelu', 'swish', 'hard_swish'}


def _var_shape(block, name):
    v = block.vars.get(name)
    return list(v.shape) if v is not None and v.shape else None


def _count(block, op):
    """(input_shape, out_shape, params, flops) or None to skip."""
    ins = [n for n in op.input_names()]
    outs = [n for n in op.output_names()]
    if not ins or not outs:
        return None
    out_shape = _var_shape(block, outs[0])
    if op.type == 'conv2d' or op.type == 'depthwise_conv2d':
        x = op.inputs.get('x', [None])[0]
        w = (op.inputs.get('weight') or op.inputs.get('w') or [None])[0]
        in_shape = _var_shape(block, x)
        w_shape = _var_shape(block, w)
        if not (in_shape and w_shape and out_shape):
            return None
        params = int(np.prod(w_shape))
        k_elems = int(np.prod(w_shape[1:]))
        flops = int(np.prod(out_shape[1:])) * k_elems * 2
        return in_shape, out_shape, params, flops
    if op.type in ('mul', 'matmul'):
        xs = _var_shape(block, ins[0])
        ys = _var_shape(block, ins[1]) if len(ins) > 1 else None
        if not (xs and ys and out_shape):
            return None
        params = int(np.prod(ys)) if len(ys) == 2 else 0
        flops = 2 * int(np.prod(out_shape[1:] or out_shape)) * ys[0]
        return xs, out_shape, params, flops
    if op.type in ('pool2d', 'pool3d'):
        in_shape = _var_shape(block, ins[0])
        if not (in_shape and out_shape):
            return None
        k = op.attrs.get('ksize', [2, 2])
        flops = int(np.prod(out_shape[1:])) * int(np.prod(k))
        return in_shape, out_shape, 0, flops
    if op.type in ('batch_norm', 'layer_norm', 'instance_norm',
                   'group_norm'):
        in_shape = _var_shape(block, ins[0])
        if not (in_shape and out_shape):
            return None
        ch = in_shape[1] if len(in_shape) > 1 else in_shape[0]
        return in_shape, out_shape, 2 * abs(ch), \
            int(np.prod(out_shape[1:] or out_shape))
    if op.type in _ACTS or op.type.startswith('elementwise_'):
        in_shape = _var_shape(block, ins[0])
        if not (in_shape and out_shape):
            return None
        return in_shape, out_shape, 0, \
            int(np.prod(out_shape[1:] or out_shape))
    return None


def summary(main_prog):
    """ref model_stat.py:summary — per-op table + totals (printed, and
    returned as (rows, total_params, total_flops))."""
    rows, total_params, total_flops = [], 0, 0
    for block in main_prog.blocks:
        for op in block.ops:
            res = _count(block, op)
            if res is None:
                continue
            in_shape, out_shape, params, flops = res
            info = OrderedDict(type=op.type, input_shape=in_shape[1:],
                               out_shape=out_shape[1:], PARAMs=params,
                               FLOPs=flops)
            rows.append(info)
            total_params += params
            total_flops += flops
    header = f"| {'No.':>4} | {'TYPE':>12} | {'INPUT':>18} | " \
             f"{'OUTPUT':>18} | {'PARAMs':>9} | {'FLOPs':>12} |"
    sep = '+' + '-' * (len(header) - 2) + '+'
    print(sep); print(header); print(sep)  # lint: allow-print (summary-table API)
    for i, r in enumerate(rows):
        print(f"| {i:>4} | {r['type']:>12} | {str(tuple(r['input_shape'])):>18} | "  # lint: allow-print
              f"{str(tuple(r['out_shape'])):>18} | {r['PARAMs']:>9} | "
              f"{r['FLOPs']:>12} |")
    print(sep)  # lint: allow-print (summary-table API)
    print(f'Total PARAMs: {total_params}({total_params / 1e9:.4f}G)')  # lint: allow-print (summary-table API)
    print(f'Total FLOPs: {total_flops}({total_flops / 1e9:.2f}G)')  # lint: allow-print (summary-table API)
    return rows, total_params, total_flops
