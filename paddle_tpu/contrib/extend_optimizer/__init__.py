"""contrib.extend_optimizer (ref: python/paddle/fluid/contrib/
extend_optimizer/) — decoupled weight decay lives in contrib.extra."""
from ..extra import extend_with_decoupled_weight_decay

__all__ = ['extend_with_decoupled_weight_decay']
