"""contrib namespace (ref: python/paddle/fluid/contrib/)."""
from . import mixed_precision
from . import memory_usage_calc
from .memory_usage_calc import (memory_usage, device_memory_stats,
                                print_memory_report)
from . import slim
from .slim import PostTrainingQuantization, WeightQuantization
from .mixed_precision import decorate, AutoMixedPrecisionLists
from . import extra
from .extra import (extend_with_decoupled_weight_decay, BasicLSTMUnit,
                    BasicGRUUnit, basic_lstm, basic_gru,
                    fused_elemwise_activation, partial_concat, partial_sum,
                    shuffle_batch, tree_conv, multiclass_nms2)
