"""contrib namespace (ref: python/paddle/fluid/contrib/)."""
from . import mixed_precision
from . import memory_usage_calc
from .memory_usage_calc import (memory_usage, device_memory_stats,
                                print_memory_report)
