"""contrib namespace (ref: python/paddle/fluid/contrib/)."""
from . import mixed_precision
from . import memory_usage_calc
from .memory_usage_calc import (memory_usage, device_memory_stats,
                                print_memory_report)
from . import slim
from .slim import PostTrainingQuantization, WeightQuantization, Compressor
from .mixed_precision import decorate, AutoMixedPrecisionLists
from . import extra
from .extra import (extend_with_decoupled_weight_decay, BasicLSTMUnit,
                    BasicGRUUnit, basic_lstm, basic_gru,
                    fused_elemwise_activation, partial_concat, partial_sum,
                    shuffle_batch, tree_conv, multiclass_nms2)
from . import decoder
from .decoder import (InitState, StateCell, TrainingDecoder,
                      BeamSearchDecoder)
from . import layers
from .layers import (sequence_topk_avg_pooling, var_conv_2d,
                     match_matrix_tensor, fused_embedding_seq_pool,
                     search_pyramid_hash, ctr_metric_bundle)
from . import extend_optimizer
from . import quantize
from .quantize import QuantizeTranspiler
from . import reader
from .reader import distributed_batch_reader
from . import utils
from .utils import (HDFSClient, multi_download, multi_upload,
                    convert_dist_to_sparse_program,
                    load_persistables_for_increment,
                    load_persistables_for_inference)
from . import model_stat
from .model_stat import summary
from . import op_frequence
from .op_frequence import op_freq_statistic
from . import trainer
from .trainer import (Trainer, CheckpointConfig, BeginEpochEvent,
                      EndEpochEvent, BeginStepEvent, EndStepEvent)
from . import inferencer
from .inferencer import Inferencer
