"""contrib namespace (ref: python/paddle/fluid/contrib/)."""
from . import mixed_precision
