"""contrib beam-search decoder API (ref: python/paddle/fluid/contrib/
decoder/beam_search_decoder.py).

The reference builds these on DynamicRNN + LoDTensorArrays with a dynamic
While loop. The TPU formulation keeps the same user API — InitState /
StateCell (with the `state_updater` decorator) / TrainingDecoder /
BeamSearchDecoder — but lowers to StaticRNN (lax.scan, fixed trip count):

- TrainingDecoder traces the user block once; states become scan carries.
  Step inputs are batch-major (B, T, ...) padded tensors (the repo-wide
  LoDTensor convention) and outputs come back batch-major.
- BeamSearchDecoder.decode() builds the reference's standard search loop
  (embed prev ids → state update → softmax fc → topk → beam step) in a
  dense (B*beam, ...) layout over `max_len` masked steps, reordering
  carried states by parent index each step, and `__call__` backtraces
  with gather_tree. Custom search bodies override decode() — same
  extension point the reference documents.
"""
import contextlib

from ...core import unique_name
from ...layer_helper import LayerHelper

__all__ = ['InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder']


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState:
    """ref beam_search_decoder.py:InitState — an initial decoder state,
    either a given Variable (`init`) or a fill shaped like a batch
    reference (`init_boot` + shape/value)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                'init_boot must be provided to infer the shape of InitState.')
        else:
            from ...layers.tensor import fill_constant_batch_size_like
            self._init = fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._dtype = dtype
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """ref beam_search_decoder.py:StateCell — named states + step inputs
    with a user-registered updater:

        state_cell = StateCell(inputs={'x': None}, states={'h': init_h},
                               out_state='h')

        @state_cell.state_updater
        def updater(cell):
            h = cell.get_state('h')
            x = cell.get_input('x')
            cell.set_state('h', some_layer(x, h))
    """

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper('state_cell', name=name)
        self._cur_states = {}
        self._init_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError('state must be an InitState object.')
            self._cur_states[state_name] = state
            self._init_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = dict(inputs)
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._state_updater = None
        self._out_state = out_state
        if out_state not in self._cur_states:
            raise ValueError('out_state must be one state in states')

    # -- decoder binding --
    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError('StateCell has already entered a decoder.')
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        # a fresh decoder starts from the declared InitStates (the ref's
        # per-decoder _states_holder reset)
        self._cur_states = dict(self._init_states)

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder or self._cur_decoder_obj is not decoder_obj:
            raise ValueError('Inconsistent decoder object in StateCell.')
        self._in_decoder = False
        self._cur_decoder_obj = None

    # -- user API --
    def state_updater(self, updater):
        """Decorator registering the per-step update function."""
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell is not self:
                raise ValueError('updater bound to another StateCell')
            updater(state_cell)
        return _decorator

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError(f'Unknown state {state_name}')
        v = self._cur_states[state_name]
        return v.value if isinstance(v, InitState) else v

    def get_input(self, input_name):
        if input_name not in self._inputs or self._inputs[input_name] is None:
            raise ValueError(f'Invalid input {input_name}.')
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        if state_name not in self._cur_states:
            raise ValueError(f'Unknown state {state_name}')
        self._cur_states[state_name] = state_value

    def compute_state(self, inputs):
        """Bind this step's inputs and run the updater."""
        if self._state_updater is None:
            raise ValueError('no state_updater registered')
        for name, value in inputs.items():
            if name not in self._inputs:
                raise ValueError(f'unknown input {name}')
            self._inputs[name] = value
        self._state_updater(self)

    def update_states(self):
        """Commit the current states to the enclosing decoder's carries."""
        if self._cur_decoder_obj is None:
            raise ValueError('StateCell must be inside a decoder block')
        self._cur_decoder_obj._commit_states(self)

    def out_state(self):
        return self.get_state(self._out_state)


class TrainingDecoder:
    """ref beam_search_decoder.py:TrainingDecoder — teacher-forced decoder
    over (B, T, ...) step inputs:

        decoder = TrainingDecoder(state_cell)
        with decoder.block():
            w = decoder.step_input(trg_embedding)   # (B, T, D) → (B, D)
            decoder.state_cell.compute_state(inputs={'x': w})
            decoder.state_cell.update_states()
            decoder.output(decoder.state_cell.get_state('h'))
        outputs = decoder()                          # (B, T, H)
    """
    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    type = _DecoderType.TRAINING

    def __init__(self, state_cell, name=None):
        from ...layers.control_flow import StaticRNN
        self._helper = LayerHelper('training_decoder', name=name)
        self._srnn = StaticRNN()
        self._status = TrainingDecoder.BEFORE_DECODER
        self.state_cell = state_cell
        self.state_cell._enter_decoder(self)
        self._pre = {}          # state name → memory pre-var

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError('decoder.block() can only be invoked once')
        self._status = TrainingDecoder.IN_DECODER
        with self._srnn.step():
            for name in self.state_cell._state_names:
                init = self.state_cell._cur_states[name]
                pre = self._srnn.memory(init=init.value)
                self._pre[name] = pre
                self.state_cell.set_state(name, pre)
            yield self
        self._status = TrainingDecoder.AFTER_DECODER
        self.state_cell._leave_decoder(self)

    def _in_parent_block(self):
        """Build ops in the block surrounding the StaticRNN step block."""
        from ...framework import default_main_program

        @contextlib.contextmanager
        def guard():
            program = default_main_program()
            cur = program.current_block_idx
            program.current_block_idx = self._srnn._block.parent_idx
            try:
                yield
            finally:
                program.current_block_idx = cur
        return guard()

    def step_input(self, x):
        """(B, T, ...) batch-major sequence → this step's (B, ...) slice."""
        from ...layers.common import apply_op_layer
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('step_input must be invoked inside block()')
        if x.shape is None:
            raise ValueError('step_input needs a statically-shaped input')
        with self._in_parent_block():
            xt = apply_op_layer('transpose_batch_time', {'x': x})
            xt.shape = (x.shape[1], x.shape[0]) + tuple(x.shape[2:])
        return self._srnn.step_input(xt)

    def static_input(self, x):
        """A per-batch input visible unchanged at every step (sub-blocks
        read enclosing-block vars directly in the scan lowering)."""
        return x

    def _commit_states(self, state_cell):
        for name, pre in self._pre.items():
            new = state_cell._cur_states[name]
            if new is not pre:
                self._srnn.update_memory(pre, new)

    def output(self, *outputs):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('output must be invoked inside block()')
        for o in outputs:
            self._srnn.step_output(o)

    def __call__(self, *args, **kwargs):
        from ...layers.common import apply_op_layer
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError('call TrainingDecoder after its block finishes')
        outs = self._srnn()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        res = []
        for o in outs:   # (T, B, ...) → (B, T, ...)
            ot = apply_op_layer('transpose_batch_time', {'x': o})
            if o.shape is not None:
                ot.shape = (o.shape[1], o.shape[0]) + tuple(o.shape[2:])
            res.append(ot)
        return res[0] if len(res) == 1 else res


class BeamSearchDecoder:
    """ref beam_search_decoder.py:BeamSearchDecoder — inference-time beam
    search driven by the same StateCell:

        decoder = BeamSearchDecoder(state_cell, init_ids, init_scores,
                                    target_dict_dim, word_dim,
                                    topk_size=50, max_len=T, beam_size=W,
                                    end_id=1)
        decoder.decode()
        translation_ids, translation_scores = decoder()

    Dense layout: every tensor carries (B*beam) rows; states are expanded
    to the beam on entry and reordered by parent index after each
    selection (the reference's sequence_expand-by-score-LoD reordering).
    Returns ids (B, beam, max_len) int64 and final scores (B, beam).
    Custom loops: override decode() (the reference's extension point).
    """
    type = _DecoderType.BEAM_SEARCH

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        self._helper = LayerHelper('beam_search_decoder', name=name)
        self.state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = min(int(topk_size), int(target_dict_dim))
        self._sparse_emb = sparse_emb
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._outputs = None

    def _expand_to_beam(self, x):
        """(B, ...) → (B*W, ...) row-tiling (shared helper)."""
        from ...layers.rnn import expand_to_beam
        return expand_to_beam(x, self._beam_size)

    def decode(self):
        """Build the standard search loop (ref decode(), :653)."""
        from ...layers import nn as L
        from ...layers import tensor as T
        from ...layers.rnn import beam_search
        from ...layers.control_flow import StaticRNN
        import numpy as np

        cell = self.state_cell
        cell._enter_decoder(self)
        W = self._beam_size
        b0 = (self._init_ids.shape or [-1])[0]
        if b0 is None or int(b0) <= 0:
            raise ValueError(
                'BeamSearchDecoder needs a static batch size: declare '
                'init_ids with a concrete leading dim (got '
                f'{self._init_ids.shape})')

        # beam-expand the search state in the enclosing block
        ids0 = self._expand_to_beam(T.cast(self._init_ids, 'int64'))
        ids0 = L.reshape(ids0, shape=[-1, 1])
        scores0 = self._expand_to_beam(T.cast(self._init_scores, 'float32'))
        scores0 = L.reshape(scores0, shape=[-1, 1])
        # keep only beam 0 live initially so identical beams don't flood
        # the top-k (the reference gets this from the init LoD structure)
        beam_penalty = T.fill_constant_array(
            np.where(np.tile(np.arange(W), ids0.shape[0] // W) > 0,
                     -1e9, 0.0).reshape(-1, 1).astype('float32'))
        scores0 = L.elementwise_add(scores0, beam_penalty)

        state_inits = {}
        for name in cell._state_names:
            init = cell._cur_states[name]
            state_inits[name] = self._expand_to_beam(init.value)
        static_feeds = {k: self._expand_to_beam(v)
                        for k, v in self._input_var_dict.items()}

        times = T.fill_constant_array(
            np.arange(self._max_len, dtype=np.int64))
        srnn = StaticRNN()
        self._srnn = srnn
        with srnn.step():
            _ = srnn.step_input(times)
            pre_ids = srnn.memory(init=ids0)
            pre_scores = srnn.memory(init=scores0)
            self._pre = {}
            for name in cell._state_names:
                pre = srnn.memory(init=state_inits[name])
                self._pre[name] = pre
                cell.set_state(name, pre)

            flat_ids = L.reshape(pre_ids, shape=[-1])
            emb = L.embedding(flat_ids,
                              size=[self._target_dict_dim, self._word_dim],
                              is_sparse=self._sparse_emb)
            feed_dict = dict(static_feeds)
            for input_name in cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = emb
            cell.compute_state(inputs=feed_dict)
            current_state = cell.out_state()
            scores = L.fc(current_state, size=self._target_dict_dim,
                          act='softmax')
            topk_scores, topk_indices = L.topk(scores, k=self._topk_size)
            accu_scores = L.elementwise_add(
                L.log(L.scale(topk_scores, scale=1.0, bias=1e-20)),
                pre_scores, axis=0)
            sel_ids, sel_scores, parent = beam_search(
                pre_ids, pre_scores, topk_indices, accu_scores, W,
                end_id=self._end_id, return_parent_idx=True)
            # static shapes for the scan-stacked outputs (B known from
            # init_ids; shape inference is lazy elsewhere)
            BW = int(self._init_ids.shape[0]) * W
            sel_ids.shape = (BW, 1)
            sel_scores.shape = (BW, 1)
            parent.shape = (BW,)
            srnn.update_memory(pre_ids, sel_ids)
            srnn.update_memory(pre_scores, sel_scores)
            for name, pre in self._pre.items():
                new = cell._cur_states[name]
                reordered = L.gather(new, parent)
                srnn.update_memory(pre, reordered)
            srnn.step_output(sel_ids)
            srnn.step_output(parent)
            srnn.step_output(sel_scores)
        cell._leave_decoder(self)
        self._outputs = srnn()

    def _commit_states(self, state_cell):
        # states are committed (with parent reordering) inside decode()
        pass

    def early_stop(self):
        """The fixed-trip-count scan already masks finished beams inside
        beam_search (finished rows only extend with end_id), which is the
        TPU replacement for dynamically stopping the While loop."""

    def __call__(self):
        """(translation_ids (B, W, max_len), translation_scores (B, W))."""
        from ...layers import nn as L
        from ...layers.rnn import gather_tree
        if self._outputs is None:
            raise ValueError('call decode() before reading the results')
        from ...layers import tensor as T
        step_ids, step_parents, step_scores = self._outputs
        T_, BW = step_ids.shape[0], step_ids.shape[1]
        B = BW // self._beam_size
        ids_tbw = L.reshape(step_ids, shape=[T_, B, self._beam_size])
        par_tbw = L.reshape(step_parents, shape=[T_, B, self._beam_size])
        # parent indices are flat (B*W); make them beam-local for the tree
        par_local = L.elementwise_mod(
            par_tbw, T.fill_constant([1], 'int64', self._beam_size))
        full = gather_tree(ids_tbw, par_local)       # (T, B, W)
        trans_ids = L.transpose(full, perm=[1, 2, 0])  # (B, W, T)
        last = L.slice(step_scores, axes=[0], starts=[T_ - 1], ends=[T_])
        last_scores = L.reshape(last, shape=[B, self._beam_size])
        return trans_ids, last_scores
