"""contrib.decoder (ref: python/paddle/fluid/contrib/decoder/)."""
from .beam_search_decoder import (InitState, StateCell, TrainingDecoder,
                                  BeamSearchDecoder)

__all__ = ['InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder']
