"""Memory introspection (SURVEY §2.11).

- memory_usage(program, batch_size): static estimate from the program's var
  shapes — parity with ref python/paddle/fluid/contrib/memory_usage_calc.py:46
  (same (lower, upper, unit) contract).
- device_memory_stats(): LIVE HBM arena report from jax.Device.memory_stats()
  — the TPU replacement for the reference's allocator counters
  (paddle/fluid/memory/allocation/*).
"""
from __future__ import annotations

from ..framework import Program

_DTYPE_BYTES = {
    'float16': 2, 'bfloat16': 2, 'float32': 4, 'float64': 8,
    'int8': 1, 'uint8': 1, 'int16': 2, 'int32': 4, 'int64': 8, 'bool': 1,
}

# upper bound factor for activation workspace / fragmentation — mirrors the
# reference's two-sided estimate rather than claiming exactness
_UPPER_FACTOR = 1.7


def memory_usage(program, batch_size):
    """Estimate (lower, upper, unit) memory usage of `program` at
    `batch_size` (ref memory_usage_calc.py:46). -1/None dims are read as the
    batch dim and replaced by batch_size."""
    if not isinstance(program, Program):
        raise TypeError(
            "Calculating Memory Usage requires Program as its Parameter. "
            "But you passed in %s" % type(program))
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    total = 0.0
    for var in program.list_vars():
        shape = var.shape
        if shape is None:
            continue
        numel = 1
        for s in shape:
            numel *= batch_size if s in (-1, None) else int(s)
        total += numel * _DTYPE_BYTES.get(str(var.dtype), 4)

    lower, upper = total, total * _UPPER_FACTOR
    for unit in ('B', 'KB', 'MB', 'GB'):
        if upper < 1024 or unit == 'GB':
            return lower, upper, unit
        lower /= 1024.0
        upper /= 1024.0


def device_memory_stats(device=None):
    """Live HBM stats per device: {device: {bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...}}. Returns {} for backends without allocator stats
    (e.g. the CPU test mesh)."""
    import jax
    devices = [device] if device is not None else jax.devices()
    report = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            report[str(d)] = dict(stats)
    return report


def print_memory_report():
    """Human-readable HBM live-arena report (one line per device)."""
    report = device_memory_stats()
    if not report:
        print("[paddle_tpu.memory] no allocator stats on this backend")  # lint: allow-print (console report API)
        return report
    for dev, st in report.items():
        in_use = st.get('bytes_in_use', 0) / 2**20
        peak = st.get('peak_bytes_in_use', 0) / 2**20
        limit = st.get('bytes_limit', 0) / 2**20
        print(f"[paddle_tpu.memory] {dev}: in_use={in_use:.1f}MB "  # lint: allow-print (console report API)
              f"peak={peak:.1f}MB limit={limit:.1f}MB")
    return report
