"""Legacy high-level Inferencer (ref: python/paddle/fluid/contrib/
inferencer.py) — infer_func rebuilds the inference graph; params load
from param_path; infer() runs the jitted program."""
from .. import io as fluid_io
from ..core.scope import Scope, scope_guard
from ..executor import Executor
from ..framework import Program, program_guard

__all__ = ['Inferencer']


class Inferencer:
    """ref inferencer.py:Inferencer(infer_func, param_path, place)."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.param_path = param_path
        self.scope = Scope()
        self.parallel = parallel
        self.place = place
        self.exe = Executor(place)
        self.inference_program = Program()
        startup = Program()
        with program_guard(self.inference_program, startup):
            out = infer_func()
            self.predict_var = out[0] if isinstance(out, (list, tuple)) \
                else out
        self.inference_program = self.inference_program.clone(for_test=True)
        with scope_guard(self.scope):
            self.exe.run(startup)
            fluid_io.load_persistables(self.exe, param_path,
                                       self.inference_program)

    def infer(self, inputs, return_numpy=True):
        """ref inferencer.py:infer — inputs: {var_name: ndarray}."""
        if not isinstance(inputs, dict):
            raise ValueError(
                'inputs should be a map of {"input_name": input_var}')
        with scope_guard(self.scope):
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var],
                                return_numpy=return_numpy)
