"""Static-graph quantization transpiler (ref: python/paddle/fluid/contrib/
quantize/quantize_transpiler.py:80).

The reference rewrites the Program: fake-quant/dequant op pairs around
every quantizable op's inputs for QAT, then freezes scales for int8
deploy. Here the rewrite inserts the registered STE fake-quant ops
(ops/quant_ops.py) in front of quantizable compute ops, which XLA then
fuses into the step — training proceeds with quantization noise exactly
like the reference's QAT. Freezing (inference int8) is served by
inference.Config.enable_int8 / slim's PTQ path.
"""
import numpy as np

from ...framework import Operator

__all__ = ['QuantizeTranspiler']

_QUANTIZABLE_OP_TYPES = ('conv2d', 'depthwise_conv2d', 'mul', 'matmul')
# input slots holding (activation, weight) per quantizable type — conv ops
# name the weight slot 'weight', matmul-family ops 'y'
_SLOTS = {'conv2d': ('x', 'weight'), 'depthwise_conv2d': ('x', 'weight'),
          'mul': ('x', 'y'), 'matmul': ('x', 'y')}


def _quantized_var_name(var_name):
    return f'{var_name}.quantized'


def _dequantized_var_name(var_name):
    return f'{var_name}.dequantized'


class QuantizeTranspiler:
    """ref quantize_transpiler.py:80."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type='abs_max',
                 weight_quantize_type='abs_max', window_size=10000,
                 moving_rate=0.9):
        quant_types = ('abs_max', 'range_abs_max',
                       'moving_average_abs_max')
        if activation_quantize_type not in quant_types:
            raise ValueError(
                f'Unknown activation_quantize_type: '
                f'{activation_quantize_type}')
        if weight_quantize_type != 'abs_max':
            raise ValueError(
                f'Unknown weight_quantize_type: {weight_quantize_type}')
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.window_size = window_size
        self.moving_rate = moving_rate

    def _insert_fake_quant(self, block, idx, var_name, bits):
        """Insert fake_quantize_dequantize before op idx; returns the
        dequantized var name."""
        src = block.var(var_name)
        out_name = _dequantized_var_name(var_name)
        if not block.has_var(out_name):
            block.create_var(name=out_name, shape=src.shape,
                             dtype=src.dtype)
            block.create_var(name=out_name + '@SCALE', shape=[1],
                             dtype='float32')
        op = Operator(block, 'fake_quantize_dequantize_abs_max',
                      {'x': var_name},
                      {'Out': out_name, 'OutScale': out_name + '@SCALE'},
                      {'bit_length': bits})
        block.ops.insert(idx, op)
        return out_name

    def training_transpile(self, program=None, startup_program=None):
        """ref quantize_transpiler.py:training_transpile — rewrite the
        program in place for quantization-aware training."""
        from ...framework import default_main_program
        program = program or default_main_program()
        n_rewritten = 0
        for block in program.blocks:
            i = 0
            while i < len(block.ops):
                op = block.ops[i]
                already = any(
                    n.endswith('.dequantized')
                    for ns in op.inputs.values() for n in ns)
                if op.type in _QUANTIZABLE_OP_TYPES and not already:
                    act_slot, w_slot = _SLOTS[op.type]
                    inserted = 0
                    for slot, bits in ((act_slot, self.activation_bits),
                                       (w_slot, self.weight_bits)):
                        names = op.inputs.get(slot)
                        if not names:
                            continue
                        deq = self._insert_fake_quant(
                            block, i, names[0], bits)
                        op.inputs[slot] = [deq]
                        inserted += 1
                    n_rewritten += 1
                    i += inserted
                i += 1
        program._bump_version()
        return n_rewritten

    def freeze_program(self, program, place=None, fuse_bn=False):
        """ref quantize_transpiler.py:freeze_program — for inference the
        fake-quant pairs stay in-graph (XLA folds them); scale freezing
        for true int8 weights is the slim PTQ / inference int8 path."""
        return program

    def convert_to_int8(self, program, place=None, scope=None):
        """ref quantize_transpiler.py:convert_to_int8 — quantize every
        Parameter feeding a quantizable op to int8.

        The int8 tensor + scale land in the scope as `<name>@INT8` /
        `<name>@SCALE` (the deploy artifacts the int8 Predictor consumes),
        and the dense fp32 parameter is REPLACED by its int8→fp32
        reconstruction so the program's numerics genuinely reflect int8
        weights from this point on."""
        from ...core.scope import global_scope
        from ...framework import Parameter
        scope = scope or global_scope()
        n = 0
        for block in program.blocks:
            for op in block.ops:
                if op.type not in _QUANTIZABLE_OP_TYPES:
                    continue
                _, w_slot = _SLOTS[op.type]
                for name in op.inputs.get(w_slot, []):
                    base = name.split('.dequantized')[0]
                    v = block.vars.get(base)
                    if not isinstance(v, Parameter):
                        continue
                    w = scope.find(base)
                    if w is None:
                        continue
                    w = np.asarray(w)
                    scale = np.abs(w).max() or 1.0
                    q = np.clip(np.round(w / scale * 127), -127,
                                127).astype(np.int8)
                    scope.set(base + '@INT8', q)
                    scope.set(base + '@SCALE', np.float32(scale))
                    scope.set(base, (q.astype(np.float32) * scale
                                     / 127.0).astype(w.dtype))
                    n += 1
        return n
