"""contrib.quantize (ref: python/paddle/fluid/contrib/quantize/)."""
from .quantize_transpiler import QuantizeTranspiler

__all__ = ['QuantizeTranspiler']
