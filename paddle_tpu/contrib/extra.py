"""High-value contrib surface (ref: python/paddle/fluid/contrib/):
decoupled weight decay (AdamW), basic_lstm/basic_gru helpers, and the
contrib layer functions that map onto existing TPU ops. The legacy
NAS/pruning/distillation Compressor framework, MKLDNN passes, and
HDFSClient are out of scope for the TPU build (see docs/MIGRATION.md).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..layers.common import apply_op_layer

__all__ = [
    'extend_with_decoupled_weight_decay',
    'BasicLSTMUnit', 'BasicGRUUnit', 'basic_lstm', 'basic_gru',
    'fused_elemwise_activation', 'partial_concat', 'partial_sum',
    'shuffle_batch', 'tree_conv', 'multiclass_nms2',
]


def extend_with_decoupled_weight_decay(base_optimizer_cls):
    """ref: contrib/extend_optimizer/extend_optimizer_with_weight_decay.py.
    Returns a subclass applying DECOUPLED weight decay (AdamW-style:
    p *= 1 - lr*coeff before the inner rule, not folded into the
    gradient)."""

    class DecoupledWeightDecay(base_optimizer_cls):
        def __init__(self, weight_decay=0.01, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._decoupled_wd = float(weight_decay)

        # -- dygraph: decay the PRE-update weights (the torch-AdamW form;
        # the inner step's donated buffers make a post-hoc subtraction of
        # the old weights unsafe) --
        def _dygraph_minimize(self, loss, parameter_list=None):
            params = parameter_list or self._parameter_list
            coeff = self._decoupled_wd * self._current_lr()
            for p in (params or []):
                if getattr(p, 'trainable', True) and p.grad is not None:
                    p.value = p.value * (1.0 - coeff)
            return super()._dygraph_minimize(loss, parameter_list)

        # -- static: p -= wd * lr * p before the inner update op, with the
        # LIVE lr var so scheduled learning rates scale the decay too --
        def _append_optimize_op(self, param, grad, lr):
            decay = apply_op_layer(
                'scale', {'x': lr}, {'scale': self._decoupled_wd})
            shrink = apply_op_layer('elementwise_mul',
                                    {'x': param, 'y': decay})
            from ..layer_helper import LayerHelper
            helper = LayerHelper('decoupled_wd')
            helper.main_program.current_block().append_op(
                type='elementwise_sub',
                inputs={'x': param.name, 'y': shrink.name},
                outputs={'Out': param.name}, attrs={})
            super()._append_optimize_op(param, grad, lr)

    DecoupledWeightDecay.__name__ = \
        base_optimizer_cls.__name__ + 'WithDecoupledWeightDecay'
    return DecoupledWeightDecay


from ..dygraph.layers import Layer as _Layer
from ..dygraph.tape import dispatch_op as _dispatch


class BasicLSTMUnit(_Layer):
    """ref: contrib/layers/rnn_impl.py:BasicLSTMUnit — one LSTM step. A
    dygraph Layer (weights are real trainable parameters on the tape)."""

    def __init__(self, name_scope=None, hidden_size=None, forget_bias=1.0,
                 dtype='float32', **kw):
        super().__init__()
        self._hidden = hidden_size
        self._forget_bias = float(forget_bias)
        self._dtype = dtype
        self._built = False

    def _ensure(self, in_dim):
        if not self._built:
            self.weight = self.create_parameter(
                [in_dim + self._hidden, 4 * self._hidden], None, self._dtype)
            self.bias = self.create_parameter(
                [4 * self._hidden], None, self._dtype, is_bias=True)
            self._built = True

    def forward(self, x, pre_hidden, pre_cell):
        self._ensure(x.shape[-1])
        xh = _dispatch('concat', {'xs': [x, pre_hidden]}, {'axis': -1})
        gates = _dispatch('matmul', {'x': xh, 'y': self.weight}, {})
        gates = _dispatch('elementwise_add', {'x': gates, 'y': self.bias},
                          {'axis': -1})
        # NOTE: ref BasicLSTMUnit's gate layout is i, j(candidate), f, o —
        # different from the lstm_unit OP's i, f, o, g — so the split is
        # done here, not via the op, to keep exchanged weights compatible
        # (ref: contrib/layers/rnn_impl.py:816 `i, j, f, o = split(...)`)
        i, j, f, o = (_dispatch('split', {'x': gates},
                                {'num_or_sections': 4, 'dim': -1}))
        sig = lambda t: _dispatch('sigmoid', {'x': t}, {})
        tanh = lambda t: _dispatch('tanh', {'x': t}, {})
        fb = sig(_dispatch('scale', {'x': f},
                           {'bias': self._forget_bias}))
        c = _dispatch('elementwise_add',
                      {'x': _dispatch('elementwise_mul',
                                      {'x': pre_cell, 'y': fb}, {}),
                       'y': _dispatch('elementwise_mul',
                                      {'x': sig(i), 'y': tanh(j)}, {})}, {})
        h = _dispatch('elementwise_mul', {'x': tanh(c), 'y': sig(o)}, {})
        return h, c


class BasicGRUUnit(_Layer):
    """ref: contrib/layers/rnn_impl.py:BasicGRUUnit (dygraph Layer)."""

    def __init__(self, name_scope=None, hidden_size=None, dtype='float32',
                 **kw):
        super().__init__()
        self._hidden = hidden_size
        self._dtype = dtype
        self._built = False

    def _ensure(self, in_dim):
        if not self._built:
            self.wx = self.create_parameter(
                [in_dim, 3 * self._hidden], None, self._dtype)
            self.wh = self.create_parameter(
                [self._hidden, 3 * self._hidden], None, self._dtype)
            self._built = True

    def forward(self, x, pre_hidden):
        self._ensure(x.shape[-1])
        proj = _dispatch('matmul', {'x': x, 'y': self.wx}, {})
        h, _, _ = _dispatch(
            'gru_unit', {'x': proj, 'hidden': pre_hidden, 'weight': self.wh},
            {})
        return h


def _flat_state(t, hidden_size):
    """Accept both (B, H) and the returned (1, B, H) stateful form."""
    if t is not None and t.shape is not None and len(t.shape) == 3:
        t = apply_op_layer('reshape', {'x': t}, {'shape': [-1, hidden_size]})
    return t


def _last_state(t):
    """(B, T, H) → (num_layers=1, B, H): the reference's stateful-RNN
    shape, so last states feed back as the next init states."""
    s = apply_op_layer('slice', {'x': t},
                       {'axes': [1], 'starts': [-1], 'ends': [2 ** 30]})
    return apply_op_layer('transpose', {'x': s}, {'perm': [1, 0, 2]})


def _check_rnn_config(num_layers, bidirectional, dropout_prob):
    if num_layers != 1 or bidirectional or dropout_prob:
        raise NotImplementedError(
            "basic_lstm/basic_gru support single-layer unidirectional "
            "without dropout (the ref model configs); got "
            f"num_layers={num_layers}, bidirectional={bidirectional}, "
            f"dropout_prob={dropout_prob}")


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, forget_bias=1.0, dtype='float32',
               name=None):
    """ref: contrib/layers/rnn_impl.py:basic_lstm — static-graph layer over
    the scan-based `lstm` op; weights are trainable parameters. Returns
    (hidden, last_hidden (1, B, H), last_cell (1, B, H)); last states can
    feed back as init_hidden/init_cell."""
    _check_rnn_config(num_layers, bidirectional, dropout_prob)
    from ..layer_helper import LayerHelper
    helper = LayerHelper('basic_lstm', name=name)
    x = input
    if not batch_first:
        x = apply_op_layer('transpose_batch_time', {'x': x}, {})
    D = x.shape[-1]
    from ..initializer import NumpyArrayInitializer
    wx = helper.create_parameter(None, [D, 4 * hidden_size], dtype)
    wh = helper.create_parameter(None, [hidden_size, 4 * hidden_size], dtype)
    # gate order i,f,c,o (ops/rnn_ops.py): the forget slice starts at the
    # standard forget_bias so gates open (~sigmoid(1)) at init
    b_init = np.zeros((4 * hidden_size,), np.float32)
    b_init[hidden_size:2 * hidden_size] = float(forget_bias)
    b = helper.create_parameter(None, [4 * hidden_size], dtype, is_bias=True,
                                default_initializer=NumpyArrayInitializer(
                                    b_init))
    proj = apply_op_layer('matmul', {'x': x, 'y': wx}, {})
    hidden, cell = apply_op_layer(
        'lstm', {'x': proj, 'h0': _flat_state(init_hidden, hidden_size),
                 'c0': _flat_state(init_cell, hidden_size), 'w_h': wh,
                 'bias': b, 'seq_len': sequence_length}, {})
    last_h, last_c = _last_state(hidden), _last_state(cell)
    if not batch_first:
        hidden = apply_op_layer('transpose_batch_time', {'x': hidden}, {})
    return hidden, last_h, last_c


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, dtype='float32', name=None):
    """ref: contrib/layers/rnn_impl.py:basic_gru (same contract notes as
    basic_lstm)."""
    _check_rnn_config(num_layers, bidirectional, dropout_prob)
    from ..layer_helper import LayerHelper
    helper = LayerHelper('basic_gru', name=name)
    x = input
    if not batch_first:
        x = apply_op_layer('transpose_batch_time', {'x': x}, {})
    D = x.shape[-1]
    wx = helper.create_parameter(None, [D, 3 * hidden_size], dtype)
    gate_w = helper.create_parameter(None, [hidden_size, 2 * hidden_size],
                                     dtype)
    cand_w = helper.create_parameter(None, [hidden_size, hidden_size], dtype)
    proj = apply_op_layer('matmul', {'x': x, 'y': wx}, {})
    out = apply_op_layer(
        'gru', {'x': proj, 'h0': _flat_state(init_hidden, hidden_size),
                'gate_w': gate_w, 'cand_w': cand_w,
                'seq_len': sequence_length}, {})
    last = _last_state(out)
    if not batch_first:
        out = apply_op_layer('transpose_batch_time', {'x': out}, {})
    return out, last


# ---- contrib layer functions over existing ops (apply_op_layer already
# dispatches eagerly in dygraph mode) ----

def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """ref: contrib/layers/nn.py:fused_elemwise_activation over
    operators/fused/fused_elemwise_activation_op.cc. The reference
    contract for [binary, unary] is Binary(X, Unary(Y)); for
    [unary, binary] it is Unary(Binary(X, Y)). On TPU the fusion itself
    is XLA's job — only the composition order matters here."""
    if len(functor_list) != 2 or sum(
            f.strip().startswith('elementwise_') for f in functor_list) != 1:
        raise ValueError(
            f"functor_list must hold exactly one binary (elementwise_*) and "
            f"one unary functor, got {functor_list}")
    f0, f1 = (f.strip() for f in functor_list)

    def unary(f, t):
        if f == 'scale':
            return apply_op_layer('scale', {'x': t}, {'scale': scale})
        return apply_op_layer(f, {'x': t}, {})

    if f0.startswith('elementwise_'):     # Binary(X, Unary(Y))
        return apply_op_layer(f0, {'x': x, 'y': unary(f1, y)},
                              {'axis': axis})
    # Unary(Binary(X, Y))
    return unary(f0, apply_op_layer(f1, {'x': x, 'y': y}, {'axis': axis}))


def _col_slice(x, start_index, length):
    dim = int(x.shape[-1])
    start = start_index + dim if start_index < 0 else start_index
    end = dim if length == -1 else start + length
    return apply_op_layer('slice', {'x': x},
                          {'axes': [1], 'starts': [start], 'ends': [end]})


def partial_concat(input, start_index=0, length=-1):
    """ref: contrib partial_concat_op: concat column slices of each input."""
    parts = [_col_slice(x, start_index, length) for x in input]
    return apply_op_layer('concat', {'xs': parts}, {'axis': 1})


def partial_sum(input, start_index=0, length=-1):
    """ref: contrib partial_sum_op: sum the column slices of the inputs."""
    parts = [_col_slice(x, start_index, length) for x in input]
    return apply_op_layer('sum', {'xs': parts}, {})


def shuffle_batch(x, seed=None):
    """ref: contrib shuffle_batch_op (uses the registered rng op)."""
    return apply_op_layer('shuffle_batch', {'x': x},
                          {'seed': int(seed or 0)})


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act='tanh', param_attr=None, bias_attr=None,
              name=None):
    """ref: contrib/layers/nn.py:tree_conv over the registered op."""
    from ..layer_helper import LayerHelper
    from ..initializer import XavierInitializer
    helper = LayerHelper('tree_conv', param_attr=param_attr, name=name)
    feat = nodes_vector.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                [feat, 3, output_size, num_filters],
                                'float32',
                                default_initializer=XavierInitializer())
    out = apply_op_layer('tree_conv',
                         {'nodes': nodes_vector, 'edges': edge_set,
                          'weight': w}, {'max_depth': max_depth})
    if act:
        out = apply_op_layer(act, {'x': out}, {})
    return out


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """ref: contrib multiclass_nms2 — NMS that can also return indices."""
    out, idx, _ = apply_op_layer(
        'multiclass_nms', {'bboxes': bboxes, 'scores': scores},
        {'background_label': background_label,
         'score_threshold': score_threshold, 'nms_top_k': nms_top_k,
         'nms_threshold': nms_threshold, 'nms_eta': nms_eta,
         'keep_top_k': keep_top_k, 'normalized': normalized}, name=name)
    if return_index:
        return out, idx
    return out
