"""Op-frequency statistics (ref: python/paddle/fluid/contrib/
op_frequence.py:23)."""
from collections import OrderedDict

from ..framework import Program

__all__ = ['op_freq_statistic']


def op_freq_statistic(program):
    """Count single-op and adjacent-op-pair frequencies over the program.
    Returns (uni_op_freq, adj_2_op_freq) OrderedDicts sorted by count."""
    if not isinstance(program, Program):
        raise ValueError(f'{program} is not a Program instance')
    uni, adj = {}, {}
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = prev + '->' + op.type
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
    uni_sorted = OrderedDict(
        sorted(uni.items(), key=lambda x: x[1], reverse=True))
    adj_sorted = OrderedDict(
        sorted(adj.items(), key=lambda x: x[1], reverse=True))
    return uni_sorted, adj_sorted
