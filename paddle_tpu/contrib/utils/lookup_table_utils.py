"""Distributed lookup-table loading helpers (ref: python/paddle/fluid/
contrib/utils/lookup_table_utils.py). The reference rewrites PS-era
programs whose embedding table was sharded across pservers; here the
table is one dense persistable, so conversion = clearing the
`is_distributed` mark, and the loaders restore the non-table persistables
and the table separately."""
import os

import numpy as np

from ...distribute_lookup_table import LOOKUP_TABLE_TYPE
from ...core.scope import global_scope

__all__ = ['convert_dist_to_sparse_program',
           'load_persistables_for_increment',
           'load_persistables_for_inference']


def convert_dist_to_sparse_program(program):
    """ref lookup_table_utils.py:convert_dist_to_sparse_program — clone the
    program with distributed lookup_tables downgraded to local sparse
    ones."""
    out = program.clone()
    for block in out.blocks:
        for op in block.ops:
            if op.type == LOOKUP_TABLE_TYPE and \
                    op.attrs.get('is_distributed'):
                op.attrs['is_distributed'] = False
                op.attrs['is_sparse'] = True
    return out


def _load_table(lookup_table_var_name, path):
    scope = global_scope()
    if os.path.isdir(path):
        # pserver shard layout: one file per shard, rows concatenated
        shards = []
        for f in sorted(os.listdir(path)):
            shards.append(np.load(os.path.join(path, f),
                                  allow_pickle=False))
        table = np.concatenate(shards, axis=0)
    else:
        with np.load(path) as data:
            table = data[lookup_table_var_name] \
                if lookup_table_var_name in data.files else data[data.files[0]]
    scope.set(lookup_table_var_name, table)


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var, lookup_table_var_path):
    """ref lookup_table_utils.py:load_persistables_for_increment — restore
    all persistables except the big table from `dirname`, then the table
    itself from its own path."""
    from ... import io as fluid_io
    name = getattr(lookup_table_var, 'name', lookup_table_var)
    fluid_io.load_vars(
        executor, dirname, program,
        predicate=lambda v: fluid_io.is_persistable(v) and v.name != name)
    _load_table(name, lookup_table_var_path)


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name):
    """ref lookup_table_utils.py:load_persistables_for_inference — the
    table loads from its own shard file/dir when present (PS layout),
    otherwise from the bundled persistables archive this repo's
    save_persistables writes."""
    from ... import io as fluid_io
    table_path = os.path.join(dirname, lookup_table_var_name)
    if os.path.exists(table_path):
        fluid_io.load_vars(
            executor, dirname, program,
            predicate=lambda v: fluid_io.is_persistable(v)
            and v.name != lookup_table_var_name)
        _load_table(lookup_table_var_name, table_path)
    else:
        # bundled layout: the table is a normal persistable in params.npz
        fluid_io.load_vars(executor, dirname, program,
                           predicate=fluid_io.is_persistable)
        if global_scope().find(lookup_table_var_name) is None:
            raise IOError(
                f'lookup table {lookup_table_var_name!r} found neither as '
                f'a shard file under {dirname} nor in the bundled '
                f'persistables')
