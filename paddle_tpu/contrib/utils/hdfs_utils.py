"""HDFS helpers (ref: python/paddle/fluid/contrib/utils/hdfs_utils.py).

The reference shells out to `hadoop fs`. TPU pods read from mounted / GCS
paths instead, so this client maps HDFS-style calls onto the local
filesystem rooted at the configured fs path (hdfs://host/p → <root>/p)
— scripts doing ls/upload/download/mkdirs keep working against a staged
directory. When a real `hadoop` binary is on PATH it is used directly.
"""
import os
import shutil
import subprocess

__all__ = ['HDFSClient', 'multi_download', 'multi_upload']


def _have_hadoop(hadoop_home):
    return hadoop_home and os.path.exists(
        os.path.join(hadoop_home, 'bin', 'hadoop'))


class HDFSClient:
    """ref hdfs_utils.py:HDFSClient(hadoop_home, configs)."""

    def __init__(self, hadoop_home=None, configs=None):
        self.hadoop_home = hadoop_home
        self.configs = dict(configs or {})
        name = self.configs.get('fs.default.name', 'hdfs://localhost')
        self.local_root = os.environ.get(
            'PADDLE_TPU_HDFS_ROOT',
            os.path.join(os.path.expanduser('~/.cache/paddle_tpu/hdfs'),
                         name.replace('://', '_').replace('/', '_')))

    @staticmethod
    def _strip_scheme(hdfs_path):
        """hdfs://host/p → /p (local paths pass through)."""
        if '://' in hdfs_path:
            rest = hdfs_path.split('://', 1)[1]
            return '/' + rest.split('/', 1)[1] if '/' in rest else '/'
        return hdfs_path

    def _local(self, hdfs_path):
        return os.path.join(self.local_root,
                            self._strip_scheme(hdfs_path).lstrip('/'))

    def _run_hadoop(self, *args):
        cmd = [os.path.join(self.hadoop_home, 'bin', 'hadoop'), 'fs']
        for k, v in self.configs.items():
            cmd += ['-D', f'{k}={v}']
        cmd += list(args)
        return subprocess.run(cmd, capture_output=True).returncode == 0

    def is_exist(self, hdfs_path):
        """ref :is_exist."""
        if _have_hadoop(self.hadoop_home):
            return self._run_hadoop('-test', '-e', hdfs_path)
        return os.path.exists(self._local(hdfs_path))

    def is_dir(self, hdfs_path):
        """ref :is_dir."""
        if _have_hadoop(self.hadoop_home):
            return self._run_hadoop('-test', '-d', hdfs_path)
        return os.path.isdir(self._local(hdfs_path))

    def delete(self, hdfs_path):
        """ref :delete."""
        if _have_hadoop(self.hadoop_home):
            return self._run_hadoop('-rm', '-r', hdfs_path)
        p = self._local(hdfs_path)
        if os.path.isdir(p):
            shutil.rmtree(p)
        elif os.path.exists(p):
            os.remove(p)
        return True

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False):
        """ref :rename."""
        if _have_hadoop(self.hadoop_home):
            return self._run_hadoop('-mv', hdfs_src_path, hdfs_dst_path)
        src, dst = self._local(hdfs_src_path), self._local(hdfs_dst_path)
        if os.path.exists(dst):
            if not overwrite:
                return False
            self.delete(hdfs_dst_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.rename(src, dst)
        return True

    def makedirs(self, hdfs_path):
        """ref :makedirs."""
        if _have_hadoop(self.hadoop_home):
            return self._run_hadoop('-mkdir', '-p', hdfs_path)
        os.makedirs(self._local(hdfs_path), exist_ok=True)
        return True

    def ls(self, hdfs_path):
        """ref :ls — list of file paths under hdfs_path."""
        if _have_hadoop(self.hadoop_home):
            raise NotImplementedError(
                'parse `hadoop fs -ls` output via upload/download flows')
        p = self._local(hdfs_path)
        if not os.path.isdir(p):
            return []
        return sorted(os.path.join(hdfs_path, f) for f in os.listdir(p))

    def lsr(self, hdfs_path, excludes=None):
        """ref :lsr — recursive ls."""
        excludes = set(excludes or ())
        out = []
        root = self._local(hdfs_path)
        for dirpath, _, files in os.walk(root):
            for f in files:
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, self.local_root)
                posix = '/' + rel.replace(os.sep, '/')
                if posix not in excludes:
                    out.append(posix)
        return sorted(out)

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        """ref :upload — local → hdfs."""
        if _have_hadoop(self.hadoop_home):
            args = ['-put'] + (['-f'] if overwrite else []) \
                + [local_path, hdfs_path]
            return self._run_hadoop(*args)
        dst = self._local(hdfs_path)
        if os.path.exists(dst) and not overwrite:
            return False
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(local_path):
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(local_path, dst, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, dst)
        return True

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        """ref :download — hdfs → local."""
        if _have_hadoop(self.hadoop_home):
            return self._run_hadoop('-get', hdfs_path, local_path)
        src = self._local(hdfs_path)
        if not os.path.exists(src):
            return False
        if os.path.exists(local_path) and not overwrite:
            return False
        os.makedirs(os.path.dirname(local_path) or '.', exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, local_path, dirs_exist_ok=True)
        else:
            shutil.copy2(src, local_path)
        return True


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """ref hdfs_utils.py:multi_download — download this trainer's shard of
    the files under hdfs_path."""
    root = client._strip_scheme(hdfs_path)
    files = client.lsr(hdfs_path)
    my_files = files[trainer_id::trainers]
    out = []
    for f in my_files:
        rel = os.path.relpath(f, root)
        dst = os.path.join(local_path, rel)
        client.download(f, dst)
        out.append(dst)
    return out


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """ref hdfs_utils.py:multi_upload."""
    for dirpath, _, files in os.walk(local_path):
        for f in files:
            full = os.path.join(dirpath, f)
            rel = os.path.relpath(full, local_path)
            client.upload(os.path.join(hdfs_path, rel), full,
                          overwrite=overwrite)
    return True
