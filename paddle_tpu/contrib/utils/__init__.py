"""contrib.utils (ref: python/paddle/fluid/contrib/utils/)."""
from .lookup_table_utils import (convert_dist_to_sparse_program,
                                 load_persistables_for_increment,
                                 load_persistables_for_inference)
from .hdfs_utils import HDFSClient, multi_download, multi_upload

__all__ = ['convert_dist_to_sparse_program',
           'load_persistables_for_increment',
           'load_persistables_for_inference',
           'HDFSClient', 'multi_download', 'multi_upload']
