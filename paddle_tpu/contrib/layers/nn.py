"""contrib.layers.nn (ref: python/paddle/fluid/contrib/layers/nn.py).

The text-matching family (match_matrix_tensor, var_conv_2d,
sequence_topk_avg_pooling, search_pyramid_hash, fused_embedding_seq_pool)
takes the reference's LoD arguments as (B,) length Variables (or None for
dense batches) over padded tensors — see ops/contrib_ops.py for the masked
TPU formulations. The remaining names re-export contrib.extra.
"""
from ...layer_helper import LayerHelper
from ...initializer import XavierInitializer, NormalInitializer
from ...layers.common import apply_op_layer
from ...layers.sequence_lod import _seq_len
from ..extra import (fused_elemwise_activation, tree_conv, multiclass_nms2,
                     shuffle_batch, partial_concat, partial_sum)

__all__ = ['fused_elemwise_activation', 'sequence_topk_avg_pooling',
           'var_conv_2d', 'match_matrix_tensor', 'tree_conv',
           'fused_embedding_seq_pool', 'multiclass_nms2',
           'search_pyramid_hash', 'shuffle_batch', 'partial_concat',
           'partial_sum']


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype='float32', name=None,
                        x_len=None, y_len=None):
    """ref contrib/layers/nn.py:219 — learned bilinear matching matrices.
    x: (B, Lx, D1), y: (B, Ly, D2) padded (lengths threaded implicitly for
    LoDTensor feeds, or passed as x_len/y_len). Returns (out, tmp) like
    the reference: out (B, channel_num, Lx, Ly), tmp the x·W
    intermediate."""
    helper = LayerHelper('match_matrix_tensor', param_attr=param_attr,
                         act=act, name=name)
    d1, d2 = x.shape[-1], y.shape[-1]
    w = helper.create_parameter(helper.param_attr, [d1, channel_num, d2],
                                dtype,
                                default_initializer=XavierInitializer())
    out, tmp = apply_op_layer(
        'match_matrix_tensor',
        {'x': x, 'y': y, 'w': w, 'x_len': _seq_len(x, x_len),
         'y_len': _seq_len(y, y_len)},
        {'channel_num': channel_num}, n_outputs=2)
    if act:
        out = helper.append_activation(out)
    return out, tmp


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype='float32',
                name=None):
    """ref contrib/layers/nn.py:103 — conv over per-sample-sized images.
    input: (B, input_channel, H, W) padded; row/col: (B,) valid
    height/width Variables (the reference's LoD carriers)."""
    helper = LayerHelper('var_conv_2d', param_attr=param_attr, act=act,
                         name=name)
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = helper.create_parameter(
        helper.param_attr, [output_channel, input_channel, k[0], k[1]],
        dtype, default_initializer=NormalInitializer(scale=0.1))
    out = apply_op_layer('var_conv_2d',
                         {'x': input, 'w': w, 'row': row, 'col': col},
                         {'stride': stride})
    if act:
        out = helper.append_activation(out)
    return out


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """ref contrib/layers/nn.py:302 — top-k column averages per row and
    channel. input: (B, channel_num, R, C) padded (e.g. the
    match_matrix_tensor output); row/col: (B,) valid sizes."""
    return apply_op_layer('sequence_topk_avg_pooling',
                          {'x': input, 'row': row, 'col': col},
                          {'topks': list(topks),
                           'channel_num': channel_num})


def fused_embedding_seq_pool(input, size, is_sparse=False, padding_idx=None,
                             combiner='sum', param_attr=None,
                             dtype='float32', sequence_length=None):
    """ref contrib/layers/nn.py:435 — one fused lookup+pool op (XLA fuses
    the gather and the masked reduction). input: (B, T) ids."""
    helper = LayerHelper('fused_embedding_seq_pool', param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, list(size), dtype,
                                default_initializer=XavierInitializer())
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    return apply_op_layer(
        'fused_embedding_seq_pool',
        {'ids': input, 'w': w, 'length': _seq_len(input, sequence_length)},
        {'combiner': combiner, 'padding_idx': pad})


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent, is_training, use_filter,
                        white_list_len, black_list_len, seed,
                        lr=1.0, param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype='float32',
                        sequence_length=None):
    """ref contrib/layers/nn.py:631 — pyramid n-gram hash embedding.
    input: (B, T) ids. The white/black-list filtering args are accepted
    (the hash space is dense here, so filtering is a no-op) and
    rand_len folds into the table width."""
    helper = LayerHelper('search_pyramid_hash', param_attr=param_attr,
                         name=name)
    w = helper.create_parameter(
        helper.param_attr, [space_len, num_emb], dtype,
        default_initializer=NormalInitializer(scale=1.0 / num_emb))
    return apply_op_layer(
        'search_pyramid_hash',
        {'ids': input, 'w': w,
         'length': _seq_len(input, sequence_length)},
        {'num_emb': num_emb, 'space_len': space_len,
         'pyramid_layer': pyramid_layer, 'rand_len': rand_len,
         'drop_out_percent': drop_out_percent, 'is_training': is_training,
         'seed': seed})
