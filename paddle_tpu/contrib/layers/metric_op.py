"""contrib.layers.metric_op (ref: python/paddle/fluid/contrib/layers/
metric_op.py:ctr_metric_bundle)."""
from ...core import unique_name
from ...layer_helper import LayerHelper
from ...layers.tensor import create_global_var
from ...layers.common import apply_op_layer

__all__ = ['ctr_metric_bundle']


def ctr_metric_bundle(input, label):
    """ref metric_op.py:30 — streaming CTR metrics.

    Accumulates into six persistable counters every executor run (the
    accumulate ops fuse into the jitted step): local_sqrerr, local_abserr,
    local_prob (sum of predicted ctr), local_q (sum of label*prob),
    local_pos_num (sum of positive labels), local_ins_num (instances
    seen). Finalize as the reference documents: MAE = abserr/ins_num,
    RMSE = sqrt(sqrerr/ins_num), ctr = prob/ins_num, q = q/ins_num
    (allreduce the counters first when distributed)."""
    helper = LayerHelper('ctr_metric_bundle')

    def acc(name):
        return create_global_var(
            [1], 0.0, 'float32', persistable=True,
            name=unique_name.generate(f'ctr_{name}'))

    local_sqrerr = acc('sqrerr')
    local_abserr = acc('abserr')
    local_prob = acc('prob')
    local_q = acc('q')
    local_pos_num = acc('pos_num')
    local_ins_num = acc('ins_num')

    from ...layers import nn as L
    from ...layers import tensor as T
    fl = T.cast(label, 'float32')
    err = apply_op_layer('elementwise_sub', {'x': input, 'y': fl}, {})
    batch_sqr = L.reduce_sum(apply_op_layer('square', {'x': err}, {}))
    batch_abs = L.reduce_sum(apply_op_layer('abs', {'x': err}, {}))
    batch_prob = L.reduce_sum(input)
    batch_q = L.reduce_sum(apply_op_layer(
        'elementwise_mul', {'x': input, 'y': fl}, {}))
    batch_pos = L.reduce_sum(fl)
    batch_ins = L.reduce_sum(T.ones_like(fl))

    block = helper.main_program.current_block()
    for acc_var, batch in ((local_sqrerr, batch_sqr),
                           (local_abserr, batch_abs),
                           (local_prob, batch_prob),
                           (local_q, batch_q),
                           (local_pos_num, batch_pos),
                           (local_ins_num, batch_ins)):
        block.append_op(type='elementwise_add',
                        inputs={'x': acc_var.name, 'y': batch.name},
                        outputs={'Out': acc_var.name}, attrs={})
    return (local_sqrerr, local_abserr, local_prob, local_q,
            local_pos_num, local_ins_num)
