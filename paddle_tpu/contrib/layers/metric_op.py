"""contrib.layers.metric_op (ref: python/paddle/fluid/contrib/layers/
metric_op.py:ctr_metric_bundle)."""
from ...core import unique_name
from ...layer_helper import LayerHelper
from ...layers.tensor import create_global_var
from ...layers.common import apply_op_layer

__all__ = ['ctr_metric_bundle']


def ctr_metric_bundle(input, label):
    """ref metric_op.py:30 — streaming CTR metrics.

    Accumulates into four persistable counters every executor run (the
    accumulate ops fuse into the jitted step): local_sqrerr, local_abserr,
    local_prob (sum of predicted ctr), local_q (sum of label*prob).
    Finalize as the reference documents: MAE = abserr/N,
    RMSE = sqrt(sqrerr/N), ctr = prob/N, q = q/N (allreduce first when
    distributed)."""
    helper = LayerHelper('ctr_metric_bundle')

    def acc(name):
        return create_global_var(
            [1], 0.0, 'float32', persistable=True,
            name=unique_name.generate(f'ctr_{name}'))

    local_sqrerr = acc('sqrerr')
    local_abserr = acc('abserr')
    local_prob = acc('prob')
    local_q = acc('q')

    from ...layers import nn as L
    from ...layers import tensor as T
    fl = T.cast(label, 'float32')
    err = apply_op_layer('elementwise_sub', {'x': input, 'y': fl}, {})
    batch_sqr = L.reduce_sum(apply_op_layer('square', {'x': err}, {}))
    batch_abs = L.reduce_sum(apply_op_layer('abs', {'x': err}, {}))
    batch_prob = L.reduce_sum(input)
    batch_q = L.reduce_sum(apply_op_layer(
        'elementwise_mul', {'x': input, 'y': fl}, {}))

    block = helper.main_program.current_block()
    for acc_var, batch in ((local_sqrerr, batch_sqr),
                           (local_abserr, batch_abs),
                           (local_prob, batch_prob),
                           (local_q, batch_q)):
        block.append_op(type='elementwise_add',
                        inputs={'x': acc_var.name, 'y': batch.name},
                        outputs={'Out': acc_var.name}, attrs={})
    return local_sqrerr, local_abserr, local_prob, local_q
