"""contrib.layers.rnn_impl (ref: python/paddle/fluid/contrib/layers/
rnn_impl.py) — implementations live in contrib.extra."""
from ..extra import BasicGRUUnit, basic_gru, BasicLSTMUnit, basic_lstm

__all__ = ['BasicGRUUnit', 'basic_gru', 'BasicLSTMUnit', 'basic_lstm']
