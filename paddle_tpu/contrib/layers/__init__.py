"""contrib.layers (ref: python/paddle/fluid/contrib/layers/): nn extras,
basic RNN impls (contrib.extra), ctr metric bundle."""
from .nn import (fused_elemwise_activation, sequence_topk_avg_pooling,
                 var_conv_2d, match_matrix_tensor, tree_conv,
                 fused_embedding_seq_pool, multiclass_nms2,
                 search_pyramid_hash, shuffle_batch, partial_concat,
                 partial_sum)
from .rnn_impl import BasicGRUUnit, basic_gru, BasicLSTMUnit, basic_lstm
from .metric_op import ctr_metric_bundle

__all__ = ['fused_elemwise_activation', 'sequence_topk_avg_pooling',
           'var_conv_2d', 'match_matrix_tensor', 'tree_conv',
           'fused_embedding_seq_pool', 'multiclass_nms2',
           'search_pyramid_hash', 'shuffle_batch', 'partial_concat',
           'partial_sum', 'BasicGRUUnit', 'basic_gru', 'BasicLSTMUnit',
           'basic_lstm', 'ctr_metric_bundle']
