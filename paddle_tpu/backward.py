"""append_backward: mark the program for autodiff.

Parity with reference python/paddle/fluid/backward.py. The reference builds
explicit grad ops per-op via GradOpMaker; the TPU design instead inserts ONE
backward marker op — the Executor's lowering wraps the forward segment in
`jax.value_and_grad` over the parameter subtree, which is both simpler and
lets XLA fuse/schedule the whole backward pass.

Sparse embedding tables (``lookup_table(is_sparse=True)``, docs/SPARSE.md)
leave the dense parameter list: the marker records them as *sparse params*
with one `_sparse_site`-stamped lookup per read, and declares a padded-COO
gradient pair (``@GRAD@ROWS`` int32 + ``@GRAD@VALS``) per table that the
lowering fills by coalescing the per-occurrence surrogate cotangents —
O(nnz·D) instead of the dense V×D scatter-add.
"""
from __future__ import annotations

from .framework import BACKWARD_OP_TYPE, Parameter


def _grad_name(name):
    return name + '@GRAD'


def _sparse_table_sites(program, param_names):
    """Tables eligible for rows-only gradients: trainable params whose
    EVERY read (across all blocks) is a global-block
    ``lookup_table(is_sparse=True)`` op with a fed (``is_data``) ids var.
    A table also read densely (weight tying, a projection reuse) stays on
    the dense path — sparsifying it would silently drop the dense
    contribution. Returns {param: [(site_key, ids_name, op)]}."""
    from .ops.sparse_ops import sparse_grad_enabled
    if not sparse_grad_enabled():
        return {}
    wanted = set(param_names)
    blk = program.global_block()
    sites = {}
    readers = {}     # param -> list of (block_idx, op) reading it
    for b in program.blocks:
        for op in b.ops:
            if op.type == BACKWARD_OP_TYPE:
                continue
            for n in op.input_names():
                if n in wanted:
                    readers.setdefault(n, []).append((b.idx, op))
    for p, reads in readers.items():
        ok = []
        for bi, op in reads:
            ids_names = op.inputs.get('ids') or []
            if (bi == 0 and op.type == 'lookup_table'
                    and op.attrs.get('is_sparse')
                    and (op.inputs.get('w') or [None])[0] == p
                    and ids_names and blk.has_var(ids_names[0])
                    and getattr(blk.var(ids_names[0]), 'is_data', False)):
                ok.append((op, ids_names[0]))
            else:
                ok = None
                break
        if ok:
            sites[p] = [(f'{p}@SPARSE@{i}', ids_name, op)
                        for i, (op, ids_name) in enumerate(ok)]
    return sites


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Returns list of (param_var, grad_var) like the reference. Sparse
    tables come back as (param, vals_var) where ``vals_var`` carries
    ``is_sparse_rows=True`` and ``sparse_rows_var`` (the optimizer routes
    those through the ``sparse_*`` scatter-apply update ops)."""
    program = loss.block.program
    block = program.global_block()
    params = [p for p in program.all_parameters() if p.trainable]
    if parameter_list:
        wanted = {p if isinstance(p, str) else p.name for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    if no_grad_set:
        banned = {v if isinstance(v, str) else v.name for v in no_grad_set}
        params = [p for p in params if p.name not in banned]
    if not params:
        raise ValueError("no trainable parameters to differentiate")

    sparse_sites = _sparse_table_sites(program, [p.name for p in params])
    dense_params = [p for p in params if p.name not in sparse_sites]
    sparse_params = [p for p in params if p.name in sparse_sites]

    param_grads = []
    for p in dense_params:
        g = block.create_var(name=_grad_name(p.name), shape=list(p.shape),
                             dtype=p.dtype, stop_gradient=True)
        param_grads.append((p, g))

    sparse_rows_names, sparse_vals_names, site_records = [], [], []
    sparse_grads = []
    for p in sparse_params:
        dim = int(p.shape[1])
        rows = block.create_var(name=p.name + '@GRAD@ROWS', shape=[-1],
                                dtype='int32', stop_gradient=True)
        vals = block.create_var(name=p.name + '@GRAD@VALS',
                                shape=[-1, dim], dtype=p.dtype,
                                stop_gradient=True)
        vals.is_sparse_rows = True
        vals.sparse_rows_var = rows
        sparse_rows_names.append(rows.name)
        sparse_vals_names.append(vals.name)
        for site_key, ids_name, op in sparse_sites[p.name]:
            op._set_attr('_sparse_site', site_key)
            site_records.append([site_key, p.name, ids_name])
        sparse_grads.append((p, vals))

    marker_attrs = {'loss': loss.name,
                    'params': [p.name for p, _ in param_grads],
                    'checkpoints': [c.name if hasattr(c, 'name') else c
                                    for c in (checkpoints or [])]}
    marker_outputs = {'Grads': [g.name for _, g in param_grads]}
    if sparse_params:
        marker_attrs['sparse_params'] = [p.name for p in sparse_params]
        marker_attrs['sparse_sites'] = site_records
        marker_outputs['SparseRows'] = sparse_rows_names
        marker_outputs['SparseVals'] = sparse_vals_names

    block.append_op(BACKWARD_OP_TYPE,
                    inputs={'Loss': loss.name},
                    outputs=marker_outputs,
                    attrs=marker_attrs)
    return param_grads + sparse_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients parity: symbolic grads of targets w.r.t. inputs.
    Restricted form: single scalar target (covers ref model usage)."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    block = t.block
    grads = []
    for x in inputs:
        g = block.create_var(name=_grad_name(x.name), shape=list(x.shape or []),
                             dtype=x.dtype, stop_gradient=True)
        grads.append(g)
    block.append_op(
        BACKWARD_OP_TYPE,
        inputs={'Loss': t.name},
        outputs={'Grads': [g.name for g in grads]},
        attrs={'loss': t.name, 'params': [x.name for x in inputs],
               'wrt_inputs': True, 'checkpoints': []})
    return grads
