"""append_backward: mark the program for autodiff.

Parity with reference python/paddle/fluid/backward.py. The reference builds
explicit grad ops per-op via GradOpMaker; the TPU design instead inserts ONE
backward marker op — the Executor's lowering wraps the forward segment in
`jax.value_and_grad` over the parameter subtree, which is both simpler and
lets XLA fuse/schedule the whole backward pass.
"""
from __future__ import annotations

from .framework import BACKWARD_OP_TYPE, Parameter


def _grad_name(name):
    return name + '@GRAD'


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Returns list of (param_var, grad_var) like the reference."""
    program = loss.block.program
    block = program.global_block()
    params = [p for p in program.all_parameters() if p.trainable]
    if parameter_list:
        wanted = {p if isinstance(p, str) else p.name for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    if no_grad_set:
        banned = {v if isinstance(v, str) else v.name for v in no_grad_set}
        params = [p for p in params if p.name not in banned]
    if not params:
        raise ValueError("no trainable parameters to differentiate")

    param_grads = []
    for p in params:
        g = block.create_var(name=_grad_name(p.name), shape=list(p.shape),
                             dtype=p.dtype, stop_gradient=True)
        param_grads.append((p, g))

    block.append_op(
        BACKWARD_OP_TYPE,
        inputs={'Loss': loss.name},
        outputs={'Grads': [g.name for _, g in param_grads]},
        attrs={'loss': loss.name,
               'params': [p.name for p, _ in param_grads],
               'checkpoints': [c.name if hasattr(c, 'name') else c
                               for c in (checkpoints or [])]})
    return param_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients parity: symbolic grads of targets w.r.t. inputs.
    Restricted form: single scalar target (covers ref model usage)."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    block = t.block
    grads = []
    for x in inputs:
        g = block.create_var(name=_grad_name(x.name), shape=list(x.shape or []),
                             dtype=x.dtype, stop_gradient=True)
        grads.append(g)
    block.append_op(
        BACKWARD_OP_TYPE,
        inputs={'Loss': t.name},
        outputs={'Grads': [g.name for g in grads]},
        attrs={'loss': t.name, 'params': [x.name for x in inputs],
               'wrt_inputs': True, 'checkpoints': []})
    return grads
