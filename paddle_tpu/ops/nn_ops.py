"""Neural-network ops: conv, pool, normalization, embedding, dropout, resize.

Parity targets: reference paddle/fluid/operators/{conv,pool,batch_norm,
layer_norm,group_norm,instance_norm,data_norm,dropout,lookup_table,softmax,
lrn,interpolate,grid_sampler,affine_grid,pixel_shuffle,unfold,im2sequence,
row_conv,bilinear_tensor_product}_op.* — implemented as jax functionals on
lax.conv_general_dilated / reduce_window so XLA tiles them onto the MXU.
Layouts: Paddle default NCHW is honored; NHWC supported via data_format attr
(preferred on TPU).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from ..core.dtypes import to_jax_dtype


def _pair(v, n=2):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


def _match_weight_dtype(x, w):
    """AMP harmonization: an fp32 activation meeting a low-precision
    weight computes in the WEIGHT's dtype (the master-weight design casts
    params to the compute dtype; feeds may still arrive fp32)."""
    if (jnp.issubdtype(x.dtype, jnp.floating)
            and jnp.issubdtype(w.dtype, jnp.floating)
            and x.dtype != w.dtype):
        return x.astype(w.dtype)
    return x


def _conv_dims(data_format, nd):
    if nd == 2:
        return ('NCHW', 'OIHW', 'NCHW') if data_format == 'NCHW' else ('NHWC', 'HWIO', 'NHWC')
    return ('NCDHW', 'OIDHW', 'NCDHW') if data_format == 'NCDHW' else ('NDHWC', 'DHWIO', 'NDHWC')


@register_op('conv2d')
def conv2d(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCHW'):
    """ref: paddle/fluid/operators/conv_op.cc (weights always OIHW)."""
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    x = _match_weight_dtype(x, w)
    stride = _pair(stride)
    dilation = _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()  # 'SAME' | 'VALID'
    else:
        p = _pair(padding)
        pad = [(p[0], p[0]), (p[1], p[1])] if len(p) == 2 else \
            [(p[0], p[1]), (p[2], p[3])]
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _conv_dims(data_format, 2))
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=x.dtype if x.dtype == jnp.float32 else None)


@register_op('conv3d')
def conv3d(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCDHW'):
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    x = _match_weight_dtype(x, w)
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    p = _pair(padding, 3)
    pad = [(pi, pi) for pi in p] if not isinstance(padding, str) else padding.upper()
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _conv_dims(data_format, 3))
    return lax.conv_general_dilated(x, w, stride, pad, rhs_dilation=dilation,
                                    dimension_numbers=dn, feature_group_count=groups)


@register_op('conv2d_transpose')
def conv2d_transpose(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
                     output_size=None, data_format='NCHW'):
    """ref: paddle/fluid/operators/conv_transpose_op.cc. Weight layout IOHW."""
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    x = _match_weight_dtype(x, w)
    stride = _pair(stride)
    p = _pair(padding)
    # grad-of-conv formulation: lhs_dilation = stride
    k = (w.shape[2], w.shape[3])
    pad = [(dilation * (k[0] - 1) - p[0], dilation * (k[0] - 1) - p[0]),
           (dilation * (k[1] - 1) - p[1], dilation * (k[1] - 1) - p[1])]
    if data_format == 'NCHW':
        dims = ('NCHW', 'OIHW', 'NCHW')
    else:
        dims = ('NHWC', 'HWIO', 'NHWC')
    if groups > 1:
        ci = w.shape[0]
        w = w.reshape(groups, ci // groups, *w.shape[1:]).transpose(0, 2, 1, 3, 4) \
            .reshape(-1, ci // groups, *w.shape[2:])
    else:
        w = jnp.swapaxes(w, 0, 1)  # IOHW -> OIHW
    w = jnp.flip(w, axis=(-2, -1))
    dn = lax.conv_dimension_numbers(x.shape, w.shape, dims)
    return lax.conv_general_dilated(x, w, window_strides=(1, 1), padding=pad,
                                    lhs_dilation=stride, rhs_dilation=_pair(dilation),
                                    dimension_numbers=dn, feature_group_count=groups)


@register_op('conv3d_transpose')
def conv3d_transpose(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
                     data_format='NCDHW'):
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    x = _match_weight_dtype(x, w)
    stride = _pair(stride, 3)
    p = _pair(padding, 3)
    d = _pair(dilation, 3)
    k = w.shape[2:]
    pad = [(d[i] * (k[i] - 1) - p[i],) * 2 for i in range(3)]
    w = jnp.swapaxes(w, 0, 1)
    w = jnp.flip(w, axis=(-3, -2, -1))
    dn = lax.conv_dimension_numbers(x.shape, w.shape, ('NCDHW', 'OIDHW', 'NCDHW'))
    return lax.conv_general_dilated(x, w, (1, 1, 1), pad, lhs_dilation=stride,
                                    rhs_dilation=d, dimension_numbers=dn,
                                    feature_group_count=groups)


def _pool(x, ksize, stride, padding, pool_type, nd, ceil_mode=False,
          exclusive=True, data_format='NCHW', global_pool=False):
    x = jnp.asarray(x)
    spatial = tuple(range(2, 2 + nd)) if data_format.startswith('NC') else tuple(range(1, 1 + nd))
    if global_pool:
        ksize = [x.shape[a] for a in spatial]
        stride = ksize
        padding = [0] * nd
    ksize = _pair(ksize, nd)
    stride = _pair(stride if stride is not None else ksize, nd)
    p = _pair(padding, nd)
    window = [1] * x.ndim
    strides = [1] * x.ndim
    pads = [(0, 0)] * x.ndim
    for i, a in enumerate(spatial):
        window[a] = ksize[i]
        strides[a] = stride[i]
        extra = 0
        if ceil_mode:
            size = x.shape[a]
            rem = (size + 2 * p[i] - ksize[i]) % stride[i]
            extra = (stride[i] - rem) % stride[i] if rem else 0
        pads[a] = (p[i], p[i] + extra)
    import numpy as np
    if pool_type == 'max':
        # init must stay a concrete literal: a traced constant breaks the
        # select-and-scatter grad rule under jit-of-grad
        init = np.array(-np.inf if jnp.issubdtype(x.dtype, jnp.floating)
                        else np.iinfo(x.dtype).min, x.dtype)
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    # avg
    ones = jnp.ones_like(x)
    zero = np.array(0, x.dtype)
    s = lax.reduce_window(x, zero, lax.add, window, strides, pads)
    if exclusive:
        cnt = lax.reduce_window(ones, zero, lax.add, window, strides, pads)
    else:
        cnt = np.array(math.prod(ksize), x.dtype)
    return s / cnt


@register_op('pool2d')
def pool2d(x, *, pool_size=-1, pool_type='max', pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, exclusive=True,
           data_format='NCHW'):
    """ref: paddle/fluid/operators/pool_op.cc."""
    return _pool(x, pool_size, pool_stride, pool_padding, pool_type, 2,
                 ceil_mode, exclusive, data_format, global_pooling)


@register_op('pool3d')
def pool3d(x, *, pool_size=-1, pool_type='max', pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, exclusive=True,
           data_format='NCDHW'):
    return _pool(x, pool_size, pool_stride, pool_padding, pool_type, 3,
                 ceil_mode, exclusive, data_format, global_pooling)


@register_op('adaptive_pool2d')
def adaptive_pool2d(x, *, pool_size, pool_type='max'):
    """ref: adaptive pooling in paddle/fluid/operators/pool_op.cc (adaptive=True).
    Requires divisible spatial dims (true for all ref model configs)."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    oh, ow = _pair(pool_size)
    x = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if pool_type == 'max':
        return jnp.max(x, axis=(3, 5))
    return jnp.mean(x, axis=(3, 5))


@register_op('adaptive_pool3d')
def adaptive_pool3d(x, *, pool_size, pool_type='max'):
    x = jnp.asarray(x)
    n, c, d, h, w = x.shape
    od, oh, ow = _pair(pool_size, 3)
    x = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
    if pool_type == 'max':
        return jnp.max(x, axis=(3, 5, 7))
    return jnp.mean(x, axis=(3, 5, 7))


@register_op('softmax')
def softmax(x, *, axis=-1):
    return jax.nn.softmax(jnp.asarray(x), axis=axis)


@register_op('log_softmax')
def log_softmax(x, *, axis=-1):
    return jax.nn.log_softmax(jnp.asarray(x), axis=axis)


def _bound_sync_axes():
    """Mesh axes batch stats reduce over for sync-BN: the partitioner's
    data axes that are LIVE in the surrounding trace (shard_map). On the
    GSPMD executor no axis is bound — and none is needed: jnp.mean over
    the globally-sharded batch already reduces over every shard, so
    sync_stats is the identity there by construction."""
    from ..parallel.collective import _axis_bound
    from ..partition import get_partitioner
    return tuple(a for a in (get_partitioner().data_axes() or ())
                 if _axis_bound(a))


@register_op('batch_norm', outputs=['Y', 'MeanOut', 'VarianceOut'])
def batch_norm(x, scale, bias, mean, variance, *, momentum=0.9, epsilon=1e-5,
               is_test=False, use_global_stats=False, data_layout='NCHW',
               sync_stats=False):
    """ref: paddle/fluid/operators/batch_norm_op.cc. Returns (y, new_running_
    mean, new_running_var); the graph aliases MeanOut/VarianceOut onto the
    input stat vars so the lowered step updates state functionally.

    ``sync_stats`` (the reference's sync_batch_norm, arXiv 1909.09756's
    large-batch ingredient): batch mean/variance are reduced over the
    partitioner's data axes, so every shard normalizes with GLOBAL-batch
    statistics — mean via pmean of per-shard means (equal shard sizes),
    variance via the E[x²]−E[x]² decomposition over the same reductions.
    Under explicit SPMD (shard_map) this emits real collectives; on the
    GSPMD executor the plain batch reduction is already global."""
    x = jnp.asarray(x)
    scale = jnp.asarray(scale)
    bias = jnp.asarray(bias)
    mean = jnp.asarray(mean)
    variance = jnp.asarray(variance)
    if data_layout == 'NCHW' and x.ndim > 2:
        axes = (0,) + tuple(range(2, x.ndim))
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        shape = (1,) * (x.ndim - 1) + (-1,)
    if is_test or use_global_stats:
        m, v = mean, variance
        new_mean, new_var = mean, variance
    else:
        xf = x.astype(jnp.float32)
        sync_axes = _bound_sync_axes() if sync_stats else ()
        if sync_axes:
            m = lax.pmean(jnp.mean(xf, axes), sync_axes)
            ex2 = lax.pmean(jnp.mean(jnp.square(xf), axes), sync_axes)
            v = ex2 - jnp.square(m)
        else:
            m = jnp.mean(xf, axes)
            v = jnp.var(xf, axes)
        new_mean = momentum * mean + (1 - momentum) * m.astype(mean.dtype)
        new_var = momentum * variance + (1 - momentum) * v.astype(variance.dtype)
        new_mean = lax.stop_gradient(new_mean)
        new_var = lax.stop_gradient(new_var)
    inv = lax.rsqrt(v.astype(jnp.float32) + epsilon).astype(x.dtype)
    y = (x - m.astype(x.dtype).reshape(shape)) * inv.reshape(shape) \
        * scale.reshape(shape) + bias.reshape(shape)
    return y, new_mean, new_var


@register_op('layer_norm')
def layer_norm(x, scale=None, bias=None, *, begin_norm_axis=1, epsilon=1e-5):
    """ref: paddle/fluid/operators/layer_norm_op.cc."""
    x = jnp.asarray(x)
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axes, keepdims=True)
    v = jnp.var(xf, axes, keepdims=True)
    y = ((xf - m) * lax.rsqrt(v + epsilon)).astype(x.dtype)
    norm_shape = x.shape[begin_norm_axis:]
    if scale is not None:
        y = y * jnp.asarray(scale).reshape(norm_shape)
    if bias is not None:
        y = y + jnp.asarray(bias).reshape(norm_shape)
    return y


@register_op('instance_norm')
def instance_norm(x, scale=None, bias=None, *, epsilon=1e-5):
    """ref: paddle/fluid/operators/instance_norm_op.cc (NCHW)."""
    x = jnp.asarray(x)
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axes, keepdims=True)
    v = jnp.var(x, axes, keepdims=True)
    y = (x - m) * lax.rsqrt(v + epsilon)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * jnp.asarray(scale).reshape(shape)
    if bias is not None:
        y = y + jnp.asarray(bias).reshape(shape)
    return y


@register_op('group_norm')
def group_norm(x, scale=None, bias=None, *, groups, epsilon=1e-5,
               data_layout='NCHW'):
    """ref: paddle/fluid/operators/group_norm_op.cc."""
    x = jnp.asarray(x)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape(n, groups, c // groups, *spatial)
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axes, keepdims=True)
    v = jnp.var(xg, axes, keepdims=True)
    y = ((xg - m) * lax.rsqrt(v + epsilon)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * jnp.asarray(scale).reshape(shape)
    if bias is not None:
        y = y + jnp.asarray(bias).reshape(shape)
    return y


@register_op('data_norm', outputs=['Y', 'BatchSizeOut', 'BatchSumOut', 'BatchSquareSumOut'])
def data_norm(x, batch_size, batch_sum, batch_square_sum, *, epsilon=1e-4,
              is_test=False):
    """ref: paddle/fluid/operators/data_norm_op.cc (CTR models)."""
    x = jnp.asarray(x)
    bsize = jnp.asarray(batch_size)
    bsum = jnp.asarray(batch_sum)
    bsq = jnp.asarray(batch_square_sum)
    mean = bsum / bsize
    scale = jnp.sqrt(bsize / (bsq - bsum * bsum / bsize + epsilon))
    y = (x - mean) * scale
    if is_test:
        return y, bsize, bsum, bsq
    n = jnp.asarray(x.shape[0], bsize.dtype)
    nb = lax.stop_gradient(bsize + n)
    ns = lax.stop_gradient(bsum + jnp.sum(x, 0))
    nq = lax.stop_gradient(bsq + jnp.sum(jnp.square(x), 0))
    return y, nb, ns, nq


@register_op('dropout', needs_rng=True)
def dropout(x, *, dropout_prob=0.5, is_test=False,
            dropout_implementation='downgrade_in_infer', key=None):
    """ref: paddle/fluid/operators/dropout_op.cc. Both paddle semantics:
    downgrade_in_infer (scale at infer) and upscale_in_train."""
    x = jnp.asarray(x)
    if is_test:
        if dropout_implementation == 'downgrade_in_infer':
            return x * (1.0 - dropout_prob)
        return x
    if dropout_prob == 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - dropout_prob, x.shape)
    if dropout_implementation == 'upscale_in_train':
        return jnp.where(keep, x / (1.0 - dropout_prob), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


@register_op('lookup_table')
def lookup_table(w, ids, *, padding_idx=-1, is_sparse=False,
                 is_distributed=False, _sparse_site=None):
    """Embedding lookup (ref: paddle/fluid/operators/lookup_table_op.cc).

    ``is_sparse=True`` + a bound ``_sparse_site`` (the static sparse-grad
    path, docs/SPARSE.md): the gathered rows add a zero-valued surrogate
    from the trace context (exact: +0.0), so the backward produces the
    per-occurrence row cotangent O(nnz·D) instead of the dense V×D
    scatter — the table itself is a non-differentiated constant in that
    mode. Outside a sparse trace (eval clones, inference programs,
    PADDLE_TPU_SPARSE_GRAD=0) the surrogate resolves to None and this is
    the plain dense gather."""
    w = jnp.asarray(w)
    ids = jnp.asarray(ids)
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids[..., 0]
    surrogate = None
    if _sparse_site is not None:
        from .sparse_ops import site_value
        surrogate = site_value(_sparse_site)
    if surrogate is not None:
        w = lax.stop_gradient(w)
    out = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)
    if surrogate is not None:
        out = out + surrogate.reshape(out.shape)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


@register_op('lrn')
def lrn(x, *, n=5, k=1.0, alpha=1e-4, beta=0.75):
    """ref: paddle/fluid/operators/lrn_op.cc (NCHW)."""
    x = jnp.asarray(x)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    window = [1, n, 1, 1]
    import numpy as np
    s = lax.reduce_window(pad, np.array(0, x.dtype), lax.add, window,
                          [1, 1, 1, 1], [(0, 0)] * 4)
    return x / jnp.power(k + alpha * s, beta)


@register_op('interpolate')
def interpolate(x, *, out_shape, method='bilinear', align_corners=True,
                align_mode=1, data_format='NCHW'):
    """ref: paddle/fluid/operators/interpolate_op.cc (bilinear/nearest/trilinear)."""
    x = jnp.asarray(x)
    if data_format == 'NCHW' or data_format == 'NCDHW':
        spatial_start = 2
    else:
        spatial_start = 1
    in_sp = x.shape[spatial_start:spatial_start + len(out_shape)]
    out_sp = tuple(int(s) for s in out_shape)

    def src_idx(out_len, in_len):
        i = jnp.arange(out_len, dtype=jnp.float32)
        if method == 'nearest':
            if align_corners:
                return jnp.round(i * (in_len - 1) / max(out_len - 1, 1))
            return jnp.floor(i * in_len / out_len)
        if align_corners:
            return i * (in_len - 1) / max(out_len - 1, 1)
        if align_mode == 0:
            return jnp.clip((i + 0.5) * in_len / out_len - 0.5, 0, in_len - 1)
        return jnp.clip(i * in_len / out_len, 0, in_len - 1)

    if method == 'nearest':
        out = x
        for d, (ol, il) in enumerate(zip(out_sp, in_sp)):
            idx = src_idx(ol, il).astype(jnp.int32)
            out = jnp.take(out, idx, axis=spatial_start + d)
        return out
    # (bi/tri)linear: separable 1-D lerps
    out = x.astype(jnp.float32)
    for d, (ol, il) in enumerate(zip(out_sp, in_sp)):
        axis = spatial_start + d
        si = src_idx(ol, il)
        lo = jnp.floor(si).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, il - 1)
        w = (si - lo).astype(out.dtype)
        a = jnp.take(out, lo, axis=axis)
        b = jnp.take(out, hi, axis=axis)
        shape = [1] * out.ndim
        shape[axis] = ol
        w = w.reshape(shape)
        out = a * (1 - w) + b * w
    return out.astype(x.dtype)


@register_op('pixel_shuffle')
def pixel_shuffle(x, *, upscale_factor):
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, c // (r * r), h * r, w * r)


@register_op('unfold')
def unfold(x, *, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (ref: paddle/fluid/operators/unfold_op.cc)."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    xp = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(lax.slice(
                xp, (0, 0, i * dh, j * dw),
                (n, c, i * dh + (oh - 1) * sh + 1, j * dw + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))
    col = jnp.stack(patches, 2)  # n, c, kh*kw, oh, ow
    return col.reshape(n, c * kh * kw, oh * ow)


@register_op('im2sequence')
def im2sequence(x, *, filter_size, stride=1, padding=0):
    """ref: paddle/fluid/operators/im2sequence_op.cc (OCR feature slicing)."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    kh, kw = _pair(filter_size)
    out = unfold(x, kernel_sizes=filter_size, strides=stride, paddings=padding)
    # (n, c*kh*kw, L) -> (n*L, c*kh*kw)
    return out.transpose(0, 2, 1).reshape(-1, c * kh * kw)


@register_op('row_conv')
def row_conv(x, w):
    """Lookahead row convolution (ref: paddle/fluid/operators/row_conv_op.cc),
    batched dense formulation: x (B, T, D), w (future_context+1, D)."""
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    ctx = w.shape[0]
    out = jnp.zeros_like(x)
    for i in range(ctx):
        shifted = jnp.pad(x, [(0, 0), (0, i), (0, 0)])[:, i:, :]
        out = out + shifted * w[i]
    return out


@register_op('bilinear_tensor_product')
def bilinear_tensor_product(x, y, weight, bias=None):
    """ref: paddle/fluid/operators/bilinear_tensor_product_op.cc.
    out[b,k] = x[b]ᵀ W[k] y[b] + bias[k]."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    w = jnp.asarray(weight)
    out = jnp.einsum('bi,kij,bj->bk', x, w, y)
    if bias is not None:
        out = out + jnp.asarray(bias)
    return out


@register_op('fsp')
def fsp(x, y):
    """Flow-of-solution-procedure matrix for distillation
    (ref: paddle/fluid/operators/fsp_op.cc)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    n, c1 = x.shape[0], x.shape[1]
    c2 = y.shape[1]
    hw = x.shape[2] * x.shape[3]
    xm = x.reshape(n, c1, hw)
    ym = y.reshape(n, c2, hw)
    return jnp.einsum('nch,ndh->ncd', xm, ym) / hw


@register_op('add_position_encoding')
def add_position_encoding(x, *, alpha=1.0, beta=1.0):
    """ref: paddle/fluid/operators/add_position_encoding_op.cc."""
    x = jnp.asarray(x)
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2 * i / d)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=1)
    return alpha * x + beta * pe[None, :, :].astype(x.dtype)


@register_op('grid_sampler')
def grid_sampler(x, grid):
    """Bilinear grid sample (ref: paddle/fluid/operators/grid_sampler_op.cc).
    x: NCHW, grid: NHW2 in [-1, 1]."""
    x = jnp.asarray(x)
    grid = jnp.asarray(grid)
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)

    def sample(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        batch = jnp.arange(n)[:, None, None]
        v = x[batch, :, yi, xi]  # n, gh, gw, c
        inb = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
        return jnp.where(inb[..., None], v, 0.0)

    wx = (gx - x0)[..., None]
    wy = (gy - y0)[..., None]
    v00 = sample(y0, x0)
    v01 = sample(y0, x0 + 1)
    v10 = sample(y0 + 1, x0)
    v11 = sample(y0 + 1, x0 + 1)
    out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
           + v10 * (1 - wx) * wy + v11 * wx * wy)
    return out.transpose(0, 3, 1, 2)


@register_op('affine_grid')
def affine_grid(theta, *, out_shape):
    """ref: paddle/fluid/operators/affine_grid_op.cc. theta: (N,2,3)."""
    theta = jnp.asarray(theta)
    n, _, h, w = out_shape
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # h,w,3
    return jnp.einsum('hwk,njk->nhwj', base.astype(theta.dtype), theta)


@register_op('affine_channel')
def affine_channel(x, scale, bias, *, data_layout='NCHW'):
    x = jnp.asarray(x)
    shape = (1, -1, 1, 1) if data_layout == 'NCHW' else (1, 1, 1, -1)
    return x * jnp.asarray(scale).reshape(shape) + jnp.asarray(bias).reshape(shape)


@register_op('l2_normalize')
def l2_normalize(x, *, axis=-1, epsilon=1e-12):
    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return x / jnp.maximum(norm, epsilon)


@register_op('norm', outputs=['Out', 'Norm'])
def norm(x, *, axis=-1, epsilon=1e-10):
    x = jnp.asarray(x)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + epsilon)
    return x / n, n


# ---------------------------------------------------------------------------
# Fused / paged attention and their pallas-unavailable fallback accounting.
#
# Both attention ops prefer a pallas TPU kernel and fall back to an XLA
# formulation elsewhere (or on kernel shape rejection). The fallback is
# counted, not shouted: ONE process-wide warning through log_helper (the op
# bodies run at trace time under the eager kernel cache / jit, so a warning
# per call would really be a warning per compiled shape — still log spam in
# a server that compiles a prefill ladder), and a counter of fallback traces
# exposed via pallas_fallback_stats() plus an at-export `attention_pallas_
# fallbacks` gauge in the telemetry registry.
# ---------------------------------------------------------------------------

_PALLAS_FALLBACKS = {'warned': False, 'count': 0, 'last': ''}


def _pallas_fallback(kernel_name, exc, shape):
    _PALLAS_FALLBACKS['count'] += 1
    _PALLAS_FALLBACKS['last'] = (
        f'{kernel_name} q{tuple(shape)} {type(exc).__name__}: '
        f'{str(exc)[:200]}')
    if not _PALLAS_FALLBACKS['warned']:
        _PALLAS_FALLBACKS['warned'] = True
        import logging
        from ..log_helper import get_logger
        get_logger(__name__, logging.WARNING).warning(
            "%s: pallas kernel unavailable for q%s (%s: %s); falling back "
            "to the XLA formulation. Warning once per process; further "
            "fallbacks are counted (ops.nn_ops.pallas_fallback_stats / the "
            "attention_pallas_fallbacks gauge).",
            kernel_name, tuple(shape), type(exc).__name__, str(exc)[:200])


def pallas_fallback_stats():
    """{'count': fallback traces (≈ one per compiled shape), 'warned': bool,
    'last': last fallback reason} for fused_attention + paged_attention."""
    return dict(_PALLAS_FALLBACKS)


def reset_pallas_fallback_stats():
    _PALLAS_FALLBACKS.update(warned=False, count=0, last='')


def _collect_pallas_fallback_gauge():
    from .. import observability as _obs
    g = _obs.registry.gauge(
        'attention_pallas_fallbacks',
        'attention ops (fused_attention / paged_attention) that fell back '
        'from the pallas TPU kernel to the XLA formulation, counted per '
        'compiled shape')
    g.set(float(_PALLAS_FALLBACKS['count']))


def _register_fallback_collector():
    try:
        from .. import observability as _obs
        _obs.registry.register_collector(_collect_pallas_fallback_gauge)
    except Exception:   # circular-import-safe: the gauge is best-effort
        pass


_register_fallback_collector()


@register_op('fused_attention')
def fused_attention(q, k, v, bias=None, *, sm_scale=1.0, causal=False):
    """Fused multi-head attention, (B, H, S, D) layout. On TPU this lowers
    to the pallas flash-attention kernel
    (jax.experimental.pallas.ops.tpu.flash_attention — online softmax, no
    S×S materialization, custom vjp); elsewhere (and for shapes the kernel
    rejects) it falls back to the XLA softmax(QKᵀ)V form that the compiler
    fuses. Measured on v5e (PERF.md §3): XLA wins on raw step time up to
    S=2048 (56-73 TF/s vs 13-26), so this op is NOT the default attention
    path — its value is the O(S) memory footprint for long-context configs
    where the S×S score tensor won't fit."""
    import jax as _jax
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    if _jax.default_backend() == 'tpu':
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention)
            # the kernel computes (QKᵀ + ab)·sm_scale; our contract is
            # QKᵀ·sm_scale + bias, so pre-divide the bias
            ab = None if bias is None else jnp.broadcast_to(
                jnp.asarray(bias) / float(sm_scale),
                q.shape[:3] + (k.shape[2],))
            return flash_attention(q, k, v, ab=ab, causal=causal,
                                   sm_scale=float(sm_scale))
        except Exception as e:   # kernel shape rejection → XLA fallback
            _pallas_fallback('fused_attention', e, q.shape)
    scores = jnp.einsum('bhqd,bhkd->bhqk', q, k) * sm_scale
    if bias is not None:
        scores = scores + jnp.asarray(bias)
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bhqk,bhkd->bhqd', probs, v)


@register_op('paged_attention')
def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    k_scales=None, v_scales=None, *,
                    sm_scale=1.0, pages_per_compute_block=4):
    """Single-token decode attention over a paged KV cache (the decode half
    of the serving decode engine — docs/SERVING.md "Stateful decode";
    kernel blueprint: Ragged Paged Attention, PAPERS.md arxiv 2604.15464).

    - ``q``: (S, H, D) — one query token per decode slot — or (S, H, K, D)
      for the MULTI-QUERY decode read speculative decoding verifies with
      (K fed tokens per slot in one step; see below).
    - ``k_pages`` / ``v_pages``: (H, num_blocks, block_size, D) — the cache
      pool. Block 0 is the scratch block (inactive slots point at it).
    - ``block_tables``: (S, max_blocks_per_seq) int32 — each slot's cache
      blocks in sequence order; tail entries beyond the context are
      arbitrary valid block ids (masked by ``context_lens``).
    - ``k_scales`` / ``v_scales``: optional (H, num_blocks, block_size)
      f32 — per-row dequant scales for int8 pools (PADDLE_TPU_KV_DTYPE=
      int8). Dequantization happens AFTER the per-slot gather, so only the
      slots' working set is ever materialized at f32; bf16 pools pass no
      scales and simply cast after the gather. Scale-zero rows (unwritten,
      incl. the scratch block) dequantize to exact zeros, preserving the
      masking contract below at every dtype.
    - ``context_lens``: (S,) int32 — tokens to attend per slot, INCLUDING
      the token written at position context_len-1 this step. In the
      multi-query form this is the extent of fed-token ROW 0; row j
      attends ``context_lens + j`` keys (a causal staircase over the K
      fed positions — row j sees the prior context plus fed tokens 0..j).

    On TPU this dispatches the pallas paged-attention kernel
    (jax.experimental.pallas.ops.tpu.paged_attention — ragged block walk,
    no dense gather); elsewhere (and on kernel rejection, counted via
    pallas_fallback_stats) the XLA fallback gathers the slot's blocks into
    a dense (S, H, T, D) view and runs the batched-matmul → mask →
    softmax → matmul sequence the unfused MultiHeadAttention path uses.
    Masked key positions get *exactly-zero* probability mass (the mask
    value underflows exp), and `jnp.matmul` rows are extent-independent on
    XLA CPU (measured; einsum dot_general is NOT), so a decode step is
    bitwise-identical to the matching row of a whole-sequence forward at
    the same padded key extent, and stale values in reused blocks can
    never bleed (0.0 × finite == 0.0)."""
    import jax as _jax
    q = jnp.asarray(q)
    k_pages = jnp.asarray(k_pages)
    v_pages = jnp.asarray(v_pages)
    block_tables = jnp.asarray(block_tables, jnp.int32)
    context_lens = jnp.asarray(context_lens, jnp.int32)
    if (_jax.default_backend() == 'tpu' and q.ndim == 3
            and k_pages.dtype == jnp.float32):
        # the stock pallas kernel is single-query over f32 pools; the
        # multi-query (S,H,K,D) verify read AND the quantized pools
        # (bf16/int8 payload needs the dequant-after-gather below) use the
        # XLA formulation on every backend until a ragged quantized kernel
        # lands (Ragged Paged Attention is the blueprint) — deliberate
        # dispatch, not counted as a pallas fallback
        try:
            from jax.experimental.pallas.ops.tpu.paged_attention import (
                paged_attention as _tpu_paged_attention)
            ppcb = min(int(pages_per_compute_block), block_tables.shape[1])
            return _tpu_paged_attention(
                q * jnp.asarray(sm_scale, q.dtype), k_pages, v_pages,
                context_lens, block_tables,
                pages_per_compute_block=max(ppcb, 1))
        except Exception as e:   # kernel shape rejection → XLA fallback
            _pallas_fallback('paged_attention', e, q.shape)
    if q.ndim == 4:
        # multi-query decode (speculative verify): K fed tokens per slot.
        # Same matmul → mask → softmax → matmul sequence as the
        # single-query path, so each row j is bitwise-identical to the
        # (S, 1) step that would have read the same K/V at extent
        # context_lens + j (the tests prove it across ragged extents).
        s, h, kq, d = q.shape
        k = _gather_pages(k_pages, block_tables, s, h, d, k_scales)
        v = _gather_pages(v_pages, block_tables, s, h, d, v_scales)
        t_pad = k.shape[2]
        scores = jnp.matmul(q, jnp.swapaxes(k, -1, -2))    # (S, H, K, T)
        if sm_scale != 1.0:
            scores = scores * jnp.asarray(sm_scale, scores.dtype)
        valid = jnp.arange(t_pad, dtype=jnp.int32)[None, None, None, :] \
            < (context_lens[:, None, None, None]
               + jnp.arange(kq, dtype=jnp.int32)[None, None, :, None])
        scores = jnp.where(valid, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.matmul(probs, v)                        # (S, H, K, D)
    s, h, d = q.shape
    k = _gather_pages(k_pages, block_tables, s, h, d, k_scales)
    v = _gather_pages(v_pages, block_tables, s, h, d, v_scales)
    t_pad = k.shape[2]
    # same op sequence as the unfused MHA path (matmul·α → mask → softmax
    # → matmul), q extent 1: bitwise-equal to the whole-sequence rows
    scores = jnp.matmul(q[:, :, None, :], jnp.swapaxes(k, -1, -2))
    if sm_scale != 1.0:
        scores = scores * jnp.asarray(sm_scale, scores.dtype)
    valid = jnp.arange(t_pad, dtype=jnp.int32)[None, None, None, :] \
        < context_lens[:, None, None, None]
    scores = jnp.where(valid, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.matmul(probs, v)
    return out.reshape(s, h, d)


def _gather_pages(pages, block_tables, s, h, d, scales=None):
    """(H, NB, BS, D) cache pool + (S, nbs) tables → dense (S, H, nbs·BS, D)
    per-slot key/value view (the XLA stand-in for the kernel's block walk).

    f32 pools pass through untouched (the bitwise-contract path). Quantized
    pools dequantize AFTER the gather — int8 payload × its per-row f32
    ``scales`` (gathered with the identical take/reshape/transpose, shape
    (S, H, nbs·BS)), bf16 payload a plain f32 cast — so the dense working
    set is f32 but the resident pool never is."""
    nb = block_tables.shape[1]
    bs = pages.shape[2]
    g = jnp.take(pages, block_tables.reshape(-1), axis=1)
    g = g.reshape(h, s, nb, bs, d).transpose(1, 0, 2, 3, 4)
    g = g.reshape(s, h, nb * bs, d)
    if scales is not None:
        sc = jnp.take(jnp.asarray(scales, jnp.float32),
                      block_tables.reshape(-1), axis=1)
        sc = sc.reshape(h, s, nb, bs).transpose(1, 0, 2, 3)
        return g.astype(jnp.float32) * sc.reshape(s, h, nb * bs)[..., None]
    if g.dtype != jnp.float32:
        return g.astype(jnp.float32)
    return g


@register_op('paged_prefill_attention')
def paged_prefill_attention(q, k, v, k_pages, v_pages, block_tables,
                            k_scales=None, v_scales=None, *,
                            sm_scale=1.0):
    """Prefill-phase attention for the decode engine: causal whole-prompt
    attention whose KEY EXTENT is the paged-cache view, so prefill rows are
    bitwise-identical to the decode steps (and to a whole-sequence forward
    at the engine's padded context length) that later attend to the same
    cache through `paged_attention`.

    - ``q``/``k``/``v``: (B, H, Lq, D) — the bucket-padded prompt's
      projections (the caller has ALREADY written k/v into the cache
      blocks; they are passed for the TPU kernel path, which attends the
      raw whole sequence without the gather).
    - ``k_pages``/``v_pages``/``block_tables``: the cache view, as in
      :func:`paged_attention` (tables (B, max_blocks_per_seq)).

    Row r attends keys 0..r (causal). Rows past the real prompt length are
    garbage-in-garbage-out: finite, never read, and overwritten by decode
    steps before any masked read could see them.

    ``k_scales``/``v_scales``: per-row dequant scales for int8 pools, as in
    :func:`paged_attention`. Quantized pools take the XLA gather+dequant
    path on every backend (the raw-k/v TPU kernel would attend the
    UN-quantized projections — bitwise-different from the decode steps that
    later read the quantized cache, breaking the prefill/decode parity the
    engine is built on)."""
    import jax as _jax
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    if (_jax.default_backend() == 'tpu'
            and jnp.asarray(k_pages).dtype == jnp.float32):
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention)
            return flash_attention(q, k, v, causal=True,
                                   sm_scale=float(sm_scale))
        except Exception as e:
            _pallas_fallback('paged_prefill_attention', e, q.shape)
    b, h, lq, d = q.shape
    kd = _gather_pages(jnp.asarray(k_pages),
                       jnp.asarray(block_tables, jnp.int32), b, h, d,
                       k_scales)
    vd = _gather_pages(jnp.asarray(v_pages),
                       jnp.asarray(block_tables, jnp.int32), b, h, d,
                       v_scales)
    t_pad = kd.shape[2]
    scores = jnp.matmul(q, jnp.swapaxes(kd, -1, -2))
    if sm_scale != 1.0:
        scores = scores * jnp.asarray(sm_scale, scores.dtype)
    causal = jnp.arange(t_pad, dtype=jnp.int32)[None, None, None, :] \
        <= jnp.arange(lq, dtype=jnp.int32)[None, None, :, None]
    scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.matmul(probs, vd)
