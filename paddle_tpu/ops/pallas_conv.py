"""TPU conv-efficiency kernels (PERF.md §1 "Where the ceiling is"):

1. `stem_space_to_depth` — the 7×7/s2 ResNet stem re-laid-out as a 4×4/s1
   conv on a 2×2 space-to-depth grid (input 224×224×3 → 112×115×12-ish).
   Bit-for-bit the same dot products, but the MXU sees 12 input channels
   instead of 3 and a stride-1 window instead of stride-2 — the standard
   MLPerf-class ResNet stem optimization, expressed in pure XLA ops.

2. `fused_conv1x1_bn_act` — pallas kernel fusing a 1×1 conv (a matmul on
   the MXU) with the BatchNorm affine and activation in the epilogue, so
   the conv output never round-trips to HBM between conv and BN. 1×1 convs
   are ~45% of ResNet-50's conv FLOPs (all bottleneck reduce/expand convs).
   Falls back to the equivalent XLA form off-TPU or on shape rejection.

Measured decisions pend TPU access (tools/bench_fused_conv.py is the
harness); both paths are exact-parity tested against the reference
formulation on CPU (pallas interpret mode).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op


# ---------------------------------------------------------------------------
# space-to-depth stem
# ---------------------------------------------------------------------------

@register_op('conv2d_stem_s2d')
def stem_space_to_depth(x, weight, *, data_format='NHWC'):
    """Equivalent of conv2d(x, weight, stride=2, padding=3) for a 7×7 HWIO
    `weight` (the NHWC conv weight layout), NHWC `x` — via 2×2
    space-to-depth.

    Derivation (per spatial axis): y[i] = Σ_{k=0..7} xp[2i+k]·w8[k] with
    xp = pad(x, (4, 2)) and w8 = [0, w0..w6] (zero tap in FRONT aligns the
    even grid: pad-left 4 = original pad 3 + the shift the zero tap
    absorbs). Writing k = 2t+r splits the sum over the s2d channel r and a
    4-tap stride-1 window t on the half-resolution grid.
    """
    if data_format != 'NHWC':
        raise ValueError('stem_space_to_depth requires NHWC')
    x = jnp.asarray(x)
    w = jnp.asarray(weight)           # HWIO, 7×7
    if w.shape[:2] != (7, 7):
        raise ValueError(f'stem kernel must be 7x7 HWIO, got {w.shape}')
    from .nn_ops import _match_weight_dtype
    x = _match_weight_dtype(x, w)     # same AMP rule as conv2d: x → w.dtype
    n, h, hw, c = x.shape
    o = w.shape[-1]
    # zero tap in front → 8×8, then split even/odd taps
    w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
    # W2[tH, tW, rH·2C + rW·C + c, o] = w8[2tH+rH, 2tW+rW, c, o]
    w2 = w8.reshape(4, 2, 4, 2, c, o)
    w2 = w2.transpose(0, 2, 1, 3, 4, 5)          # tH tW rH rW c o
    w2 = w2.reshape(4, 4, 4 * c, o)              # HWIO, I = rH·rW·c packed
    # output size of conv(k=7, s=2, p=3); padded length 2·out+6 keeps the
    # last window in range and the s2d grid even for any input parity
    h_out, w_out = (h - 1) // 2 + 1, (hw - 1) // 2 + 1
    pad_h, pad_w = 2 * h_out + 2 - h, 2 * w_out + 2 - hw
    xp = jnp.pad(x, ((0, 0), (4, pad_h), (4, pad_w), (0, 0)))
    h2, w2dim = h_out + 3, w_out + 3
    xs = xp.reshape(n, h2, 2, w2dim, 2, c).transpose(0, 1, 3, 2, 4, 5)
    xs = xs.reshape(n, h2, w2dim, 4 * c)         # channel = rH·2C + rW·C + c
    dn = jax.lax.conv_dimension_numbers(xs.shape, w2.shape,
                                        ('NHWC', 'HWIO', 'NHWC'))
    return jax.lax.conv_general_dilated(
        xs, w2, window_strides=(1, 1), padding='VALID',
        dimension_numbers=dn,
        preferred_element_type=x.dtype if x.dtype == jnp.float32 else None)


# ---------------------------------------------------------------------------
# pallas fused 1×1 conv + BN affine + activation
# ---------------------------------------------------------------------------

_PALLAS_FALLBACK_WARNED = False

def _fused_kernel(x_ref, w_ref, scale_ref, shift_ref, o_ref, *, act):
    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=jnp.float32)
    y = acc * scale_ref[...] + shift_ref[...]
    if act == 'relu':
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def _pallas_matmul_affine(x2d, w, scale, shift, act, out_dtype,
                          interpret=False, bm=256, bn=128):
    from jax.experimental import pallas as pl
    m, k = x2d.shape
    ko, n = w.shape
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    return pl.pallas_call(
        functools.partial(_fused_kernel, act=act),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
    )(x2d, w, scale.reshape(1, -1), shift.reshape(1, -1))


@register_op('fused_conv1x1_bn_act')
def fused_conv1x1_bn_act(x, weight, scale, shift, *, act=None,
                         data_format='NHWC', force_pallas=None):
    """out = act((x ⊛ weight) * scale + shift) for a 1×1 HWIO weight (the
    NHWC conv weight layout), NHWC x. scale/shift are the folded BN affine
    (γ/√(σ²+ε), β − μ·that) — inference mode, or training mode after the
    stats pass.

    TPU: one pallas matmul with the affine+act in the epilogue (the conv
    output never hits HBM unnormalized). Elsewhere: the same math in XLA.
    """
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    if data_format != 'NHWC':
        raise ValueError('fused_conv1x1_bn_act requires NHWC')
    if w.shape[:2] != (1, 1):
        raise ValueError(f'kernel must be 1x1 HWIO, got {w.shape}')
    from .nn_ops import _match_weight_dtype
    x = _match_weight_dtype(x, w)     # same AMP rule as conv2d: x → w.dtype
    scale = jnp.asarray(scale, x.dtype)
    shift = jnp.asarray(shift, x.dtype)
    n, h, hw, c = x.shape
    o = w.shape[-1]
    w2d = w.reshape(c, o)                         # (C, O)
    use_pallas = force_pallas if force_pallas is not None else \
        jax.default_backend() == 'tpu'
    if use_pallas:
        if force_pallas:
            # explicit request (tests, benches): a broken kernel must FAIL,
            # not silently measure/verify the XLA fallback
            y = _pallas_matmul_affine(
                x.reshape(-1, c), w2d, scale, shift, act, x.dtype,
                interpret=jax.default_backend() != 'tpu')
            return y.reshape(n, h, hw, o)
        try:
            y = _pallas_matmul_affine(
                x.reshape(-1, c), w2d, scale, shift, act, x.dtype,
                interpret=jax.default_backend() != 'tpu')
            return y.reshape(n, h, hw, o)
        except Exception as e:  # auto mode: shape rejection → XLA fallback
            global _PALLAS_FALLBACK_WARNED
            if not _PALLAS_FALLBACK_WARNED:
                _PALLAS_FALLBACK_WARNED = True
                import logging
                logging.getLogger(__name__).warning(
                    "fused_conv1x1_bn_act: pallas kernel unavailable for "
                    "x%s (%s: %s); falling back to XLA conv+affine",
                    tuple(x.shape), type(e).__name__, str(e)[:200])
    y = jnp.einsum('nhwc,co->nhwo', x, w2d) * scale + shift
    if act == 'relu':
        y = jnp.maximum(y, 0.0)
    return y
