"""Sequence (LoD) ops on padded batches with explicit lengths.

Parity targets: /root/reference/paddle/fluid/operators/sequence_ops/* and
python/paddle/fluid/layers/sequence_lod.py. The reference stores ragged
batches as LoD tensors (flattened rows + offset table); the TPU formulation
is a padded (B, T, ...) tensor + a (B,) length vector — static shapes, MXU
friendly, maskable. Every op takes `length=None` meaning "all rows full".

Valid data is always LEFT-PACKED: row b occupies steps [0, length[b]).
"""
from __future__ import annotations

import jax
from ..core.dtypes import runtime_int64 as _i64
import jax.numpy as jnp

from .registry import register_op


def _lens(x, length):
    B, T = x.shape[0], x.shape[1]
    if length is None:
        return jnp.full((B,), T, jnp.int32)
    return jnp.asarray(length).reshape(B).astype(jnp.int32)


def _time_mask(x, length):
    """(B, T) bool validity mask."""
    B, T = x.shape[0], x.shape[1]
    return jnp.arange(T)[None, :] < _lens(x, length)[:, None]


@register_op('sequence_mask')
def sequence_mask(x, *, maxlen=-1, dtype='int64'):
    """x: (B,) lengths → (B, maxlen) 0/1 mask (ref: sequence_mask_op.h)."""
    x = jnp.asarray(x).reshape(-1)
    maxlen = int(maxlen)
    out = jnp.arange(maxlen)[None, :] < x[:, None]
    from ..core.dtypes import to_jax_dtype
    return out.astype(to_jax_dtype(dtype))


@register_op('sequence_softmax')
def sequence_softmax(x, length=None):
    """Masked softmax over the time dim. x: (B, T) or (B, T, 1)."""
    x = jnp.asarray(x)
    squeeze = (x.ndim == 3 and x.shape[-1] == 1)
    v = x[..., 0] if squeeze else x
    mask = _time_mask(v, length)
    v = jnp.where(mask, v, -jnp.inf)
    out = jax.nn.softmax(v, axis=1)
    out = jnp.where(mask, out, 0.0)
    return out[..., None] if squeeze else out


@register_op('sequence_pool', outputs=('Out', 'MaxIndex'))
def sequence_pool(x, length=None, *, pool_type='average', pad_value=0.0):
    """(B, T, D) → (B, D) pooled over valid steps (ref: sequence_pool_op.h).
    Empty rows get pad_value. Also returns argmax index (for 'max')."""
    x = jnp.asarray(x)
    mask = _time_mask(x, length)[:, :, None]
    lens = _lens(x, length)
    pt = pool_type.lower()
    if pt in ('sum', 'average', 'sqrt'):
        s = jnp.sum(jnp.where(mask, x, 0.0), axis=1)
        denom = jnp.maximum(lens, 1).astype(x.dtype)[:, None]
        if pt == 'average':
            s = s / denom
        elif pt == 'sqrt':
            s = s / jnp.sqrt(denom)
        out = s
        idx = jnp.zeros((x.shape[0], x.shape[2]), _i64())
    elif pt == 'max':
        neg = jnp.where(mask, x, -jnp.inf)
        out = jnp.max(neg, axis=1)
        idx = jnp.argmax(neg, axis=1).astype(_i64())
    elif pt == 'min':
        out = jnp.min(jnp.where(mask, x, jnp.inf), axis=1)
        idx = jnp.zeros((x.shape[0], x.shape[2]), _i64())
    elif pt in ('first', 'last'):
        t = jnp.zeros_like(lens) if pt == 'first' \
            else jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(x, t[:, None, None].astype(jnp.int32),
                                  axis=1)[:, 0]
        idx = jnp.broadcast_to(t[:, None], (x.shape[0], x.shape[2]))
        idx = idx.astype(_i64())
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    empty = (lens == 0)[:, None]
    out = jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)
    return out, idx


@register_op('sequence_reverse')
def sequence_reverse(x, length=None):
    """Reverse each valid prefix, padding stays (ref: sequence_reverse_op.h)."""
    from .rnn_ops import _flip_padded
    x = jnp.asarray(x)
    if length is None:
        return jnp.flip(x, axis=1)
    return _flip_padded(x, _lens(x, length))


@register_op('sequence_concat', outputs=('Out', 'OutLen'), variadic=('xs',))
def sequence_concat(xs, lens=None, *, n_inputs=0):
    """Concat per-row valid prefixes of several padded batches, left-packing
    the result (ref: sequence_concat_op.h). lens: list matching xs or None."""
    xs = [jnp.asarray(x) for x in xs]
    B = xs[0].shape[0]
    lens_list = [_lens(x, None if lens is None else lens[i])
                 for i, x in enumerate(xs)]
    T_out = sum(x.shape[1] for x in xs)
    feat = xs[0].shape[2:]
    out = jnp.zeros((B, T_out) + feat, xs[0].dtype)
    offset = jnp.zeros((B,), jnp.int32)
    b_idx = jnp.arange(B)[:, None]
    for x, ln in zip(xs, lens_list):
        T = x.shape[1]
        t_idx = jnp.arange(T)[None, :]
        valid = t_idx < ln[:, None]
        tgt = offset[:, None] + t_idx
        tgt = jnp.where(valid, tgt, T_out)  # dump slot (dropped by mode)
        out = out.at[b_idx, tgt].set(x, mode='drop')
        offset = offset + ln
    return out, offset


@register_op('sequence_pad', outputs=('Out', 'Length'))
def sequence_pad(x, pad_value, length=None, *, maxlen=-1):
    """Pad/truncate to maxlen, writing pad_value into invalid slots
    (ref: sequence_pad_op.h)."""
    x = jnp.asarray(x)
    pad = jnp.asarray(pad_value, x.dtype)
    T = x.shape[1]
    maxlen = T if maxlen in (-1, None) else int(maxlen)
    lens = jnp.minimum(_lens(x, length), maxlen)
    if maxlen > T:
        cfg = [(0, 0, 0), (0, maxlen - T, 0)] + [(0, 0, 0)] * (x.ndim - 2)
        x = jax.lax.pad(x, jnp.asarray(0, x.dtype), cfg)
    elif maxlen < T:
        x = x[:, :maxlen]
    mask = jnp.arange(maxlen)[None, :] < lens[:, None]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    return jnp.where(mask, x, pad), lens.astype(_i64())


@register_op('sequence_unpad')
def sequence_unpad(x, length):
    """Zero out positions past each row's length (dense inverse of pad)."""
    x = jnp.asarray(x)
    mask = _time_mask(x, length)
    return jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)), x, 0.0)


@register_op('sequence_reshape', outputs=('Out', 'OutLen'))
def sequence_reshape(x, length=None, *, new_dim):
    """Per-row ragged reshape: row of len*D elems → len*D/new_dim rows of
    new_dim (ref: sequence_reshape_op.h). Works because valid data is
    left-packed; padding must be zero."""
    x = jnp.asarray(x)
    B, T, D = x.shape
    lens = _lens(x, length)
    mask = _time_mask(x, length)[:, :, None]
    x = jnp.where(mask, x, 0.0)
    T_new = T * D // new_dim
    out = x.reshape(B, T_new, new_dim)
    new_lens = (lens * D) // new_dim
    return out, new_lens.astype(_i64())


@register_op('sequence_slice', outputs=('Out', 'OutLen'))
def sequence_slice(x, offset, slice_length, length=None):
    """Per-row slice [offset, offset+slice_length), left-packed
    (ref: sequence_slice_op.h)."""
    x = jnp.asarray(x)
    B, T = x.shape[0], x.shape[1]
    off = jnp.asarray(offset).reshape(B).astype(jnp.int32)
    sl = jnp.asarray(slice_length).reshape(B).astype(jnp.int32)
    t_idx = jnp.arange(T)[None, :]
    src = jnp.clip(off[:, None] + t_idx, 0, T - 1)
    gathered = jnp.take_along_axis(
        x, src.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)
    valid = t_idx < sl[:, None]
    valid = valid.reshape((B, T) + (1,) * (x.ndim - 2))
    return jnp.where(valid, gathered, 0.0), sl.astype(_i64())


@register_op('sequence_expand_as')
def sequence_expand_as(x, y, y_length=None):
    """Broadcast each row's FIRST valid step of x across y's valid steps
    (ref: sequence_expand_as_op.h — dense broadcast case; general LoD
    re-batching is not static-shape representable)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    first = x[:, 0] if x.ndim >= 3 else x  # (B, D) or (B,)
    if first.ndim == 1:
        first = first[:, None]
    out = jnp.broadcast_to(first[:, None, :],
                           (y.shape[0], y.shape[1], first.shape[-1]))
    mask = _time_mask(y, y_length)[:, :, None]
    return jnp.where(mask, out, 0.0)


@register_op('sequence_enumerate')
def sequence_enumerate(x, length=None, *, win_size, pad_value=0):
    """(B, T) ids → (B, T, win) sliding windows, pad past row end
    (ref: sequence_enumerate_op.h)."""
    x = jnp.asarray(x)
    B, T = x.shape[0], x.shape[1]
    lens = _lens(x, length)
    t = jnp.arange(T)[None, :, None]
    w = jnp.arange(win_size)[None, None, :]
    src = t + w                                       # (1, T, win)
    valid = src < lens[:, None, None]
    src = jnp.clip(src, 0, T - 1)
    gathered = x[jnp.arange(B)[:, None, None],
                 jnp.broadcast_to(src, (B, T, win_size))]
    return jnp.where(valid, gathered, jnp.asarray(pad_value, x.dtype))


@register_op('sequence_scatter')
def sequence_scatter(x, index, updates, length=None):
    """out[b, index[b,t]] += updates[b,t] for valid t
    (ref: sequence_scatter_op.h)."""
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    updates = jnp.asarray(updates)
    B = x.shape[0]
    mask = _time_mask(index, length)
    upd = jnp.where(mask, updates, 0.0)
    b_idx = jnp.arange(B)[:, None]
    return x.at[b_idx, index].add(upd)


@register_op('sequence_conv')
def sequence_conv(x, w, bias=None, length=None, *, context_length=3,
                  context_start=None, padding=True):
    """Context-window conv over time (ref: sequence_conv_op.h): gather the
    window [t+start, t+start+len) per step (zeros outside the valid prefix),
    flatten to (B, T, len*D), then one MXU matmul with w (len*D, F)."""
    x = jnp.asarray(x)
    B, T, D = x.shape
    start = -((context_length - 1) // 2) if context_start is None \
        else context_start
    lens = _lens(x, length)
    cols = []
    t_idx = jnp.arange(T)[None, :]
    for k in range(context_length):
        src = t_idx + start + k
        valid = (src >= 0) & (src < lens[:, None])
        srcc = jnp.clip(src, 0, T - 1)
        g = jnp.take_along_axis(x, srcc[:, :, None], axis=1)
        cols.append(jnp.where(valid[:, :, None], g, 0.0))
    ctx = jnp.concatenate(cols, axis=-1)              # (B, T, len*D)
    out = ctx @ jnp.asarray(w)
    if bias is not None:
        out = out + bias
    mask = _time_mask(x, length)[:, :, None]
    return jnp.where(mask, out, 0.0)
