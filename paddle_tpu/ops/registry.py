"""Op registry: name → jax functional implementation.

The TPU-native replacement for the reference's OpKernel registry
(/root/reference/paddle/fluid/framework/op_registry.h). Each op is ONE pure
jax function; its gradient comes from jax.vjp (no hand-written GradOpMaker),
its shape inference from jax.eval_shape (no hand-written InferShape).

Conventions:
- positional parameters of the functional = input slots, in order;
- keyword-only parameters = attrs;
- ops needing randomness take a keyword-only `key` (jax PRNG key) and are
  registered with needs_rng=True;
- default output slot list is ['Out']; multi-output ops declare their slots.
- a slot named in `variadic` receives a Python list of arrays (e.g. concat).
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional, Sequence

_REGISTRY: Dict[str, 'OpDef'] = {}

# program-level bookkeeping attrs that must NEVER reach an op kernel's
# kwargs (filtered by the executor run path, shape inference, the pipeline
# isomorphism signature, and the debugger printer alike). '_rng_salt' is
# the IR pass pipeline's stamp of an op's pre-rewrite position — the
# lowering folds the step key with it so removing/fusing ops never shifts
# a surviving op's random stream (ir/pass_base.py).
NON_KERNEL_ATTRS = frozenset({'initializer', 'op_device', '_rng_salt'})


class OpDef:
    def __init__(self, name: str, fn: Callable, input_slots: List[str],
                 output_slots: List[str], variadic: frozenset,
                 needs_rng: bool, optional: frozenset,
                 atomic_output: bool = False):
        self.name = name
        self.fn = fn
        self.input_slots = input_slots
        self.output_slots = output_slots
        self.variadic = variadic
        self.needs_rng = needs_rng
        self.optional = optional
        # atomic_output: the single 'Out' result is one value even if it is a
        # Python list (TensorArray) — never fan it out across output names.
        self.atomic_output = atomic_output

    def __repr__(self):
        return f"OpDef({self.name}, in={self.input_slots}, out={self.output_slots})"


def register_op(name: str, outputs: Sequence[str] = ('Out',),
                variadic: Sequence[str] = (), needs_rng: bool = False,
                atomic_output: bool = False, optional: Sequence[str] = ()):
    """Decorator registering a jax functional as a graph op.

    `optional` explicitly marks input slots the kernel tolerates as None
    when a `=None` default is impossible positionally (e.g. lstm's h0/c0
    precede required weight slots). The static verifier
    (paddle_tpu/analysis/) reads this metadata: a non-optional slot left
    empty at program build is a 'missing-input' diagnostic."""

    def deco(fn):
        sig = inspect.signature(fn)
        input_slots, opt = [], set(optional)
        for pname, p in sig.parameters.items():
            if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                          inspect.Parameter.POSITIONAL_OR_KEYWORD):
                input_slots.append(pname)
                if p.default is None:
                    opt.add(pname)
            # keyword-only params are attrs (incl. `key` for rng ops)
        unknown = opt - set(input_slots)
        if unknown:
            raise ValueError(
                f"op {name!r}: optional={sorted(unknown)} are not input "
                f"slots (slots: {input_slots})")
        if name in _REGISTRY:
            raise ValueError(f"op {name!r} registered twice")
        _REGISTRY[name] = OpDef(name, fn, input_slots, list(outputs),
                                frozenset(variadic), needs_rng,
                                frozenset(opt), atomic_output)
        return fn

    return deco


def get_op(name: str) -> OpDef:
    if name not in _REGISTRY:
        raise KeyError(f"unknown op type {name!r}; registered: "
                       f"{sorted(_REGISTRY)[:20]}...")
    return _REGISTRY[name]


def has_op(name: str) -> bool:
    return name in _REGISTRY


def all_ops():
    return dict(_REGISTRY)


def custom_op(name: str, outputs: Sequence[str] = ('Out',), **kw):
    """py_func / custom-op escape hatch (ref: fluid.layers.py_func,
    python/paddle/fluid/layers/nn.py:12864): register any jax-traceable python
    function as a graph op usable from both static layers and dygraph."""
    return register_op(name, outputs=outputs, **kw)
