"""Sparse embedding fast path: rows-only gradients + scatter-apply updates.

The Fluid reference's parameter-server half (SelectedRows gradients,
``lookup_table(is_sparse=True)``, sparse SGD/Adagrad/Adam) exchanged and
applied embedding gradients as (rows, values) pairs — O(nnz·D) for a V×D
table instead of the O(V·D) dense scatter-add jax.vjp produces. This
module is the TPU-native reconstruction (ROADMAP item 5):

- **Padded COO**: a gradient is ``(rows int32 (K,), vals f32 (K, D))``
  where ``K`` is a compile-stable rung of the nnz **bucket ladder**
  (powers of two, floor ``PADDLE_TPU_SPARSE_NNZ_BUCKET``). Pad entries
  carry ``rows == vocab`` (an out-of-range sentinel) and zero vals; XLA
  scatter drops out-of-bounds updates, so padding is free at apply time.
- **Coalescing**: ``coalesce_rows`` dedups occurrences with
  ``jnp.unique(size=K)`` + ``segment_sum`` — fixed output shapes, so the
  number of compiled variants is bounded by the ladder, not the data.
- **Updates**: ``sparse_sgd`` / ``sparse_momentum`` / ``sparse_adagrad``
  / ``sparse_adam`` gather the touched slot rows, apply the dense
  formula on K rows, and scatter the results back (``mode='drop'``).
  ``sparse_adam`` is the reference's lazy mode: moments advance only on
  touched rows; the beta-power schedule advances globally per step.
- **Dygraph**: :class:`SparseRowsGrad` is the tape's gradient carrier —
  a registered pytree with the accumulation algebra ``backward()`` needs
  (sparse+sparse re-coalesces, sparse+dense densifies).

Knobs (strict parse, README table): ``PADDLE_TPU_SPARSE_GRAD`` (``1``
default; ``0`` restores the dense-scatter legacy path everywhere),
``PADDLE_TPU_SPARSE_NNZ_BUCKET`` (ladder floor, default 64),
``PADDLE_TPU_EMBED_OOB`` ∈ {error, clip} (out-of-range-id policy of the
validation layers; the kernels always clip — docs/SPARSE.md).

Always-on ``sparse_*`` metrics (docs/OBSERVABILITY.md): like serving,
the interesting consumers (bench, fleet dashboards) must see rows/step
and dedup without PADDLE_TPU_TELEMETRY, and the increments are host-side
noise next to a device step.
"""
from __future__ import annotations

import os
import threading

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op
from ..observability import registry as _registry

__all__ = ['sparse_grad_enabled', 'nnz_bucket', 'bucket_floor',
           'oob_policy', 'coalesce_rows', 'flatten_ids', 'SparseRowsGrad',
           'site_value', 'site_context', 'SPARSE_UPDATE_OPS',
           'record_sparse_lookup', 'sparse_metrics_snapshot']

ENV_SPARSE_GRAD = 'PADDLE_TPU_SPARSE_GRAD'
ENV_NNZ_BUCKET = 'PADDLE_TPU_SPARSE_NNZ_BUCKET'
ENV_EMBED_OOB = 'PADDLE_TPU_EMBED_OOB'

# dense optimizer op type → its rows-only counterpart (optimizer.py
# consults this to emit/apply sparse updates; unsupported types raise
# naming this set)
SPARSE_UPDATE_OPS = {
    'sgd': 'sparse_sgd',
    'momentum': 'sparse_momentum',
    'adagrad': 'sparse_adagrad',
    'adam': 'sparse_adam',
}


def sparse_grad_enabled():
    """Whether ``lookup_table(is_sparse=True)`` takes the rows-only
    gradient path. Strict parse: only '0'/'1' are accepted."""
    v = os.environ.get(ENV_SPARSE_GRAD, '1')
    if v not in ('0', '1'):
        raise ValueError(
            f"{ENV_SPARSE_GRAD}={v!r} invalid (supported: 0, 1)")
    return v == '1'


def bucket_floor():
    """Smallest nnz-bucket rung (strict-parse positive int env knob)."""
    v = os.environ.get(ENV_NNZ_BUCKET, '64')
    try:
        n = int(v)
    except ValueError:
        n = -1
    if n < 1:
        raise ValueError(
            f"{ENV_NNZ_BUCKET}={v!r} invalid (expected a positive int)")
    return n


def oob_policy():
    """Out-of-range embedding-id policy of the VALIDATION layers (serving
    validate(), PADDLE_TPU_VERIFY=full feed checks): 'error' rejects the
    request/feed, 'clip' is the legacy escape hatch (ids silently clip to
    row V-1 on device, exactly the pre-PR behavior)."""
    v = os.environ.get(ENV_EMBED_OOB, 'error')
    if v not in ('error', 'clip'):
        raise ValueError(
            f"{ENV_EMBED_OOB}={v!r} invalid (supported: error, clip)")
    return v


def nnz_bucket(nnz):
    """Ladder rung for ``nnz`` id occurrences: smallest power-of-two
    multiple of the floor that is >= nnz. Compile count per (table,
    feed-signature family) is bounded by the ladder's log2 span."""
    b = bucket_floor()
    n = max(int(nnz), 1)
    while b < n:
        b *= 2
    return b


def flatten_ids(ids):
    """The kernel's id normalization (lookup_table squeezes a trailing
    (…, 1) LoD column), flattened to 1-D int32."""
    ids = jnp.asarray(ids)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return ids.reshape(-1).astype(jnp.int32)


def coalesce_rows(ids, vals, vocab, bucket=None):
    """Dedup per-occurrence gradients into padded COO.

    ``ids`` (N,) int, ``vals`` (N, D) → ``(rows (K,) int32, out (K, D))``
    with K a ladder rung (or the explicit ``bucket``). Occurrence ids are
    clipped to [0, vocab-1] first — the exact rows the legacy dense
    gather trained — so sparse-vs-dense parity holds even for bad ids;
    pad entries get ``rows == vocab`` and zero vals (dropped by the
    scatter at apply time)."""
    ids = jnp.asarray(ids).reshape(-1).astype(jnp.int32)
    vals = jnp.asarray(vals)
    vals = vals.reshape(ids.shape[0], -1)
    k = int(bucket) if bucket is not None else nnz_bucket(ids.shape[0])
    clipped = jnp.clip(ids, 0, vocab - 1)
    rows, inv = jnp.unique(clipped, size=k, fill_value=vocab,
                           return_inverse=True)
    out = jax.ops.segment_sum(vals, inv.reshape(-1), num_segments=k)
    # fill rows (== vocab) may alias a real segment only when unique
    # overflows k, which cannot happen: k >= nnz >= unique count
    return rows, out


def _occupied(rows, vocab):
    """Number of non-pad COO entries (traced-safe)."""
    return jnp.sum((jnp.asarray(rows) < vocab).astype(jnp.int32))


# ---------------------------------------------------------------------------
# dygraph gradient carrier
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class SparseRowsGrad:
    """Rows-only gradient of an embedding table: padded COO plus the
    table geometry. Supports the tape's accumulation algebra (``+``) and
    densification (the correctness escape hatch)."""

    def __init__(self, rows, vals, vocab, dim):
        self.rows = rows
        self.vals = vals
        self.vocab = int(vocab)
        self.dim = int(dim)

    # pytree protocol: rows/vals are leaves, geometry is static
    def tree_flatten(self):
        return (self.rows, self.vals), (self.vocab, self.dim)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    @property
    def shape(self):
        return (self.vocab, self.dim)

    @property
    def dtype(self):
        return jnp.asarray(self.vals).dtype

    @property
    def nnz(self):
        return int(self.rows.shape[0])

    def densify(self):
        """(vocab, dim) dense gradient — the legacy representation."""
        dense = jnp.zeros((self.vocab, self.dim), self.vals.dtype)
        return dense.at[self.rows].add(self.vals, mode='drop')

    def coalesced(self, bucket=None):
        rows, vals = coalesce_rows(self.rows, self.vals, self.vocab,
                                   bucket=bucket)
        return SparseRowsGrad(rows, vals, self.vocab, self.dim)

    def __add__(self, other):
        if isinstance(other, SparseRowsGrad):
            if (other.vocab, other.dim) != (self.vocab, self.dim):
                raise ValueError(
                    f'cannot accumulate sparse grads of tables '
                    f'{(self.vocab, self.dim)} vs {(other.vocab, other.dim)}')
            rows = jnp.concatenate([self.rows, other.rows])
            vals = jnp.concatenate([jnp.asarray(self.vals),
                                    jnp.asarray(other.vals)])
            r, v = coalesce_rows(rows, vals, self.vocab)
            return SparseRowsGrad(r, v, self.vocab, self.dim)
        if other is None:
            return self
        # mixed sparse + dense (e.g. the same table also read densely):
        # correctness first — densify
        return self.densify() + jnp.asarray(other)

    __radd__ = __add__

    def __repr__(self):
        return (f'SparseRowsGrad(rows={self.rows.shape[0]}, '
                f'table=({self.vocab}, {self.dim}))')


# ---------------------------------------------------------------------------
# static-path surrogate plumbing (executor._lower <-> lookup_table kernel)
# ---------------------------------------------------------------------------
#
# The backward marker lowers to ONE jax.value_and_grad over the parameter
# dict; a dense table in that dict backprops a V×D scatter. Instead,
# append_backward moves sparse tables OUT of the dense param list and
# _lower adds one zero-valued (nnz, D) SURROGATE per lookup site. The
# lookup kernel adds the surrogate to its gathered rows (exact: +0.0), so
# d loss/d surrogate is the per-occurrence row cotangent — O(nnz·D) —
# and the table itself is a non-differentiated constant. The surrogate
# tracers only exist inside the traced forward, so they reach the kernel
# through this thread-local context, keyed by the op's `_sparse_site`
# attr (set while the whole value_and_grad call runs; remat replays of a
# checkpointed segment re-read it).

_SITE_CTX = threading.local()


class site_context:
    """Bind ``{site_key: surrogate tracer}`` for the current trace."""

    def __init__(self, values):
        self._values = values

    def __enter__(self):
        stack = getattr(_SITE_CTX, 'stack', None)
        if stack is None:
            stack = _SITE_CTX.stack = []
        stack.append(self._values)
        return self

    def __exit__(self, *exc):
        _SITE_CTX.stack.pop()


def site_value(key):
    """The bound surrogate for ``key``, or None outside a sparse trace
    (eval clones, inference programs, PADDLE_TPU_SPARSE_GRAD=0 runs)."""
    stack = getattr(_SITE_CTX, 'stack', None)
    if not stack:
        return None
    for values in reversed(stack):
        if key in values:
            return values[key]
    return None


# ---------------------------------------------------------------------------
# rows-only update ops (static graph; the dygraph step calls the same fns)
# ---------------------------------------------------------------------------

def _prep(param, rows, vals):
    p = jnp.asarray(param)
    r = jnp.asarray(rows).astype(jnp.int32)
    v = jnp.asarray(vals).astype(p.dtype)
    return p, r, v


@register_op('sparse_sgd', outputs=['ParamOut'])
def sparse_sgd(param, rows, vals, lr):
    """SGD over touched rows only (ref: sgd_op.h SelectedRows branch)."""
    p, r, v = _prep(param, rows, vals)
    return p.at[r].add(-jnp.asarray(lr) * v, mode='drop')


@register_op('sparse_momentum', outputs=['ParamOut', 'VelocityOut'])
def sparse_momentum(param, rows, vals, velocity, lr, *, mu=0.9,
                    use_nesterov=False):
    """Lazy momentum: velocity rows decay+accumulate only when touched."""
    p, r, v = _prep(param, rows, vals)
    vel = jnp.asarray(velocity)
    vel_rows = vel[jnp.clip(r, 0, p.shape[0] - 1)]
    vel_new = mu * vel_rows + v
    lr = jnp.asarray(lr)
    if use_nesterov:
        step = (v + mu * vel_new) * lr
    else:
        step = lr * vel_new
    return (p.at[r].add(-step, mode='drop'),
            vel.at[r].set(vel_new, mode='drop'))


@register_op('sparse_adagrad', outputs=['ParamOut', 'MomentOut'])
def sparse_adagrad(param, rows, vals, moment, lr, *, epsilon=1e-6):
    """Adagrad over touched rows (ref: adagrad_op.h SelectedRows branch)."""
    p, r, v = _prep(param, rows, vals)
    m = jnp.asarray(moment)
    m_rows = m[jnp.clip(r, 0, p.shape[0] - 1)]
    m_new = m_rows + jnp.square(v)
    step = jnp.asarray(lr) * v / (jnp.sqrt(m_new) + epsilon)
    return (p.at[r].add(-step, mode='drop'),
            m.at[r].set(m_new, mode='drop'))


@register_op('sparse_adam', outputs=['ParamOut', 'Moment1Out', 'Moment2Out',
                                     'Beta1PowOut', 'Beta2PowOut'])
def sparse_adam(param, rows, vals, moment1, moment2, beta1_pow, beta2_pow,
                lr, *, beta1=0.9, beta2=0.999, epsilon=1e-8):
    """Lazy Adam (ref: adam_op.h SelectedRows branch, lazy_mode=True):
    touched rows update their moments and step; untouched rows keep stale
    moments; the bias-correction powers advance globally every step."""
    p, r, v = _prep(param, rows, vals)
    m1, m2 = jnp.asarray(moment1), jnp.asarray(moment2)
    b1p, b2p = jnp.asarray(beta1_pow), jnp.asarray(beta2_pow)
    safe = jnp.clip(r, 0, p.shape[0] - 1)
    m1_new = beta1 * m1[safe] + (1 - beta1) * v
    m2_new = beta2 * m2[safe] + (1 - beta2) * jnp.square(v)
    lr_t = jnp.asarray(lr) * jnp.sqrt(1 - b2p) / (1 - b1p)
    step = lr_t * m1_new / (jnp.sqrt(m2_new) + epsilon)
    return (p.at[r].add(-step, mode='drop'),
            m1.at[r].set(m1_new, mode='drop'),
            m2.at[r].set(m2_new, mode='drop'),
            b1p * beta1, b2p * beta2)


# ---------------------------------------------------------------------------
# always-on sparse_* metrics (serving/metrics.py convention: resolve
# through the registry per use so registry.reset() cannot orphan them)
# ---------------------------------------------------------------------------

def record_sparse_lookup(nnz, bucket, dedup_rows=None, table=''):
    """One sparse-gradient emission: raw id occurrences, the padded
    bucket they coalesced into, and (when the caller knows it host-side)
    the deduped row count — dedup ratio = ids / rows."""
    _registry.counter(
        'sparse_lookup_ids_total',
        'raw id occurrences feeding rows-only embedding gradients').inc(
            float(nnz))
    _registry.counter(
        'sparse_grad_rows_total',
        'padded COO rows emitted per step (the bucket-ladder rung)').inc(
            float(bucket))
    _registry.gauge(
        'sparse_nnz_bucket',
        'current nnz bucket rung by table').labels(table=table).set(
            float(bucket))
    if dedup_rows is not None and dedup_rows > 0:
        _registry.gauge(
            'sparse_dedup_ratio',
            'id occurrences per unique row in the last coalesce '
            '(higher = more duplicate-id traffic saved)').labels(
                table=table).set(float(nnz) / float(dedup_rows))


def sparse_metrics_snapshot():
    """Test/report helper: current sparse_* counter values."""
    return {name: _registry.counter(name, '').value
            for name in ('sparse_lookup_ids_total',
                         'sparse_grad_rows_total')}
