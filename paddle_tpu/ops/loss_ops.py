"""Loss ops.

Parity targets: reference paddle/fluid/operators/{cross_entropy,softmax_with_
cross_entropy,sigmoid_cross_entropy_with_logits,squared_l2_distance,smooth_l1,
huber_loss,kldiv_loss,bpr_loss,rank_loss,margin_rank_loss,log_loss,
center_loss,accuracy}_op.* — numerically-stable jax formulations.
"""
from __future__ import annotations

import jax
from ..core.dtypes import runtime_int64 as _i64
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _squeeze_label(label):
    label = jnp.asarray(label)
    if label.ndim >= 2 and label.shape[-1] == 1:
        return label[..., 0]
    return label


@register_op('cross_entropy')
def cross_entropy(x, label, *, soft_label=False, ignore_index=-100):
    """x are probabilities (post-softmax), matching the ref op."""
    x = jnp.asarray(x)
    eps = 1e-8
    if soft_label:
        return -jnp.sum(jnp.asarray(label) * jnp.log(x + eps), -1, keepdims=True)
    label = _squeeze_label(label)
    picked = jnp.take_along_axis(x, jnp.clip(label, 0, x.shape[-1] - 1)[..., None].astype(jnp.int32), -1)
    loss = -jnp.log(picked + eps)
    # negative sentinels (-1/-100) are valid ignore_index values
    loss = jnp.where((label == ignore_index)[..., None], 0.0, loss)
    return loss


@register_op('softmax_with_cross_entropy', outputs=['Loss', 'Softmax'])
def softmax_with_cross_entropy(logits, label, *, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False, numeric_stable_mode=True):
    logits = jnp.asarray(logits)
    logp = jax.nn.log_softmax(logits, axis=axis)
    sm = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(jnp.asarray(label) * logp, axis=axis, keepdims=True)
    else:
        label = jnp.asarray(label)
        if label.ndim == logits.ndim and label.shape[axis] == 1:
            label = jnp.squeeze(label, axis)
        li = jnp.clip(label, 0, logits.shape[axis] - 1).astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(li, axis),
                                     axis=axis)
        loss = -picked
        # negative sentinels (-1/-100) are valid ignore_index values; the
        # clip above already keeps the gather in-bounds for them
        loss = jnp.where(jnp.expand_dims(label == ignore_index, axis),
                         0.0, loss)
    return loss, sm


@register_op('sigmoid_cross_entropy_with_logits')
def sigmoid_cross_entropy_with_logits(x, label, *, ignore_index=-100,
                                      normalize=False):
    x = jnp.asarray(x)
    label = jnp.asarray(label).astype(x.dtype)
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = (label != ignore_index)
    loss = jnp.where(mask, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(mask), 1)
    return loss


@register_op('square_error_cost')
def square_error_cost(x, label):
    d = jnp.asarray(x) - jnp.asarray(label)
    return jnp.square(d)


@register_op('smooth_l1_loss')
def smooth_l1_loss(x, y, inside_weight=None, outside_weight=None, *, sigma=1.0):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    d = x - y
    if inside_weight is not None:
        d = d * jnp.asarray(inside_weight)
    s2 = sigma * sigma
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if outside_weight is not None:
        loss = loss * jnp.asarray(outside_weight)
    return jnp.sum(loss.reshape(loss.shape[0], -1), -1, keepdims=True)


@register_op('huber_loss')
def huber_loss(x, label, *, delta=1.0):
    d = jnp.asarray(label) - jnp.asarray(x)
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))


@register_op('kldiv_loss')
def kldiv_loss(x, target, *, reduction='mean'):
    """x is log-prob input, matching ref kldiv_loss_op.cc."""
    x = jnp.asarray(x)
    t = jnp.asarray(target)
    loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-12)) - x), 0.0)
    if reduction == 'mean':
        return jnp.mean(loss)
    if reduction == 'sum':
        return jnp.sum(loss)
    if reduction == 'batchmean':
        return jnp.sum(loss) / x.shape[0]
    return loss


@register_op('bpr_loss')
def bpr_loss(x, label):
    """Bayesian personalized ranking (ref: bpr_loss_op.cc)."""
    x = jnp.asarray(x)
    label = _squeeze_label(label).astype(jnp.int32)
    n, c = x.shape
    pos = jnp.take_along_axis(x, label[:, None], -1)
    diff = pos - x
    loss = -jnp.log(jax.nn.sigmoid(diff) + 1e-8)
    mask = jnp.arange(c)[None, :] != label[:, None]
    return (jnp.sum(jnp.where(mask, loss, 0.0), -1, keepdims=True) / (c - 1))


@register_op('rank_loss')
def rank_loss(label, left, right):
    label = jnp.asarray(label)
    d = jnp.asarray(left) - jnp.asarray(right)
    return jnp.log1p(jnp.exp(d)) - label * d


@register_op('margin_rank_loss')
def margin_rank_loss(label, left, right, *, margin=0.1):
    label = jnp.asarray(label)
    out = margin - label * (jnp.asarray(left) - jnp.asarray(right))
    return jnp.maximum(out, 0.0)


@register_op('log_loss')
def log_loss(x, label, *, epsilon=1e-4):
    x = jnp.asarray(x)
    label = jnp.asarray(label)
    return -label * jnp.log(x + epsilon) - (1 - label) * jnp.log(1 - x + epsilon)


@register_op('center_loss', outputs=['Loss', 'SampleCenterDiff', 'CentersOut'])
def center_loss(x, label, centers, update_rate, *, cluster_num, need_update=True):
    """ref: center_loss_op.cc."""
    x = jnp.asarray(x)
    label = _squeeze_label(label).astype(jnp.int32)
    centers = jnp.asarray(centers)
    c = centers[label]
    diff = x - c
    loss = 0.5 * jnp.sum(jnp.square(diff), -1, keepdims=True)
    if need_update:
        alpha = jnp.asarray(update_rate).reshape(())
        counts = jnp.zeros((cluster_num,), x.dtype).at[label].add(1.0) + 1.0
        delta = jnp.zeros_like(centers).at[label].add(diff)
        new_centers = centers + alpha * delta / counts[:, None]
        new_centers = lax.stop_gradient(new_centers)
    else:
        new_centers = centers
    return loss, diff, new_centers


@register_op('teacher_student_sigmoid_loss')
def teacher_student_sigmoid_loss(x, label, *, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """ref: teacher_student_sigmoid_loss_op.cc (CTR distillation)."""
    x = jnp.asarray(x)[:, 0]
    label = jnp.asarray(label).reshape(-1)
    # teacher part: label < -1 or > 1 encodes soft score z = |label| - 1 … the
    # ref treats label in {0,1} as hard, otherwise soft score.
    hard = (label >= 0.0) & (label <= 1.0)
    ce_hard = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    z = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    soft = jnp.abs(label) - 1.0
    ce_soft = jnp.maximum(z, 0) - z * soft + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return jnp.where(hard, ce_hard, ce_soft)[:, None]


@register_op('accuracy', outputs=['Out', 'Correct', 'Total'])
def accuracy(pred, label, *, k=1):
    """ref: paddle/fluid/operators/metrics/accuracy_op.cc. pred: probs/logits."""
    pred = jnp.asarray(pred)
    label = _squeeze_label(label).astype(jnp.int32)
    _, top = lax.top_k(pred, k)
    correct = jnp.any(top == label[:, None], -1)
    total = jnp.asarray(pred.shape[0], _i64())
    ncorrect = jnp.sum(correct).astype(_i64())
    return (ncorrect.astype(jnp.float32) / total.astype(jnp.float32),
            ncorrect, total)


@register_op('mean_iou', outputs=['Out', 'Wrong', 'Correct'])
def mean_iou(pred, label, *, num_classes):
    pred = jnp.asarray(pred).reshape(-1).astype(jnp.int32)
    label = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    inter = jnp.zeros((num_classes,), jnp.float32).at[pred].add(
        (pred == label).astype(jnp.float32))
    parea = jnp.zeros((num_classes,), jnp.float32).at[pred].add(1.0)
    larea = jnp.zeros((num_classes,), jnp.float32).at[label].add(1.0)
    union = parea + larea - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-9), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    return miou, (parea - inter).astype(jnp.int32), inter.astype(jnp.int32)
