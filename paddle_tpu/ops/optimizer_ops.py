"""Optimizer update ops — pure (param, grad, slots…) → (new param, new slots…).

Parity targets: reference paddle/fluid/operators/optimizers/{sgd,momentum,
adam,adamax,adagrad,rmsprop,adadelta,ftrl,lamb,lars_momentum,decayed_adagrad,
dpsgd}_op.* — one jax functional each; the whole parameter update fuses into
the jitted train step (no per-param kernel launches).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op('sgd', outputs=['ParamOut'])
def sgd(param, grad, lr):
    return jnp.asarray(param) - jnp.asarray(lr) * jnp.asarray(grad)


@register_op('momentum', outputs=['ParamOut', 'VelocityOut'])
def momentum(param, grad, velocity, lr, *, mu=0.9, use_nesterov=False):
    p, g, v = jnp.asarray(param), jnp.asarray(grad), jnp.asarray(velocity)
    lr = jnp.asarray(lr)
    v_new = mu * v + g
    if use_nesterov:
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return p_new, v_new


@register_op('lars_momentum', outputs=['ParamOut', 'VelocityOut'])
def lars_momentum(param, grad, velocity, lr, *, mu=0.9, lars_coeff=0.001,
                  lars_weight_decay=0.0005, epsilon=0.0):
    p, g, v = jnp.asarray(param), jnp.asarray(grad), jnp.asarray(velocity)
    lr = jnp.asarray(lr)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0),
        lr * lars_coeff * pn / (gn + lars_weight_decay * pn + epsilon), lr)
    v_new = mu * v + local_lr * (g + lars_weight_decay * p)
    return p - v_new, v_new


@register_op('adam', outputs=['ParamOut', 'Moment1Out', 'Moment2Out',
                              'Beta1PowOut', 'Beta2PowOut'])
def adam(param, grad, moment1, moment2, beta1_pow, beta2_pow, lr, *,
         beta1=0.9, beta2=0.999, epsilon=1e-8):
    p, g = jnp.asarray(param), jnp.asarray(grad)
    m1, m2 = jnp.asarray(moment1), jnp.asarray(moment2)
    b1p, b2p = jnp.asarray(beta1_pow), jnp.asarray(beta2_pow)
    lr = jnp.asarray(lr)
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    pn = p - lr_t * m1n / (jnp.sqrt(m2n) + epsilon)
    return pn, m1n, m2n, b1p * beta1, b2p * beta2


@register_op('adamax', outputs=['ParamOut', 'MomentOut', 'InfNormOut', 'Beta1PowOut'])
def adamax(param, grad, moment, inf_norm, beta1_pow, lr, *, beta1=0.9,
           beta2=0.999, epsilon=1e-8):
    p, g = jnp.asarray(param), jnp.asarray(grad)
    m, u = jnp.asarray(moment), jnp.asarray(inf_norm)
    b1p = jnp.asarray(beta1_pow)
    lr = jnp.asarray(lr)
    mn = beta1 * m + (1 - beta1) * g
    un = jnp.maximum(beta2 * u, jnp.abs(g))
    pn = p - (lr / (1 - b1p)) * mn / (un + epsilon)
    return pn, mn, un, b1p * beta1


@register_op('adagrad', outputs=['ParamOut', 'MomentOut'])
def adagrad(param, grad, moment, lr, *, epsilon=1e-6):
    p, g, m = jnp.asarray(param), jnp.asarray(grad), jnp.asarray(moment)
    mn = m + jnp.square(g)
    return p - jnp.asarray(lr) * g / (jnp.sqrt(mn) + epsilon), mn


@register_op('decayed_adagrad', outputs=['ParamOut', 'MomentOut'])
def decayed_adagrad(param, grad, moment, lr, *, decay=0.95, epsilon=1e-6):
    p, g, m = jnp.asarray(param), jnp.asarray(grad), jnp.asarray(moment)
    mn = decay * m + (1 - decay) * jnp.square(g)
    return p - jnp.asarray(lr) * g / (jnp.sqrt(mn) + epsilon), mn


@register_op('rmsprop', outputs=['ParamOut', 'MeanSquareOut', 'MomentOut', 'MeanGradOut'])
def rmsprop(param, grad, mean_square, moment, mean_grad, lr, *, rho=0.95,
            epsilon=1e-6, momentum=0.0, centered=False):
    p, g = jnp.asarray(param), jnp.asarray(grad)
    ms, mom, mg = jnp.asarray(mean_square), jnp.asarray(moment), jnp.asarray(mean_grad)
    lr = jnp.asarray(lr)
    msn = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mgn = rho * mg + (1 - rho) * g
        denom = jnp.sqrt(msn - jnp.square(mgn) + epsilon)
    else:
        mgn = mg
        denom = jnp.sqrt(msn + epsilon)
    momn = momentum * mom + lr * g / denom
    return p - momn, msn, momn, mgn


@register_op('adadelta', outputs=['ParamOut', 'AvgSquaredGradOut', 'AvgSquaredUpdateOut'])
def adadelta(param, grad, avg_squared_grad, avg_squared_update, *, rho=0.95,
             epsilon=1e-6):
    p, g = jnp.asarray(param), jnp.asarray(grad)
    asg, asu = jnp.asarray(avg_squared_grad), jnp.asarray(avg_squared_update)
    asgn = rho * asg + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((asu + epsilon) / (asgn + epsilon)) * g
    asun = rho * asu + (1 - rho) * jnp.square(update)
    return p + update, asgn, asun


@register_op('ftrl', outputs=['ParamOut', 'SquaredAccumOut', 'LinearAccumOut'])
def ftrl(param, grad, squared_accum, linear_accum, lr, *, l1=0.0, l2=0.0,
         lr_power=-0.5):
    p, g = jnp.asarray(param), jnp.asarray(grad)
    sq, lin = jnp.asarray(squared_accum), jnp.asarray(linear_accum)
    lr = jnp.asarray(lr)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    x = -new_lin + jnp.clip(new_lin, -l1, l1)
    y = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pn = jnp.where(jnp.abs(new_lin) > l1, x / y, jnp.zeros_like(p))
    return pn, new_sq, new_lin


@register_op('lamb', outputs=['ParamOut', 'Moment1Out', 'Moment2Out',
                              'Beta1PowOut', 'Beta2PowOut'])
def lamb(param, grad, moment1, moment2, beta1_pow, beta2_pow, lr, *,
         weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6):
    p, g = jnp.asarray(param), jnp.asarray(grad)
    m1, m2 = jnp.asarray(moment1), jnp.asarray(moment2)
    b1p, b2p = jnp.asarray(beta1_pow), jnp.asarray(beta2_pow)
    lr = jnp.asarray(lr)
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * jnp.square(g)
    m1h = m1n / (1 - b1p)
    m2h = m2n / (1 - b2p)
    r = m1h / (jnp.sqrt(m2h) + epsilon) + weight_decay * p
    pnorm = jnp.sqrt(jnp.sum(jnp.square(p)))
    rnorm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((pnorm > 0) & (rnorm > 0), pnorm / rnorm, 1.0)
    return p - lr * trust * r, m1n, m2n, b1p * beta1, b2p * beta2


@register_op('dpsgd', outputs=['ParamOut'], needs_rng=True)
def dpsgd(param, grad, lr, *, clip=10.0, batch_size=16.0, sigma=1.0, key=None):
    """Differentially-private SGD (ref: dpsgd_op.cc)."""
    p, g = jnp.asarray(param), jnp.asarray(grad)
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g / jnp.maximum(1.0, gn / clip)
    noise = sigma * clip / batch_size * jax.random.normal(key, g.shape, g.dtype)
    return p - jnp.asarray(lr) * (g + noise)


@register_op('dgc_momentum', outputs=['ParamOut', 'VelocityOut', 'ErrorOut'])
def dgc_momentum(param, grad, velocity, error, lr, *, mu=0.9,
                 sparsity=0.999, rampup_step=1.0, use_nesterov=False):
    """Deep Gradient Compression momentum (ref: paddle/fluid/operators/
    dgc_op.h + optimizer.py:DGCMomentumOptimizer): error-feedback
    accumulation, top-k magnitude sparsification of the local gradient,
    momentum step on the sparse gradient. On TPU the sparse gradient stays
    dense-with-zeros (XLA AllReduce already bucketizes); the compression
    semantics — what the update sees — match."""
    p, g = jnp.asarray(param), jnp.asarray(grad)
    v, e = jnp.asarray(velocity), jnp.asarray(error)
    lr = jnp.asarray(lr)
    acc = e + g
    flat = jnp.abs(acc).reshape(-1)
    n = flat.shape[0]
    k = max(1, int(n * (1.0 - sparsity)))
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = (jnp.abs(acc) >= thresh).astype(p.dtype)
    sparse = acc * mask
    e_new = acc - sparse
    v_new = mu * v + sparse
    if use_nesterov:
        p_new = p - lr * (sparse + mu * v_new)
    else:
        p_new = p - lr * v_new
    return p_new, v_new, e_new


@register_op('check_finite_and_unscale', outputs=['Out', 'FoundInfinite'],
             variadic=['xs'])
def check_finite_and_unscale(xs, scale):
    """Fused grad finite-check + unscale (ref: paddle/fluid/operators/amp/
    check_finite_and_unscale_op.*): one reduction over ALL grads inside the
    jitted step — no per-param host syncs."""
    inv = 1.0 / jnp.reshape(jnp.asarray(scale), ())
    outs = [jnp.asarray(x) * inv for x in xs]
    found = jnp.logical_not(
        jnp.all(jnp.stack([jnp.all(jnp.isfinite(o)) for o in outs])))
    return outs, jnp.reshape(found, (1,))


@register_op('update_loss_scaling',
             outputs=['LossScaling', 'OutGoodSteps', 'OutBadSteps'])
def update_loss_scaling(found_inf, prev_loss_scaling, in_good_steps,
                        in_bad_steps, *, incr_every_n_steps=1000,
                        decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                        decr_ratio=0.8):
    """Dynamic loss-scale update (ref: paddle/fluid/operators/amp/
    update_loss_scaling_op.* + contrib/mixed_precision/fp16_utils.py:283),
    fused into the train step: branchless jnp.where arithmetic."""
    found = jnp.reshape(jnp.asarray(found_inf), ()).astype(bool)
    scale = jnp.reshape(jnp.asarray(prev_loss_scaling), ()).astype(jnp.float32)
    good = jnp.reshape(jnp.asarray(in_good_steps), ()).astype(jnp.int32)
    bad = jnp.reshape(jnp.asarray(in_bad_steps), ()).astype(jnp.int32)
    bad_n = jnp.where(found, bad + 1, jnp.zeros_like(bad))
    good_n = jnp.where(found, jnp.zeros_like(good), good + 1)
    decr = bad_n >= decr_every_n_nan_or_inf
    incr = good_n >= incr_every_n_steps
    scale_n = jnp.where(decr, jnp.maximum(scale * decr_ratio, 1.0),
                        jnp.where(incr, scale * incr_ratio, scale))
    bad_n = jnp.where(decr, jnp.zeros_like(bad_n), bad_n)
    good_n = jnp.where(incr, jnp.zeros_like(good_n), good_n)
    return (jnp.reshape(scale_n, (1,)), jnp.reshape(good_n, (1,)),
            jnp.reshape(bad_n, (1,)))
