"""contrib layer ops (ref: python/paddle/fluid/contrib/layers/nn.py).

Text-matching / CTR ops reformulated for TPU: the reference's LoD inputs
(per-sample matrix sizes, ragged sequences) become padded dense tensors
plus (B,) length vectors (None → full size), masked so results match the
ragged semantics. Everything is fixed-shape and fuses under XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _len_mask(lengths, size, dtype=jnp.bool_):
    """(B,) lengths → (B, size) validity mask (all-valid when None)."""
    if lengths is None:
        return None
    return (jnp.arange(size)[None, :]
            < jnp.asarray(lengths)[:, None]).astype(dtype)


@register_op('match_matrix_tensor', outputs=['Out', 'Tmp'])
def match_matrix_tensor(x, y, w, x_len=None, y_len=None, *, channel_num=1):
    """ref contrib/layers/nn.py:219 — out[b,c,i,j] = x[b,i]ᵀ W_c y[b,j].

    x: (B, Lx, D1), y: (B, Ly, D2), w: (D1, C, D2) →
    Out (B, C, Lx, Ly), Tmp (B, Lx, C, D2) (the x·W intermediate the
    reference also returns)."""
    tmp = jnp.einsum('bxd,dce->bxce', x, w)
    out = jnp.einsum('bxce,bye->bcxy', tmp, y)
    mx = _len_mask(x_len, x.shape[1], out.dtype)
    my = _len_mask(y_len, y.shape[1], out.dtype)
    if mx is not None:
        out = out * mx[:, None, :, None]
    if my is not None:
        out = out * my[:, None, None, :]
    return out, tmp


@register_op('var_conv_2d')
def var_conv_2d(x, w, row=None, col=None, *, stride=1):
    """ref contrib/layers/nn.py:103 — per-sample-sized conv2d.

    x: (B, Cin, H, W) padded; row/col: (B,) per-sample valid height/width.
    SAME-padded conv at `stride`, with out-of-extent positions (of both
    input and output) masked to zero — matching the reference's
    LoD-derived per-sample image sizes."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    mr = _len_mask(row, x.shape[2], x.dtype)
    mc = _len_mask(col, x.shape[3], x.dtype)
    if mr is not None:
        x = x * mr[:, None, :, None]
    if mc is not None:
        x = x * mc[:, None, None, :]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding='SAME',
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    if row is not None:
        out_rows = (jnp.asarray(row) + stride[0] - 1) // stride[0]
        out = out * _len_mask(out_rows, out.shape[2],
                              out.dtype)[:, None, :, None]
    if col is not None:
        out_cols = (jnp.asarray(col) + stride[1] - 1) // stride[1]
        out = out * _len_mask(out_cols, out.shape[3],
                              out.dtype)[:, None, None, :]
    return out


@register_op('sequence_topk_avg_pooling')
def sequence_topk_avg_pooling(x, row=None, col=None, *, topks,
                              channel_num=1):
    """ref contrib/layers/nn.py:302 — per-row top-k column averages.

    x: (B, C, R, Cc); for each (b, c, r): sort the valid columns
    descending and emit mean of the top k for each k in `topks` (fewer
    than k valid values → zero-padded, i.e. sum(valid top)/k, the
    reference's behavior). Out: (B, R, C * len(topks))."""
    B, C, R, Cc = x.shape
    neg = jnp.finfo(x.dtype).min
    mc = _len_mask(col, Cc)
    if mc is not None:
        x = jnp.where(mc[:, None, None, :], x, neg)
    sorted_desc = -jnp.sort(-x, axis=-1)            # (B, C, R, Cc)
    if mc is not None:
        # invalid slots were -inf; zero them so cumsum = sum of valid
        valid_n = jnp.asarray(col)[:, None, None, None]
        pos = jnp.arange(Cc)[None, None, None, :]
        sorted_desc = jnp.where(pos < valid_n, sorted_desc, 0.0)
    csum = jnp.cumsum(sorted_desc, axis=-1)          # (B, C, R, Cc)
    outs = []
    for k in topks:
        idx = min(k, Cc) - 1
        outs.append(csum[..., idx] / float(k))       # (B, C, R)
    out = jnp.stack(outs, axis=-1)                   # (B, C, R, K)
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(B, R, C * len(topks))
    mr = _len_mask(row, R, out.dtype)
    if mr is not None:
        out = out * mr[:, :, None]
    return out


@register_op('fused_embedding_seq_pool')
def fused_embedding_seq_pool(ids, w, length=None, *, combiner='sum',
                             padding_idx=-1):
    """ref contrib/layers/nn.py:435 — embedding lookup + sequence pool in
    one fused op. ids: (B, T) int; w: (V, D) → (B, D)."""
    ids = jnp.asarray(ids)
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    emb = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)  # (B,T,D)
    valid = jnp.ones(ids.shape, emb.dtype)
    if padding_idx is not None and padding_idx >= 0:
        valid = valid * (ids != padding_idx).astype(emb.dtype)
    m = _len_mask(length, ids.shape[1], emb.dtype)
    if m is not None:
        valid = valid * m
    emb = emb * valid[..., None]
    s = jnp.sum(emb, axis=1)
    if combiner == 'sum':
        return s
    if combiner == 'mean':
        # denominator = the LENGTH-masked step count, padding_idx rows
        # INCLUDED (they contribute zero rows but still count) — exactly
        # embedding + sequence_pool('average'); excluding them here made
        # the fused op drift from the unfused pair on batches with pad
        # ids (tests/layers/test_fused_embedding_seq_pool.py)
        count = (jnp.sum(m, axis=1, keepdims=True) if m is not None
                 else jnp.full((ids.shape[0], 1), ids.shape[1], emb.dtype))
        return s / jnp.maximum(count, 1.0)
    raise ValueError(f'unknown combiner {combiner!r}')


@register_op('search_pyramid_hash', needs_rng=True)
def search_pyramid_hash(ids, w, length=None, *, num_emb, space_len,
                        pyramid_layer=2, rand_len=16,
                        drop_out_percent=0.0, is_training=True,
                        seed=0, key=None):
    """ref contrib/layers/nn.py:631 — pyramid n-gram hash embedding.

    For each n-gram length 2..pyramid_layer, token windows hash (FNV-style
    modular mix, deterministic in `seed`) into a table of shape
    (space_len, num_emb); position t accumulates the embeddings of every
    n-gram starting at t. ids: (B, T) int → (B, T, num_emb), masked by
    `length`; training applies dropout at drop_out_percent."""
    ids = jnp.asarray(ids)
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    B, T = ids.shape
    out = jnp.zeros((B, T, num_emb), w.dtype)
    m = _len_mask(length, T, jnp.int32)
    valid = m if m is not None else jnp.ones((B, T), jnp.int32)
    for n in range(2, pyramid_layer + 1):
        if n > T:
            break
        h = jnp.zeros((B, T - n + 1), jnp.uint32) + jnp.uint32(
            2166136261 ^ (seed & 0x7fffffff))
        ok = jnp.ones((B, T - n + 1), jnp.int32)
        for i in range(n):
            tok = jax.lax.dynamic_slice_in_dim(ids, i, T - n + 1, axis=1)
            h = (h * jnp.uint32(16777619)) ^ tok.astype(jnp.uint32)
            ok = ok * jax.lax.dynamic_slice_in_dim(valid, i, T - n + 1,
                                                   axis=1)
        idx = (h % jnp.uint32(space_len)).astype(jnp.int32)
        emb = jnp.take(w, idx, axis=0) * ok[..., None].astype(w.dtype)
        out = out.at[:, :T - n + 1, :].add(emb)
    if is_training and drop_out_percent > 0 and key is not None:
        keep = 1.0 - drop_out_percent
        mask = jax.random.bernoulli(key, keep, out.shape)
        out = jnp.where(mask, out / keep, 0.0)
    if m is not None:
        out = out * m[..., None].astype(out.dtype)
    return out
