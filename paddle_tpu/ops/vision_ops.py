"""Region-of-interest and deformable ops.

Parity targets: /root/reference/paddle/fluid/operators/{roi_pool,roi_align,
psroi_pool,prroi_pool,deformable_conv,deformable_psroi_pooling}_op.*

TPU formulation: the reference's CUDA kernels loop over output elements and
gather with data-dependent addresses. Here every roi/bin/sample index is
computed as a dense tensor and resolved with vectorized `take` (static
shapes, vmap over rois), so XLA can tile the gathers and the bilinear math
onto the VPU/MXU. ROI batch mapping uses an explicit (R,) `batch_ids` vector
instead of the reference's LoD offset table.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _bilinear_sample(img, y, x):
    """img: (C, H, W); y, x: (...,) float coords. Zero outside [0,H)x[0,W)
    like the reference kernels. Returns (C, ...)."""
    H, W = img.shape[-2], img.shape[-1]
    valid = (y > -1.0) & (y < H) & (x > -1.0) & (x < W)
    y = jnp.clip(y, 0.0, H - 1)
    x = jnp.clip(x, 0.0, W - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    hy, hx = 1.0 - ly, 1.0 - lx
    flat = img.reshape(img.shape[0], -1)           # (C, H*W)
    def g(yy, xx):
        return jnp.take(flat, yy * W + xx, axis=1)  # (C, ...)
    val = (g(y0, x0) * (hy * hx) + g(y0, x1) * (hy * lx)
           + g(y1, x0) * (ly * hx) + g(y1, x1) * (ly * lx))
    return jnp.where(valid, val, 0.0)


def _batch_ids(rois, batch_ids):
    R = rois.shape[0]
    if batch_ids is None:
        return jnp.zeros((R,), jnp.int32)
    return jnp.asarray(batch_ids).reshape(R).astype(jnp.int32)


@register_op('roi_pool', outputs=['Out', 'Argmax'])
def roi_pool(x, rois, batch_ids=None, *, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    """Max-pool each roi into (pooled_h, pooled_w) bins with the reference's
    integer bin quantization (roi_pool_op.cu)."""
    x = jnp.asarray(x)
    rois = jnp.asarray(rois)
    N, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width
    bids = _batch_ids(rois, batch_ids)

    def one(roi, bid):
        img = x[bid]                                   # (C, H, W)
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        i = jnp.arange(ph, dtype=x.dtype)
        j = jnp.arange(pw, dtype=x.dtype)
        hs = jnp.clip(jnp.floor(i * bin_h) + y1, 0, H)        # (ph,)
        he = jnp.clip(jnp.ceil((i + 1) * bin_h) + y1, 0, H)
        ws = jnp.clip(jnp.floor(j * bin_w) + x1, 0, W)
        we = jnp.clip(jnp.ceil((j + 1) * bin_w) + x1, 0, W)
        hh = jnp.arange(H, dtype=x.dtype)
        wwv = jnp.arange(W, dtype=x.dtype)
        mh = (hh[None, :] >= hs[:, None]) & (hh[None, :] < he[:, None])  # (ph,H)
        mw = (wwv[None, :] >= ws[:, None]) & (wwv[None, :] < we[:, None])  # (pw,W)
        neg = jnp.asarray(-jnp.inf, x.dtype)
        t = jnp.where(mw[None, None, :, :], img[:, :, None, :], neg)  # (C,H,pw,W)
        t = t.max(axis=-1)                                             # (C,H,pw)
        o = jnp.where(mh[None, :, :, None], t[:, None, :, :], neg)     # (C,ph,H,pw)
        o = o.max(axis=2)                                              # (C,ph,pw)
        empty = (mh.sum(1)[:, None] * mw.sum(1)[None, :]) == 0         # (ph,pw)
        return jnp.where(empty[None], 0.0, o)

    out = jax.vmap(one)(rois, bids)                    # (R, C, ph, pw)
    return out, jnp.zeros_like(out, jnp.int32)


@register_op('roi_align')
def roi_align(x, rois, batch_ids=None, *, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1):
    """Average of bilinear samples per bin (roi_align_op.cu). A static sample
    count is required under jit: sampling_ratio<=0 falls back to 2 (the
    common adaptive outcome for roi≈bin-sized regions)."""
    x = jnp.asarray(x)
    rois = jnp.asarray(rois)
    N, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width
    s = sampling_ratio if sampling_ratio > 0 else 2
    bids = _batch_ids(rois, batch_ids)

    def one(roi, bid):
        img = x[bid]
        x1, y1, x2, y2 = (roi[k] * spatial_scale for k in range(4))
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        i = jnp.arange(ph, dtype=x.dtype)[:, None, None, None]
        j = jnp.arange(pw, dtype=x.dtype)[None, :, None, None]
        sy = jnp.arange(s, dtype=x.dtype)[None, None, :, None]
        sx = jnp.arange(s, dtype=x.dtype)[None, None, None, :]
        yy = y1 + i * bin_h + (sy + 0.5) * bin_h / s   # (ph,pw,s,s)
        xx = x1 + j * bin_w + (sx + 0.5) * bin_w / s
        yy = jnp.broadcast_to(yy, (ph, pw, s, s))
        xx = jnp.broadcast_to(xx, (ph, pw, s, s))
        v = _bilinear_sample(img, yy, xx)               # (C,ph,pw,s,s)
        return v.mean(axis=(-1, -2))

    return jax.vmap(one)(rois, bids)


@register_op('psroi_pool')
def psroi_pool(x, rois, batch_ids=None, *, output_channels=1, spatial_scale=1.0,
               pooled_height=1, pooled_width=1):
    """Position-sensitive average roi pooling (psroi_pool_op.cu): output
    channel c at bin (i,j) pools input channel c*ph*pw + i*pw + j."""
    x = jnp.asarray(x)
    rois = jnp.asarray(rois)
    N, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width
    oc = output_channels
    bids = _batch_ids(rois, batch_ids)

    def one(roi, bid):
        img = x[bid]
        x1 = jnp.round(roi[0]) * spatial_scale
        y1 = jnp.round(roi[1]) * spatial_scale
        x2 = jnp.round(roi[2] + 1.0) * spatial_scale
        y2 = jnp.round(roi[3] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h, bin_w = rh / ph, rw / pw
        i = jnp.arange(ph, dtype=x.dtype)
        j = jnp.arange(pw, dtype=x.dtype)
        hs = jnp.clip(jnp.floor(y1 + i * bin_h), 0, H)
        he = jnp.clip(jnp.ceil(y1 + (i + 1) * bin_h), 0, H)
        ws = jnp.clip(jnp.floor(x1 + j * bin_w), 0, W)
        we = jnp.clip(jnp.ceil(x1 + (j + 1) * bin_w), 0, W)
        hh = jnp.arange(H, dtype=x.dtype)
        wwv = jnp.arange(W, dtype=x.dtype)
        mh = ((hh[None, :] >= hs[:, None]) & (hh[None, :] < he[:, None])
              ).astype(x.dtype)                       # (ph,H)
        mw = ((wwv[None, :] >= ws[:, None]) & (wwv[None, :] < we[:, None])
              ).astype(x.dtype)                       # (pw,W)
        # sum over each bin: (C,ph,pw)
        sums = jnp.einsum('chw,ih,jw->cij', img, mh, mw)
        area = jnp.maximum(mh.sum(1)[:, None] * mw.sum(1)[None, :], 1.0)
        pooled = sums / area                          # (C,ph,pw)
        # position-sensitive channel select: out[c,i,j] = pooled[c*ph*pw+i*pw+j, i, j]
        csel = (jnp.arange(oc)[:, None, None] * (ph * pw)
                + jnp.arange(ph)[None, :, None] * pw
                + jnp.arange(pw)[None, None, :])      # (oc,ph,pw)
        return pooled.reshape(C, ph * pw)[
            csel, (jnp.arange(ph)[None, :, None] * pw
                   + jnp.arange(pw)[None, None, :])]

    return jax.vmap(one)(rois, bids)


@register_op('prroi_pool')
def prroi_pool(x, rois, batch_ids=None, *, output_channels=None,
               spatial_scale=1.0, pooled_height=1, pooled_width=1):
    """Precise RoI pooling (prroi_pool_op.h): continuous integral of the
    bilinearly-interpolated map over each bin, approximated by a dense 4×4
    sample grid per bin (exact for the piecewise-linear integrand up to
    quadrature error; keeps shapes static for XLA)."""
    x = jnp.asarray(x)
    rois = jnp.asarray(rois)
    ph, pw = pooled_height, pooled_width
    s = 4
    bids = _batch_ids(rois, batch_ids)

    def one(roi, bid):
        img = x[bid]
        x1, y1, x2, y2 = (roi[k] * spatial_scale for k in range(4))
        bin_h = (y2 - y1) / ph
        bin_w = (x2 - x1) / pw
        i = jnp.arange(ph, dtype=x.dtype)[:, None, None, None]
        j = jnp.arange(pw, dtype=x.dtype)[None, :, None, None]
        sy = jnp.arange(s, dtype=x.dtype)[None, None, :, None]
        sx = jnp.arange(s, dtype=x.dtype)[None, None, None, :]
        yy = jnp.broadcast_to(y1 + i * bin_h + (sy + 0.5) * bin_h / s,
                              (ph, pw, s, s))
        xx = jnp.broadcast_to(x1 + j * bin_w + (sx + 0.5) * bin_w / s,
                              (ph, pw, s, s))
        v = _bilinear_sample(img, yy, xx)
        return v.mean(axis=(-1, -2))

    return jax.vmap(one)(rois, bids)


@register_op('deformable_conv')
def deformable_conv(x, offset, mask, weight, *, stride=1, padding=0,
                    dilation=1, groups=1, deformable_groups=1,
                    im2col_step=1, modulated=True):
    """Deformable conv v1/v2 (deformable_conv_op.cu): bilinear-sample the
    input at offset-shifted taps to build columns, then one big matmul —
    the im2col+GEMM shape XLA maps straight onto the MXU."""
    x = jnp.asarray(x)
    offset = jnp.asarray(offset)
    w = jnp.asarray(weight)
    N, C, H, W = x.shape
    Co, Ci_g, kh, kw = w.shape
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    phd, pwd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    Ho = (H + 2 * phd - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pwd - (dw * (kw - 1) + 1)) // sw + 1
    dg = deformable_groups
    cpg = C // dg                                     # channels per deform group

    def one(img, off, msk):
        # off: (2*dg*kh*kw, Ho, Wo) ordered [dg][kh][kw][2] with (y, x) pairs
        off = off.reshape(dg, kh * kw, 2, Ho, Wo)
        oy = jnp.arange(Ho, dtype=x.dtype)[:, None] * sh - phd
        ox = jnp.arange(Wo, dtype=x.dtype)[None, :] * sw - pwd
        kyx = jnp.stack(jnp.meshgrid(jnp.arange(kh, dtype=x.dtype) * dh,
                                     jnp.arange(kw, dtype=x.dtype) * dw,
                                     indexing='ij'), -1).reshape(kh * kw, 2)
        cols = []
        for g in range(dg):
            yy = oy[None] + kyx[:, 0][:, None, None] + off[g, :, 0]  # (khkw,Ho,Wo)
            xx = ox[None] + kyx[:, 1][:, None, None] + off[g, :, 1]
            v = _bilinear_sample(img[g * cpg:(g + 1) * cpg], yy, xx)
            if modulated and msk is not None:
                m = msk.reshape(dg, kh * kw, Ho, Wo)[g]
                v = v * m[None]
            cols.append(v)                            # (cpg, khkw, Ho, Wo)
        col = jnp.concatenate(cols, 0)                # (C, khkw, Ho, Wo)
        col = col.reshape(C, kh, kw, Ho, Wo)
        if groups == 1:
            return jnp.einsum('ckltv,ockl->otv', col, w)
        outs = []
        cg = C // groups
        og = Co // groups
        for gi in range(groups):
            outs.append(jnp.einsum(
                'ckltv,ockl->otv', col[gi * cg:(gi + 1) * cg],
                w[gi * og:(gi + 1) * og]))
        return jnp.concatenate(outs, 0)

    msk = None if mask is None else jnp.asarray(mask)
    if msk is None:
        return jax.vmap(lambda img, off: one(img, off, None))(x, offset)
    return jax.vmap(one)(x, offset, msk)


@register_op('deformable_roi_pooling')
def deformable_roi_pooling(x, rois, trans, batch_ids=None, *,
                           no_trans=False, spatial_scale=1.0,
                           output_channels=1, group_size=1, pooled_height=1,
                           pooled_width=1, part_size=None, sample_per_part=4,
                           trans_std=0.1):
    """Deformable PS-ROI pooling (deformable_psroi_pooling_op.cu): per-bin
    learned offsets shift the sampling region before position-sensitive
    average pooling."""
    x = jnp.asarray(x)
    rois = jnp.asarray(rois)
    N, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width
    sp = sample_per_part
    gs = group_size if isinstance(group_size, int) else group_size[0]
    bids = _batch_ids(rois, batch_ids)
    part_h = part_size if part_size else ph
    part_w = part_size if part_size else pw

    def _ps_select(v, oc):
        """Position-sensitive channel pick: out[c,i,j] = v[c*ph*pw+i*pw+j,i,j]."""
        if v.shape[0] == oc:
            return v
        flat = v.reshape(v.shape[0], ph * pw)
        csel = (jnp.arange(oc)[:, None, None] * (ph * pw)
                + jnp.arange(ph)[None, :, None] * pw
                + jnp.arange(pw)[None, None, :])
        ij = (jnp.arange(ph)[None, :, None] * pw
              + jnp.arange(pw)[None, None, :])
        return flat[csel, ij]

    def one(roi, tr, bid):
        img = x[bid]
        x1 = jnp.round(roi[0]) * spatial_scale - 0.5
        y1 = jnp.round(roi[1]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[2]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h, bin_w = rh / ph, rw / pw
        sub_h = bin_h / sp
        sub_w = bin_w / sp
        i = jnp.arange(ph)[:, None]
        j = jnp.arange(pw)[None, :]
        if no_trans:
            dy = jnp.zeros((ph, pw), x.dtype)
            dx = jnp.zeros((ph, pw), x.dtype)
        else:
            pi = (i * part_h // ph).astype(jnp.int32)
            pj = (j * part_w // pw).astype(jnp.int32)
            dy = tr[0][pi, pj] * trans_std * rh
            dx = tr[1][pi, pj] * trans_std * rw
        sy = jnp.arange(sp, dtype=x.dtype)[None, None, :, None]
        sx = jnp.arange(sp, dtype=x.dtype)[None, None, None, :]
        yy = (y1 + i[..., None, None] * bin_h + dy[..., None, None]
              + (sy + 0.5) * sub_h)
        xx = (x1 + j[..., None, None] * bin_w + dx[..., None, None]
              + (sx + 0.5) * sub_w)
        yy = jnp.broadcast_to(yy, (ph, pw, sp, sp))
        xx = jnp.broadcast_to(xx, (ph, pw, sp, sp))
        v = _bilinear_sample(img, yy, xx).mean(axis=(-1, -2))  # (C,ph,pw)
        return _ps_select(v, output_channels)

    tr = (jnp.zeros((rois.shape[0], 2, part_h, part_w), x.dtype)
          if trans is None else jnp.asarray(trans))
    return jax.vmap(one)(rois, tr, bids)
