"""Random ops with explicit PRNG-key plumbing (core/random.py).

Parity targets: reference paddle/fluid/operators/{uniform_random,
gaussian_random,truncated_gaussian_random,randint,sampling_id,random_crop}_op.*
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.dtypes import to_jax_dtype


def _seeded(key, seed):
    """Paddle semantics: seed==0 → framework PRNG stream; else deterministic."""
    return jax.random.PRNGKey(seed) if seed else key


@register_op('uniform_random', needs_rng=True)
def uniform_random(*, shape, min=-1.0, max=1.0, dtype='float32', seed=0,
                   key=None):
    return jax.random.uniform(_seeded(key, seed), tuple(shape),
                              to_jax_dtype(dtype), min, max)


@register_op('gaussian_random', needs_rng=True)
def gaussian_random(*, shape, mean=0.0, std=1.0, dtype='float32', seed=0,
                    key=None):
    return mean + std * jax.random.normal(_seeded(key, seed), tuple(shape),
                                          to_jax_dtype(dtype))


@register_op('truncated_gaussian_random', needs_rng=True)
def truncated_gaussian_random(*, shape, mean=0.0, std=1.0, dtype='float32', seed=0, key=None):
    return mean + std * jax.random.truncated_normal(
        _seeded(key, seed), -2.0, 2.0, tuple(shape), to_jax_dtype(dtype))


@register_op('randint', needs_rng=True)
def randint(*, shape, low, high, dtype='int64', seed=0, key=None):
    return jax.random.randint(_seeded(key, seed), tuple(shape), low, high, to_jax_dtype(dtype))


@register_op('randperm', needs_rng=True)
def randperm(*, n, dtype='int64', seed=0, key=None):
    return jax.random.permutation(_seeded(key, seed), n).astype(to_jax_dtype(dtype))


@register_op('uniform_random_batch_size_like', needs_rng=True)
def uniform_random_batch_size_like(ref, *, shape, min=-1.0, max=1.0,
                                   input_dim_idx=0, output_dim_idx=0,
                                   dtype='float32', seed=0, key=None):
    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(ref).shape[input_dim_idx]
    return jax.random.uniform(_seeded(key, seed), tuple(shape), to_jax_dtype(dtype), min, max)


@register_op('gaussian_random_batch_size_like', needs_rng=True)
def gaussian_random_batch_size_like(ref, *, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    dtype='float32', seed=0, key=None):
    shape = list(shape)
    shape[output_dim_idx] = jnp.asarray(ref).shape[input_dim_idx]
    return mean + std * jax.random.normal(_seeded(key, seed), tuple(shape), to_jax_dtype(dtype))


@register_op('sampling_id', needs_rng=True)
def sampling_id(x, *, seed=0, key=None):
    """Sample category ids from probability rows (ref: sampling_id_op.cc)."""
    x = jnp.asarray(x)
    return jax.random.categorical(_seeded(key, seed), jnp.log(jnp.maximum(x, 1e-20)), axis=-1)


@register_op('random_crop', needs_rng=True)
def random_crop(x, *, shape, seed=0, key=None):
    """ref: random_crop_op.cc — random spatial crop to `shape` (trailing dims)."""
    x = jnp.asarray(x)
    ndim_crop = len(shape)
    starts = []
    for i, s in enumerate(shape):
        dim = x.ndim - ndim_crop + i
        limit = x.shape[dim] - s
        k = jax.random.fold_in(_seeded(key, seed), i)
        starts.append(jax.random.randint(k, (), 0, limit + 1))
    start_idx = [jnp.asarray(0)] * (x.ndim - ndim_crop) + starts
    sizes = list(x.shape[:x.ndim - ndim_crop]) + list(shape)
    return jax.lax.dynamic_slice(x, start_idx, sizes)


@register_op('shuffle_batch', needs_rng=True)
def shuffle_batch(x, *, seed=0, key=None):
    x = jnp.asarray(x)
    perm = jax.random.permutation(_seeded(key, seed), x.shape[0])
    return jnp.take(x, perm, axis=0)
