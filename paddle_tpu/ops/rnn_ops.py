"""Whole-sequence recurrence ops: lstm / gru / gather_tree.

Parity targets: /root/reference/paddle/fluid/operators/lstm_op.cc,
gru_op.cc, gather_tree_op.cc. The reference runs per-timestep CUDA kernels
over LoD-batched sequences; the TPU design runs ONE lax.scan over a padded
(B, T, ...) batch with a length mask — static shapes, reverse-differentiable,
fused by XLA into a single loop.

Gate layouts (documented, since weights are created by our own layers —
checkpoints are not imported from the reference):
  lstm: projected input x is (B, T, 4D) with gate order [i, f, c̃, o]
        (ref lstm_op.cc:188 formulas; peepholes are D-vectors W_ic/W_fc/W_oc)
  gru:  projected input x is (B, T, 3D) with order [u, r, c̃]
        (ref gru_op.cc:152-155: h_t = (1-u)⊙h_{t-1} + u⊙c̃_t)
"""
from __future__ import annotations

import jax
from ..core.dtypes import runtime_int64 as _i64
import jax.numpy as jnp

from .registry import register_op

_ACTS = {
    'sigmoid': jax.nn.sigmoid,
    'tanh': jnp.tanh,
    'relu': jax.nn.relu,
    'identity': lambda x: x,
}


def _mask_step(t, seq_len, new, old):
    """Keep `new` where t < seq_len else carry `old` (per batch row)."""
    if seq_len is None:
        return new
    keep = (t < seq_len)[:, None]
    return jnp.where(keep, new, old)


@register_op('lstm', outputs=('Hidden', 'Cell'), optional=('h0', 'c0'))
def lstm(x, h0, c0, w_h, bias, peephole=None, seq_len=None, proj_w=None, *,
         use_peepholes=False, is_reverse=False, gate_activation='sigmoid',
         cell_activation='tanh', candidate_activation='tanh'):
    """x: (B, T, 4D) pre-projected input; w_h: (H, 4D) recurrent weight where
    H = proj size if proj_w given else D; bias: (4D,); peephole: (3D,) as
    [W_ic, W_fc, W_oc]; proj_w: (D, P) for dynamic_lstmp.
    Returns Hidden (B, T, H), Cell (B, T, D)."""
    act_g = _ACTS[gate_activation]
    act_c = _ACTS[cell_activation]
    act_cand = _ACTS[candidate_activation]
    x = jnp.asarray(x)
    B, T, D4 = x.shape
    D = D4 // 4
    if is_reverse:
        x = jnp.flip(x, axis=1) if seq_len is None else _flip_padded(x, seq_len)
    xs = jnp.swapaxes(x, 0, 1)  # (T, B, 4D)
    if use_peepholes and peephole is not None:
        w_ic, w_fc, w_oc = jnp.split(jnp.asarray(peephole), 3)
    else:
        w_ic = w_fc = w_oc = None

    def step(carry, inp):
        t, h, c = carry
        x_t = inp
        gates = x_t + h @ w_h + bias
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = act_g(gi)
        f = act_g(gf)
        cand = act_cand(gc)
        c_new = f * c + i * cand
        if w_oc is not None:
            go = go + c_new * w_oc
        o = act_g(go)
        h_new = o * act_c(c_new)
        if proj_w is not None:
            h_new = h_new @ proj_w
        h_new = _mask_step(t, seq_len, h_new, h)
        c_new = _mask_step(t, seq_len, c_new, c)
        return (t + 1, h_new, c_new), (h_new, c_new)

    H = w_h.shape[0]
    h_init = jnp.zeros((B, H), x.dtype) if h0 is None else jnp.asarray(h0)
    c_init = jnp.zeros((B, D), x.dtype) if c0 is None else jnp.asarray(c0)
    _, (hs, cs) = jax.lax.scan(step, (jnp.zeros((), jnp.int32), h_init,
                                      c_init), xs)
    hs = jnp.swapaxes(hs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, 1) if seq_len is None else _flip_padded(hs, seq_len)
        cs = jnp.flip(cs, 1) if seq_len is None else _flip_padded(cs, seq_len)
    return hs, cs


@register_op('gru', optional=('h0',))
def gru(x, h0, gate_w, cand_w, seq_len=None, *, is_reverse=False,
        gate_activation='sigmoid', candidate_activation='tanh',
        origin_mode=False):
    """x: (B, T, 3D) pre-projected [u, r, c̃]; gate_w: (D, 2D) recurrent
    weight for [u, r]; cand_w: (D, D) for the candidate.
    origin_mode=True uses h = u*h_prev + (1-u)*c̃ (ref gru_op origin_mode)."""
    act_g = _ACTS[gate_activation]
    act_c = _ACTS[candidate_activation]
    x = jnp.asarray(x)
    B, T, D3 = x.shape
    D = D3 // 3
    if is_reverse:
        x = jnp.flip(x, axis=1) if seq_len is None else _flip_padded(x, seq_len)
    xs = jnp.swapaxes(x, 0, 1)

    def step(carry, x_t):
        t, h = carry
        xu, xr, xc = jnp.split(x_t, 3, axis=-1)
        ur = act_g(jnp.concatenate([xu, xr], -1) + h @ gate_w)
        u, r = jnp.split(ur, 2, axis=-1)
        c = act_c(xc + (r * h) @ cand_w)
        h_new = u * h + (1.0 - u) * c if origin_mode \
            else (1.0 - u) * h + u * c
        h_new = _mask_step(t, seq_len, h_new, h)
        return (t + 1, h_new), h_new

    h_init = jnp.zeros((B, D), x.dtype) if h0 is None else jnp.asarray(h0)
    _, hs = jax.lax.scan(step, (jnp.zeros((), jnp.int32), h_init), xs)
    hs = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, 1) if seq_len is None else _flip_padded(hs, seq_len)
    return hs


def _flip_padded(x, seq_len):
    """Reverse each row's valid prefix, keeping padding in place
    (the LoD-aware reverse of ref sequence_reverse_op.h)."""
    B, T = x.shape[0], x.shape[1]
    t_idx = jnp.arange(T)[None, :]                      # (1, T)
    lens = jnp.asarray(seq_len).reshape(B, 1)
    src = jnp.where(t_idx < lens, lens - 1 - t_idx, t_idx)
    return jnp.take_along_axis(
        x, src.reshape((B, T) + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1)


@register_op('beam_search_step',
             outputs=('SelectedIds', 'SelectedScores', 'ParentIdx'))
def beam_search_step(pre_ids, pre_scores, ids, scores, *, beam_size, end_id,
                     is_accumulated=True, return_parent_idx=False):
    """One beam step over dense candidates (ref: beam_search_op.cc, LoD
    formulation → dense): pre_ids/pre_scores (B*W, 1); ids/scores (B*W, K)
    per-beam candidates. Finished beams (pre_id == end_id) only continue
    with end_id at their existing score. Returns (B*W, 1) selections and
    flat parent indices."""
    pre_ids = jnp.asarray(pre_ids).reshape(-1)        # (B*W,)
    pre_scores = jnp.asarray(pre_scores).reshape(-1)
    ids = jnp.asarray(ids)
    scores = jnp.asarray(scores)
    BW, K = scores.shape
    W = beam_size
    B = BW // W
    if not is_accumulated:
        scores = pre_scores[:, None] + jnp.log(jnp.clip(scores, 1e-20))
    finished = (pre_ids == end_id)
    # finished beams: candidate 0 = end_id at pre_score, others -inf
    fin_scores = jnp.full((BW, K), -1e9, scores.dtype).at[:, 0].set(pre_scores)
    fin_ids = jnp.full((BW, K), end_id, ids.dtype)
    scores = jnp.where(finished[:, None], fin_scores, scores)
    ids = jnp.where(finished[:, None], fin_ids, ids)
    flat_scores = scores.reshape(B, W * K)
    top_scores, top_idx = jax.lax.top_k(flat_scores, W)     # (B, W)
    parent = top_idx // K + (jnp.arange(B) * W)[:, None]    # flat beam index
    sel_ids = ids.reshape(B, W * K)[jnp.arange(B)[:, None], top_idx]
    return (sel_ids.reshape(BW, 1).astype(_i64()),
            top_scores.reshape(BW, 1),
            parent.reshape(BW).astype(_i64()))


@register_op('gather_tree')
def gather_tree(ids, parents):
    """Beam-search backtrace (ref: gather_tree_op.cc): walk parent pointers
    from the last step to reconstruct full beams. ids/parents: (T, B, W)."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T, B, W = ids.shape
    b_idx = jnp.arange(B)[:, None]

    def step(carry, inp):
        parent = carry                       # (B, W) current beam index
        ids_t, parents_t = inp               # each (B, W)
        out = ids_t[b_idx, parent]
        new_parent = parents_t[b_idx, parent]
        return new_parent, out

    init = jnp.tile(jnp.arange(W)[None, :], (B, 1))
    _, outs = jax.lax.scan(step, init, (ids, parents), reverse=True)
    return outs
