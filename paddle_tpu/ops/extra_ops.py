"""Ops that the reference implements inside layer functions or aux kernels:
spectral_norm, nce, hsigmoid, dice_loss, edit_distance, warpctc, gru_unit,
tree_conv, auc. Registered here so both static layers and dygraph share them.
"""
from __future__ import annotations

import numpy as np
import jax
from ..core.dtypes import runtime_int64 as _i64
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op('spectral_norm')
def spectral_norm(w, *, dim=0, power_iters=1, eps=1e-12):
    """ref: paddle/fluid/operators/spectral_norm_op.cc — power iteration."""
    w = jnp.asarray(w)
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    u = jnp.ones((wm.shape[0],), w.dtype)
    v = jnp.ones((wm.shape[1],), w.dtype)
    for _ in range(max(power_iters, 1)):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wm @ v
    return w / sigma


@register_op('nce', needs_rng=True)
def nce(x, label, weight, bias, *, num_total_classes, num_neg_samples=10,
        key=None):
    """Noise-contrastive estimation (ref: paddle/fluid/operators/nce_op.cc),
    uniform negative sampling inside the jitted step."""
    x = jnp.asarray(x)
    label = jnp.asarray(label).reshape(-1)
    w = jnp.asarray(weight)
    b = jnp.asarray(bias)
    neg = jax.random.randint(key, (num_neg_samples,), 0, num_total_classes)
    pos_logit = jnp.sum(x * w[label], -1) + b[label]
    neg_logit = x @ w[neg].T + b[neg]
    pos_loss = -jax.nn.log_sigmoid(pos_logit)
    neg_loss = -jnp.sum(jax.nn.log_sigmoid(-neg_logit), -1)
    return (pos_loss + neg_loss)[:, None]


@register_op('hsigmoid')
def hsigmoid(x, label, weight, bias, *, num_classes):
    """Hierarchical sigmoid over a complete binary tree
    (ref: paddle/fluid/operators/hierarchical_sigmoid_op.cc)."""
    x = jnp.asarray(x)
    label = jnp.asarray(label).reshape(-1)
    w = jnp.asarray(weight)
    b = jnp.asarray(bias)
    code_len = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)
    ids = label + num_classes
    losses = jnp.zeros((x.shape[0],), x.dtype)
    for _ in range(code_len):
        parent = ids // 2
        is_right = (ids % 2).astype(x.dtype)
        valid = (parent >= 1) & (parent < num_classes)
        node = jnp.clip(parent - 1, 0, num_classes - 1)
        logit = jnp.sum(x * w[node], -1) + b[node]
        ce = jnp.maximum(logit, 0) - logit * is_right + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
        losses = losses + jnp.where(valid, ce, 0.0)
        ids = parent
    return losses[:, None]


@register_op('dice_loss')
def dice_loss(x, label, *, epsilon=1e-5):
    x = jnp.asarray(x)
    label = jnp.asarray(label)
    if label.shape[-1] == 1:
        label = jax.nn.one_hot(label[..., 0], x.shape[-1])
    label = label.astype(x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = 2 * jnp.sum(x * label, reduce_dims)
    union = jnp.sum(x, reduce_dims) + jnp.sum(label, reduce_dims)
    return jnp.mean(1 - (inter + epsilon) / (union + epsilon))


@register_op('edit_distance', outputs=['Out', 'SequenceNum'])
def edit_distance(x, label, x_len=None, label_len=None, *, normalized=True):
    """Levenshtein DP via lax.scan, static shapes
    (ref: paddle/fluid/operators/edit_distance_op.cc)."""
    x = jnp.asarray(x)
    label = jnp.asarray(label)
    b, n = x.shape
    m = label.shape[1]
    xl = jnp.asarray(x_len).reshape(-1) if x_len is not None else jnp.full((b,), n)
    ll = jnp.asarray(label_len).reshape(-1) if label_len is not None \
        else jnp.full((b,), m)

    def per_row(xr, lr, nx, nl):
        # DP over full padded matrix with masking on lengths
        row0 = jnp.arange(m + 1, dtype=jnp.float32)

        def step(prev, i):
            def inner(left, j):
                up = prev[j + 1]
                diag = prev[j]
                cost = jnp.where(xr[i] == lr[j], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(left + 1, up + 1), diag + cost)
                return val, val
            first = prev[0] + 1
            _, rest = lax.scan(inner, first, jnp.arange(m))
            row = jnp.concatenate([first[None], rest])
            row = jnp.where(i < nx, row, prev)
            return row, None

        final, _ = lax.scan(step, row0, jnp.arange(n))
        return final[nl]

    d = jax.vmap(per_row)(x, label, xl, ll).astype(jnp.float32)
    if normalized:
        d = d / jnp.maximum(ll.astype(jnp.float32), 1.0)
    return d[:, None], jnp.asarray([b], _i64())


@register_op('warpctc')
def warpctc(logits, label, logit_len=None, label_len=None, *, blank=0,
            norm_by_times=False):
    """CTC loss, log-space forward algorithm over lax.scan — the TPU-native
    replacement for the warp-ctc CUDA dependency
    (ref: paddle/fluid/operators/warpctc_op.cc)."""
    logits = jnp.asarray(logits)
    label = jnp.asarray(label)
    if logits.ndim == 3 and logits.shape[0] != label.shape[0]:
        logits = jnp.swapaxes(logits, 0, 1)  # (T,B,C) → (B,T,C)
    b, t, c = logits.shape
    l = label.shape[1]
    logp = jax.nn.log_softmax(logits, -1)
    tl = jnp.asarray(logit_len).reshape(-1) if logit_len is not None \
        else jnp.full((b,), t)
    ll = jnp.asarray(label_len).reshape(-1) if label_len is not None \
        else jnp.full((b,), l)
    ext = jnp.full((b, 2 * l + 1), blank)
    ext = ext.at[:, 1::2].set(label)
    neg_inf = -1e30

    def per_seq(lp, e, nt, nl):
        s = 2 * nl + 1
        alpha0 = jnp.full((2 * l + 1,), neg_inf)
        alpha0 = alpha0.at[0].set(lp[0, blank])
        alpha0 = alpha0.at[1].set(jnp.where(nl > 0, lp[0, e[1]], neg_inf))

        def step(alpha, ti):
            prev1 = jnp.concatenate([jnp.array([neg_inf]), alpha[:-1]])
            prev2 = jnp.concatenate([jnp.array([neg_inf, neg_inf]), alpha[:-2]])
            idx = jnp.arange(2 * l + 1)
            same = jnp.concatenate([jnp.array([True, True]), e[2:] == e[:-2]])
            allow2 = (idx % 2 == 1) & (~same)
            cand = jnp.logaddexp(alpha, prev1)
            cand = jnp.where(allow2, jnp.logaddexp(cand, prev2), cand)
            new = cand + lp[ti, e]
            new = jnp.where(ti < nt, new, alpha)
            return new, None

        alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, t))
        ll_prob = jnp.logaddexp(alphaT[s - 1], alphaT[s - 2])
        loss = -ll_prob
        if norm_by_times:
            loss = loss / jnp.maximum(nt, 1)
        return loss

    return jax.vmap(per_seq)(logp, ext, tl, ll)[:, None]


@register_op('ctc_greedy_decoder', outputs=['Out', 'OutLen'])
def ctc_greedy_decoder(x, length=None, *, blank, padding_value=-1):
    """ref: paddle/fluid/operators/ctc_align_op.cc — argmax, merge repeats,
    drop blanks; output padded with padding_value. `length` masks pad frames
    of the (B, T, C) batch out of the decode."""
    x = jnp.asarray(x)  # (B, T, C) probs
    ids = jnp.argmax(x, -1)  # B, T
    b, t = ids.shape
    prev = jnp.concatenate([jnp.full_like(ids[:, :1], -1), ids[:, :-1]], 1)
    keep = (ids != blank) & (ids != prev)
    pos = jnp.arange(t)[None, :]
    if length is not None:
        valid = pos < jnp.asarray(length).reshape(b, 1)
        keep = keep & valid
    order = jnp.argsort(~keep, axis=1, stable=True)
    gathered = jnp.take_along_axis(ids, order, 1)
    counts = jnp.sum(keep, 1)
    out = jnp.where(pos < counts[:, None], gathered, padding_value)
    return out, counts


@register_op('gru_unit', outputs=['Hidden', 'ResetHidden', 'Gate'])
def gru_unit(x, hidden, weight, bias=None, *, origin_mode=False):
    """ref: paddle/fluid/operators/gru_unit_op.cc. x: (B, 3D) projected input."""
    x = jnp.asarray(x)
    h = jnp.asarray(hidden)
    w = jnp.asarray(weight)
    d = h.shape[-1]
    g = x + (jnp.asarray(bias) if bias is not None else 0.0)
    wu_r = w[:, :2 * d]
    wc = w[:, 2 * d:]
    ur = jax.nn.sigmoid(g[:, :2 * d] + h @ wu_r)
    u, r = ur[:, :d], ur[:, d:]
    rh = r * h
    c = jnp.tanh(g[:, 2 * d:] + rh @ wc)
    if origin_mode:
        new_h = u * h + (1 - u) * c
    else:
        new_h = (1 - u) * h + u * c
    return new_h, rh, jnp.concatenate([ur, c], -1)


@register_op('lstm_unit', outputs=['H', 'C'])
def lstm_unit(x, cell, *, forget_bias=0.0):
    """ref: paddle/fluid/operators/lstm_unit_op.cc. x: (B, 4D) gates."""
    x = jnp.asarray(x)
    c_prev = jnp.asarray(cell)
    d = c_prev.shape[-1]
    # gate layout matches the reference kernel: i, f, o at 2D, candidate g
    # at 3D — weights exchanged with the reference stay bit-compatible
    i, f, o, g = jnp.split(x, 4, axis=-1)
    new_c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(g)
    new_h = jnp.tanh(new_c) * jax.nn.sigmoid(o)
    return new_h, new_c


@register_op('tree_conv')
def tree_conv(nodes, edges, weight, *, max_depth=8):
    """Tree-based convolution (ref: paddle/fluid/operators/tree_conv_op.cc),
    dense positional-role formulation for static shapes."""
    nodes = jnp.asarray(nodes)
    w = jnp.asarray(weight)  # F,3,O,K
    agg_self = jnp.einsum('bnf,fok->bnok', nodes, w[:, 0])
    agg_l = jnp.einsum('bnf,fok->bnok', nodes, w[:, 1])
    agg_r = jnp.einsum('bnf,fok->bnok', nodes, w[:, 2])
    # linear output — the layer wrapper owns the activation (double-tanh
    # otherwise; ref applies act outside the kernel too)
    return agg_self + 0.5 * (agg_l + agg_r)


@register_op('auc')
def auc(pred, label, *, num_thresholds=200):
    """Batch ROC-AUC by rank statistic (ref: paddle/fluid/operators/metrics/
    auc_op.cc keeps global accumulators; metrics.Auc does that on top)."""
    p = jnp.asarray(pred)
    p = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else p.reshape(-1)
    y = jnp.asarray(label).reshape(-1).astype(jnp.float32)
    order = jnp.argsort(p)
    n = p.shape[0]
    ranks = jnp.zeros((n,)).at[order].set(jnp.arange(1, n + 1, dtype=jnp.float32))
    pos = jnp.sum(y)
    neg = n - pos
    sum_ranks_pos = jnp.sum(jnp.where(y > 0, ranks, 0.0))
    return (sum_ranks_pos - pos * (pos + 1) / 2) / jnp.maximum(pos * neg, 1.0)


@register_op('linear_chain_crf', outputs=['LogLikelihood', 'Alpha',
                                          'EmissionExps', 'TransitionExps'])
def linear_chain_crf(emission, transition, label, length=None):
    """ref: paddle/fluid/operators/linear_chain_crf_op.cc. Batched dense form:
    emission (B,T,N), transition (N+2,N) with rows 0/1 = start/stop weights."""
    em = jnp.asarray(emission)
    tr = jnp.asarray(transition)
    lb = jnp.asarray(label)
    if lb.ndim == 3 and lb.shape[-1] == 1:
        lb = lb[..., 0]
    b, t, n = em.shape
    start, stop, trans = tr[0], tr[1], tr[2:]
    ln = jnp.asarray(length).reshape(-1) if length is not None \
        else jnp.full((b,), t)

    def per_seq(e, y, nt):
        a0 = start + e[0]

        def step(alpha, ti):
            nxt = jax.scipy.special.logsumexp(
                alpha[:, None] + trans, axis=0) + e[ti]
            nxt = jnp.where(ti < nt, nxt, alpha)
            return nxt, None
        alphaT, _ = lax.scan(step, a0, jnp.arange(1, t))
        logz = jax.scipy.special.logsumexp(alphaT + stop)
        # score of gold path
        idx = jnp.arange(t)
        em_score = jnp.sum(jnp.where(idx < nt,
                                     jnp.take_along_axis(e, y[:, None], 1)[:, 0],
                                     0.0))
        pair_valid = (idx[1:] < nt)
        tr_score = jnp.sum(jnp.where(pair_valid, trans[y[:-1], y[1:]], 0.0))
        last = jnp.clip(nt - 1, 0, t - 1)
        gold = em_score + tr_score + start[y[0]] + stop[y[last]]
        return -(gold - logz)

    nll = jax.vmap(per_seq)(em, lb, ln)
    return nll[:, None], em, jnp.exp(em), jnp.exp(tr)


@register_op('crf_decoding')
def crf_decoding(emission, transition, length=None):
    """Viterbi decode (ref: paddle/fluid/operators/crf_decoding_op.cc)."""
    em = jnp.asarray(emission)
    tr = jnp.asarray(transition)
    b, t, n = em.shape
    start, stop, trans = tr[0], tr[1], tr[2:]
    ln = jnp.asarray(length).reshape(-1) if length is not None \
        else jnp.full((b,), t)

    def per_seq(e, nt):
        a0 = start + e[0]

        def fwd(alpha, ti):
            scores = alpha[:, None] + trans
            best = jnp.max(scores, axis=0) + e[ti]
            bp = jnp.argmax(scores, axis=0)
            new = jnp.where(ti < nt, best, alpha)
            return new, bp

        alphaT, bps = lax.scan(fwd, a0, jnp.arange(1, t))
        lastn = jnp.argmax(alphaT + stop)

        def bwd(nxt, ti):
            cur = bps[ti][nxt]
            keep = ti + 1 < nt
            cur = jnp.where(keep, cur, nxt)
            return cur, cur

        _, path_rev = lax.scan(bwd, lastn, jnp.arange(t - 2, -1, -1))
        path = jnp.concatenate([path_rev[::-1], lastn[None]])
        return path

    return jax.vmap(per_seq)(em, ln).astype(_i64())


@register_op('chunk_eval', outputs=['Precision', 'Recall', 'F1',
                                    'NumInferChunks', 'NumLabelChunks',
                                    'NumCorrectChunks'])
def chunk_eval(inference, label, length=None, *, num_chunk_types,
               chunk_scheme='IOB', excluded_chunk_types=None):
    """ref: paddle/fluid/operators/chunk_eval_op.cc — IOB span F1 on padded
    id sequences; `length` masks pad positions out of the chunk counts.
    Tag encoding: tag = type * tag_num + {B:0, I:1}."""
    inf = jnp.asarray(inference).reshape(jnp.asarray(inference).shape[0], -1)
    lab = jnp.asarray(label).reshape(inf.shape)
    tag_num = 2 if chunk_scheme == 'IOB' else 4
    if length is not None:
        valid = (jnp.arange(inf.shape[1])[None, :]
                 < jnp.asarray(length).reshape(-1, 1))
    else:
        valid = jnp.ones_like(inf, bool)

    def starts(seq):
        typ = seq // tag_num
        pos = seq % tag_num
        prev = jnp.concatenate([jnp.full_like(seq[:, :1], -1), seq[:, :-1]], 1)
        ptyp = prev // tag_num
        is_b = (pos == 0)
        cont_break = (typ != ptyp)
        return is_b | cont_break

    inf_start = starts(inf) & valid
    lab_start = starts(lab) & valid
    num_inf = jnp.sum(inf_start)
    num_lab = jnp.sum(lab_start)
    correct = jnp.sum(inf_start & lab_start & (inf == lab))
    prec = correct / jnp.maximum(num_inf, 1)
    rec = correct / jnp.maximum(num_lab, 1)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-9)
    return (prec.astype(jnp.float32), rec.astype(jnp.float32),
            f1.astype(jnp.float32), num_inf.astype(_i64()),
            num_lab.astype(_i64()), correct.astype(_i64()))


# ---------------------------------------------------------------------------
# misc long-tail ops (ref: paddle/fluid/operators/{hash,similarity_focus,
# cvm,filter_by_instag,scatter_nd,shape,rank,size}_op.*)
# ---------------------------------------------------------------------------


@register_op('scatter_nd')
def scatter_nd(index, updates, *, shape):
    """zeros(shape) with `updates` summed in at `index` (scatter_nd_op.h)."""
    index = jnp.asarray(index)
    updates = jnp.asarray(updates)
    out = jnp.zeros(tuple(shape), updates.dtype)
    return out.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@register_op('shape')
def shape_op(x):
    return jnp.asarray(jnp.asarray(x).shape, jnp.int32)


@register_op('rank')
def rank_op(x):
    return jnp.asarray(jnp.asarray(x).ndim, jnp.int32)


@register_op('size')
def size_op(x):
    return jnp.asarray(jnp.asarray(x).size, _i64())


@register_op('hash')
def hash_op(x, *, num_hash=1, mod_by=100000000):
    """Bucketize integer id rows with num_hash independent hashes
    (hash_op.h uses XXH64; any well-mixed integer hash satisfies the
    contract — stable buckets in [0, mod_by))."""
    x = jnp.asarray(x).astype(jnp.uint32)
    flat = x.reshape(x.shape[0], -1)

    def mix(v, seed):
        # splitmix32-style avalanche, vectorized
        v = v ^ jnp.uint32(seed)
        v = (v ^ (v >> 16)) * jnp.uint32(0x85ebca6b)
        v = (v ^ (v >> 13)) * jnp.uint32(0xc2b2ae35)
        return v ^ (v >> 16)

    outs = []
    for h in range(num_hash):
        acc = jnp.full((flat.shape[0],),
                       jnp.uint32((0x9e3779b9 * (h + 1)) & 0xFFFFFFFF))
        for c in range(flat.shape[1]):
            acc = mix(acc ^ flat[:, c],
                      (0x9e3779b9 + h * 0x61c88647 + c) & 0xFFFFFFFF)
        outs.append((acc % jnp.uint32(mod_by)).astype(_i64()))
    return jnp.stack(outs, 1)[:, :, None]


@register_op('similarity_focus')
def similarity_focus(x, *, axis, indexes):
    """Greedy bipartite focus mask (similarity_focus_op.h): repeatedly take
    the largest untagged element of the selected slice, tag its row+col, and
    light the full fiber along `axis` at that position. lax.fori_loop with a
    masked argmax replaces the reference's sort+scan."""
    x = jnp.asarray(x)
    if x.ndim != 4 or axis not in (1, 2, 3):
        raise ValueError("similarity_focus expects rank-4 input, axis in 1..3")
    # view with `axis` first: (B, A, M, N_)
    order = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
    xv = x.transpose(order)
    B, A, M, N_ = xv.shape
    steps = min(M, N_)

    def per_slice(mat):                       # (M, N_) → (M, N_) 0/1
        def body(_, st):
            sel, rt, ct = st
            masked = jnp.where(rt[:, None] | ct[None, :], -jnp.inf, mat)
            flat = jnp.argmax(masked)
            r, c = flat // N_, flat % N_
            return (sel.at[r, c].set(1.0),
                    rt.at[r].set(True), ct.at[c].set(True))
        sel, _, _ = lax.fori_loop(
            0, steps, body,
            (jnp.zeros((M, N_), x.dtype), jnp.zeros(M, bool),
             jnp.zeros(N_, bool)))
        return sel

    sel = jnp.zeros((B, M, N_), x.dtype)
    for idx in indexes:
        sel = jnp.maximum(sel, jax.vmap(per_slice)(xv[:, idx]))
    out = jnp.broadcast_to(sel[:, None], (B, A, M, N_))
    inv = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 2, 3, 1)}[axis]
    return out.transpose(inv)


@register_op('cvm')
def cvm(x, cvm_in, *, use_cvm=True):
    """Continuous-value-model feature adjust (cvm_op.h): show/click columns
    are log-transformed in, or stripped out."""
    x = jnp.asarray(x)
    c = jnp.asarray(cvm_in)
    if use_cvm:
        show = jnp.log(c[:, :1] + 1.0)
        click = jnp.log(c[:, 1:2] + 1.0) - jnp.log(c[:, :1] + 1.0)
        return jnp.concatenate([show, click, x[:, 2:]], axis=1)
    return x[:, 2:]


@register_op('filter_by_instag', outputs=['Out', 'LossWeight', 'IndexMap'])
def filter_by_instag(x, ins_tag, filter_tag, *, is_lod=False,
                     out_val_if_empty=0):
    """Row filter by tag membership. TPU formulation: static-shape masking —
    kept rows pass through, dropped rows zero out, LossWeight marks keeps
    (the reference compacts rows; downstream loss×weight gives identical
    training math without dynamic shapes)."""
    x = jnp.asarray(x)
    tags = jnp.asarray(ins_tag)            # (B, K) padded tag lists
    filt = jnp.asarray(filter_tag).reshape(-1)
    if tags.ndim == 1:
        tags = tags[:, None]
    keep = (tags[:, :, None] == filt[None, None, :]).any(axis=(1, 2))
    w = keep.astype(x.dtype)
    out = jnp.where(keep.reshape((-1,) + (1,) * (x.ndim - 1)), x,
                    jnp.asarray(out_val_if_empty, x.dtype))
    idx = jnp.arange(x.shape[0], dtype=_i64())
    return out, w[:, None], jnp.stack([idx, idx], axis=1)


@register_op('lod_reset', outputs=['Out', 'Length'])
def lod_reset(x, y=None, *, target_lod=None):
    """Re-associate sequence structure: emits the data unchanged plus the
    new (B,) length vector (offsets→lengths; the padded-batch analogue of
    swapping the LoD table, lod_reset_op.h)."""
    x = jnp.asarray(x)
    if y is not None:
        off = jnp.asarray(y).reshape(-1).astype(jnp.int32)
    elif target_lod is not None:
        off = jnp.asarray(target_lod, jnp.int32)
    else:
        raise ValueError("lod_reset needs y or target_lod")
    # both y's data and target_lod are LoD OFFSET tables like the reference;
    # the padded-batch formulation carries lengths = diff(offsets)
    return x, off[1:] - off[:-1]


@register_op('merge_selected_rows')
def merge_selected_rows(x):
    """SelectedRows (sparse grad rows) are already dense-coalesced in the
    TPU lowering — identity (merge_selected_rows_op.h)."""
    return jnp.asarray(x)


@register_op('get_tensor_from_selected_rows')
def get_tensor_from_selected_rows(x):
    return jnp.asarray(x)
