"""Elementwise math, matmul, reductions, comparisons, logicals.

Parity targets: /root/reference/paddle/fluid/operators/elementwise/*,
matmul_op.cc, mul_op.cc, reduce_ops/*, controlflow/compare_op.cc, scale_op.cc.
All are thin jax functionals — XLA fuses them; gradients via jax.vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _align_y(x, y, axis=-1):
    """Paddle elementwise broadcast: align y at `axis` of x (ref:
    paddle/fluid/operators/elementwise/elementwise_op_function.h)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if y.ndim == 0 or x.shape == y.shape or y.ndim >= x.ndim:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    trailing = x.ndim - axis - y.ndim
    if trailing > 0:
        y = y.reshape(y.shape + (1,) * trailing)
    return y


def _ew(name, fn):
    @register_op(name)
    def op(x, y, *, axis=-1):
        return fn(jnp.asarray(x), _align_y(x, y, axis))
    op.__name__ = name
    return op


elementwise_add = _ew('elementwise_add', jnp.add)
elementwise_sub = _ew('elementwise_sub', jnp.subtract)
elementwise_mul = _ew('elementwise_mul', jnp.multiply)
elementwise_div = _ew('elementwise_div', jnp.divide)
elementwise_max = _ew('elementwise_max', jnp.maximum)
elementwise_min = _ew('elementwise_min', jnp.minimum)
elementwise_pow = _ew('elementwise_pow', jnp.power)
elementwise_mod = _ew('elementwise_mod', jnp.mod)
elementwise_floordiv = _ew('elementwise_floordiv', jnp.floor_divide)


@register_op('scale')
def scale(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    x = jnp.asarray(x)
    s = jnp.asarray(scale, x.dtype)
    b = jnp.asarray(bias, x.dtype)
    return x * s + b if bias_after_scale else (x + b) * s


@register_op('matmul')
def matmul(x, y, *, transpose_x=False, transpose_y=False, alpha=1.0):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return out


@register_op('mul')
def mul(x, y, *, x_num_col_dims=1, y_num_col_dims=1):
    """Flatten-to-2D matmul (ref: paddle/fluid/operators/mul_op.cc)."""
    import math
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    xs, ys = x.shape, y.shape
    xm = x.reshape((math.prod(xs[:x_num_col_dims]), -1))
    ym = y.reshape((math.prod(ys[:y_num_col_dims]), -1))
    out = xm @ ym
    out_shape = xs[:x_num_col_dims] + ys[y_num_col_dims:]
    return out.reshape(out_shape)


@register_op('cumsum')
def cumsum(x, *, axis=None, exclusive=False, reverse=False, flatten=False):
    """Cumulative sum (ref: paddle/fluid/operators/cum_op.cc). axis=None
    follows the reference: flatten and cumsum over all elements."""
    x = jnp.asarray(x)
    if axis is None or flatten:
        x = x.reshape(-1)
        axis = 0
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


@register_op('sum', variadic=['xs'])
def sum_op(xs):
    """Add N tensors (ref: paddle/fluid/operators/sum_op.cc)."""
    if not isinstance(xs, (list, tuple)):
        return jnp.asarray(xs)
    out = jnp.asarray(xs[0])
    for x in xs[1:]:
        out = out + jnp.asarray(x)
    return out


@register_op('clip')
def clip(x, *, min, max):
    return jnp.clip(jnp.asarray(x), min, max)


@register_op('clip_by_norm')
def clip_by_norm(x, *, max_norm):
    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


# ---------------------------------------------------------------------------
# unary math / activations (ref: paddle/fluid/operators/activation_op.cc)
# ---------------------------------------------------------------------------

def _unary(name, fn):
    @register_op(name)
    def op(x):
        return fn(jnp.asarray(x))
    op.__name__ = name
    return op


sigmoid = _unary('sigmoid', jax.nn.sigmoid)
logsigmoid = _unary('logsigmoid', jax.nn.log_sigmoid)
exp = _unary('exp', jnp.exp)
tanh = _unary('tanh', jnp.tanh)
atan = _unary('atan', jnp.arctan)
tanh_shrink = _unary('tanh_shrink', lambda x: x - jnp.tanh(x))
sqrt = _unary('sqrt', jnp.sqrt)
rsqrt = _unary('rsqrt', lax.rsqrt)
abs_ = _unary('abs', jnp.abs)
ceil = _unary('ceil', jnp.ceil)
floor = _unary('floor', jnp.floor)
cos = _unary('cos', jnp.cos)
sin = _unary('sin', jnp.sin)
acos = _unary('acos', jnp.arccos)
asin = _unary('asin', jnp.arcsin)
cosh = _unary('cosh', jnp.cosh)
sinh = _unary('sinh', jnp.sinh)
round_ = _unary('round', jnp.round)
reciprocal = _unary('reciprocal', lambda x: 1.0 / x)
log_ = _unary('log', jnp.log)
square = _unary('square', jnp.square)
softplus = _unary('softplus', jax.nn.softplus)
softsign = _unary('softsign', jax.nn.soft_sign)
relu = _unary('relu', jax.nn.relu)
sign = _unary('sign', jnp.sign)
erf = _unary('erf', lax.erf)


@register_op('gelu')
def gelu(x, *, approximate=False):
    return jax.nn.gelu(jnp.asarray(x), approximate=approximate)


@register_op('leaky_relu')
def leaky_relu(x, *, alpha=0.02):
    x = jnp.asarray(x)
    return jnp.where(x >= 0, x, alpha * x)


@register_op('relu6')
def relu6(x, *, threshold=6.0):
    return jnp.clip(jnp.asarray(x), 0.0, threshold)


@register_op('elu')
def elu(x, *, alpha=1.0):
    x = jnp.asarray(x)
    return jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))


@register_op('selu')
def selu(x, *, scale=1.0507009873554805, alpha=1.6732632423543772):
    x = jnp.asarray(x)
    return scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))


@register_op('prelu')
def prelu(x, alpha, *, mode='all'):
    x = jnp.asarray(x)
    a = jnp.asarray(alpha)
    if mode == 'channel' and a.size > 1:
        a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == 'all':
        a = a.reshape(())if a.size == 1 else a
    return jnp.where(x >= 0, x, a * x)


@register_op('brelu')
def brelu(x, *, t_min=0.0, t_max=24.0):
    return jnp.clip(jnp.asarray(x), t_min, t_max)


@register_op('soft_relu')
def soft_relu(x, *, threshold=40.0):
    x = jnp.clip(jnp.asarray(x), -threshold, threshold)
    return jnp.log1p(jnp.exp(x))


@register_op('stanh')
def stanh(x, *, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * jnp.asarray(x))


@register_op('hard_sigmoid')
def hard_sigmoid(x, *, slope=0.2, offset=0.5):
    return jnp.clip(slope * jnp.asarray(x) + offset, 0.0, 1.0)


@register_op('hard_swish')
def hard_swish(x, *, threshold=6.0, scale=6.0, offset=3.0):
    x = jnp.asarray(x)
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


@register_op('swish')
def swish(x, *, beta=1.0):
    x = jnp.asarray(x)
    return x * jax.nn.sigmoid(beta * x)


@register_op('hard_shrink')
def hard_shrink(x, *, threshold=0.5):
    x = jnp.asarray(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@register_op('softshrink')
def softshrink(x, *, lambda_=0.5):
    x = jnp.asarray(x)
    return jnp.where(x > lambda_, x - lambda_, jnp.where(x < -lambda_, x + lambda_, 0.0))


@register_op('thresholded_relu')
def thresholded_relu(x, *, threshold=1.0):
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x, 0.0)


@register_op('maxout')
def maxout(x, *, groups, axis=1):
    x = jnp.asarray(x)
    c = x.shape[axis]
    assert c % groups == 0
    shape = list(x.shape)
    shape[axis:axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)


@register_op('pow')
def pow_op(x, *, factor=1.0):
    return jnp.power(jnp.asarray(x), factor)


@register_op('mean')
def mean(x):
    return jnp.mean(jnp.asarray(x))


# ---------------------------------------------------------------------------
# reductions (ref: paddle/fluid/operators/reduce_ops/*)
# ---------------------------------------------------------------------------

def _norm_dim(dim, ndim):
    if dim is None:
        return None
    dims = [dim] if isinstance(dim, int) else list(dim)
    return tuple(d % ndim for d in dims)


def _reduce(name, fn):
    @register_op(name)
    def op(x, *, dim=None, keep_dim=False, reduce_all=False):
        x = jnp.asarray(x)
        axis = None if reduce_all or dim is None else _norm_dim(dim, x.ndim)
        return fn(x, axis=axis, keepdims=keep_dim)
    op.__name__ = name
    return op


reduce_sum = _reduce('reduce_sum', jnp.sum)
reduce_mean = _reduce('reduce_mean', jnp.mean)
reduce_max = _reduce('reduce_max', jnp.max)
reduce_min = _reduce('reduce_min', jnp.min)
reduce_prod = _reduce('reduce_prod', jnp.prod)
reduce_all = _reduce('reduce_all', jnp.all)
reduce_any = _reduce('reduce_any', jnp.any)


@register_op('logsumexp')
def logsumexp(x, *, dim=None, keep_dim=False):
    x = jnp.asarray(x)
    return jax.scipy.special.logsumexp(x, axis=_norm_dim(dim, x.ndim), keepdims=keep_dim)


# ---------------------------------------------------------------------------
# comparisons / logicals (ref: paddle/fluid/operators/controlflow/compare_op.cc)
# ---------------------------------------------------------------------------

def _cmp(name, fn):
    @register_op(name)
    def op(x, y):
        return fn(jnp.asarray(x), jnp.asarray(y))
    op.__name__ = name
    return op


equal = _cmp('equal', jnp.equal)
not_equal = _cmp('not_equal', jnp.not_equal)
less_than = _cmp('less_than', jnp.less)
less_equal = _cmp('less_equal', jnp.less_equal)
greater_than = _cmp('greater_than', jnp.greater)
greater_equal = _cmp('greater_equal', jnp.greater_equal)
logical_and = _cmp('logical_and', jnp.logical_and)
logical_or = _cmp('logical_or', jnp.logical_or)
logical_xor = _cmp('logical_xor', jnp.logical_xor)
logical_not = _unary('logical_not', jnp.logical_not)


@register_op('isfinite')
def isfinite(x):
    return jnp.all(jnp.isfinite(jnp.asarray(x)))


@register_op('has_inf')
def has_inf(x):
    return jnp.any(jnp.isinf(jnp.asarray(x)))


@register_op('has_nan')
def has_nan(x):
    return jnp.any(jnp.isnan(jnp.asarray(x)))


@register_op('cos_sim')
def cos_sim(x, y):
    """Row-wise cosine similarity (ref: paddle/fluid/operators/cos_sim_op.cc)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    xn = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, -1, keepdims=True))
    return jnp.sum(x * y, -1, keepdims=True) / (xn * yn)


@register_op('kron')
def kron(x, y):
    return jnp.kron(jnp.asarray(x), jnp.asarray(y))


@register_op('dot')
def dot(x, y):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    return jnp.sum(x * y, axis=-1, keepdims=True)


@register_op('increment')
def increment(x, *, value=1.0):
    x = jnp.asarray(x)
    return x + jnp.asarray(value, x.dtype)
