"""Functional op library. Importing this package registers all ops.

Modules double as a direct functional API (used by dygraph layers), e.g.
`from paddle_tpu.ops import nn_ops as F; F.conv2d(x, w, stride=1)`.
"""
from . import registry
from .registry import register_op, get_op, has_op, all_ops, custom_op
from . import (math_ops, tensor_ops, nn_ops, loss_ops, random_ops,
               optimizer_ops, extra_ops, rnn_ops, sequence_ops, vision_ops,
               detection_ops, quant_ops, contrib_ops, pallas_conv, fused_ops,
               sparse_ops)

# collective ops live in parallel/collective.py (jax collectives usable
# inside shard_map programs), not in this registry.
