"""Fused kernels backing the IR pass pipeline (paddle_tpu/ir/).

Two families, mirroring the reference ops the ``framework/ir`` fusion
passes emit:

- ``fused_elemwise_add_activation`` (ref: fused_elemwise_activation_op.cc)
  — one dispatch for the (bias-add, activation) pair the
  fuse_elewise_add_act pass collapses;
- ``fused_sgd`` / ``fused_momentum`` / ``fused_adam`` — multi-tensor
  apply over ONE flattened parameter bundle (ref: the executables behind
  fuse_all_optimizer_ops). The update arithmetic runs once over the
  bundle, so the jaxpr carries O(#params) cheap reshape/slice equations
  instead of O(#params) copies of the full update chain; Adam's per-param
  bias-correction scalars expand over the bundle with one
  ``jnp.repeat(..., total_repeat_length=)`` gather.

The update math is written expression-for-expression like the per-param
ops in optimizer_ops.py: elementwise arithmetic over a concatenation of
the same values is bit-identical, which the pass-parity suite asserts.

All three bundle ops are update ops (they run after the backward marker,
outside jax.value_and_grad), so they need no custom vjp. Tradeoff,
measured on CPU (PERF.md §10): XLA's backend compile of the bundled
update costs ~5-10% more than N small per-param kernels — paid once EVER
per program via the persistent compile cache (PR 1) — while the trace,
which every cold process pays on every cache hit, shrinks ~1.4×.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .math_ops import _align_y
from .registry import register_op

_ACTS = {'relu': jax.nn.relu, 'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh}


@register_op('fused_elemwise_add_activation')
def fused_elemwise_add_activation(x, y, *, functor='relu', axis=-1):
    return _ACTS[functor](jnp.add(jnp.asarray(x), _align_y(x, y, axis)))


# ---------------------------------------------------------------------------
# multi-tensor optimizer apply
# ---------------------------------------------------------------------------

def _bundle(xs):
    """list of arrays → (flat concat, shapes, sizes). Static at trace time;
    1-D members concatenate as-is (ravel would be a no-op equation)."""
    xs = [jnp.asarray(x) for x in xs]
    shapes = [x.shape for x in xs]
    sizes = [int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes]
    return (jnp.concatenate([x if x.ndim == 1 else jnp.ravel(x)
                             for x in xs]), shapes, sizes)


def _split(flat, shapes, sizes):
    out, off = [], 0
    for shp, sz in zip(shapes, sizes):
        seg = flat[off:off + sz]
        out.append(seg if shp == (sz,) else jnp.reshape(seg, shp))
        off += sz
    return out


def _per_param(vec, sizes):
    """(N,) per-param scalars → flat (sum(sizes),) vector, each scalar
    repeated over its parameter's span."""
    total = int(sum(sizes))
    return jnp.repeat(vec, np.asarray(sizes), total_repeat_length=total)


@register_op('fused_sgd', outputs=['ParamOut'],
             variadic=['params', 'grads'])
def fused_sgd(params, grads, lr):
    P, shapes, sizes = _bundle(params)
    G, _, _ = _bundle(grads)
    lr = jnp.reshape(jnp.asarray(lr), ())
    return _split(P - lr * G, shapes, sizes)


@register_op('fused_momentum', outputs=['ParamOut', 'VelocityOut'],
             variadic=['params', 'grads', 'velocities'])
def fused_momentum(params, grads, velocities, lr, *, mu=0.9,
                   use_nesterov=False):
    P, shapes, sizes = _bundle(params)
    G, _, _ = _bundle(grads)
    V, _, _ = _bundle(velocities)
    lr = jnp.reshape(jnp.asarray(lr), ())
    v_new = mu * V + G
    if use_nesterov:
        p_new = P - (G + mu * v_new) * lr
    else:
        p_new = P - lr * v_new
    return _split(p_new, shapes, sizes), _split(v_new, shapes, sizes)


@register_op('fused_lars_momentum', outputs=['ParamOut', 'VelocityOut'],
             variadic=['params', 'grads', 'velocities'])
def fused_lars_momentum(params, grads, velocities, lr, *, mu=0.9,
                        lars_coeff=0.001, lars_weight_decay=0.0005,
                        epsilon=0.0):
    """Multi-tensor LARS: the per-LAYER trust ratios are reduced at each
    member's own shape (bitwise-equal to the per-param op's norms), then
    expanded over the bundle so the momentum/update chain runs once over
    the flat concatenation — elementwise, hence bit-identical to N
    separate lars_momentum ops."""
    P, shapes, sizes = _bundle(params)
    G, _, _ = _bundle(grads)
    V, _, _ = _bundle(velocities)
    lr = jnp.reshape(jnp.asarray(lr), ())
    pns = jnp.stack([jnp.sqrt(jnp.sum(jnp.square(jnp.asarray(p))))
                     for p in params])
    gns = jnp.stack([jnp.sqrt(jnp.sum(jnp.square(jnp.asarray(g))))
                     for g in grads])
    local_lr = jnp.where(
        (pns > 0) & (gns > 0),
        lr * lars_coeff * pns / (gns + lars_weight_decay * pns + epsilon),
        lr)
    L = _per_param(local_lr, sizes)
    v_new = mu * V + L * (G + lars_weight_decay * P)
    return _split(P - v_new, shapes, sizes), _split(v_new, shapes, sizes)


@register_op('fused_adam', outputs=['ParamOut', 'Moment1Out', 'Moment2Out',
                                    'Beta1PowOut', 'Beta2PowOut'],
             variadic=['params', 'grads', 'moment1s', 'moment2s',
                       'beta1_pows', 'beta2_pows'])
def fused_adam(params, grads, moment1s, moment2s, beta1_pows, beta2_pows,
               lr, *, beta1=0.9, beta2=0.999, epsilon=1e-8):
    P, shapes, sizes = _bundle(params)
    G, _, _ = _bundle(grads)
    M1, _, _ = _bundle(moment1s)
    M2, _, _ = _bundle(moment2s)
    # the _pow slots are (1,)-shaped per param → concatenated they are (N,)
    b1p, _, _ = _bundle(beta1_pows)
    b2p, _, _ = _bundle(beta2_pows)
    lr = jnp.reshape(jnp.asarray(lr), ())
    m1n = beta1 * M1 + (1 - beta1) * G
    m2n = beta2 * M2 + (1 - beta2) * jnp.square(G)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)          # (N,)
    pn = P - _per_param(lr_t, sizes) * m1n / (jnp.sqrt(m2n) + epsilon)
    n = len(sizes)
    pow_shapes, pow_sizes = [(1,)] * n, [1] * n
    return (_split(pn, shapes, sizes), _split(m1n, shapes, sizes),
            _split(m2n, shapes, sizes),
            _split(b1p * beta1, pow_shapes, pow_sizes),
            _split(b2p * beta2, pow_shapes, pow_sizes))
