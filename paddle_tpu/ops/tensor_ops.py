"""Tensor manipulation ops.

Parity targets: reference paddle/fluid/operators/{cast,concat,split,reshape,
transpose,slice,strided_slice,gather,scatter,expand,stack,unstack,squeeze,
unsqueeze,flatten,reverse,fill_constant,assign,arg_min_max,argsort,top_k,
where,diag,eye,one_hot,shard_index,range,linspace,unique}_op.*

TPU notes: everything static-shaped. `unique` (dynamic output in the ref)
returns a padded result + count, the XLA-compatible formulation.
"""
from __future__ import annotations

import math

import jax
from ..core.dtypes import runtime_int64 as _i64
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from ..core.dtypes import to_jax_dtype


@register_op('cast')
def cast(x, *, dtype):
    return jnp.asarray(x).astype(to_jax_dtype(dtype))


@register_op('concat', variadic=['xs'])
def concat(xs, *, axis=0):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return jnp.concatenate([jnp.asarray(x) for x in xs], axis=axis)


@register_op('split', outputs=['Out'], variadic=[])
def split(x, *, num_or_sections, dim=-1):
    x = jnp.asarray(x)
    dim = dim % x.ndim
    if isinstance(num_or_sections, int):
        parts = jnp.split(x, num_or_sections, axis=dim)
    else:
        sizes = list(num_or_sections)
        if any(s in (-1, None) for s in sizes):
            known = sum(s for s in sizes if s not in (-1, None))
            sizes = [x.shape[dim] - known if s in (-1, None) else s for s in sizes]
        idx = [sum(sizes[:i + 1]) for i in range(len(sizes) - 1)]
        parts = jnp.split(x, idx, axis=dim)
    return list(parts)


@register_op('reshape')
def reshape(x, *, shape):
    x = jnp.asarray(x)
    shape = list(shape)
    # Paddle semantics: 0 means copy input dim, -1 inferred
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return x.reshape(shape)


@register_op('transpose')
def transpose(x, *, perm):
    return jnp.transpose(jnp.asarray(x), axes=perm)


@register_op('squeeze')
def squeeze(x, *, axes=None):
    x = jnp.asarray(x)
    if not axes:
        return jnp.squeeze(x)
    return jnp.squeeze(x, axis=tuple(a % x.ndim for a in axes))


@register_op('unsqueeze')
def unsqueeze(x, *, axes):
    x = jnp.asarray(x)
    axes = [axes] if isinstance(axes, int) else list(axes)
    for a in sorted(axes):
        x = jnp.expand_dims(x, a)
    return x


@register_op('stack', variadic=['xs'])
def stack(xs, *, axis=0):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return jnp.stack([jnp.asarray(x) for x in xs], axis=axis)


@register_op('unstack', outputs=['Y'])
def unstack(x, *, axis=0, num=None):
    x = jnp.asarray(x)
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis)]


@register_op('slice')
def slice_op(x, *, axes, starts, ends):
    x = jnp.asarray(x)
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    return x[tuple(idx)]


@register_op('strided_slice')
def strided_slice(x, *, axes, starts, ends, strides):
    x = jnp.asarray(x)
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sd)
    return x[tuple(idx)]


@register_op('crop_tensor')
def crop_tensor(x, *, shape, offsets=None):
    x = jnp.asarray(x)
    offsets = offsets or [0] * x.ndim
    shape = [x.shape[i] if s in (-1, None) else s for i, s in enumerate(shape)]
    return lax.dynamic_slice(x, offsets, shape)


@register_op('gather')
def gather(x, index, *, overwrite=True):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    return jnp.take(x, index, axis=0)


@register_op('gather_nd')
def gather_nd(x, index):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    idx_depth = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx] if idx_depth == x.ndim else x[idx]


@register_op('scatter')
def scatter(x, ids, updates, *, overwrite=True):
    x = jnp.asarray(x)
    ids = jnp.asarray(ids).reshape(-1)
    updates = jnp.asarray(updates)
    if overwrite:
        return x.at[ids].set(updates)
    return x.at[ids].set(0).at[ids].add(updates)


@register_op('scatter_nd_add')
def scatter_nd_add(x, index, updates):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    updates = jnp.asarray(updates)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@register_op('expand')
def expand(x, *, expand_times):
    return jnp.tile(jnp.asarray(x), expand_times)


@register_op('expand_as')
def expand_as(x, target):
    x = jnp.asarray(x)
    t = jnp.asarray(target)
    times = [ts // xs for ts, xs in zip(t.shape, x.shape)]
    return jnp.tile(x, times)


@register_op('tile')
def tile(x, *, repeat_times):
    return jnp.tile(jnp.asarray(x), repeat_times)


@register_op('flatten')
def flatten(x, *, axis=1):
    x = jnp.asarray(x)
    lead = math.prod(x.shape[:axis]) if axis > 0 else 1
    return x.reshape((lead, -1))


@register_op('flatten2')
def flatten2(x, *, axis=1):
    x = jnp.asarray(x)
    lead = math.prod(x.shape[:axis]) if axis > 0 else 1
    return x.reshape((lead, -1))


@register_op('reverse')
def reverse(x, *, axis):
    x = jnp.asarray(x)
    axis = [axis] if isinstance(axis, int) else axis
    return jnp.flip(x, axis=tuple(a % x.ndim for a in axis))


@register_op('fill_constant')
def fill_constant(*, shape, value, dtype='float32'):
    # numpy (not jnp): stays a trace-time CONSTANT inside jit, so counters /
    # TensorArray indices built from it remain concrete; XLA folds it anyway.
    import numpy as np
    import ml_dtypes
    np_dtype = np.dtype(dtype) if dtype not in ('bfloat16',) else ml_dtypes.bfloat16
    return np.full(tuple(shape), value, np_dtype)


@register_op('fill_constant_batch_size_like')
def fill_constant_batch_size_like(ref, *, shape, value, dtype='float32',
                                  input_dim_idx=0, output_dim_idx=0):
    ref = jnp.asarray(ref)
    shape = list(shape)
    shape[output_dim_idx] = ref.shape[input_dim_idx]
    return jnp.full(tuple(shape), value, to_jax_dtype(dtype))


@register_op('fill_zeros_like')
def fill_zeros_like(x):
    return jnp.zeros_like(jnp.asarray(x))


@register_op('fill_any_like')
def fill_any_like(x, *, value, dtype=None):
    x = jnp.asarray(x)
    dt = to_jax_dtype(dtype) if dtype is not None else x.dtype
    return jnp.full_like(x, value, dtype=dt)


@register_op('assign')
def assign(x):
    return jnp.asarray(x)


@register_op('arg_min')
def arg_min(x, *, axis=0, dtype='int64', keepdims=False):
    return jnp.argmin(jnp.asarray(x), axis=axis, keepdims=keepdims).astype(to_jax_dtype(dtype))


@register_op('arg_max')
def arg_max(x, *, axis=0, dtype='int64', keepdims=False):
    return jnp.argmax(jnp.asarray(x), axis=axis, keepdims=keepdims).astype(to_jax_dtype(dtype))


@register_op('argsort', outputs=['Out', 'Indices'])
def argsort(x, *, axis=-1, descending=False):
    x = jnp.asarray(x)
    idx = jnp.argsort(-x if descending else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return out, idx.astype(_i64())


@register_op('top_k', outputs=['Out', 'Indices'])
def top_k(x, *, k):
    x = jnp.asarray(x)
    vals, idx = lax.top_k(x, k)
    return vals, idx.astype(_i64())


@register_op('where_index')
def where_index(cond):
    """Paddle `where(cond)` → indices; dynamic output in ref, here padded with
    -1 to the max count (XLA-compatible)."""
    cond = jnp.asarray(cond)
    flat = cond.reshape(-1)
    n = flat.shape[0]
    order = jnp.argsort(~flat)  # trues first, stable
    count = jnp.sum(flat)
    ranks = jnp.arange(n)
    sel = jnp.where(ranks < count, order[ranks], -1)
    idx = jnp.stack(jnp.unravel_index(jnp.clip(sel, 0, n - 1), cond.shape), -1)
    return jnp.where(sel[:, None] >= 0, idx, -1).astype(_i64())


@register_op('where')
def where(cond, x, y):
    return jnp.where(jnp.asarray(cond), jnp.asarray(x), jnp.asarray(y))


@register_op('diag')
def diag(x):
    return jnp.diag(jnp.asarray(x))


@register_op('eye')
def eye(*, num_rows, num_columns=None, dtype='float32'):
    return jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype))


@register_op('one_hot')
def one_hot(x, *, depth, allow_out_of_range=False):
    x = jnp.asarray(x)
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return jax.nn.one_hot(x, depth, dtype=jnp.float32)


@register_op('shard_index')
def shard_index(x, *, index_num, nshards, shard_id, ignore_value=-1):
    x = jnp.asarray(x)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


@register_op('range')
def arange(*, start, end, step, dtype='float32'):
    return jnp.arange(start, end, step, dtype=to_jax_dtype(dtype))


@register_op('linspace')
def linspace(*, start, stop, num, dtype='float32'):
    return jnp.linspace(start, stop, int(num), dtype=to_jax_dtype(dtype))


@register_op('unique_with_counts', outputs=['Out', 'Index', 'Count'])
def unique_with_counts(x, *, dtype='int32'):
    """Padded-unique: Out has x.size slots, valid prefix length = number of
    uniques (ref dynamic-shape unique_op.cc re-expressed statically)."""
    x = jnp.asarray(x).reshape(-1)
    sorted_x = jnp.sort(x)
    first = jnp.concatenate([jnp.array([True]), sorted_x[1:] != sorted_x[:-1]])
    uniq = jnp.where(first, sorted_x, sorted_x[0])
    # compact unique values to the front
    order = jnp.argsort(~first)
    out = jnp.where(jnp.arange(x.size) < jnp.sum(first), sorted_x[order], 0)
    inv = jnp.searchsorted(jnp.sort(jnp.where(first, sorted_x, sorted_x.max() + 0)), x)
    counts = jnp.sum(jnp.asarray(x)[None, :] == out[:, None], -1)
    return out, inv.astype(to_jax_dtype(dtype)), counts.astype(to_jax_dtype(dtype))


@register_op('pad')
def pad(x, *, paddings, pad_value=0.0):
    x = jnp.asarray(x)
    pw = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return jnp.pad(x, pw, constant_values=pad_value)


@register_op('pad2d')
def pad2d(x, *, paddings, mode='constant', pad_value=0.0, data_format='NCHW'):
    x = jnp.asarray(x)
    t, b, l, r = paddings
    if data_format == 'NCHW':
        pw = [(0, 0), (0, 0), (t, b), (l, r)]
    else:
        pw = [(0, 0), (t, b), (l, r), (0, 0)]
    if mode == 'constant':
        return jnp.pad(x, pw, constant_values=pad_value)
    jmode = {'reflect': 'reflect', 'edge': 'edge'}[mode]
    return jnp.pad(x, pw, mode=jmode)


@register_op('pad_constant_like')
def pad_constant_like(x, y, *, pad_value=0.0):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    pw = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pw, constant_values=pad_value)


@register_op('label_smooth')
def label_smooth(x, prior_dist=None, *, epsilon=0.1):
    x = jnp.asarray(x)
    k = x.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * x + epsilon * jnp.asarray(prior_dist)
    return (1 - epsilon) * x + epsilon / k


@register_op('multiplex', variadic=['xs'])
def multiplex(index, xs):
    xs = jnp.stack([jnp.asarray(x) for x in xs])
    idx = jnp.asarray(index).reshape(-1)
    return xs[idx, jnp.arange(idx.shape[0])]


@register_op('space_to_depth')
def space_to_depth(x, *, blocksize):
    x = jnp.asarray(x)  # NCHW
    n, c, h, w = x.shape
    bs = blocksize
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * bs * bs, h // bs, w // bs)


@register_op('shuffle_channel')
def shuffle_channel(x, *, group):
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    x = x.reshape(n, group, c // group, h, w)
    return x.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)


@register_op('temporal_shift')
def temporal_shift(x, *, seg_num, shift_ratio=0.25):
    x = jnp.asarray(x)
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    fwd = jnp.concatenate([x[:, 1:, :c1], jnp.zeros_like(x[:, :1, :c1])], 1)
    bwd = jnp.concatenate([jnp.zeros_like(x[:, :1, c1:2 * c1]), x[:, :-1, c1:2 * c1]], 1)
    keep = x[:, :, 2 * c1:]
    return jnp.concatenate([fwd, bwd, keep], 2).reshape(nt, c, h, w)


@register_op('matrix_diag_part')
def matrix_diag_part(x):
    """Diagonal of the last two dims (used by MultivariateNormalDiag)."""
    return jnp.diagonal(jnp.asarray(x), axis1=-2, axis2=-1)


@register_op('transpose_batch_time')
def transpose_batch_time(x):
    """Swap leading (time, batch) dims; rank<2 passes through. Rank-agnostic
    so decode outputs with build-time-unknown shapes can still be wired."""
    x = jnp.asarray(x)
    return jnp.swapaxes(x, 0, 1) if x.ndim >= 2 else x
