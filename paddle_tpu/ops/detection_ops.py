"""Detection ops (ref: /root/reference/paddle/fluid/operators/detection/*).

TPU formulation rules:
- every output is FIXED-SHAPE: selections (NMS, proposal generation,
  target sampling) return padded tensors + a valid count / -1 sentinel
  instead of the reference's LoD-shaped dynamic outputs;
- greedy data-dependent loops (NMS, bipartite match) are lax.fori_loop with
  masked argmax — static trip counts, no host sync;
- batch is handled with vmap; ragged ground truth arrives padded with a
  validity convention (all-zero boxes are padding, like the reference's
  empty LoD rows).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_NEG = -1e9


# ---------------------------------------------------------------------------
# geometry helpers
# ---------------------------------------------------------------------------


def _area(b, normalized=True):
    norm = 0.0 if normalized else 1.0
    return jnp.maximum(b[..., 2] - b[..., 0] + norm, 0) * \
        jnp.maximum(b[..., 3] - b[..., 1] + norm, 0)


def _pairwise_iou(x, y, normalized=True):
    """x (N,4), y (M,4) → (N,M) IoU (iou_similarity_op.h)."""
    norm = 0.0 if normalized else 1.0
    x1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    y1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    x2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    y2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    inter = jnp.maximum(x2 - x1 + norm, 0) * jnp.maximum(y2 - y1 + norm, 0)
    union = _area(x)[:, None] + _area(y)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op('iou_similarity')
def iou_similarity(x, y, *, box_normalized=True):
    x, y = jnp.asarray(x), jnp.asarray(y)
    if x.ndim == 3:                       # batched
        return jax.vmap(lambda a, b: _pairwise_iou(a, b, box_normalized))(x, y)
    return _pairwise_iou(x, y, box_normalized)


@register_op('box_clip')
def box_clip(x, im_info):
    """Clip (..., 4) boxes to image extents; im_info rows are (h, w, scale)
    (box_clip_op.h clips to im/scale - 1)."""
    x = jnp.asarray(x)
    info = jnp.asarray(im_info)
    if info.ndim == 1:
        info = info[None]
    h = info[:, 0] / info[:, 2] - 1
    w = info[:, 1] / info[:, 2] - 1
    shape = (-1,) + (1,) * (x.ndim - 2)
    w = w.reshape(shape)
    h = h.reshape(shape)
    if x.ndim == 2:                       # single image (M, 4)
        if info.shape[0] != 1:
            raise ValueError(
                "box_clip: 2-D boxes need a single im_info row; batch the "
                "boxes to (B, M, 4) for per-image clipping")
        w, h = w.reshape(()), h.reshape(())
    return jnp.stack([jnp.minimum(jnp.maximum(x[..., 0], 0), w),
                      jnp.minimum(jnp.maximum(x[..., 1], 0), h),
                      jnp.minimum(jnp.maximum(x[..., 2], 0), w),
                      jnp.minimum(jnp.maximum(x[..., 3], 0), h)], -1)


@register_op('polygon_box_transform')
def polygon_box_transform(x):
    """(N, 2K, H, W) EAST quad offsets → absolute coords: even channels are
    x-offsets from the pixel's column, odd channels from its row
    (polygon_box_transform_op.cc: out = 4*pos - offset)."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    cols = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    rows = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    even = jnp.arange(c) % 2 == 0
    base = jnp.where(even[None, :, None, None], cols * 4.0, rows * 4.0)
    return base - x


@register_op('box_coder')
def box_coder(prior_box, prior_box_var, target_box, *,
              code_type='encode_center_size', box_normalized=True,
              variance=None, axis=0):
    """Center-size box encode/decode (box_coder_op.h)."""
    pb = jnp.asarray(prior_box)           # (M, 4)
    tb = jnp.asarray(target_box)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    pvar = None if prior_box_var is None else jnp.asarray(prior_box_var)

    if code_type == 'encode_center_size':
        tw = tb[:, 2] - tb[:, 0] + norm   # tb (N, 4)
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = (tb[:, 0] + tb[:, 2]) / 2
        tcy = (tb[:, 1] + tb[:, 3]) / 2
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
            jnp.log(jnp.abs(th[:, None] / ph[None, :]))], -1)   # (N, M, 4)
        if pvar is not None:
            out = out / pvar[None, :, :]
        elif variance:
            out = out / jnp.asarray(variance)[None, None, :]
        return out

    # decode: tb (N, M, 4) deltas [or (N, 4) broadcast along `axis`]
    if tb.ndim == 2:
        tb = tb[:, None, :] if axis == 0 else tb[None, :, :]
    if pvar is not None:
        v = pvar[None, :, :] if axis == 0 else pvar[:, None, :]
        tb = tb * v
    elif variance:
        tb = tb * jnp.asarray(variance)[None, None, :]
    if axis == 0:
        pw_, ph_, pcx_, pcy_ = (a[None, :] for a in (pw, ph, pcx, pcy))
    else:
        pw_, ph_, pcx_, pcy_ = (a[:, None] for a in (pw, ph, pcx, pcy))
    ocx = tb[..., 0] * pw_ + pcx_
    ocy = tb[..., 1] * ph_ + pcy_
    ow = jnp.exp(tb[..., 2]) * pw_
    oh = jnp.exp(tb[..., 3]) * ph_
    return jnp.stack([ocx - ow / 2, ocy - oh / 2,
                      ocx + ow / 2 - norm, ocy + oh / 2 - norm], -1)


# ---------------------------------------------------------------------------
# anchors / priors
# ---------------------------------------------------------------------------


def _expand_aspect_ratios(aspect_ratios, flip):
    out = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - o) < 1e-6 for o in out):
            out.append(ar)
            if flip:
                out.append(1.0 / ar)
    return out


@register_op('prior_box', outputs=['Boxes', 'Variances'])
def prior_box(input, image, *, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes (prior_box_op.h): (H, W, P, 4) normalized corners +
    matching variances."""
    feat = jnp.asarray(input)
    img = jnp.asarray(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = step_w if step_w > 0 else iw / fw
    sh = step_h if step_h > 0 else ih / fh
    ars = _expand_aspect_ratios(list(aspect_ratios), flip)
    max_sizes = list(max_sizes or [])

    whs = []                      # per-prior (half_w, half_h) in pixels
    for s, mn in enumerate(list(min_sizes)):
        if min_max_aspect_ratios_order:
            whs.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = (mn * max_sizes[s]) ** 0.5
                whs.append((m / 2.0, m / 2.0))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * ar ** 0.5 / 2.0, mn / ar ** 0.5 / 2.0))
        else:
            for ar in ars:
                whs.append((mn * ar ** 0.5 / 2.0, mn / ar ** 0.5 / 2.0))
            if max_sizes:
                m = (mn * max_sizes[s]) ** 0.5
                whs.append((m / 2.0, m / 2.0))
    whs = jnp.asarray(whs)                              # (P, 2)
    cx = (jnp.arange(fw) + offset) * sw                 # (W,)
    cy = (jnp.arange(fh) + offset) * sh                 # (H,)
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, whs.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, whs.shape[0]))
    hw = whs[None, None, :, 0]
    hh = whs[None, None, :, 1]
    boxes = jnp.stack([(cxg - hw) / iw, (cyg - hh) / ih,
                       (cxg + hw) / iw, (cyg + hh) / ih], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance), boxes.shape)
    return boxes.astype(feat.dtype), var.astype(feat.dtype)


@register_op('density_prior_box', outputs=['Boxes', 'Variances'])
def density_prior_box(input, image, *, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      step_w=0.0, step_h=0.0, offset=0.5, flatten_to_2d=False):
    """Density prior boxes (density_prior_box_op.h): each fixed_size spawns a
    density×density grid of shifted centers per aspect ratio."""
    feat = jnp.asarray(input)
    img = jnp.asarray(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = step_w if step_w > 0 else iw / fw
    sh = step_h if step_h > 0 else ih / fh

    prior_whs = []     # (half_w, half_h, shift_x, shift_y)
    for size, dens in zip(list(fixed_sizes), list(densities)):
        for ar in list(fixed_ratios):
            bw = size * ar ** 0.5
            bh = size / ar ** 0.5
            shift = sw / dens       # reference uses step/density shifts
            for dy in range(dens):
                for dx in range(dens):
                    ox = -sw / 2.0 + shift / 2.0 + dx * shift
                    oy = -sh / 2.0 + shift / 2.0 + dy * shift
                    prior_whs.append((bw / 2.0, bh / 2.0, ox, oy))
    pw = jnp.asarray(prior_whs)                          # (P, 4)
    P = pw.shape[0]
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    cxg = cx[None, :, None] + pw[None, None, :, 2]
    cyg = cy[:, None, None] + pw[None, None, :, 3]
    cxg = jnp.broadcast_to(cxg, (fh, fw, P))
    cyg = jnp.broadcast_to(cyg, (fh, fw, P))
    hw = pw[None, None, :, 0]
    hh = pw[None, None, :, 1]
    boxes = jnp.stack([(cxg - hw) / iw, (cyg - hh) / ih,
                       (cxg + hw) / iw, (cyg + hh) / ih], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return boxes.astype(feat.dtype), var.astype(feat.dtype)


@register_op('anchor_generator', outputs=['Anchors', 'Variances'])
def anchor_generator(input, *, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5):
    """RPN anchors in absolute pixels (anchor_generator_op.h):
    (H, W, A, 4)."""
    feat = jnp.asarray(input)
    fh, fw = feat.shape[2], feat.shape[3]
    sx, sy = stride[0], stride[1]
    whs = []
    for ar in list(aspect_ratios):
        for sz in list(anchor_sizes):
            area = sx * sy
            area_ratios = area / ar
            base_w = round(area_ratios ** 0.5)
            base_h = round(base_w * ar)
            scale_w = sz / sx
            scale_h = sz / sy
            whs.append((scale_w * base_w / 2.0, scale_h * base_h / 2.0))
    whs = jnp.asarray(whs)
    cx = jnp.arange(fw) * sx + offset * sx
    cy = jnp.arange(fh) * sy + offset * sy
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, whs.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, whs.shape[0]))
    hw = whs[None, None, :, 0]
    hh = whs[None, None, :, 1]
    anchors = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh], -1)
    var = jnp.broadcast_to(jnp.asarray(variances), anchors.shape)
    return anchors.astype(feat.dtype), var.astype(feat.dtype)


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------


def _nms_keep(boxes, scores, iou_threshold, top_k, normalized=True,
              iou=None):
    """Greedy NMS: returns 0/1 keep mask over M boxes (≤ top_k kept).
    scores below -1e8 are treated as already dead. Pass a precomputed
    pairwise `iou` when calling repeatedly on the same boxes."""
    M = boxes.shape[0]
    if iou is None:
        iou = _pairwise_iou(boxes, boxes, normalized)
    steps = min(top_k, M) if top_k > 0 else M

    def body(_, st):
        keep, alive = st
        masked = jnp.where(alive, scores, _NEG)
        i = jnp.argmax(masked)
        ok = masked[i] > _NEG / 2
        keep = keep.at[i].set(keep[i] | ok)
        sup = (iou[i] > iou_threshold) | (jnp.arange(M) == i)
        alive = alive & jnp.where(ok, ~sup, alive)
        return keep, alive

    keep, _ = lax.fori_loop(0, steps, body,
                            (jnp.zeros(M, bool), scores > _NEG / 2))
    return keep


@register_op('multiclass_nms', outputs=['Out', 'Index', 'NmsRoisNum'])
def multiclass_nms(bboxes, scores, *, background_label=0,
                   score_threshold=0.0, nms_top_k=-1, nms_threshold=0.3,
                   nms_eta=1.0, keep_top_k=-1, normalized=True):
    """Per-class NMS then cross-class top-k (multiclass_nms_op.cc).
    bboxes (B, M, 4), scores (B, C, M) → (B, K, 6) [label, score, box],
    rows past the per-image count padded with -1."""
    bboxes = jnp.asarray(bboxes)
    scores = jnp.asarray(scores)
    B, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    K = keep_top_k if keep_top_k > 0 else C * M
    per_class = nms_top_k if nms_top_k > 0 else M

    classes = [c for c in range(C) if c != background_label]
    if not classes:          # every class is background → zero detections
        K0 = keep_top_k if keep_top_k > 0 else M
        return (jnp.full((B, K0, 6), -1.0, bboxes.dtype),
                jnp.full((B, K0), -1, jnp.int32),
                jnp.zeros((B,), jnp.int32))
    cls_ids = jnp.asarray(classes)

    def one(boxes, sc):
        iou = _pairwise_iou(boxes, boxes, normalized)   # shared across classes
        cls_sc = sc[cls_ids]                            # (C', M)
        s = jnp.where(cls_sc > score_threshold, cls_sc, _NEG)
        keep = jax.vmap(lambda row: _nms_keep(
            boxes, row, nms_threshold, per_class, normalized, iou=iou))(s)
        s = jnp.where(keep, s, _NEG)
        all_s = s.reshape(-1)                           # (C'*M,)
        all_l = jnp.broadcast_to(cls_ids[:, None].astype(jnp.float32),
                                 s.shape).reshape(-1)
        k = min(K, all_s.shape[0])
        top_s, idx = lax.top_k(all_s, k)
        box_idx = idx % M                               # index into INPUT boxes
        valid = top_s > _NEG / 2
        row = jnp.concatenate([
            jnp.where(valid, all_l[idx], -1.0)[:, None],
            jnp.where(valid, top_s, -1.0)[:, None],
            jnp.where(valid[:, None], boxes[box_idx], -1.0)], -1)
        box_idx = jnp.where(valid, box_idx, -1)
        return row, box_idx, jnp.sum(valid)

    out, idx, num = jax.vmap(one)(bboxes, scores)
    return out, idx.astype(jnp.int32), num.astype(jnp.int32)


@register_op('locality_aware_nms', outputs=['Out', 'Num'])
def locality_aware_nms(bboxes, scores, *, score_threshold=0.0,
                       nms_top_k=-1, nms_threshold=0.3, keep_top_k=-1,
                       normalized=True):
    """EAST-style NMS (locality_aware_nms_op.cc): boxes overlapping above
    the threshold are first merged score-weighted, then standard NMS runs.
    Single class: bboxes (B, M, 4), scores (B, 1, M)."""
    bboxes = jnp.asarray(bboxes)
    scores = jnp.asarray(scores)
    B, M = bboxes.shape[0], bboxes.shape[1]
    K = keep_top_k if keep_top_k > 0 else M

    def one(boxes, sc):
        s = jnp.where(sc[0] > score_threshold, sc[0], _NEG)
        iou = _pairwise_iou(boxes, boxes, normalized)
        w = jnp.where((iou > nms_threshold) & (s[None, :] > _NEG / 2),
                      jnp.maximum(s[None, :], 0.0), 0.0)   # (M, M)
        wsum = jnp.maximum(w.sum(1, keepdims=True), 1e-10)
        merged = (w @ boxes) / wsum
        boxes = jnp.where((s > _NEG / 2)[:, None], merged, boxes)
        keep = _nms_keep(boxes, s, nms_threshold, K, normalized)
        ms = jnp.where(keep, s, _NEG)
        top_s, idx = lax.top_k(ms, min(K, M))
        valid = top_s > _NEG / 2
        row = jnp.concatenate([
            jnp.where(valid, 0.0, -1.0)[:, None],
            jnp.where(valid, top_s, -1.0)[:, None],
            jnp.where(valid[:, None], boxes[idx], -1.0)], -1)
        return row, jnp.sum(valid)

    out, num = jax.vmap(one)(bboxes, scores)
    return out, num.astype(jnp.int32)


# ---------------------------------------------------------------------------
# matching / target assignment
# ---------------------------------------------------------------------------


@register_op('bipartite_match', outputs=['ColToRowMatchIndices',
                                         'ColToRowMatchDist'])
def bipartite_match(dist_matrix, *, match_type='bipartite',
                    dist_threshold=0.5):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally largest entry, pair its row (gt) and column (prior).
    dist (B, N, M) [or (N, M)] → per-column gt index (-1 unmatched) + dist.
    match_type='per_prediction' additionally matches leftover columns to
    their argmax row when it exceeds dist_threshold."""
    dist = jnp.asarray(dist_matrix)
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]
    B, N, M = dist.shape

    def one(d):
        steps = min(N, M)

        def body(_, st):
            match, mdist, rt, ct = st
            masked = jnp.where(rt[:, None] | ct[None, :], _NEG, d)
            flat = jnp.argmax(masked)
            r, c = flat // M, flat % M
            # strictly positive distance only — zero rows (padding gt /
            # zero-IoU) never match, like the reference
            ok = masked.reshape(-1)[flat] > 0
            match = jnp.where(ok, match.at[c].set(r), match)
            mdist = jnp.where(ok, mdist.at[c].set(d[r, c]), mdist)
            rt = jnp.where(ok, rt.at[r].set(True), rt)
            ct = jnp.where(ok, ct.at[c].set(True), ct)
            return match, mdist, rt, ct

        match, mdist, rt, ct = lax.fori_loop(
            0, steps, body,
            (jnp.full((M,), -1, jnp.int32), jnp.zeros((M,), d.dtype),
             jnp.zeros(N, bool), jnp.zeros(M, bool)))
        if match_type == 'per_prediction':
            best_r = jnp.argmax(d, 0).astype(jnp.int32)
            best_v = jnp.max(d, 0)
            extra = (match < 0) & (best_v > dist_threshold)
            match = jnp.where(extra, best_r, match)
            mdist = jnp.where(extra, best_v, mdist)
        return match, mdist

    m, md = jax.vmap(one)(dist)
    return (m[0], md[0]) if squeeze else (m, md)


@register_op('target_assign', outputs=['Out', 'OutWeight'])
def target_assign(x, match_indices, neg_indices=None, *, mismatch_value=0):
    """Gather per-prior targets by match index (target_assign_op.h):
    x (B, N, K) [gt entities], match (B, M) → out (B, M, K); unmatched
    priors take mismatch_value with weight 0 (neg_indices rows get weight 1
    with mismatch_value)."""
    x = jnp.asarray(x)
    mi = jnp.asarray(match_indices)

    def one(xb, mb):
        safe = jnp.clip(mb, 0, x.shape[1] - 1)
        g = xb[safe]                               # (M, K)
        matched = (mb >= 0)[:, None]
        out = jnp.where(matched, g, jnp.asarray(mismatch_value, x.dtype))
        w = matched.astype(jnp.float32)
        return out, w

    out, w = jax.vmap(one)(x, mi)
    if neg_indices is not None:
        neg = jnp.asarray(neg_indices)             # (B, M) 0/1 mask
        w = jnp.maximum(w, neg[..., None].astype(w.dtype))
    return out, w


@register_op('sigmoid_focal_loss')
def sigmoid_focal_loss(x, label, fg_num, *, gamma=2.0, alpha=0.25):
    """Focal loss (sigmoid_focal_loss_op.cu): x (N, C) logits, label (N, 1)
    in [0, C] where 0 = background; normalized by fg_num."""
    x = jnp.asarray(x)
    lb = jnp.asarray(label).reshape(-1)
    fg = jnp.maximum(jnp.asarray(fg_num, x.dtype).reshape(()), 1.0)
    C = x.shape[1]
    # per-class one-hot target: class c at column c-1
    t = (lb[:, None] == jnp.arange(1, C + 1)[None, :]).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = -(t * jax.nn.log_sigmoid(x) + (1 - t) * jax.nn.log_sigmoid(-x))
    pt = t * p + (1 - t) * (1 - p)
    a = t * alpha + (1 - t) * (1 - alpha)
    return a * ((1 - pt) ** gamma) * ce / fg


@register_op('rpn_target_assign', outputs=['LocationIndex', 'ScoreIndex',
                                           'TargetLabel', 'TargetBBox',
                                           'BBoxInsideWeight'])
def rpn_target_assign(anchors, gt_boxes, is_crowd=None, im_info=None, *,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor labeling (rpn_target_assign_op.cc), single image, masked:
    anchors (A, 4), gt (G, 4 — zero rows are padding). Returns fixed-size
    (A,) label (1 fg / 0 bg / -1 ignore) + per-anchor regression targets;
    index outputs are 0/1 masks instead of dynamic index lists. Sampling is
    deterministic top-k by overlap (use_random is accepted but the TPU
    formulation keeps selection deterministic)."""
    an = jnp.asarray(anchors).reshape(-1, 4)
    gt = jnp.asarray(gt_boxes).reshape(-1, 4)
    gt_valid = _area(gt, False) > 0
    if is_crowd is not None:    # crowd gts never become matching targets
        gt_valid = gt_valid & (jnp.asarray(is_crowd).reshape(-1) == 0)
    iou = _pairwise_iou(an, gt, normalized=False)      # (A, G)
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    best_gt = jnp.argmax(iou, 1)
    best_iou = jnp.max(iou, 1)
    # anchors that are the best for some gt are fg too
    best_for_gt = jnp.max(jnp.where(gt_valid[None, :],
                                    iou == jnp.max(iou, 0, keepdims=True),
                                    False), 1)
    fg = (best_iou >= rpn_positive_overlap) | best_for_gt
    bg = (best_iou < rpn_negative_overlap) & ~fg
    if im_info is not None and rpn_straddle_thresh >= 0:
        # anchors straddling the image boundary by more than the threshold
        # are excluded from both fg and bg (label -1), like the reference
        info = jnp.asarray(im_info).reshape(-1)
        imh, imw = info[0], info[1]
        inside = ((an[:, 0] >= -rpn_straddle_thresh) &
                  (an[:, 1] >= -rpn_straddle_thresh) &
                  (an[:, 2] < imw + rpn_straddle_thresh) &
                  (an[:, 3] < imh + rpn_straddle_thresh))
        fg = fg & inside
        bg = bg & inside
    # cap fg count at fg_fraction * batch; prefer highest overlap
    max_fg = int(rpn_batch_size_per_im * rpn_fg_fraction)
    A = an.shape[0]
    fg_rank = jnp.argsort(jnp.argsort(-jnp.where(fg, best_iou, -1.0)))
    fg = fg & (fg_rank < max_fg)
    n_fg = jnp.sum(fg)
    max_bg = rpn_batch_size_per_im - n_fg
    bg_rank = jnp.argsort(jnp.argsort(-jnp.where(bg, 1.0 - best_iou, -1.0)))
    bg = bg & (bg_rank < max_bg)
    label = jnp.where(fg, 1, jnp.where(bg, 0, -1)).astype(jnp.int32)
    tgt = _encode_per_anchor(an, gt[best_gt])
    inside_w = fg.astype(jnp.float32)[:, None] * jnp.ones((1, 4), jnp.float32)
    return (fg.astype(jnp.int32), (fg | bg).astype(jnp.int32),
            label, tgt.astype(jnp.float32), inside_w)


def _encode_per_anchor(an, gt):
    """Per-anchor center-size encoding (anchor i ↔ gt row i)."""
    aw = an[:, 2] - an[:, 0] + 1.0
    ah = an[:, 3] - an[:, 1] + 1.0
    acx = an[:, 0] + aw / 2
    acy = an[:, 1] + ah / 2
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    return jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                      jnp.log(jnp.maximum(gw / aw, 1e-10)),
                      jnp.log(jnp.maximum(gh / ah, 1e-10))], -1)


@register_op('retinanet_target_assign',
             outputs=['LocationIndex', 'ScoreIndex', 'TargetLabel',
                      'TargetBBox', 'BBoxInsideWeight', 'ForegroundNumber'])
def retinanet_target_assign(anchors, gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, *, positive_overlap=0.5,
                            negative_overlap=0.4):
    """Retinanet anchor labeling: like RPN but no subsampling and labels are
    the gt class (retinanet_target_assign in rpn_target_assign_op.cc)."""
    an = jnp.asarray(anchors).reshape(-1, 4)
    gt = jnp.asarray(gt_boxes).reshape(-1, 4)
    gl = jnp.asarray(gt_labels).reshape(-1)
    gt_valid = _area(gt, False) > 0
    if is_crowd is not None:
        gt_valid = gt_valid & (jnp.asarray(is_crowd).reshape(-1) == 0)
    iou = jnp.where(gt_valid[None, :], _pairwise_iou(an, gt, False), 0.0)
    best_gt = jnp.argmax(iou, 1)
    best_iou = jnp.max(iou, 1)
    best_for_gt = jnp.max(jnp.where(gt_valid[None, :],
                                    iou == jnp.max(iou, 0, keepdims=True),
                                    False), 1)
    fg = (best_iou >= positive_overlap) | best_for_gt
    bg = (best_iou < negative_overlap) & ~fg
    label = jnp.where(fg, gl[best_gt], jnp.where(bg, 0, -1)).astype(jnp.int32)
    tgt = _encode_per_anchor(an, gt[best_gt])
    inside_w = fg.astype(jnp.float32)[:, None] * jnp.ones((1, 4), jnp.float32)
    return (fg.astype(jnp.int32), (fg | bg).astype(jnp.int32), label,
            tgt.astype(jnp.float32), inside_w,
            jnp.maximum(jnp.sum(fg), 1).astype(jnp.int32))


# ---------------------------------------------------------------------------
# proposals
# ---------------------------------------------------------------------------


@register_op('generate_proposals', outputs=['RpnRois', 'RpnRoiProbs',
                                            'RpnRoisNum'])
def generate_proposals(scores, bbox_deltas, im_info, anchors, variances, *,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    """RPN proposal generation (generate_proposals_op.cc): decode anchors
    with deltas, clip, drop tiny boxes, top-k, NMS. Fixed-size outputs
    (B, post_nms_top_n, 4) + per-image count."""
    sc = jnp.asarray(scores)              # (B, A, H, W)
    bd = jnp.asarray(bbox_deltas)         # (B, 4A, H, W)
    info = jnp.asarray(im_info)           # (B, 3)
    an = jnp.asarray(anchors).reshape(-1, 4)
    var = jnp.asarray(variances).reshape(-1, 4)
    B = sc.shape[0]
    A = sc.shape[1]
    H, W = sc.shape[2], sc.shape[3]
    M = A * H * W

    def one(s, d, im):
        s = s.transpose(1, 2, 0).reshape(-1)              # (H*W*A,)
        d = d.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        a = jnp.broadcast_to(an.reshape(1, 1, A, 4), (H, W, A, 4)).reshape(-1, 4) \
            if an.shape[0] == A else an
        v = jnp.broadcast_to(var.reshape(1, 1, -1, 4), (H, W, A, 4)).reshape(-1, 4) \
            if var.shape[0] == A else var
        # decode center-size with variances
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        dv = d * v
        cx = dv[:, 0] * aw + acx
        cy = dv[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(dv[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(dv[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2 - 1, cy + h / 2 - 1], -1)
        # clip to image
        imh = im[0] - 1
        imw = im[1] - 1
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, imw),
                           jnp.clip(boxes[:, 1], 0, imh),
                           jnp.clip(boxes[:, 2], 0, imw),
                           jnp.clip(boxes[:, 3], 0, imh)], -1)
        ms = min_size * im[2]
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1) >= ms) & \
                  ((boxes[:, 3] - boxes[:, 1] + 1) >= ms)
        s = jnp.where(keep_sz, s, _NEG)
        k = min(pre_nms_top_n, M)
        top_s, idx = lax.top_k(s, k)
        top_b = boxes[idx]
        keep = _nms_keep(top_b, top_s, nms_thresh, post_nms_top_n,
                         normalized=False)
        ks = jnp.where(keep, top_s, _NEG)
        fin_s, fin_i = lax.top_k(ks, min(post_nms_top_n, k))
        valid = fin_s > _NEG / 2
        out_b = jnp.where(valid[:, None], top_b[fin_i], 0.0)
        out_s = jnp.where(valid, fin_s, 0.0)
        return out_b, out_s, jnp.sum(valid)

    rois, probs, num = jax.vmap(one)(sc, bd, info)
    return rois, probs, num.astype(jnp.int32)


@register_op('distribute_fpn_proposals',
             outputs=['MultiFpnRois', 'RestoreIndex', 'MultiLevelRoisNum'])
def distribute_fpn_proposals(fpn_rois, *, min_level, max_level, refer_level,
                             refer_scale):
    """Assign rois to FPN levels by scale (distribute_fpn_proposals_op.h):
    level = refer + floor(log2(sqrt(area)/refer_scale)). Fixed-shape: one
    (R, 4) tensor per level with non-member rows zeroed, plus per-level
    0/1 masks (instead of compacted LoD outputs) and the identity restore
    index."""
    rois = jnp.asarray(fpn_rois).reshape(-1, 4)
    R = rois.shape[0]
    scale = jnp.sqrt(jnp.maximum(_area(rois, False), 1e-6))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs = []
    masks = []
    for L in range(min_level, max_level + 1):
        m = (lvl == L)
        outs.append(jnp.where(m[:, None], rois, 0.0))
        masks.append(m.astype(jnp.int32))
    restore = jnp.arange(R, dtype=jnp.int32)[:, None]
    return jnp.stack(outs, 0), restore, jnp.stack(masks, 0)


@register_op('collect_fpn_proposals', outputs=['FpnRois', 'RoisNum'])
def collect_fpn_proposals(multi_rois, multi_scores, *, post_nms_top_n):
    """Merge per-level rois by global score top-k
    (collect_fpn_proposals_op.h). multi_rois (L, R, 4), multi_scores (L, R)
    → (post_nms_top_n, 4)."""
    rois = jnp.asarray(multi_rois).reshape(-1, 4)
    scores = jnp.asarray(multi_scores).reshape(-1)
    k = min(post_nms_top_n, scores.shape[0])
    top_s, idx = lax.top_k(scores, k)
    return rois[idx], jnp.sum(top_s > 0).astype(jnp.int32)


@register_op('box_decoder_and_assign', outputs=['DecodeBox',
                                                'OutputAssignBox'])
def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score, *,
                           box_clip=4.135):
    """Decode per-class deltas and pick each roi's best-class box
    (box_decoder_and_assign_op.cu)."""
    pb = jnp.asarray(prior_box)           # (N, 4)
    pv = jnp.asarray(prior_box_var).reshape(-1)
    tb = jnp.asarray(target_box)          # (N, 4*C)
    sc = jnp.asarray(box_score)           # (N, C)
    N, C = sc.shape
    d = tb.reshape(N, C, 4) * pv[None, None, :]
    d = jnp.clip(d, -box_clip, box_clip)
    pw = pb[:, 2] - pb[:, 0] + 1.0
    ph = pb[:, 3] - pb[:, 1] + 1.0
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    cx = d[..., 0] * pw[:, None] + pcx[:, None]
    cy = d[..., 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(d[..., 2]) * pw[:, None]
    h = jnp.exp(d[..., 3]) * ph[:, None]
    dec = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - 1, cy + h / 2 - 1], -1)   # (N, C, 4)
    best = jnp.argmax(sc, 1)
    assign = dec[jnp.arange(N), best]
    return dec.reshape(N, C * 4), assign


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------


@register_op('yolo_box', outputs=['Boxes', 'Scores'])
def yolo_box(x, img_size, *, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True):
    """Decode YOLOv3 head (yolo_box_op.h): x (B, A*(5+C), H, W) →
    boxes (B, A*H*W, 4) in image pixels, scores (B, A*H*W, C) —
    anchor-major box index order, matching the reference kernel."""
    x = jnp.asarray(x)
    imgs = jnp.asarray(img_size)          # (B, 2) [h, w]
    B, _, H, W = x.shape
    A = len(anchors) // 2
    C = class_num
    anc = jnp.asarray(anchors, x.dtype).reshape(A, 2)
    input_size = downsample_ratio * H
    v = x.reshape(B, A, 5 + C, H, W)
    tx, ty, tw, th = v[:, :, 0], v[:, :, 1], v[:, :, 2], v[:, :, 3]
    conf = jax.nn.sigmoid(v[:, :, 4])                       # (B, A, H, W)
    cls = jax.nn.sigmoid(v[:, :, 5:])                       # (B, A, C, H, W)
    gx = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    imh = imgs[:, 0].astype(x.dtype)[:, None, None, None]
    imw = imgs[:, 1].astype(x.dtype)[:, None, None, None]
    bx = (gx + jax.nn.sigmoid(tx)) * imw / W
    by = (gy + jax.nn.sigmoid(ty)) * imh / H
    bw = jnp.exp(tw) * anc[None, :, 0, None, None] * imw / input_size
    bh = jnp.exp(th) * anc[None, :, 1, None, None] * imh / input_size
    x1 = bx - bw / 2
    y1 = by - bh / 2
    x2 = bx + bw / 2
    y2 = by + bh / 2
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1)                 # (B, A, H, W, 4)
    mask = (conf > conf_thresh).astype(x.dtype)
    score = cls * (conf * mask)[:, :, None]                 # (B, A, C, H, W)
    # Anchor-major (A, H, W) box index order + zeroed coords below
    # conf_thresh, matching yolo_box_op.h (box_idx = an_idx-major).
    boxes = (boxes * mask[..., None]).reshape(B, -1, 4)
    score = score.transpose(0, 1, 3, 4, 2).reshape(B, -1, C)
    return boxes, score


@register_op('yolov3_loss', outputs=['Loss', 'ObjectnessMask',
                                     'GTMatchMask'])
def yolov3_loss(x, gt_box, gt_label, gt_score=None, *, anchors, anchor_mask,
                class_num, ignore_thresh=0.7, downsample_ratio=32,
                use_label_smooth=True):
    """YOLOv3 training loss (yolov3_loss_op.h). x (B, A*(5+C), H, W);
    gt_box (B, G, 4) normalized cx,cy,w,h (zero rows = padding). Each gt is
    assigned the best-IoU anchor from the FULL anchor list; the loss applies
    only when that anchor is in this head's anchor_mask."""
    x = jnp.asarray(x)
    gtb = jnp.asarray(gt_box)
    gtl = jnp.asarray(gt_label)
    B, _, H, W = x.shape
    mask_anchors = list(anchor_mask)
    A = len(mask_anchors)
    C = class_num
    all_anc = jnp.asarray(anchors, x.dtype).reshape(-1, 2)
    anc = all_anc[jnp.asarray(mask_anchors)]
    input_size = downsample_ratio * H
    G = gtb.shape[1]
    v = x.reshape(B, A, 5 + C, H, W)
    px, py = v[:, :, 0], v[:, :, 1]
    pw, ph = v[:, :, 2], v[:, :, 3]
    pobj = v[:, :, 4]
    pcls = v[:, :, 5:]
    smooth = 1.0 / max(C, 1) if use_label_smooth else 0.0

    gt_valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)        # (B, G)
    # best anchor per gt by IoU of (w, h) at origin over the FULL anchor set
    gw = gtb[..., 2] * input_size                           # pixels
    gh = gtb[..., 3] * input_size
    inter = jnp.minimum(gw[..., None], all_anc[None, None, :, 0]) * \
        jnp.minimum(gh[..., None], all_anc[None, None, :, 1])
    union = gw[..., None] * gh[..., None] + \
        (all_anc[:, 0] * all_anc[:, 1])[None, None] - inter
    an_iou = inter / jnp.maximum(union, 1e-10)
    best_anchor = jnp.argmax(an_iou, -1)                    # (B, G)
    # position in this head's mask (or -1)
    in_mask = jnp.full_like(best_anchor, -1)
    for pos, a in enumerate(mask_anchors):
        in_mask = jnp.where(best_anchor == a, pos, in_mask)
    gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
    responsible = gt_valid & (in_mask >= 0)

    def per_image(pxi, pyi, pwi, phi, pobji, pclsi, gb, gl, gs, resp, am,
                  gii, gjj):
        # scatter gt targets onto (A, H, W) grids
        tx = gb[:, 0] * W - gii                       # (G,)
        ty = gb[:, 1] * H - gjj
        am_safe = jnp.clip(am, 0, A - 1)
        tw = jnp.log(jnp.maximum(
            gb[:, 2] * input_size / jnp.maximum(anc[am_safe, 0], 1e-10),
            1e-10))
        th = jnp.log(jnp.maximum(
            gb[:, 3] * input_size / jnp.maximum(anc[am_safe, 1], 1e-10),
            1e-10))
        scale = 2.0 - gb[:, 2] * gb[:, 3]

        # non-responsible rows write into a garbage anchor slot A (sliced
        # off below) so padding can never clobber a real target at (0,0,0)
        slot = jnp.where(resp, am_safe, A)
        idx = (slot, gjj, gii)
        obj_t = jnp.zeros((A + 1, H, W)).at[idx].max(1.0)[:A]
        # per-gt sample weight (mixup gt_score); default 1
        sc_t = jnp.zeros((A + 1, H, W)).at[idx].max(gs)[:A]
        tgt = jnp.zeros((A + 1, H, W, 5)).at[idx].set(
            jnp.stack([tx, ty, tw, th, scale], -1))[:A]
        onehot = (gl[:, None] == jnp.arange(C)[None, :]).astype(x.dtype)
        onehot = onehot * (1.0 - smooth) + smooth / max(C, 1)
        cls_t = jnp.zeros((A + 1, H, W, C)).at[idx].set(onehot)[:A]

        # objectness ignore mask: predicted boxes with IoU > thresh vs any gt
        gxs = jnp.arange(W, dtype=x.dtype)[None, None, :]
        gys = jnp.arange(H, dtype=x.dtype)[None, :, None]
        bx = (gxs + jax.nn.sigmoid(pxi)) / W
        by = (gys + jax.nn.sigmoid(pyi)) / H
        bw = jnp.exp(pwi) * anc[:, 0, None, None] / input_size
        bh = jnp.exp(phi) * anc[:, 1, None, None] / input_size
        pred = jnp.stack([bx - bw / 2, by - bh / 2,
                          bx + bw / 2, by + bh / 2], -1).reshape(-1, 4)
        gtc = jnp.stack([gb[:, 0] - gb[:, 2] / 2, gb[:, 1] - gb[:, 3] / 2,
                         gb[:, 0] + gb[:, 2] / 2, gb[:, 1] + gb[:, 3] / 2],
                        -1)
        iou = _pairwise_iou(pred, gtc)                  # (AHW, G)
        iou = jnp.where((_area(gtc) > 0)[None, :], iou, 0.0)
        ignore = (jnp.max(iou, 1) > ignore_thresh).reshape(A, H, W)

        obj_mask = obj_t                                # 1 at responsible
        noobj_mask = (1.0 - obj_mask) * (1.0 - ignore)
        s = tgt[..., 4]

        def bce(logit, t):
            return -(t * jax.nn.log_sigmoid(logit)
                     + (1 - t) * jax.nn.log_sigmoid(-logit))

        w = obj_mask * sc_t
        loss_xy = w * s * (bce(pxi, tgt[..., 0])
                           + bce(pyi, tgt[..., 1]))
        loss_wh = w * s * 0.5 * ((pwi - tgt[..., 2]) ** 2
                                 + (phi - tgt[..., 3]) ** 2)
        loss_obj = obj_mask * sc_t * bce(pobji, 1.0) \
            + noobj_mask * bce(pobji, 0.0)
        loss_cls = w[..., None] * bce(
            pclsi.transpose(0, 2, 3, 1), cls_t)
        total = (loss_xy.sum() + loss_wh.sum() + loss_obj.sum()
                 + loss_cls.sum())
        return total, obj_mask, resp.astype(jnp.int32)

    gts = jnp.ones(gtl.shape, x.dtype) if gt_score is None \
        else jnp.asarray(gt_score).reshape(gtl.shape).astype(x.dtype)
    loss, objm, matchm = jax.vmap(per_image)(
        px, py, pw, ph, pobj, pcls, gtb, gtl, gts, responsible, in_mask,
        gi, gj)
    return loss, objm, matchm


# ---------------------------------------------------------------------------
# roi_perspective_transform
# ---------------------------------------------------------------------------


@register_op('roi_perspective_transform', outputs=['Out', 'Mask'])
def roi_perspective_transform(x, rois, *, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """Warp quadrilateral rois to (th, tw) rectangles via the inverse
    perspective transform + bilinear sampling
    (roi_perspective_transform_op.cc). rois: (R, 8) quad corners clockwise
    from top-left."""
    x = jnp.asarray(x)
    rois = jnp.asarray(rois).reshape(-1, 8) * spatial_scale
    th, tw = transformed_height, transformed_width
    C = x.shape[1]

    def homography(quad):
        """Map unit rect corners (0,0),(tw-1,0),(tw-1,th-1),(0,th-1) →
        quad; solve the 8-dof projective transform."""
        dst = quad.reshape(4, 2)
        src = jnp.asarray([[0, 0], [tw - 1, 0], [tw - 1, th - 1],
                           [0, th - 1]], x.dtype)
        rowsA = []
        for i in range(4):
            sx, sy = src[i, 0], src[i, 1]
            dx, dy = dst[i, 0], dst[i, 1]
            rowsA.append(jnp.stack([sx, sy, jnp.asarray(1.0, x.dtype),
                                    jnp.zeros((), x.dtype),
                                    jnp.zeros((), x.dtype),
                                    jnp.zeros((), x.dtype),
                                    -dx * sx, -dx * sy]))
            rowsA.append(jnp.stack([jnp.zeros((), x.dtype),
                                    jnp.zeros((), x.dtype),
                                    jnp.zeros((), x.dtype), sx, sy,
                                    jnp.asarray(1.0, x.dtype),
                                    -dy * sx, -dy * sy]))
        A = jnp.stack(rowsA)                     # (8, 8)
        b = dst.reshape(-1)
        h = jnp.linalg.solve(A + 1e-8 * jnp.eye(8, dtype=x.dtype), b)
        return jnp.concatenate([h, jnp.ones(1, x.dtype)]).reshape(3, 3)

    def one(img, quad):
        Hm = homography(quad)
        ys, xs = jnp.meshgrid(jnp.arange(th, dtype=x.dtype),
                              jnp.arange(tw, dtype=x.dtype), indexing='ij')
        ones = jnp.ones_like(xs)
        pts = jnp.stack([xs, ys, ones], 0).reshape(3, -1)   # (3, th*tw)
        mapped = Hm @ pts
        mx = mapped[0] / jnp.maximum(jnp.abs(mapped[2]), 1e-8) * \
            jnp.sign(mapped[2])
        my = mapped[1] / jnp.maximum(jnp.abs(mapped[2]), 1e-8) * \
            jnp.sign(mapped[2])
        from .vision_ops import _bilinear_sample
        v = _bilinear_sample(img, my.reshape(th, tw), mx.reshape(th, tw))
        inb = ((mx >= 0) & (mx <= img.shape[-1] - 1) &
               (my >= 0) & (my <= img.shape[-2] - 1)).reshape(th, tw)
        return v, inb.astype(jnp.int32)

    # all rois sample image 0 unless a batch_ids convention is layered above
    out, mask = jax.vmap(lambda q: one(x[0], q))(rois)
    return out, mask[:, None]


@register_op('ssd_loss')
def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, *, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type='per_prediction', normalize=True):
    """Fused SSD training loss (ref: layers/detection.py:ssd_loss, composed
    there from 8 ops): match → encode → smooth-l1 + softmax-ce → masked
    hard-negative mining, all in one XLA-fusable program over the batch.
    gt zero-rows are padding."""
    loc = jnp.asarray(location)           # (B, M, 4)
    conf = jnp.asarray(confidence)        # (B, M, C)
    gtb = jnp.asarray(gt_box)             # (B, G, 4)
    gtl = jnp.asarray(gt_label)
    if gtl.ndim == 3:
        gtl = gtl[..., 0]
    pb = jnp.asarray(prior_box)           # (M, 4)
    pv = None if prior_box_var is None else jnp.asarray(prior_box_var)
    B, M, C = conf.shape

    pw = pb[:, 2] - pb[:, 0]
    ph = pb[:, 3] - pb[:, 1]
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2

    def one(lc, cf, gb, gl):
        valid = _area(gb) > 0                               # (G,)
        iou = jnp.where(valid[:, None], _pairwise_iou(gb, pb), 0.0)
        match, _ = bipartite_match(
            iou, match_type=match_type, dist_threshold=overlap_threshold)
        pos = match >= 0                                    # (M,)
        mg = jnp.clip(match, 0, gb.shape[0] - 1)
        g = gb[mg]                                          # (M, 4)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-10)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-10)
        tgt = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                         jnp.log(gw / pw), jnp.log(gh / ph)], -1)
        if pv is not None:
            tgt = tgt / pv
        diff = jnp.abs(lc - tgt)
        sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5).sum(-1)
        loc_l = jnp.where(pos, sl1, 0.0)
        tlabel = jnp.where(pos, gl[mg], background_label)
        logp = jax.nn.log_softmax(cf, -1)
        conf_l = -jnp.take_along_axis(logp, tlabel[:, None].astype(jnp.int32),
                                      1)[:, 0]
        n_pos = jnp.sum(pos)
        neg_cand = jnp.where(pos, _NEG, conf_l)
        rank = jnp.argsort(jnp.argsort(-neg_cand))
        neg = (~pos) & (rank < (neg_pos_ratio * n_pos))
        total = (loc_loss_weight * loc_l.sum()
                 + conf_loss_weight * jnp.sum(
                     jnp.where(pos | neg, conf_l, 0.0)))
        return total, n_pos

    totals, n_pos = jax.vmap(one)(loc, conf, gtb, gtl)
    if normalize:
        totals = totals / jnp.maximum(jnp.sum(n_pos).astype(loc.dtype), 1.0)
    return totals[:, None]


@register_op('box_encode_per_row')
def box_encode_per_row(boxes, gt, *, weights=(0.1, 0.1, 0.2, 0.2)):
    """Row-aligned center-size encode: box i against gt i, scaled by the
    bbox regression weights (the detection-head target form used by
    generate_proposal_labels)."""
    enc = _encode_per_anchor(jnp.asarray(boxes).reshape(-1, 4),
                             jnp.asarray(gt).reshape(-1, 4))
    return enc / jnp.asarray(weights, enc.dtype)


@register_op('detection_map')
def detection_map(det, gt_label, gt_box, gt_difficult=None, *, class_num,
                  overlap_threshold=0.5, background_label=0,
                  evaluate_difficult=True, ap_type='integral'):
    """Single-batch mAP (ref: paddle/fluid/operators/detection_map_op.cc).
    det (M, 6): [label, score, x1, y1, x2, y2], rows with score<=0 are
    padding; gt_label (G, 1), gt_box (G, 4), rows with all-zero boxes are
    padding; gt_difficult (G,) optional 0/1. Greedy IoU matching per class
    (fori_loop over score-ranked detections with a matched-gt mask carry),
    then integral/11point AP. With evaluate_difficult=False (VOC protocol),
    difficult GTs are excluded from the recall denominator and detections
    matched to them are ignored (neither tp nor fp). Fixed shapes
    throughout — no dynamic gather."""
    det = jnp.asarray(det)
    gtl = jnp.asarray(gt_label).reshape(-1)
    gtb = jnp.asarray(gt_box).reshape(-1, 4)
    difficult = (jnp.zeros_like(gtl, dtype=bool) if gt_difficult is None
                 else jnp.asarray(gt_difficult).reshape(-1).astype(bool))
    if evaluate_difficult:
        difficult = jnp.zeros_like(difficult)
    M = det.shape[0]
    d_label = det[:, 0].astype(jnp.int32)
    d_score = det[:, 1]
    d_box = det[:, 2:6]
    d_valid = d_score > 0
    g_valid = jnp.any(gtb != 0, axis=1)
    iou = _pairwise_iou(d_box, gtb)                  # (M, G)

    order = jnp.argsort(-jnp.where(d_valid, d_score, -jnp.inf))
    aps = []
    for c in range(class_num):
        if c == background_label:
            continue
        dc = d_valid & (d_label == c)
        gc = g_valid & (gtl == c)
        n_gt = jnp.sum(gc & (~difficult))

        def body(i, carry):
            g_matched, tp, fp = carry
            di = order[i]
            active = dc[di]
            cand = jnp.where(gc & (~g_matched), iou[di], -1.0)
            best = jnp.argmax(cand)
            ok = active & (cand[best] >= overlap_threshold)
            ignored = ok & difficult[best]     # matched a difficult GT
            g_matched = g_matched.at[best].set(g_matched[best] | ok)
            tp = tp.at[i].set(jnp.where(active & ok & (~ignored), 1.0, 0.0))
            fp = fp.at[i].set(jnp.where(active & (~ok), 1.0, 0.0))
            return g_matched, tp, fp

        g0 = jnp.zeros_like(gc)
        tp0 = jnp.zeros((M,), det.dtype)
        fp0 = jnp.zeros((M,), det.dtype)
        _, tp, fp = jax.lax.fori_loop(0, M, body, (g0, tp0, fp0))
        ctp = jnp.cumsum(tp)
        cfp = jnp.cumsum(fp)
        recall = ctp / jnp.maximum(n_gt.astype(det.dtype), 1.0)
        precision = ctp / jnp.maximum(ctp + cfp, 1.0)
        if ap_type == '11point':
            pts = jnp.linspace(0.0, 1.0, 11)
            ap = jnp.mean(jax.vmap(
                lambda t: jnp.max(jnp.where(recall >= t, precision, 0.0))
            )(pts))
        else:  # integral
            d_rec = jnp.diff(recall, prepend=0.0)
            ap = jnp.sum(precision * d_rec)
        aps.append(jnp.where(n_gt > 0, ap, jnp.nan))
    aps = jnp.stack(aps)
    present = ~jnp.isnan(aps)
    n_present = jnp.maximum(jnp.sum(present), 1)
    return jnp.reshape(
        jnp.sum(jnp.where(present, aps, 0.0)) / n_present.astype(det.dtype),
        (1,))
