"""Fake-quantization ops for QAT/PTQ (SURVEY §2.6).

Parity targets: /root/reference/paddle/fluid/operators/fake_quantize_op.*
(abs_max, channel_wise_abs_max, moving_average_abs_max) as driven by the
reference's slim/quantization passes. Quant-dequant with a straight-through
estimator (jax.custom_vjp): the forward snaps to the int grid, the backward
passes gradients through inside the clip range — the standard QAT rule the
reference implements with its fake_quantize grad kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@jax.custom_vjp
def _ste_quant_dequant(x, scale, bit_length):
    qmax = 2.0 ** (bit_length - 1) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _ste_fwd(x, scale, bit_length):
    return _ste_quant_dequant(x, scale, bit_length), (x, scale)


def _ste_bwd(res, g):
    x, scale = res
    s = jnp.maximum(scale, 1e-8)
    inside = (jnp.abs(x) <= s).astype(g.dtype)
    return g * inside, None, None


_ste_quant_dequant.defvjp(_ste_fwd, _ste_bwd)


@register_op('fake_quantize_dequantize_abs_max', outputs=['Out', 'OutScale'])
def fake_quantize_dequantize_abs_max(x, *, bit_length=8):
    x = jnp.asarray(x)
    scale = jnp.max(jnp.abs(x))
    return _ste_quant_dequant(x, scale, bit_length), scale.reshape(1)


@register_op('fake_channel_wise_quantize_dequantize_abs_max',
             outputs=['Out', 'OutScale'])
def fake_channel_wise_quantize_dequantize_abs_max(x, *, bit_length=8,
                                                  quant_axis=0):
    x = jnp.asarray(x)
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    out = _ste_quant_dequant(x, scale, bit_length)
    return out, scale.reshape(-1)


@register_op('fake_quantize_dequantize_moving_average_abs_max',
             outputs=['Out', 'OutScale', 'StateOut', 'AccumOut'])
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_scale, state=None, accum=None, *, moving_rate=0.9,
        bit_length=8, is_test=False):
    """Activation observer: EMA of abs-max (fake_quantize_op.cc
    FakeQuantizeMovingAverageAbsMax)."""
    x = jnp.asarray(x)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = jnp.asarray(in_scale).reshape(())
        st = jnp.asarray(state).reshape(()) if state is not None \
            else jnp.ones(())
        ac = jnp.asarray(accum).reshape(()) if accum is not None \
            else scale
    else:
        st_prev = jnp.asarray(state).reshape(()) if state is not None \
            else jnp.ones(())
        ac_prev = jnp.asarray(accum).reshape(()) if accum is not None \
            else jnp.asarray(in_scale).reshape(())
        st = st_prev * moving_rate + 1.0
        ac = ac_prev * moving_rate + cur
        scale = ac / st
    out = _ste_quant_dequant(x, scale, bit_length)
    return out, scale.reshape(1), st.reshape(1), ac.reshape(1)


@register_op('quantize_linear')
def quantize_linear(x, scale, *, bit_length=8, quant_axis=-1):
    """x / scale → rounded int8 values (inference-time real quantization)."""
    x = jnp.asarray(x)
    s = jnp.maximum(jnp.asarray(scale), 1e-8)
    if quant_axis >= 0 and s.ndim >= 1 and s.size > 1:
        shape = [1] * x.ndim
        shape[quant_axis] = -1
        s = s.reshape(shape)
    qmax = 2.0 ** (bit_length - 1) - 1
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax).astype(jnp.int8)


@register_op('dequantize_linear')
def dequantize_linear(x, scale, *, bit_length=8, quant_axis=-1):
    x = jnp.asarray(x).astype(jnp.float32)
    s = jnp.asarray(scale)
    if quant_axis >= 0 and s.ndim >= 1 and s.size > 1:
        shape = [1] * x.ndim
        shape[quant_axis] = -1
        s = s.reshape(shape)
    qmax = 2.0 ** (bit_length - 1) - 1
    return x * s / qmax
