"""Inference library (SURVEY §2.9): Predictor + StableHLO export.

Parity target: the reference's C++ inference library
(/root/reference/paddle/fluid/inference: AnalysisPredictor, TensorRT/Anakin
subgraphs). The TPU analogue: load_inference_model → lower the program ONCE
to a jitted function cached by feed shapes (the same compile cache as the
Executor) → run. Engine export goes to StableHLO text/bytecode — the
portable compiler IR playing TensorRT's role on TPU — via jax.jit(...).lower.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp


class Config:
    """ref: AnalysisConfig — model path + precision switches."""

    def __init__(self, model_dir=None, model_filename=None,
                 params_filename=None):
        self.model_dir = model_dir
        self.model_filename = model_filename
        self.params_filename = params_filename
        self.precision = 'float32'
        self.quant_scales = {}
        self.weight_bits = 8

    def enable_bf16(self):
        self.precision = 'bfloat16'
        return self

    def enable_int8(self, quant_scales=None, weight_bits=8):
        """Weight-only int8 inference (ref: slim int8 deploy flow,
        contrib/slim/quantization/quantization_pass.py). `quant_scales`:
        optional {param_name: per-out-channel abs-max scale array} — e.g.
        the 'weight' entries produced by slim.quant_post / slim.convert;
        params without a provided scale get abs-max calibration from their
        own values."""
        self.precision = 'int8'
        self.quant_scales = dict(quant_scales or {})
        self.weight_bits = weight_bits
        return self

    # GPU-era toggles accepted as no-ops for script parity
    def enable_use_gpu(self, *a, **k):
        return self

    def switch_use_feed_fetch_ops(self, *a, **k):
        return self

    def disable_glog_info(self):
        return self


class Predictor:
    """ref: create_paddle_predictor(config) → AnalysisPredictor.

    Loads a saved inference model and runs it as one jitted XLA program.
    """

    def __init__(self, config_or_dir, executor=None):
        import paddle_tpu as fluid
        cfg = config_or_dir if isinstance(config_or_dir, Config) \
            else Config(str(config_or_dir))
        self.config = cfg
        self._exe = executor or fluid.Executor()
        self._scope = fluid.Scope()
        with fluid.scope_guard(self._scope):
            prog, feeds, fetches = fluid.io.load_inference_model(
                cfg.model_dir, self._exe, cfg.model_filename,
                cfg.params_filename)
        self.program = prog
        self.feed_names = feeds
        self.fetch_vars = fetches
        self.quantized_params = {}
        if cfg.precision == 'int8':
            self._quantize_weights()

    def _quantize_weights(self):
        """Rewrite the loaded program for weight-only int8: each ≥2-D float
        param becomes an int8 persistable + per-out-channel scale, and a
        `dequantize_linear` op prepended to the program reconstructs the
        float weight INSIDE the jitted step (XLA fuses it; HBM holds int8 —
        the TPU counterpart of the reference's quantized inference kernels,
        paddle/fluid/operators/fake_dequantize_op.cc)."""
        prog, scope = self.program, self._scope
        block = prog.global_block()
        bits = self.config.weight_bits
        qmax = 2.0 ** (bits - 1) - 1
        for var in list(prog.list_vars()):
            if not var.persistable:
                continue
            val = scope.find(var.name)
            if val is None:
                continue
            w = np.asarray(val)
            if w.dtype != np.float32 or w.ndim < 2:
                continue                     # biases/norm params stay float
            s = self.config.quant_scales.get(var.name)
            if s is None:
                s = np.max(np.abs(w), axis=tuple(range(1, w.ndim)))
            s = np.maximum(np.asarray(s, np.float32).reshape(-1), 1e-8)
            s_b = s.reshape((-1,) + (1,) * (w.ndim - 1))
            w_q = np.clip(np.round(w / s_b * qmax), -qmax, qmax) \
                .astype(np.int8)
            qname, sname = var.name + '@INT8', var.name + '@SCALE'
            block.create_var(name=qname, shape=list(w_q.shape), dtype='int8',
                             persistable=True, stop_gradient=True)
            block.create_var(name=sname, shape=list(s.shape),
                             dtype='float32', persistable=True,
                             stop_gradient=True)
            scope.set(qname, jnp.asarray(w_q))
            scope.set(sname, jnp.asarray(s))
            var.persistable = False          # now produced by dequant op
            block.prepend_op(type='dequantize_linear',
                             inputs={'x': qname, 'scale': sname},
                             outputs={'Out': var.name},
                             attrs={'bit_length': bits, 'quant_axis': 0})
            self.quantized_params[var.name] = s

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return [v.name if hasattr(v, 'name') else v for v in self.fetch_vars]

    def run(self, inputs):
        """inputs: list of arrays (feed order) or dict name→array.
        Returns the fetch arrays. Compiled once per feed-shape set."""
        import paddle_tpu as fluid
        if isinstance(inputs, dict):
            feed = inputs
        else:
            feed = dict(zip(self.feed_names, inputs))
        if self.config.precision == 'bfloat16':
            feed = {k: _to_bf16(v) for k, v in feed.items()}
        with fluid.scope_guard(self._scope):
            return self._exe.run(self.program, feed=feed,
                                 fetch_list=self.fetch_vars)


def _to_bf16(v):
    v = np.asarray(v)
    return v.astype(jnp.bfloat16) if v.dtype == np.float32 else v


def create_paddle_predictor(config):
    return Predictor(config)


# ---------------------------------------------------------------------------
# StableHLO export
# ---------------------------------------------------------------------------


def export_stablehlo(fn, example_args, path=None, bf16=False):
    """Lower a jittable function to StableHLO text. `fn(*example_args)` must
    be jax-traceable (use dygraph.jit.functionalize or TracedLayer to get
    one from a Layer). Returns the StableHLO module text; writes it to
    `path` when given."""
    if bf16:
        example_args = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, jnp.bfloat16)
            if hasattr(a, 'dtype') and np.asarray(a).dtype == np.float32
            else a, example_args)
    lowered = jax.jit(fn).lower(*example_args)  # lint: allow-jit (lower-only export, no XLA compile)
    text = lowered.as_text(dialect='stablehlo')
    if path:
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        with open(path, 'w') as f:
            f.write(text)
    return text


def export_program_stablehlo(program, feed_shapes, fetch_list, path=None,
                             scope=None, feed_dtypes=None):
    """Lower a static Program's (feed→fetch) computation to StableHLO.
    feed_shapes: {name: shape}."""
    import paddle_tpu as fluid
    from paddle_tpu.executor import Executor
    exe = Executor()
    dummy = {}
    for name, shape in feed_shapes.items():
        dt = (feed_dtypes or {}).get(name, 'float32')
        dummy[name] = np.zeros(shape, dt)

    ctx = fluid.scope_guard(scope) if scope is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        fn, arg_vals = exe.lower_to_callable(program, dummy, fetch_list)
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
    lowered = jax.jit(fn).lower(*arg_vals)  # lint: allow-jit (lower-only export, no XLA compile)
    text = lowered.as_text(dialect='stablehlo')
    if path:
        with open(path, 'w') as f:
            f.write(text)
    return text
