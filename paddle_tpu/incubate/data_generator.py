"""User-facing MultiSlot data generators (ref: python/paddle/fluid/incubate/
data_generator/__init__.py).

A DataGenerator subclass turns raw input lines into the MultiSlot text
format that `fluid.dataset` (dataset/fluid_dataset.py) consumes:
`<ids_num> id1 id2 ... <ids_num> ...` per line, one group per slot. The
reference runs these as subprocesses behind a pipe_command; here
run_from_stdin/run_from_memory write the same format to stdout (or any
file object via `write_to_file`) so a generator-produced file round-trips
through InMemoryDataset → train_from_dataset.
"""
from __future__ import annotations

import sys

__all__ = ['DataGenerator', 'MultiSlotDataGenerator',
           'MultiSlotStringDataGenerator']


class DataGenerator:
    """Base class: override generate_sample (line → [(slot, [feasign…])…])
    and optionally generate_batch for batch-level preprocessing."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int):
            raise ValueError(f"line_limit {type(line_limit)} must be int")
        if line_limit < 1:
            raise ValueError("line_limit can not less than 1")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # ---- drivers ----
    def _drain(self, lines, out):
        batch_samples = []
        for line in lines:
            for parsed in self.generate_sample(line)():
                if parsed is None:
                    continue
                batch_samples.append(parsed)
                if len(batch_samples) == self.batch_size_:
                    for sample in self.generate_batch(batch_samples)():
                        out.write(self._gen_str(sample))
                    batch_samples = []
        if batch_samples:
            for sample in self.generate_batch(batch_samples)():
                out.write(self._gen_str(sample))

    def run_from_memory(self, out=None):
        """Generate from generate_sample(None) — debug/benchmark path."""
        self._drain([None], out or sys.stdout)

    def run_from_stdin(self, out=None):
        """stdin lines → MultiSlot lines on stdout (the pipe_command
        contract of the reference)."""
        self._drain(sys.stdin, out or sys.stdout)

    def write_to_file(self, lines, path):
        """Convenience (TPU build): materialize a MultiSlot file for
        fluid.dataset set_filelist without a shell pipeline."""
        with open(path, 'w') as f:
            self._drain(lines, f)
        return path

    # ---- user hooks ----
    def generate_sample(self, line):
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: "
            "[(name, [feasign, ...]), ...] or ((name, [feasign, ...]), ...)")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "pls use MultiSlotDataGenerator or MultiSlotStringDataGenerator")


class MultiSlotStringDataGenerator(DataGenerator):
    """[(name, [str, ...]), ...] → `len v1 v2 ...` groups, no type check."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type; "
                "example: [('words', ['1926', '08', '17']), "
                "('label', ['1'])]")
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """[(name, [int|float, ...]), ...] → MultiSlot line, with slot schema
    (name, uint64|float) checked consistent across lines."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type; "
                "example: [('words', [1926, 8, 17]), ('label', [1])]")
        if self._proto_info is None:
            self._proto_info = []
            first = True
        else:
            first = False
            if len(line) != len(self._proto_info):
                raise ValueError(
                    "the complete field set of two given line are "
                    "inconsistent.")
        parts = []
        for index, (name, elements) in enumerate(line):
            if not isinstance(name, str):
                raise ValueError(f"name {type(name)} must be in str type")
            if not isinstance(elements, list):
                raise ValueError(
                    f"elements {type(elements)} must be in list type")
            if not elements:
                raise ValueError(
                    "the elements of each field can not be empty, you need "
                    "padding it in process().")
            if first:
                self._proto_info.append((name, "uint64"))
            else:
                if name != self._proto_info[index][0]:
                    raise ValueError(
                        "the field name of two given line are not match: "
                        f"require<{self._proto_info[index][0]}>, "
                        f"get<{name}>.")
            parts.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, float):
                    self._proto_info[index] = (name, "float")
                elif not isinstance(elem, int):
                    raise ValueError(
                        f"the type of element {type(elem)} must be in int "
                        "or float")
                parts.append(str(elem))
        return " ".join(parts) + "\n"
