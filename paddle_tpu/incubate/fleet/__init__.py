"""Fleet distributed-training namespace (ref: python/paddle/fluid/incubate/
fleet/__init__.py). `collective` and `parameter_server` modes both lower to
mesh data-parallelism with XLA collectives (SURVEY 2.8)."""
from . import base
from . import collective
from . import parameter_server
from . import utils
