"""Role makers (ref: python/paddle/fluid/incubate/fleet/base/role_maker.py).

The reference's role maker answered three questions per process — am I a
worker or a pserver, what is my rank, how many of each are there — by
parsing the ``PADDLE_*`` environment the launcher exported. On TPU the
same contract holds with the pserver half lowered away (every process is
a worker; parameter state syncs via XLA collectives — SURVEY 2.8):

- :class:`PaddleCloudRoleMaker` — THE production role maker. Reads
  ``PADDLE_TRAINERS_NUM`` / ``PADDLE_TRAINER_ID`` /
  ``PADDLE_TRAINER_ENDPOINTS`` / ``PADDLE_CURRENT_ENDPOINT`` through the
  strict-parse fleet bootstrap
  (:mod:`paddle_tpu.fleet_runtime.bootstrap`): a malformed or
  contradictory environment raises at ``generate_role()`` listing every
  expected variable. ``fleet.init(role_maker)`` then hands the validated
  :class:`~paddle_tpu.fleet_runtime.bootstrap.FleetSpec` to
  ``fleet_runtime.bootstrap`` for the jax.distributed bring-up. With no
  fleet env, topology falls back to the live jax runtime.
- :class:`UserDefinedRoleMaker` / :class:`UserDefinedCollectiveRoleMaker`
  — programmatic topologies (reference validation rules preserved).
- :data:`MPISymetricRoleMaker` — the MPI-rendezvous role makers map to
  the symmetric worker-only topology: jax.distributed covers multi-host
  rendezvous, so the cloud role maker IS the MPI one here.

``GeneralRoleMaker`` (the reference's gloo-based generalization) is an
alias of :class:`PaddleCloudRoleMaker` too: its extra knobs configured the
gloo rendezvous path, which the coordinator-based bootstrap replaces.
"""
from ....parallel.fleet import (Role, RoleMakerBase, PaddleCloudRoleMaker,
                                UserDefinedRoleMaker,
                                UserDefinedCollectiveRoleMaker)

MPISymetricRoleMaker = PaddleCloudRoleMaker
GeneralRoleMaker = PaddleCloudRoleMaker

__all__ = ['Role', 'RoleMakerBase', 'PaddleCloudRoleMaker',
           'UserDefinedRoleMaker', 'UserDefinedCollectiveRoleMaker',
           'MPISymetricRoleMaker', 'GeneralRoleMaker']
