"""Role makers (ref: python/paddle/fluid/incubate/fleet/base/role_maker.py).
Implementations live in parallel/fleet.py; this module provides the
reference import path so fleet scripts run unmodified."""
from ....parallel.fleet import (Role, RoleMakerBase, PaddleCloudRoleMaker,
                                UserDefinedRoleMaker,
                                UserDefinedCollectiveRoleMaker)

# MPI role makers map to the single-controller jax runtime: symmetric
# worker-only topology (no MPI in the TPU stack; jax.distributed covers
# multi-host rendezvous).
MPISymetricRoleMaker = PaddleCloudRoleMaker

__all__ = ['Role', 'RoleMakerBase', 'PaddleCloudRoleMaker',
           'UserDefinedRoleMaker', 'UserDefinedCollectiveRoleMaker',
           'MPISymetricRoleMaker']
