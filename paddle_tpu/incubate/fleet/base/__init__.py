from . import role_maker
