"""Collective fleet (ref: python/paddle/fluid/incubate/fleet/collective/
__init__.py). The TPU lowering lives in parallel/fleet.py: one jitted
program, feeds sharded over the mesh 'dp' axis, XLA AllReduce over ICI."""
from ....parallel.fleet import (fleet, Fleet, DistributedStrategy,
                                DistributedOptimizer)

# ref name for the strategy-honoring optimizer wrapper
CollectiveOptimizer = DistributedOptimizer

__all__ = ['fleet', 'Fleet', 'DistributedStrategy', 'DistributedOptimizer',
           'CollectiveOptimizer']
