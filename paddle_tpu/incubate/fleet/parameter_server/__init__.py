from . import distribute_transpiler
