"""Parameter-server fleet (ref: python/paddle/fluid/incubate/fleet/
parameter_server/distribute_transpiler/__init__.py:DistributedTranspiler).

TPU lowering (SURVEY 2.8 "parameter-server mode parity"): there are no
pserver processes — sparse/dense parameter state is replicated (or sharded)
over the device mesh and gradient sync is an XLA AllReduce over ICI instead
of grad send / param recv RPC. The API below keeps the reference surface so
a PS fleet script runs unmodified: `fleet.init(role)` accepts PS role
makers, `is_server()` gates to the worker branch (unless the role maker pins
Role.SERVER), and `distributed_optimizer(...).minimize(...)` produces the
same collective-DP program the collective fleet does.
"""
from .....parallel.fleet import (fleet as _collective_fleet, Fleet,
                                DistributedStrategy, DistributedOptimizer)
from .....transpiler import DistributeTranspiler, DistributeTranspilerConfig


class TranspilerOptimizer(DistributedOptimizer):
    """ref: TranspilerOptimizer — accepts a DistributeTranspilerConfig as
    strategy; transpiler knobs (slice_var_up, sync_mode, …) have no TPU
    meaning, so minimize() behaves as the collective DistributedOptimizer
    with default strategy."""

    def __init__(self, optimizer, strategy=None):
        from .....transpiler import warn_ps_lowering
        mode = 'geo-sgd' if (isinstance(strategy,
                                        DistributeTranspilerConfig)
                             and strategy.geo_sgd_mode) else \
            ('sync' if strategy is None or getattr(strategy, 'sync_mode',
                                                   True) else 'async')
        warn_ps_lowering(mode)
        if isinstance(strategy, DistributeTranspilerConfig) or strategy is None:
            ds = DistributedStrategy()
        else:
            ds = strategy
        super().__init__(optimizer, ds)
        self.transpiler_config = strategy


class _PSFleet(Fleet):
    """PS-flavored fleet singleton: distributed_optimizer returns a
    TranspilerOptimizer (reference name), everything else is the collective
    lowering from parallel/fleet.py."""

    def __init__(self):
        super().__init__(mode='ps')
        self._transpiler = None

    def distributed_optimizer(self, optimizer, strategy=None):
        self._strategy = strategy
        return TranspilerOptimizer(optimizer, strategy)

    @property
    def main_program(self):
        from .....framework import default_main_program
        return default_main_program()

    @property
    def startup_program(self):
        from .....framework import default_startup_program
        return default_startup_program()


fleet = _PSFleet()

__all__ = ['fleet', 'TranspilerOptimizer', 'DistributeTranspiler',
           'DistributeTranspilerConfig']
