"""Trainer file-barrier (ref: python/paddle/fluid/incubate/fleet/utils/
fleet_barrier_util.py — HDFS touch-file barrier). Same protocol over the
shared filesystem: each trainer touches ready/<epoch>.<trainer_id>; the
barrier completes when all trainer files for the epoch exist."""
from __future__ import annotations

import os
import time

__all__ = ['check_all_trainers_ready']


def check_all_trainers_ready(ready_path, epoch, timeout=None, poll=0.2):
    from ....parallel.fleet import fleet
    trainer_id = fleet.worker_index     # property on the collective fleet
    trainers = max(fleet.worker_num(), 1)
    os.makedirs(ready_path, exist_ok=True)
    mine = os.path.join(ready_path, f'{epoch}.{trainer_id}')
    with open(mine, 'w') as f:
        f.write(str(time.time()))
    deadline = None if timeout is None else time.time() + timeout
    while True:
        ready = sum(os.path.exists(os.path.join(ready_path,
                                                f'{epoch}.{i}'))
                    for i in range(trainers))
        if ready >= trainers:
            return True
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f'barrier {ready_path} epoch {epoch}: {ready}/{trainers} '
                'trainers ready')
        time.sleep(poll)
