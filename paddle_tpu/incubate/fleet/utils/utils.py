"""Fleet program/var tooling (ref: python/paddle/fluid/incubate/fleet/
utils/utils.py). Programs serialize through the json IR (io.py) — the
text/binary distinction of the reference's protobuf path collapses to one
format, but both spellings load it."""
from __future__ import annotations

import json
import os

import numpy as np

from ....framework import Program
from .... import io as _io

__all__ = [
    'load_program', 'load_program_binary', 'load_program_text',
    'save_program', 'program_type_trans', 'check_pruned_program_vars',
    'graphviz', 'save_var', 'load_var', 'reader', 'feed_gen',
    'check_not_expected_ops', 'check_saved_vars_try_dump', 'parse_program',
]

import logging as _logging
from ....log_helper import get_logger
logger = get_logger(__name__, _logging.INFO,
                    fmt='%(asctime)s-%(levelname)s: %(message)s')


def load_program(model_filename, is_text=False):
    """ref utils.py:51 — load a serialized Program (json IR either way)."""
    with open(model_filename) as f:
        return _io._program_from_dict(json.load(f))


def load_program_binary(model_filename):
    return load_program(model_filename, is_text=False)


def load_program_text(model_filename):
    return load_program(model_filename, is_text=True)


def save_program(program, model_filename='__model__', is_text=False):
    """ref utils.py:74."""
    with open(model_filename, 'w') as f:
        json.dump(_io._program_to_dict(program), f)


def program_type_trans(prog_dir, prog_fn, is_text):
    """ref utils.py:128 — re-serialize a program 'in the other format'
    (single json IR here; written alongside with the .bin/.pbtxt-style
    suffix so downstream path expectations hold)."""
    prog = load_program(os.path.join(prog_dir, prog_fn), is_text)
    out = prog_fn + ('.bin' if is_text else '.pbtxt')
    save_program(prog, os.path.join(prog_dir, out), not is_text)
    return out


def check_pruned_program_vars(train_prog, pruned_prog):
    """ref utils.py:83 — every var the pruned (inference) program keeps
    must exist in the train program with identical shape/dtype."""
    problems = []
    train_vars = {v.name: v for v in train_prog.list_vars()}
    for v in pruned_prog.list_vars():
        if v.is_data:
            continue
        tv = train_vars.get(v.name)
        if tv is None:
            problems.append(f'{v.name}: missing from train program')
        elif tuple(tv.shape or ()) != tuple(v.shape or ()) or \
                tv.dtype != v.dtype:
            problems.append(
                f'{v.name}: train {tv.shape}/{tv.dtype} != pruned '
                f'{v.shape}/{v.dtype}')
    return problems


def graphviz(block, output_dir='', filename='debug'):
    """ref utils.py:115 — dot render of a block via the debugger."""
    from ....debugger import draw_block_graphviz
    path = os.path.join(output_dir, filename + '.dot')
    draw_block_graphviz(block, path=path)
    return path


def save_var(np_array, var_name, shape_list, dtype, save_path):
    """ref utils.py:149 — raw little-endian dump of one var."""
    np.asarray(np_array, dtype).reshape(shape_list).tofile(save_path)
    return save_path


def load_var(var_name, shape_list, dtype, save_path):
    """ref utils.py:159."""
    return np.fromfile(save_path, dtype).reshape(shape_list)


def reader(batch_size, fn, dim):
    """ref utils.py:170 — list of (batch_size, *dim) float batches. Each
    line is consumed batch_size·prod(dim) floats at a time, so one line
    may yield several batches (the reference's `while len(fields) >= dim`
    loop); leftover floats shorter than a full batch are dropped, exactly
    as in the reference."""
    data = []
    shape = list(dim) if isinstance(dim, (list, tuple)) else [dim]
    per_sample = int(np.prod(shape))
    shape = [batch_size] + shape
    per_batch = per_sample * batch_size
    with open(fn) as f:
        for line in f:
            fields = [float(d) for d in line.strip().split(' ') if d]
            while len(fields) >= per_batch:
                tmp, fields = fields[:per_batch], fields[per_batch:]
                data.append(np.array(tmp).reshape(shape))
    return data


def feed_gen(batch_size, feeded_vars_dims, feeded_vars_filelist):
    """ref utils.py:194 — per-var batch lists."""
    return [reader(batch_size, fn, feeded_vars_dims[i])
            for i, fn in enumerate(feeded_vars_filelist)]


def check_not_expected_ops(prog, not_expected_op_types=('lookup_table',)):
    """ref utils.py:349 — report ops an inference program should not
    contain (e.g. distributed lookup tables that need the PS runtime)."""
    found = sorted({op.type for b in prog.blocks for op in b.ops
                    if op.type in set(not_expected_op_types)})
    return found


def check_saved_vars_try_dump(dump_dir, dump_prog_fn, is_text_dump_program,
                              feeded_vars=None, fetch_list=None,
                              batch_size=1, save_filename=None):
    """ref utils.py:359 — load the dumped program, verify its persistable
    vars against the saved state, and return (program, problems)."""
    prog = load_program(os.path.join(dump_dir, dump_prog_fn),
                        is_text_dump_program)
    state_path = os.path.join(dump_dir, save_filename or 'params.npz')
    if not os.path.exists(state_path):
        # nothing to verify against must FAIL the check, not pass it
        return prog, [f'saved state not found at {state_path}']
    with np.load(state_path) as data:
        saved = {k: data[k].shape for k in data.files}
    problems = []
    for v in prog.list_vars():
        if not v.persistable or v.is_data:
            continue
        if v.name not in saved:
            problems.append(f'{v.name}: not in saved state')
        elif v.shape and tuple(saved[v.name]) != tuple(v.shape):
            problems.append(f'{v.name}: saved {saved[v.name]} != '
                            f'program {v.shape}')
    return prog, problems


def parse_program(program, output_dir):
    """ref utils.py:381 — dump a human-readable program report."""
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, 'program.txt')
    with open(path, 'w') as f:
        for b in program.blocks:
            f.write(f'block {b.idx} (parent {b.parent_idx})\n')
            for v in b.vars.values():
                f.write(f'  var {v.name} shape={v.shape} '
                        f'dtype={v.dtype} persistable={v.persistable}\n')
            for op in b.ops:
                f.write(f'  op {op.type} inputs={op.inputs} '
                        f'outputs={op.outputs}\n')
    return path
