"""Fleet operational utilities (ref: python/paddle/fluid/incubate/fleet/
utils/)."""
from . import fleet_util
from . import fleet_barrier_util
from . import hdfs
from . import utils
from .fleet_util import FleetUtil
from .fleet_barrier_util import check_all_trainers_ready
from .hdfs import HDFSClient

__all__ = ['FleetUtil', 'check_all_trainers_ready', 'HDFSClient',
           'fleet_util', 'fleet_barrier_util', 'hdfs', 'utils']
