"""ref: python/paddle/fluid/incubate/fleet/utils/hdfs.py — the fleet-side
HDFS client. One implementation lives in contrib/utils/hdfs_utils.py;
re-exported here so fleet scripts' import path works unchanged."""
from ....contrib.utils.hdfs_utils import HDFSClient, multi_download, \
    multi_upload

__all__ = ['HDFSClient', 'multi_download', 'multi_upload']
