"""Fleet operational toolkit (ref: python/paddle/fluid/incubate/fleet/
utils/fleet_util.py — the pslib online-learning utilities).

TPU lowering notes:
- rank gating uses the collective fleet's worker_index (one process per
  host; rank 0 speaks);
- the reference's mpi all-reduce of AUC stat buckets is an identity here:
  the jitted step already psums metric stats across the mesh, so the scope
  holds GLOBAL buckets (ref fleet_util.py:186 reduces per-worker copies);
- model artifacts follow the same output_path/day/pass directory protocol
  (donefiles included) over the local/shared filesystem via io.py;
  pslib embedding-table RPC ops (load_fleet_model_one_table etc.) have no
  TPU meaning and raise with a pointer to the checkpoint API.
"""
from __future__ import annotations

import os
import time

import numpy as np

from .... import io as _io
from ....executor import Executor
from ....core.scope import global_scope

__all__ = ['FleetUtil']


class FleetUtil:
    """ref fleet_util.py:40 — operational helpers for fleet training."""

    def __init__(self, mode='collective'):
        self.mode = mode

    # ---- rank-0 logging ----
    def _rank(self):
        from ....parallel.fleet import fleet
        try:
            return fleet.worker_index   # property on the collective fleet
        except Exception:
            return 0

    def rank0_print(self, s):
        """ref :63 — only worker 0 prints."""
        if self._rank() == 0:
            print(s, flush=True)  # lint: allow-print (rank0_print contract is stdout)

    def rank0_info(self, s):
        if self._rank() == 0:
            import logging
            logging.getLogger(__name__).info(s)

    def rank0_error(self, s):
        if self._rank() == 0:
            import logging
            logging.getLogger(__name__).error(s)

    # ---- metric helpers ----
    def set_zero(self, var_name, scope=None, place=None, param_type='int64'):
        """ref :121 — zero a stat var in the scope."""
        scope = scope or global_scope()
        import jax.numpy as jnp
        from ....core.dtypes import to_jax_dtype
        cur = scope.find(var_name)
        if cur is None:
            raise KeyError(f'{var_name} not in scope')
        scope.set(var_name, jnp.zeros(jnp.asarray(cur).shape,
                                      to_jax_dtype(param_type)))

    @staticmethod
    def _auc_from_buckets(pos, neg):
        pos = np.asarray(pos, np.float64).reshape(-1)
        neg = np.asarray(neg, np.float64).reshape(-1)
        num_bucket = pos.size
        area = new_pos = new_neg = p = n = 0.0
        total = 0.0
        for i in range(num_bucket):
            idx = num_bucket - 1 - i
            new_pos = p + pos[idx]
            new_neg = n + neg[idx]
            total += pos[idx] + neg[idx]
            area += (new_neg - n) * (p + new_pos) / 2.0
            p, n = new_pos, new_neg
        if p * n == 0 or total == 0:
            return 0.5, int(total)
        return float(area / (p * n)), int(total)

    def get_global_auc(self, scope=None, stat_pos='_generated_var_2',
                       stat_neg='_generated_var_3'):
        """ref :186 — AUC from the pos/neg stat buckets. The buckets in
        scope are already global (in-step psum), so no host all-reduce."""
        scope = scope or global_scope()
        pos = scope.find(stat_pos)
        neg = scope.find(stat_neg)
        if pos is None or neg is None:
            self.rank0_print('not found auc bucket')
            return None
        auc, _ = self._auc_from_buckets(np.asarray(pos), np.asarray(neg))
        return auc

    def print_global_auc(self, scope=None, stat_pos='_generated_var_2',
                         stat_neg='_generated_var_3',
                         print_prefix=''):
        """ref :147."""
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f'{print_prefix} global auc = {auc}')
        return auc

    def get_global_metrics(self, scope=None,
                           stat_pos_name='_generated_var_2',
                           stat_neg_name='_generated_var_3',
                           sqrerr_name='sqrerr', abserr_name='abserr',
                           prob_name='prob', q_name='q',
                           pos_ins_num_name='pos', total_ins_num_name='total'):
        """ref :1268 — the 8-metric CTR bundle (auc, bucket_error, mae,
        rmse, actual_ctr, predicted_ctr, copc, mean_q, ins count)."""
        scope = scope or global_scope()

        def val(name):
            v = scope.find(name)
            return None if v is None else float(np.asarray(v).sum())

        pos_b = scope.find(stat_pos_name)
        neg_b = scope.find(stat_neg_name)
        if pos_b is None or neg_b is None:
            self.rank0_print('not found auc bucket')
            return None
        pos_arr = np.asarray(pos_b, np.float64).reshape(-1)
        neg_arr = np.asarray(neg_b, np.float64).reshape(-1)
        auc, _ = self._auc_from_buckets(pos_arr, neg_arr)
        total = val(total_ins_num_name) or 0.0
        pos = val(pos_ins_num_name) or 0.0
        sqrerr = val(sqrerr_name) or 0.0
        abserr = val(abserr_name) or 0.0
        prob = val(prob_name) or 0.0
        q = val(q_name) or 0.0
        keys = ('auc', 'bucket_error', 'mae', 'rmse', 'actual_ctr',
                'predicted_ctr', 'copc', 'mean_q', 'total_ins_num')
        if total <= 0:   # empty pass: stable key set, zeroed stats
            out = dict.fromkeys(keys, 0.0)
            out.update(auc=auc, total_ins_num=0)
            return out
        actual_ctr = pos / total
        predicted_ctr = prob / total
        return {
            'auc': auc,
            'bucket_error': self._bucket_error(pos_arr, neg_arr),
            'mae': abserr / total,
            'rmse': float(np.sqrt(sqrerr / total)),
            'actual_ctr': actual_ctr,
            'predicted_ctr': predicted_ctr,
            'copc': (actual_ctr / predicted_ctr) if predicted_ctr else 0.0,
            'mean_q': q / total,
            'total_ins_num': int(total),
        }

    @staticmethod
    def _bucket_error(pos, neg, k_max_span=0.01,
                      k_relative_error_bound=0.05):
        """ref :1408 — calibration error over merged prediction buckets:
        buckets merge until the adjusted CTR estimate is statistically
        tight (relative error < bound), then the |actual/predicted - 1|
        deviation is impression-weighted."""
        import math
        num_bucket = pos.size
        last_ctr, impression_sum, ctr_sum, click_sum = -1.0, 0.0, 0.0, 0.0
        error_sum, error_count = 0.0, 0.0
        for i in range(num_bucket):
            click = pos[i]
            show = pos[i] + neg[i]
            ctr = float(i) / num_bucket
            if abs(ctr - last_ctr) > k_max_span:
                last_ctr = ctr
                impression_sum = ctr_sum = click_sum = 0.0
            impression_sum += show
            ctr_sum += ctr * show
            click_sum += click
            if impression_sum == 0:
                continue
            adjust_ctr = ctr_sum / impression_sum
            if adjust_ctr == 0:
                continue
            relative_error = math.sqrt(
                (1 - adjust_ctr) / (adjust_ctr * impression_sum))
            if relative_error < k_relative_error_bound:
                actual = click_sum / impression_sum
                error_sum += abs(actual / adjust_ctr - 1) * impression_sum
                error_count += impression_sum
                last_ctr = -1
        return error_sum / error_count if error_count > 0 else 0.0

    def print_global_metrics(self, scope=None, print_prefix='', **kw):
        """ref :1457."""
        m = self.get_global_metrics(scope, **kw)
        self.rank0_print(f'{print_prefix} global metrics: {m}')
        return m

    # ---- model artifact protocol (output_path/day/pass dirs + donefiles)
    def _model_dir(self, output_path, day, pass_id=None):
        d = os.path.join(output_path, str(day))
        if pass_id is not None:
            d = os.path.join(d, str(pass_id))
        return d

    def save_model(self, output_path, day, pass_id, program=None):
        """ref :670 — persist the (train) program state under
        output_path/day/pass_id."""
        d = self._model_dir(output_path, day, pass_id)
        os.makedirs(d, exist_ok=True)
        from ....framework import default_main_program
        _io.save_persistables(Executor(), d,
                              program or default_main_program())
        return d

    def load_model(self, output_path, day, pass_id, program=None):
        """ref :645."""
        d = self._model_dir(output_path, day, pass_id)
        from ....framework import default_main_program
        _io.load_persistables(Executor(), d,
                              program or default_main_program())
        return d

    def save_batch_model(self, output_path, day, program=None):
        """ref :695 — day-level (batch) model dir."""
        return self.save_model(output_path, day, None, program)

    def save_delta_model(self, output_path, day, pass_id, program=None):
        """ref :718 — delta dirs share the pass protocol here (dense state
        has no sparse-delta distinction on TPU)."""
        return self.save_model(output_path, 'delta-' + str(day), pass_id,
                               program)

    def save_paddle_inference_model(self, executor, scope, program,
                                    feeded_vars, target_vars, output_path,
                                    day, pass_id, hadoop_fs_name=None,
                                    hadoop_fs_ugi=None, **kw):
        """ref :876 — inference slice under the day/pass dir."""
        d = self._model_dir(output_path, day, pass_id)
        os.makedirs(d, exist_ok=True)
        feeds = [v if isinstance(v, str) else v.name for v in feeded_vars]
        _io.save_inference_model(d, feeds, list(target_vars), executor,
                                 program)
        return d

    def save_paddle_params(self, executor, scope, program, model_name,
                           output_path, day, pass_id, **kw):
        """ref :965."""
        d = self._model_dir(output_path, day, pass_id)
        os.makedirs(d, exist_ok=True)
        _io.save_params(executor, d, program, filename=model_name)
        return d

    # ---- donefiles ----
    def write_model_donefile(self, output_path, day, pass_id, xbox_base_key,
                             donefile_name='donefile.txt', **kw):
        """ref :362 — append 'day\\tkey\\tpath\\tpass' to the donefile."""
        path = self._model_dir(output_path, day, pass_id)
        done = os.path.join(output_path, donefile_name)
        os.makedirs(output_path, exist_ok=True)
        with open(done, 'a') as f:
            f.write(f'{day}\t{xbox_base_key}\t{path}\t{pass_id}\t0\n')
        return done

    def write_xbox_donefile(self, output_path, day, pass_id, xbox_base_key,
                            donefile_name=None, **kw):
        """ref :456 — xbox (online serving) donefile, same local protocol."""
        name = donefile_name or ('xbox_base_done.txt' if pass_id in (-1, '-1')
                                 else 'xbox_patch_done.txt')
        return self.write_model_donefile(output_path, day, pass_id,
                                         xbox_base_key, name)

    def write_cache_donefile(self, output_path, day, pass_id, key_num,
                             donefile_name='sparse_cache.meta', **kw):
        """ref :568."""
        return self.write_model_donefile(output_path, day, pass_id, key_num,
                                         donefile_name)

    def _last_done_entry(self, output_path, donefile_name):
        done = os.path.join(output_path, donefile_name)
        if not os.path.exists(done):
            return None
        lines = [l for l in open(done).read().splitlines() if l.strip()]
        return lines[-1].split('\t') if lines else None

    def get_last_save_model(self, output_path,
                            donefile_name='donefile.txt', **kw):
        """ref :1158 — (day, pass_id, path, xbox_base_key)."""
        e = self._last_done_entry(output_path, donefile_name)
        if e is None:
            return [-1, -1, '', int(time.time())]
        return [int(e[0]), int(e[3]), e[2], int(e[1])]

    def get_last_save_xbox(self, output_path,
                           donefile_name='xbox_patch_done.txt', **kw):
        """ref :1112."""
        return self.get_last_save_model(output_path, donefile_name)

    def get_last_save_xbox_base(self, output_path,
                                donefile_name='xbox_base_done.txt', **kw):
        """ref :1067."""
        e = self._last_done_entry(output_path, donefile_name)
        if e is None:
            return [-1, '', int(time.time())]
        return [int(e[0]), e[2], int(e[1])]

    # ---- schedule logic ----
    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass, is_data_hourly_placed):
        """ref :1207 — pure schedule arithmetic (no shell expansion; pass
        explicit lists or '0..23'-style ranges)."""
        def expand(spec):
            if isinstance(spec, (list, tuple)):
                return [str(s) for s in spec]
            spec = str(spec).strip('{}')
            if '..' in spec:
                a, b = spec.split('..')
                width = len(a)
                return [str(i).zfill(width) for i in
                        range(int(a), int(b) + 1)]
            return spec.split()

        hours = expand(hours)
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left, right = int(hours[0]), int(hours[-1])
        split_path = []
        start = 0
        for _ in range(splits_per_day):
            h, m = start // 60, start % 60
            start += split_interval
            if h < left or h > right:
                continue
            split_path.append('%02d' % h if is_data_hourly_placed
                              else '%02d%02d' % (h, m))
        online_pass_interval = []
        start = 0
        for _ in range(pass_per_day):
            chunk = split_path[start:start + split_per_pass]
            if not chunk:
                break
            online_pass_interval.append(chunk)
            start += split_per_pass
        return online_pass_interval

    # ---- program tooling (delegates) ----
    def program_type_trans(self, prog_dir, prog_fn, is_text):
        from .utils import program_type_trans
        return program_type_trans(prog_dir, prog_fn, is_text)

    def draw_from_program_file(self, model_filename, is_text, output_dir,
                               output_name):
        from .utils import load_program
        return self.draw_from_program(load_program(model_filename, is_text),
                                      output_dir, output_name)

    def draw_from_program(self, program, output_dir, output_name):
        from .utils import graphviz
        return graphviz(program.global_block(), output_dir, output_name)

    def check_two_programs(self, config):
        from .utils import load_program, check_pruned_program_vars
        train = load_program(config.train_prog_path,
                             getattr(config, 'is_text_train_program', False))
        pruned = load_program(config.pruned_prog_path,
                              getattr(config, 'is_text_pruned_program',
                                      False))
        problems = check_pruned_program_vars(train, pruned)
        for p in problems:
            self.rank0_error(p)
        return not problems

    def check_vars_and_dump(self, config):
        from .utils import check_not_expected_ops, load_program
        prog = load_program(config.pruned_prog_path,
                            getattr(config, 'is_text_pruned_program', False))
        bad = check_not_expected_ops(prog)
        for b in bad:
            self.rank0_error(f'unexpected op in inference program: {b}')
        return not bad

    # ---- pslib-only RPC surface ----
    def _no_pslib(self, name):
        raise RuntimeError(
            f'{name} drives pslib embedding-table RPC, which has no TPU '
            'equivalent — dense+sparse state is mesh-sharded and saved via '
            'save_model/load_model (orbax/io checkpoints).')

    def load_fleet_model_one_table(self, table_id, path):
        self._no_pslib('load_fleet_model_one_table')

    def load_fleet_model(self, path, mode=0):
        self._no_pslib('load_fleet_model')

    def save_fleet_model(self, path, mode=0):
        self._no_pslib('save_fleet_model')

    def pull_all_dense_params(self, scope, program):
        """ref :833 — on TPU dense params already live in the scope; return
        their names (the reference returns the pulled var list)."""
        scope = scope or global_scope()
        return [v.name for v in program.list_vars()
                if v.persistable and scope.find(v.name) is not None]

    def save_cache_model(self, output_path, day, pass_id, mode=1, **kw):
        self._no_pslib('save_cache_model')

    def save_cache_base_model(self, output_path, day, **kw):
        self._no_pslib('save_cache_base_model')

    def save_xbox_base_model(self, output_path, day, **kw):
        return self.save_model(output_path, day, -1)
