"""Incubate namespace (ref: python/paddle/fluid/incubate/__init__.py)."""
from . import fleet
from . import data_generator
