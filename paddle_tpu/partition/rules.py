"""Logical axis rules: the T5X-style table mapping LOGICAL tensor axes
('batch', 'embed', 'mlp', …) onto MESH axes ('dp', 'tp', 'fsdp', …).

Models and recipes talk about what a dimension *means*; the Partitioner
owns how meaning maps onto hardware. The table is ORDERED — the first
rule whose mesh axes exist in the mesh, are not already used by another
dimension of the same tensor, and divide the dimension size wins; no
rule matching means the dimension replicates. That one lookup is what
lets `dp`, `dp×tp`, `dp×fsdp`, and `fsdp`-only meshes share every model
definition (SNIPPETS.md [1]–[3] pattern).

Parsing is strict (the PR 8/9 knob-hygiene contract): unknown logical
or mesh axis names raise ``ValueError`` listing the supported set, both
from the env knobs (``PADDLE_TPU_AXIS_RULES`` / ``PADDLE_TPU_MESH``)
and from ``DistributedStrategy.axis_rules`` / ``mesh_shape``.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec

__all__ = ['LOGICAL_AXES', 'MESH_AXES', 'DEFAULT_AXIS_RULES', 'AxisRules',
           'parse_axis_rules', 'parse_mesh_shape', 'largest_divisible_dim']

# logical tensor-dimension names models/recipes may use (SURVEY §2.8 +
# the T5X convention). 'fsdp' doubles as a logical name so a parameter
# can *ask* for ZeRO-style sharding of a specific dim.
LOGICAL_AXES = ('batch', 'embed', 'mlp', 'heads', 'kv', 'vocab', 'seq',
                'stage', 'fsdp')

# mesh axis-name convention: dp (data), fsdp (sharded params), tp
# (tensor), pp (pipeline), sp (sequence).
MESH_AXES = ('dp', 'fsdp', 'tp', 'pp', 'sp')

# Ordered rule table. A value may be one mesh axis, a tuple (the dim
# shards over their product, e.g. batch over dp×fsdp), or None
# (explicitly replicated). First match wins.
DEFAULT_AXIS_RULES = (
    ('batch', ('dp', 'fsdp')),
    ('fsdp', 'fsdp'),
    ('mlp', 'tp'),
    ('heads', 'tp'),
    ('vocab', 'tp'),
    ('kv', None),
    ('embed', None),
    ('seq', 'sp'),
    ('stage', 'pp'),
)


def _err(source, what, value, supported):
    raise ValueError(
        f"{source}: unknown {what} {value!r} "
        f"(supported: {', '.join(supported)})")


def _norm_value(value, source):
    """Rule value → tuple of mesh axes, or None (replicated)."""
    if value is None or value == '':
        return None
    if isinstance(value, str):
        value = tuple(v for v in value.replace('+', ' ').split() if v)
    axes = tuple(value)
    for a in axes:
        if a not in MESH_AXES:
            _err(source, 'mesh axis', a, MESH_AXES)
    return axes or None


def parse_axis_rules(value, source='axis_rules'):
    """Strict parse of an axis-rule table.

    Accepts ``None`` (→ None), a string ``"batch=dp+fsdp,mlp=tp,kv="``
    (``=`` with an empty right side pins a logical axis to replicated),
    or a sequence of ``(logical, mesh_axis_or_tuple_or_None)`` pairs.
    Unknown logical/mesh names raise ValueError naming the supported set.
    """
    if value is None:
        return None
    if isinstance(value, str):
        pairs = []
        for item in value.split(','):
            item = item.strip()
            if not item:
                continue
            if '=' not in item:
                raise ValueError(
                    f"{source}: expected 'logical=mesh' entries, got "
                    f"{item!r} (e.g. 'batch=dp,mlp=tp,kv=')")
            k, v = item.split('=', 1)
            pairs.append((k.strip(), v.strip()))
        value = pairs
    out = []
    for entry in value:
        if not isinstance(entry, (tuple, list)) or len(entry) != 2:
            raise ValueError(
                f"{source}: each rule must be a (logical, mesh) pair, "
                f"got {entry!r}")
        logical, mesh_axes = entry
        if logical not in LOGICAL_AXES:
            _err(source, 'logical axis', logical, LOGICAL_AXES)
        out.append((logical, _norm_value(mesh_axes, source)))
    return tuple(out)


def parse_mesh_shape(value, source='mesh_shape'):
    """Strict parse of a mesh shape: dict or ``"dp=2,tp=4"`` string →
    ordered ``{axis: size}``. Unknown axis names and non-positive sizes
    raise ValueError."""
    if value is None:
        return None
    if isinstance(value, str):
        pairs = []
        for item in value.split(','):
            item = item.strip()
            if not item:
                continue
            if '=' not in item:
                raise ValueError(
                    f"{source}: expected 'axis=size' entries, got "
                    f"{item!r} (e.g. 'dp=2,tp=4')")
            k, v = item.split('=', 1)
            pairs.append((k.strip(), v.strip()))
        value = pairs
    items = value.items() if isinstance(value, dict) else value
    out: Dict[str, int] = {}
    for axis, size in items:
        if axis not in MESH_AXES:
            _err(source, 'mesh axis', axis, MESH_AXES)
        try:
            size = int(size)
        except (TypeError, ValueError):
            raise ValueError(
                f"{source}: size of mesh axis {axis!r} must be an int, "
                f"got {size!r}")
        if size < 1:
            raise ValueError(
                f"{source}: size of mesh axis {axis!r} must be >= 1, "
                f"got {size}")
        if axis in out:
            raise ValueError(f"{source}: mesh axis {axis!r} given twice")
        out[axis] = size
    return out or None


def largest_divisible_dim(shape, size) -> Optional[int]:
    """Index of the LARGEST dim divisible by ``size`` (and >= it), or
    None. Largest-dim wins: maximizes bytes saved per device and keeps
    the all-gather contiguous — the ZeRO/fsdp placement rule."""
    best, best_size = None, 0
    for d, s in enumerate(shape):
        if isinstance(s, int) and s % size == 0 and s >= size \
                and s > best_size:
            best, best_size = d, s
    return best


class AxisRules:
    """Ordered, validated logical→mesh rule table."""

    __slots__ = ('_rules',)

    def __init__(self, rules=None):
        self._rules = parse_axis_rules(
            DEFAULT_AXIS_RULES if rules is None else rules) or ()

    @property
    def rules(self) -> Tuple:
        return self._rules

    def candidates(self, logical) -> Sequence[Optional[Tuple[str, ...]]]:
        """Rule values for ``logical``, in table order."""
        return [v for k, v in self._rules if k == logical]

    def resolve(self, logical, axis_sizes: Dict[str, int], taken=(),
                dim=None):
        """Mesh axes ``logical`` shards over in a mesh with
        ``axis_sizes``: the first rule whose (mesh-present, un-``taken``)
        axes divide ``dim`` (when known). None → replicate."""
        if logical is None:
            return None
        for value in self.candidates(logical):
            if value is None:
                return None
            axes = tuple(a for a in value
                         if axis_sizes.get(a, 0) > 1 and a not in taken)
            if not axes:
                continue
            span = int(np.prod([axis_sizes[a] for a in axes]))
            if isinstance(dim, int) and dim % span != 0:
                continue
            return axes
        return None

    def spec(self, logical_axes, axis_sizes: Dict[str, int],
             shape=None) -> PartitionSpec:
        """Resolve a whole logical spec (one logical name or None per
        dim) into a PartitionSpec, never assigning a mesh axis twice."""
        taken: set = set()
        entries = []
        for i, logical in enumerate(logical_axes):
            dim = None
            if shape is not None and i < len(shape) \
                    and isinstance(shape[i], int):
                dim = shape[i]
            axes = self.resolve(logical, axis_sizes, taken=taken, dim=dim)
            if axes is None:
                entries.append(None)
            else:
                taken.update(axes)
                entries.append(axes[0] if len(axes) == 1 else axes)
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def to_json(self):
        return [[k, list(v) if v is not None else None]
                for k, v in self._rules]

    def __repr__(self):
        return f'AxisRules({self._rules!r})'
