"""Vocab-sharded embedding tables over the partitioner's mesh.

A V×D table too big for one device's HBM shards its VOCAB dim over a
mesh axis (the ``vocab`` logical-axis rule, rules.py): device i owns
rows [i·V/p, (i+1)·V/p). The access pattern is the classic
parameter-server exchange, expressed as XLA collectives (docs/SPARSE.md
"Vocab sharding"):

- **lookup** — every device takes an equal slice of the id batch,
  routes each id to its owner shard with an ``all_to_all``, the owner
  gathers locally, a second ``all_to_all`` returns the rows, and an
  ``all_gather`` re-replicates the output batch (ids → owners → rows
  back: O(nnz·D) wire bytes, never O(V·D)).
- **gradient push** — the padded-COO gradient pair is (optionally)
  gathered across a data axis through the PR 9 quantized codec
  (``quant_collectives.sparse_allgather``: int8 rows + per-row f32
  scales), then every shard scatter-applies ONLY its owned rows — the
  out-of-bounds drop does the routing.

Single-process CPU meshes (tests) and real TPU meshes share this code;
parity vs an unsharded dense table is asserted in
tests/framework/test_sparse_embedding.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import compat
from ..parallel import quant_collectives as qc
from ..ops import sparse_ops as sp

__all__ = ['VocabShardedTable', 'sharded_lookup', 'shard_owned_apply']


def _axis_size_of(mesh: Mesh, axis: str) -> int:
    if axis not in mesh.shape:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no axis {axis!r}")
    return int(mesh.shape[axis])


def sharded_lookup(w_local, ids, axis: str, vocab: int):
    """Inside shard_map (``axis`` bound, ``w_local`` = this device's
    (V/p, D) shard, ``ids`` replicated): the all-to-all exchange above.
    Returns the replicated (nnz, D) rows."""
    n = lax.psum(1, axis)
    me = lax.axis_index(axis)
    shard = vocab // n
    ids = ids.reshape(-1).astype(jnp.int32)
    nnz = ids.shape[0]
    chunk = -(-nnz // n)
    padded = chunk * n
    if padded != nnz:
        # sentinel pad: owner formula maps `vocab` to shard n (nobody),
        # so pad lanes ride along as masked zeros
        ids = jnp.concatenate(
            [ids, jnp.full((padded - nnz,), vocab, jnp.int32)])
    # my slice of the id batch
    my_ids = lax.dynamic_slice_in_dim(ids, me * chunk, chunk)
    owner = jnp.clip(my_ids // shard, 0, n)          # vocab → n (pad)
    # request buffer: lane (k, j) asks peer k for my j-th id iff k owns it
    want = owner[None, :] == jnp.arange(n)[:, None]          # (n, chunk)
    req = jnp.where(want, my_ids[None, :], vocab)            # vocab = "no"
    got = lax.all_to_all(req, axis, split_axis=0, concat_axis=0)
    # serve: gather my owned rows for every request lane
    local = jnp.clip(got - me * shard, 0, w_local.shape[0] - 1)
    rows = jnp.take(w_local, local, axis=0)                  # (n, chunk, D)
    rows = jnp.where(((got >= me * shard)
                      & (got < (me + 1) * shard))[..., None], rows, 0.0)
    back = lax.all_to_all(rows, axis, split_axis=0, concat_axis=0)
    # exactly one peer answered each of my lanes (the owner)
    mine = jnp.sum(back * want[..., None].astype(rows.dtype), axis=0)
    out = lax.all_gather(mine, axis).reshape(padded, -1)
    return out[:nnz]


def shard_owned_apply(w_local, rows, vals, axis: str, vocab: int, update):
    """Scatter-apply a replicated COO gradient to this device's shard:
    rows re-base to the local window and everything out of window drops
    (XLA scatter semantics do the routing). ``update(w_local, local_rows,
    vals)`` is the rows-only optimizer formula."""
    n = lax.psum(1, axis)
    me = lax.axis_index(axis)
    shard = vocab // n
    rows = jnp.asarray(rows).astype(jnp.int32)
    owned = (rows >= me * shard) & (rows < (me + 1) * shard)
    # out-of-window rows → index V/p (dropped by mode='drop')
    local_rows = jnp.where(owned, rows - me * shard, w_local.shape[0])
    return update(w_local, local_rows, jnp.asarray(vals))


class VocabShardedTable:
    """A (vocab, dim) embedding table sharded over ``axis`` of ``mesh``.

    ``lookup(ids)`` returns replicated rows for any replicated id batch;
    ``sgd_push(rows, vals, lr, dp_axis=, comm_dtype=)`` applies a padded
    COO gradient, optionally gathering it across a data axis through the
    quantized sparse push first. ``full_table()`` reassembles the dense
    table (tests / checkpoint export)."""

    def __init__(self, vocab, dim, mesh: Mesh, axis: str = 'tp',
                 init=None, dtype=jnp.float32):
        self.vocab, self.dim = int(vocab), int(dim)
        self.mesh, self.axis = mesh, axis
        n = _axis_size_of(mesh, axis)
        if self.vocab % n:
            raise ValueError(
                f"vocab {self.vocab} is not divisible by mesh axis "
                f"{axis!r} size {n}")
        self.shard_rows = self.vocab // n
        if init is None:
            init = np.zeros((self.vocab, self.dim), np.float32)
        init = np.asarray(init, np.float32)
        if init.shape != (self.vocab, self.dim):
            raise ValueError(
                f"init shape {init.shape} != ({self.vocab}, {self.dim})")
        self._sharding = NamedSharding(mesh, P(axis, None))
        self.weight = jax.device_put(jnp.asarray(init, dtype),
                                     self._sharding)
        self._lookup_fn = None
        self._push_fns = {}

    # -- lookup ---------------------------------------------------------
    def lookup(self, ids):
        """(…,) int ids → (…, dim) rows (replicated)."""
        ids = jnp.asarray(ids)
        shape = ids.shape
        if self._lookup_fn is None:
            mesh, axis, vocab = self.mesh, self.axis, self.vocab

            def fn(w, flat_ids):
                body = compat.shard_map(
                    lambda wl, i: sharded_lookup(wl, i, axis, vocab),
                    mesh=mesh, in_specs=(P(axis, None), P()),
                    out_specs=P(), check_rep=False)
                return body(w, flat_ids)
            from ..core.compile_cache import setup_persistent_cache
            setup_persistent_cache()
            self._lookup_fn = jax.jit(fn)
        out = self._lookup_fn(self.weight, ids.reshape(-1))
        return out.reshape(shape + (self.dim,))

    # -- gradient push --------------------------------------------------
    def sgd_push(self, rows, vals, lr, dp_axis=None, comm_dtype=None):
        """Rows-only SGD over the shards. With ``dp_axis`` the COO pair
        is per-replica: replicas exchange entries via the quantized
        sparse push (int8 rows + f32 scales at ``comm_dtype='int8'``)
        and every shard applies the global gradient — duplicate rows
        across replicas sum in the scatter-add, which is the gradient
        reduction."""
        comm = qc.resolve_comm_dtype(comm_dtype)
        key = (dp_axis, comm)
        fn = self._push_fns.get(key)
        if fn is None:
            mesh, axis, vocab = self.mesh, self.axis, self.vocab

            def body(wl, r, v, step_lr):
                if dp_axis is not None:
                    r, v = qc.sparse_allgather(r, v, dp_axis, comm)

                def apply(w_shard, local_rows, vv):
                    return w_shard.at[local_rows].add(
                        -step_lr.astype(w_shard.dtype)
                        * vv.astype(w_shard.dtype), mode='drop')
                return shard_owned_apply(wl, r, v, axis, vocab, apply)

            in_specs = (P(axis, None),
                        P(dp_axis) if dp_axis else P(),
                        P(dp_axis, None) if dp_axis else P(),
                        P())
            fn = jax.jit(compat.shard_map(
                body, mesh=mesh, in_specs=in_specs,
                out_specs=P(axis, None), check_rep=False))
            self._push_fns[key] = fn
        n_dp = _axis_size_of(self.mesh, dp_axis) if dp_axis else 1
        qc.record_sparse_collective(
            'sharded_push', int(np.shape(rows)[0]), self.dim, comm,
            n_dp, self.vocab * self.dim)
        self.weight = fn(self.weight, jnp.asarray(rows, jnp.int32),
                         jnp.asarray(vals), jnp.asarray(lr, jnp.float32))
        return self.weight

    # -- utilities ------------------------------------------------------
    def full_table(self):
        """Dense (vocab, dim) host copy (parity tests, export)."""
        rep = jax.device_put(self.weight, NamedSharding(self.mesh, P()))
        return np.asarray(rep)
