"""Pipeline-parallel schedules on the partitioner mesh ('pp' axis).

The reference's PipelineOptimizer splits the Program across devices and
streams batches through section workers
(ref: python/paddle/fluid/optimizer.py:PipelineOptimizer +
paddle/fluid/framework/pipeline_trainer.cc). The TPU formulation keeps
ONE SPMD program on the partitioner's owned mesh: every device holds its
own stage's parameters (stacked pytree, leading dim = n_stages, sharded
over ``'pp'`` via the ``('stage', 'pp')`` logical-axis rule), and a
lax.scan steps the schedule — each tick computes the local stage and
ppermutes activations to the neighbor over ICI.

Three schedules (``PP_SCHEDULES``):

- ``gpipe``     — all m microbatch forwards, then the backward;
  residuals for every microbatch are in flight at the peak.
- ``1f1b``      — one backward immediately after each forward wave;
  at most one wave of residuals is live. Same arithmetic, lower peak.
- ``interleaved`` — v virtual stage chunks per device in circular
  placement (device i holds stages i, p+i, 2p+i, …): v chained pipeline
  passes per microbatch, finer cut granularity at the same device count.

The schedule/microbatch knobs are strict-parse
(``PADDLE_TPU_PP_SCHEDULE`` ∈ PP_SCHEDULES,
``PADDLE_TPU_PP_MICROBATCHES`` a positive int; unknown values raise
listing the contract) and the env always wins over
``DistributedStrategy`` — the PR 8/9 knob-hygiene contract.

``paddle_tpu.parallel.pipeline`` is the retired predecessor: it now
delegates here behind a warn-once deprecation shim (the
``set_default_mesh`` pattern).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import compat

__all__ = ['PP_SCHEDULES', 'ENV_PP_SCHEDULE', 'ENV_PP_MICROBATCHES',
           'pp_schedule', 'pp_microbatches', 'gpipe', 'interleaved',
           'stack_stage_params', 'pipeline_stage_scan']

PP_SCHEDULES = ('gpipe', '1f1b', 'interleaved')
ENV_PP_SCHEDULE = 'PADDLE_TPU_PP_SCHEDULE'
ENV_PP_MICROBATCHES = 'PADDLE_TPU_PP_MICROBATCHES'


def pp_schedule(default=None):
    """The pipeline schedule, env-first: ``PADDLE_TPU_PP_SCHEDULE`` when
    set (strict parse — unknown names raise listing PP_SCHEDULES), else
    `default` (a ``DistributedStrategy``/marker value, may be None)."""
    raw = os.environ.get(ENV_PP_SCHEDULE)
    if raw is None or raw == '':
        if default is not None and default not in PP_SCHEDULES:
            raise ValueError(
                f'pipeline schedule: unknown schedule {default!r} '
                f"(supported: {', '.join(PP_SCHEDULES)})")
        return default
    if raw not in PP_SCHEDULES:
        raise ValueError(
            f'{ENV_PP_SCHEDULE}: unknown schedule {raw!r} '
            f"(supported: {', '.join(PP_SCHEDULES)})")
    return raw


def pp_microbatches(default=None):
    """Microbatch-count override: ``PADDLE_TPU_PP_MICROBATCHES`` when set
    (strict parse — a positive integer), else `default`."""
    raw = os.environ.get(ENV_PP_MICROBATCHES)
    if raw is None or raw == '':
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f'{ENV_PP_MICROBATCHES}: expected a positive integer '
            f'microbatch count, got {raw!r}')
    if v <= 0:
        raise ValueError(
            f'{ENV_PP_MICROBATCHES}: must be > 0, got {raw!r}')
    return v


def _default_mesh():
    from .partitioner import get_partitioner
    return get_partitioner().mesh


def stack_stage_params(per_stage_params):
    """[{name: arr} per stage] → {name: arr[n_stages, ...]} for sharding
    over 'pp' (all stages must be isomorphic — the transformer-block case)."""
    keys = per_stage_params[0].keys()
    return {k: jnp.stack([p[k] for p in per_stage_params]) for k in keys}


def pipeline_stage_scan(stage_fn, params, xm, n_micro, axis='pp', p=None):
    """One pipeline pass INSIDE an existing shard_map over `axis`:
    `params` is the local device's (already unstacked) stage parameters,
    `xm` the (n_micro, mb, ...) microbatched input replicated across the
    axis. Each tick computes the local stage and ppermutes the activation
    to the neighbor; returns the LAST stage's (n_micro, mb, ...) outputs
    psum-broadcast to every device. This is the schedule kernel both the
    legacy `gpipe` wrapper and SpmdTrainStep's pp composition run."""
    p = p if p is not None else lax.psum(1, axis)
    idx = lax.axis_index(axis)
    T = n_micro + p - 1
    fwd_perm = [(i, i + 1) for i in range(p - 1)]
    # activations are device-varying (each stage computes differently):
    # mark the zero init for shard_map's vma typing
    zero = compat.pcast(jnp.zeros_like(xm[0]), axis, to='varying')

    def step(carry, t):
        prev_y = carry
        recv = lax.ppermute(prev_y, axis, fwd_perm)
        mb = jnp.clip(t, 0, n_micro - 1)
        x_in = jnp.where(idx == 0, xm[mb], recv)
        active = (t >= idx) & (t - idx < n_micro)
        y = stage_fn(params, x_in)
        y = jnp.where(active, y, zero)
        return y, y

    _, ys = lax.scan(step, zero, jnp.arange(T))     # (T, mb, ...)
    # device p-1 finishes microbatch i at tick i + p - 1
    outs = ys[p - 1:p - 1 + n_micro] if p > 1 else ys[:n_micro]
    # only the last stage's values are real; broadcast them to all
    outs = jnp.where(idx == p - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis)


def gpipe(stage_fn, stacked_params, x_micro, mesh=None, axis='pp'):
    """Run `stage_fn(params, x) -> y` as a pipeline over the mesh.

    stacked_params: pytree with leading dim n_stages (sharded over `axis`).
    x_micro: (n_micro, mb, ...) microbatched input (replicated).
    Returns (n_micro, mb, ...) outputs of the LAST stage (replicated).
    Stage input/output shapes must match (uniform stages)."""
    mesh = mesh or _default_mesh()
    n_micro = x_micro.shape[0]
    p = mesh.shape[axis]                                # static stage count
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != p:
        raise ValueError(
            f"gpipe: {n_stages} stacked stages but mesh axis {axis!r} has "
            f"{p} devices — one stage per device is required")

    def body(params_s, xm):
        # params_s leaves: (1, ...) local stage slice → squeeze
        params = jax.tree_util.tree_map(lambda a: a[0], params_s)
        return pipeline_stage_scan(stage_fn, params, xm, n_micro,
                                   axis=axis, p=p)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    fn = compat.shard_map(body, mesh=mesh,
                          in_specs=(param_specs, P()), out_specs=P())
    return fn(stacked_params, x_micro)


def interleaved(stage_fn, stacked_params, x_micro, mesh=None, axis='pp'):
    """Interleaved (circular) placement: v virtual stage chunks per
    device. `stacked_params` leaves have leading dims ``(v, p, ...)`` —
    chunk ``[j, i]`` is the parameters of virtual stage ``j*p + i``, so
    device i holds stages i, p+i, …, (v−1)p+i. Each microbatch flows
    through v chained pipeline passes; the output of pass j re-enters the
    ring as the input of pass j+1. Stage input/output shapes must match
    across ALL v·p virtual stages."""
    mesh = mesh or _default_mesh()
    n_micro = x_micro.shape[0]
    p = mesh.shape[axis]
    leaf = jax.tree_util.tree_leaves(stacked_params)[0]
    if leaf.ndim < 2 or leaf.shape[1] != p:
        raise ValueError(
            f'interleaved: stacked params must have leading dims '
            f'(v, p={p}, ...); got {tuple(leaf.shape)} — reshape '
            f'(v*p, ...) stage stacks to (v, p, ...)')
    v = leaf.shape[0]

    def body(params_s, xm):
        # params_s leaves: (v, 1, ...) local chunk column → squeeze dim 1
        params_v = jax.tree_util.tree_map(lambda a: a[:, 0], params_s)
        y = xm
        for j in range(v):
            params_j = jax.tree_util.tree_map(lambda a: a[j], params_v)
            y = pipeline_stage_scan(stage_fn, params_j, y, n_micro,
                                    axis=axis, p=p)
        return y

    param_specs = jax.tree_util.tree_map(
        lambda _: P(None, axis), stacked_params)
    fn = compat.shard_map(body, mesh=mesh,
                          in_specs=(param_specs, P()), out_specs=P())
    return fn(stacked_params, x_micro)
