"""Device-mesh construction — the only module that builds ``Mesh`` objects.

Replaces the reference's NCCL communicator bootstrap
(/root/reference/paddle/fluid/operators/collective/c_comm_init_op.cc,
c_gen_nccl_id_op.cc): instead of exchanging NCCL unique ids over RPC, we
build a jax.sharding.Mesh over the ICI/DCN topology and XLA lowers the
collectives onto it. Every other module obtains meshes through the
Partitioner (partition/partitioner.py); direct ``Mesh(`` construction
outside ``partition/`` is a lint violation (tools/lint_codebase.py,
``mesh-construction``) — hand-rolled meshes are exactly the per-module
plumbing this subsystem retired.

Axes convention (SURVEY §2.8, rules.MESH_AXES): dp (data), fsdp
(sharded params), tp (tensor), pp (pipeline), sp (sequence).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np
import jax
from jax.sharding import Mesh

from .rules import parse_mesh_shape

__all__ = ['make_mesh', 'make_hybrid_mesh', 'mesh_from_env',
           'process_mesh', 'topology', 'ENV_MESH']

ENV_MESH = 'PADDLE_TPU_MESH'


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({'dp': 4, 'tp': 2}).
    Uses mesh_utils for ICI-aware device ordering when available; plain
    reshape otherwise (the CPU-mesh fallback tests run on)."""
    devices = devices if devices is not None else jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh {axes} needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices[:n])
    except Exception:
        dev_array = np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def make_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int],
                     devices=None) -> Mesh:
    """Multi-slice/pod mesh: `dcn_axes` span the data-center network
    (slices), `ici_axes` the in-slice interconnect. This is the TPU
    analogue of the reference's hierarchical allreduce
    (ref: incubate/fleet DistributedStrategy.use_hierarchical_allreduce +
    NCCL hierarchical comms): laying dp over DCN and tp/fsdp over ICI makes
    XLA emit the two-level collective automatically. Uses
    mesh_utils.create_hybrid_device_mesh when slice topology is available;
    otherwise (single slice / CPU test mesh) falls back to a flat
    ICI-ordered mesh with the same named axes."""
    devices = devices if devices is not None else jax.devices()
    overlap = set(dcn_axes) & set(ici_axes)
    if overlap:
        raise ValueError(
            f"axis names {sorted(overlap)} appear in both dcn_axes and "
            f"ici_axes")
    dcn_shape = tuple(dcn_axes.values())
    ici_shape = tuple(ici_axes.values())
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    n_dcn = int(np.prod(dcn_shape))
    n_ici = int(np.prod(ici_shape))
    if n_dcn * n_ici > len(devices):
        raise ValueError(
            f"hybrid mesh {dcn_axes}x{ici_axes} needs {n_dcn * n_ici} "
            f"devices, have {len(devices)}")
    by_slice: Dict[int, list] = {}
    for d in devices:
        by_slice.setdefault(getattr(d, 'slice_index', 0), []).append(d)
    if len(by_slice) > 1:
        # pick WHOLE slices (n_dcn of them × n_ici devices each) so the
        # dcn axes really span DCN — a flat device prefix could land
        # entirely inside one slice
        usable = [ds[:n_ici] for ds in by_slice.values()
                  if len(ds) >= n_ici]
        if len(usable) < n_dcn:
            raise ValueError(
                f"hybrid mesh needs {n_dcn} slices with ≥{n_ici} devices "
                f"each; have {[len(v) for v in by_slice.values()]}")
        chosen = [d for ds in usable[:n_dcn] for d in ds]
        # create_hybrid_device_mesh wants same-rank shapes and returns
        # their ELEMENTWISE product; padding with 1s yields exactly
        # dcn_shape + ici_shape in (dcn..., ici...) order
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_shape) + ici_shape,
            dcn_shape + (1,) * len(ici_shape), chosen)
        return Mesh(dev_array, names)
    # single slice / CPU test mesh: flat ICI-ordered mesh, same named axes
    return make_mesh({**dcn_axes, **ici_axes}, devices[:n_dcn * n_ici])


def mesh_from_env() -> Optional[Mesh]:
    """Mesh described by ``PADDLE_TPU_MESH`` (e.g. ``"dp=2,tp=4"``), or
    None when unset. Strict parse: unknown axis names / bad sizes raise
    ValueError naming the supported set."""
    spec = os.environ.get(ENV_MESH)
    if not spec:
        return None
    return make_mesh(parse_mesh_shape(spec, source=ENV_MESH))


_PROCESS_MESH: Optional[Mesh] = None


def process_mesh() -> Mesh:
    """One-device-per-process ('proc',) mesh for cross-process host
    collectives (dygraph DataParallel grad sync), built once: reuse keeps
    the jit cache warm, and picking each process's FIRST local device —
    grouped by process_index, never by raw device id order, which JAX
    does not guarantee to be process-contiguous — means every mesh row is
    owned by exactly the process whose shard it carries."""
    global _PROCESS_MESH
    if _PROCESS_MESH is None:
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        devs = [per_proc[i] for i in sorted(per_proc)]
        _PROCESS_MESH = Mesh(np.array(devs), ('proc',))
    return _PROCESS_MESH


def topology():
    """Slice/pod topology report (ref: fleet's role maker endpoints)."""
    devs = jax.devices()
    info = {
        'process_index': jax.process_index(),
        'process_count': jax.process_count(),
        'local_device_count': jax.local_device_count(),
        'device_count': len(devs),
        'platform': devs[0].platform if devs else 'none',
    }
    if hasattr(devs[0], 'coords'):
        info['coords'] = [tuple(d.coords) for d in devs]
    return info
