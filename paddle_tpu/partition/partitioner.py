"""The unified SPMD Partitioner: ONE owned device mesh, logical axis
rules, and a PartitionSpec answer for every tensor a Program touches.

Before this subsystem each ``parallel/`` module hand-rolled its own mesh
and sharding plumbing, so DP×TP×FSDP could not compose (ROADMAP item 1).
Now a single :class:`Partitioner` (the T5X pattern — SNIPPETS.md
[1]–[3]) owns:

- the **device mesh**, built once from a ``DistributedStrategy`` /
  ``PADDLE_TPU_MESH`` env topology (hybrid ICI×DCN through
  ``device_mesh.make_hybrid_mesh`` when a DCN shape is given; plain
  CPU-mesh fallback for tests);
- the **logical axis rules** (rules.AxisRules) mapping logical names
  (``batch``/``embed``/``mlp``/``heads``/``kv``/``fsdp``…) onto mesh
  axes through an ordered first-match table;
- **spec resolution** for every persistable and activation of a Program
  — zero tracing, driven by the PR 10 ``analysis/infer.py`` VarInfo
  shapes (propagation.py) — which the Executor consults when lowering
  and the resilience layer records per checkpoint.

The process-global instance is the successor of the old
``parallel.mesh`` module globals: ``get_partitioner()`` /
``configure()`` replace ``set_default_mesh`` (now a deprecated shim).
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Dict, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import device_mesh
from .rules import (AxisRules, largest_divisible_dim, parse_axis_rules,
                    parse_mesh_shape)

__all__ = ['Partitioner', 'get_partitioner', 'set_partitioner', 'configure',
           'reset_partitioner', 'mesh_scope', 'state_spec_fn',
           'spec_entries', 'entries_to_json', 'ENV_AXIS_RULES']

ENV_AXIS_RULES = 'PADDLE_TPU_AXIS_RULES'

# Megatron parameter-name markers (ref: the c_allreduce-after-row-matmul
# fleet model-parallel mode): up-projections / QKV shard their OUTPUT
# features (logical 'mlp'), down-projections their INPUT features.
COLUMN_PARALLEL_MARKERS = ('ffn1', 'q_proj', 'k_proj', 'v_proj', '.q.',
                           '.k.', '.v.')
ROW_PARALLEL_MARKERS = ('ffn2', 'out_proj', '.out.')


def spec_entries(spec) -> tuple:
    """PartitionSpec → plain tuple of entries (None | str | tuple) — the
    stampable/JSON-able form checks.py and checkpoints consume."""
    return tuple(tuple(e) if isinstance(e, (tuple, list)) else e
                 for e in tuple(spec))


def entries_to_json(entries):
    return [list(e) if isinstance(e, tuple) else e for e in entries]


class Partitioner:
    """Owns the device mesh and the logical-axis rule table; resolves a
    PartitionSpec / NamedSharding for any tensor by name, shape, or
    logical axes. Thread-unsafe by design (one per process, like the
    Executor's compile cache)."""

    def __init__(self, mesh: Optional[Mesh] = None, mesh_shape=None,
                 dcn_mesh_shape=None, axis_rules=None, devices=None,
                 use_cpu_jit=False):
        # mesh precedence: explicit Mesh > mesh_shape (+DCN hybrid) >
        # PADDLE_TPU_MESH env > unconfigured (None — single-device /
        # replicated semantics, what tests get by default)
        mesh_shape = parse_mesh_shape(mesh_shape)
        dcn_mesh_shape = parse_mesh_shape(dcn_mesh_shape,
                                          source='dcn_mesh_shape')
        if mesh is None and mesh_shape is not None:
            if dcn_mesh_shape:
                mesh = device_mesh.make_hybrid_mesh(
                    mesh_shape, dcn_mesh_shape, devices)
            else:
                mesh = device_mesh.make_mesh(mesh_shape, devices)
        if mesh is None:
            mesh = device_mesh.mesh_from_env()
        self._mesh = mesh
        env_rules = os.environ.get(ENV_AXIS_RULES)
        if env_rules:
            axis_rules = parse_axis_rules(env_rules, source=ENV_AXIS_RULES)
        self._rules = (axis_rules if isinstance(axis_rules, AxisRules)
                       else AxisRules(axis_rules))
        self._use_cpu_jit = bool(use_cpu_jit)

    # -- mesh ownership --------------------------------------------------

    @property
    def mesh(self) -> Optional[Mesh]:
        return self._mesh

    @property
    def rules(self) -> AxisRules:
        return self._rules

    def set_mesh(self, mesh: Optional[Mesh]):
        self._mesh = mesh

    def axis_sizes(self) -> Dict[str, int]:
        return dict(self._mesh.shape) if self._mesh is not None else {}

    def axis_size(self, axis) -> int:
        if self._mesh is None or axis is None:
            return 1
        sizes = self._mesh.shape
        if isinstance(axis, (tuple, list)):
            return int(np.prod([sizes.get(a, 1) for a in axis]))
        return int(sizes.get(axis, 1))

    def describe(self) -> str:
        if self._mesh is None:
            return 'Partitioner(mesh=None)'
        shape = ', '.join(f'{a}={s}' for a, s in self._mesh.shape.items())
        return f'Partitioner(mesh={{{shape}}}, rules={len(self._rules.rules)})'

    # -- logical resolution ----------------------------------------------

    def mesh_axes_for(self, logical, dim=None, taken=()):
        """Mesh axes (tuple) the logical axis resolves to in the owned
        mesh, or None (replicated / unconfigured)."""
        if self._mesh is None:
            return None
        return self._rules.resolve(logical, dict(self._mesh.shape),
                                   taken=taken, dim=dim)

    def resolve_spec(self, logical_axes, shape=None) -> PartitionSpec:
        """Logical spec (one logical name or None per dim) →
        PartitionSpec under the owned mesh + rules."""
        if self._mesh is None:
            return PartitionSpec()
        return self._rules.spec(logical_axes, dict(self._mesh.shape),
                                shape=shape)

    def sharding(self, spec) -> Optional[NamedSharding]:
        if self._mesh is None:
            return None
        if not isinstance(spec, PartitionSpec):
            spec = PartitionSpec(*spec)
        return NamedSharding(self._mesh, spec)

    # -- canonical specs -------------------------------------------------

    def data_axes(self) -> tuple:
        """Mesh axes the 'batch' logical axis shards over (the gradient
        sync axes), () when unconfigured."""
        return self.mesh_axes_for('batch') or ()

    def data_spec(self, batch_dim=None) -> PartitionSpec:
        axes = self.mesh_axes_for('batch', dim=batch_dim)
        if not axes:
            return PartitionSpec()
        return PartitionSpec(axes[0] if len(axes) == 1 else axes)

    def data_sharding(self, batch_dim=None) -> Optional[NamedSharding]:
        """Sharding for a batch tensor: leading dim over the data axes,
        rest replicated; None when unconfigured."""
        if self._mesh is None:
            return None
        spec = self.data_spec(batch_dim)
        if not tuple(spec):
            return None
        return NamedSharding(self._mesh, spec)

    def replicated(self) -> Optional[NamedSharding]:
        if self._mesh is None:
            return None
        return NamedSharding(self._mesh, PartitionSpec())

    def fsdp_spec(self, shape, axis=None) -> PartitionSpec:
        """ZeRO placement: the LARGEST dim divisible by the fsdp axis
        size shards, everything else replicates (parallel/fsdp.py
        semantics, now rule-table-driven)."""
        axes = ((axis,) if axis is not None
                else self.mesh_axes_for('fsdp'))
        if not axes or self._mesh is None \
                or axes[0] not in self._mesh.shape:
            return PartitionSpec()
        ax = axes[0]
        p = self._mesh.shape[ax]
        if p <= 1:
            return PartitionSpec()
        best = largest_divisible_dim(shape, p)
        if best is None:
            return PartitionSpec()
        entries = [None] * len(shape)
        entries[best] = ax
        return PartitionSpec(*entries)

    def param_spec(self, name, shape, fsdp_axis=None) -> PartitionSpec:
        """Spec for a parameter/optimizer-slot by name + shape: Megatron
        markers map 2-D projections onto the tensor axes (logical
        'embed'×'mlp'), anything else falls back to the fsdp rule (or
        replicated). Optimizer slots inherit their parameter's spec
        because slot names embed the parameter name."""
        name = name or ''
        if len(shape) == 2:
            tp = self.mesh_axes_for('mlp', dim=None)
            if tp:
                ax = tp[0]
                if any(m in name for m in COLUMN_PARALLEL_MARKERS) \
                        and _divides(shape[1], self.axis_size(ax)):
                    return PartitionSpec(None, ax)
                if any(m in name for m in ROW_PARALLEL_MARKERS) \
                        and _divides(shape[0], self.axis_size(ax)):
                    return PartitionSpec(ax, None)
        return self.fsdp_spec(shape, axis=fsdp_axis)

    def param_sharding(self, name, shape,
                       fsdp_axis=None) -> Optional[NamedSharding]:
        if self._mesh is None:
            return None
        return NamedSharding(self._mesh,
                             self.param_spec(name, shape,
                                             fsdp_axis=fsdp_axis))

    # -- program-level resolution (zero tracing) -------------------------

    def program_specs(self, program, include_activations=False,
                      fsdp_axis=None) -> Dict[str, tuple]:
        """Spec entries for every persistable (and, optionally, every
        activation via sharding propagation over the op registry) of a
        Program — shapes come from the declared VarInfos / the PR 10
        static inference engine, never from tracing."""
        from ..analysis.infer import declared_info
        out: Dict[str, tuple] = {}
        for v in program.list_vars():
            info = declared_info(v)
            shape = info.display_shape() or ()
            if v.persistable:
                spec = self.param_spec(v.name, tuple(shape),
                                       fsdp_axis=fsdp_axis)
            elif v.is_data:
                spec = self.data_spec(
                    shape[0] if shape and isinstance(shape[0], int)
                    and shape[0] > 0 else None)
            else:
                continue
            out[v.name] = spec_entries(spec)
        if include_activations:
            from .propagation import propagate_specs
            out = propagate_specs(program, self, seed=out)
        return out

    def stamp_program(self, program, include_activations=True,
                      fsdp_axis=None) -> Dict[str, tuple]:
        """Attach ``_partition_specs`` / ``_partition_mesh_axes`` to the
        program so analysis/checks.py runs the sharding-consistency
        diagnostics on it (and IR passes re-verify them per rewrite)."""
        specs = self.program_specs(program,
                                   include_activations=include_activations,
                                   fsdp_axis=fsdp_axis)
        program._partition_specs = specs
        program._partition_mesh_axes = self.axis_sizes()
        return specs

    # -- pjit-style lowering ---------------------------------------------

    def partition(self, fn, in_shardings=None, out_shardings=None,
                  static_argnums=(), donate_argnums=()):
        """pjit-style partitioned compile of ``fn`` under the owned mesh
        (SNIPPETS.md [1] ``pjit_with_cpu_fallback``): with
        ``use_cpu_jit`` (or no mesh) the sharding annotations drop and a
        plain ``jax.jit`` runs — the CPU test fallback. Donation
        interops with the PR 1 machinery (donate_argnums passes
        through)."""
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        cpu = jax.devices()[0].platform == 'cpu'
        if self._mesh is None or (cpu and self._use_cpu_jit):
            return jax.jit(fn, static_argnums=static_argnums,
                           donate_argnums=donate_argnums)
        to_shard = lambda s: (jax.tree_util.tree_map(
            lambda x: self.sharding(x) if isinstance(x, PartitionSpec)
            else x, s, is_leaf=lambda x: isinstance(x, PartitionSpec))
            if s is not None else None)
        kw = {}
        if in_shardings is not None:
            kw['in_shardings'] = to_shard(in_shardings)
        if out_shardings is not None:
            kw['out_shardings'] = to_shard(out_shardings)
        return jax.jit(fn, static_argnums=static_argnums,
                       donate_argnums=donate_argnums, **kw)

    def shard_map(self, body, in_specs, out_specs):
        """compat.shard_map over the owned mesh — the explicit-SPMD
        surface the functional train steps lower through."""
        if self._mesh is None:
            raise ValueError(
                'Partitioner.shard_map: no mesh configured (pass '
                'mesh_shape to configure()/fleet.init, or set '
                'PADDLE_TPU_MESH)')
        from ..core import compat
        return compat.shard_map(body, mesh=self._mesh, in_specs=in_specs,
                                out_specs=out_specs)

    def replica_put(self, value, axis):
        """Broadcast ``value`` to (axis_size, *shape) and place it
        sharded over ``axis`` — the divergent-replica layout local/geo
        SGD carry (one stacked row per device)."""
        import jax.numpy as jnp
        n = self.axis_size(axis)
        arr = jnp.asarray(value)
        spec = PartitionSpec(axis, *([None] * arr.ndim))
        return jax.device_put(jnp.broadcast_to(arr, (n,) + arr.shape),
                              NamedSharding(self._mesh, spec))

    # -- checkpoint manifest ---------------------------------------------

    def state_manifest(self, program=None, fsdp_axis=None) -> dict:
        """JSON-safe record of mesh topology + rules (+ per-persistable
        specs when a program is given) — written into every checkpoint
        manifest so a restore can re-shard state onto a DIFFERENT mesh
        (the prerequisite for sharded per-host save/load, ROADMAP 2)."""
        m = {'mesh_axes': self.axis_sizes(),
             'axis_rules': self._rules.to_json()}
        if program is not None:
            m['specs'] = {
                name: entries_to_json(entries)
                for name, entries in self.program_specs(
                    program, fsdp_axis=fsdp_axis).items()}
        return m


def _divides(dim, size):
    return isinstance(dim, int) and dim > 0 and size > 0 \
        and dim % size == 0


# ---------------------------------------------------------------------------
# the process-global instance (successor of parallel.mesh's module globals)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[Partitioner] = None


def get_partitioner() -> Partitioner:
    """The process partitioner; lazily built unconfigured (mesh from
    ``PADDLE_TPU_MESH`` when set, else None)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = Partitioner()
    return _GLOBAL


def set_partitioner(p: Optional[Partitioner]):
    global _GLOBAL
    _GLOBAL = p


def reset_partitioner():
    set_partitioner(None)


def configure(mesh=None, mesh_shape=None, dcn_mesh_shape=None,
              axis_rules=None, devices=None, use_cpu_jit=False
              ) -> Partitioner:
    """Build + install the process partitioner (fleet.init's mesh
    bring-up calls this). Strict parse on mesh_shape/axis_rules. The
    global instance is updated IN PLACE when one exists, so scoped
    overrides (mesh_scope) that captured it restore correctly."""
    global _GLOBAL
    p = Partitioner(mesh=mesh, mesh_shape=mesh_shape,
                    dcn_mesh_shape=dcn_mesh_shape, axis_rules=axis_rules,
                    devices=devices, use_cpu_jit=use_cpu_jit)
    if _GLOBAL is None:
        _GLOBAL = p
    else:
        _GLOBAL._mesh = p._mesh
        _GLOBAL._rules = p._rules
        _GLOBAL._use_cpu_jit = p._use_cpu_jit
    return _GLOBAL


@contextlib.contextmanager
def mesh_scope(mesh: Optional[Mesh]):
    """Temporarily swap the partitioner's owned mesh (the mesh_guard
    successor — tests and scoped bring-up use it)."""
    p = get_partitioner()
    old = p.mesh
    p.set_mesh(mesh)
    try:
        yield mesh
    finally:
        p.set_mesh(old)


def state_spec_fn(program):
    """(name, shape) → NamedSharding resolver for a program's persistable
    state, or None when the program is not partitioned / no mesh is
    configured. The Executor consults this once per (program, scope) when
    lowering (executor.py): ``_fsdp_axis``-stamped programs keep the
    legacy pure-fsdp placement bitwise; ``_partition_params`` programs
    get the full rule-table resolution (tp + fsdp composition)."""
    p = get_partitioner()
    mesh = p.mesh
    if mesh is None:
        return None
    fsdp_axis = getattr(program, '_fsdp_axis', None)
    partitioned = getattr(program, '_partition_params', False)
    if partitioned:
        return lambda name, shape: NamedSharding(
            mesh, p.param_spec(name, tuple(shape), fsdp_axis=fsdp_axis))
    if fsdp_axis is None or fsdp_axis not in mesh.shape:
        return None
    return lambda name, shape: NamedSharding(
        mesh, p.fsdp_spec(tuple(shape), axis=fsdp_axis))


_DEPRECATION_WARNED = set()


def warn_once(key, message):
    """One-per-process deprecation warning through log_helper (repo
    invariant: never print)."""
    if key in _DEPRECATION_WARNED:
        return
    _DEPRECATION_WARNED.add(key)
    from ..log_helper import get_logger
    get_logger(__name__, logging.WARNING).warning(message)
