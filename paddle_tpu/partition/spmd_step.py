"""Composed DP×TP×FSDP training step — the partitioner's pjit-style
lowering of a functional train loop onto ONE mesh.

This is the composition `DistributedStrategy` could never express before
(ROADMAP item 1): batch sharded over the data axes, Megatron-marked
parameters sharded over ``tp``, ZeRO parameters stored as 1/p tiles over
``fsdp`` (gathered just-in-time inside the step), and EVERY gradient
sync routed through the PR 9 quantized collectives
(``parallel/quant_collectives.py``) keyed by mesh axis — replicated
parameters' gradients additionally coalesce into
``PADDLE_TPU_ALLREDUCE_BUCKET_MB``-capped buckets (the PR 9 bucketing
semantics applied to the functional path).

``loss_fn(params, batch) -> scalar`` runs INSIDE the shard_map: it sees
the full (gathered) value of fsdp parameters, the LOCAL tile of
tp-sharded parameters (write the Megatron dataflow with
``lax.psum(..., tp_axis)``, or use parallel/tensor_parallel.py's
primitives), and the local batch shard; the loss must be the mean over
the local shard. Exact `comm_dtype='f32'` passthrough keeps every sync
a plain lax collective.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import compat
from ..parallel import quant_collectives as qc
from .partitioner import get_partitioner, spec_entries

__all__ = ['SpmdTrainStep']


def _flat_axes(entries):
    out = []
    for e in entries:
        if e is None:
            continue
        out.extend(e if isinstance(e, tuple) else (e,))
    return out


class SpmdTrainStep:
    """One jitted SGD step over the partitioner's mesh with composed
    data/tensor/fsdp parallelism and quantized, bucketed gradient sync.

        p = partition.configure(mesh_shape={'dp': 2, 'fsdp': 4})
        step = SpmdTrainStep(loss_fn, params, partitioner=p, lr=0.1)
        for batch in data:          # leading dim = GLOBAL batch
            loss = step(batch)
        final = step.materialize()
    """

    def __init__(self, loss_fn, params, partitioner=None, lr=0.1,
                 comm_dtype=None, bucket_mb=None, pipeline=None):
        p = partitioner or get_partitioner()
        mesh = p.mesh
        if mesh is None:
            raise ValueError(
                'SpmdTrainStep: partitioner has no mesh (configure() a '
                'mesh_shape or set PADDLE_TPU_MESH)')
        self._p = p
        self._comm = qc.resolve_comm_dtype(comm_dtype)
        data_axes = tuple(p.data_axes())
        fsdp_axes = p.mesh_axes_for('fsdp') or ()
        fsdp_ax = fsdp_axes[0] if fsdp_axes else None
        self._n_data = max(1, p.axis_size(data_axes))
        self._data_axes = data_axes

        # pipeline composition (docs/DISTRIBUTED.md): stage-stacked
        # params shard their leading dim over the 'stage' logical rule's
        # mesh axis ('pp'); the body runs the schedule over that axis and
        # the stage grads ride the existing per-tile dp sync
        stage_names = ()
        pp_ax = pp_size = pp_m = pp_sched = None
        stage_fn = tail_fn = x_fn = None
        if pipeline is not None:
            from .pipeline import (pipeline_stage_scan, pp_microbatches,
                                   pp_schedule)
            cfg = dict(pipeline)
            stage_fn = cfg['stage_fn']
            tail_fn = cfg['tail_fn']
            stage_names = tuple(cfg['stage_params'])
            x_fn = cfg.get('x_fn') or (
                lambda b: jax.tree_util.tree_leaves(b)[0])
            pp_axes = p.mesh_axes_for('stage') or ()
            pp_ax = pp_axes[0] if pp_axes else None
            if pp_ax is None or pp_ax not in mesh.shape:
                raise ValueError(
                    "SpmdTrainStep(pipeline=...): the 'stage' logical "
                    "rule resolves to no mesh axis — configure a mesh "
                    "with a 'pp' axis (e.g. mesh_shape={'dp':2,'pp':2})")
            pp_size = mesh.shape[pp_ax]
            pp_sched = pp_schedule(cfg.get('schedule')) or 'gpipe'
            if pp_sched == 'interleaved':
                raise NotImplementedError(
                    'SpmdTrainStep pipeline: interleaved placement is '
                    'the functional partition.pipeline.interleaved path '
                    '(v-chunk stacked params); use gpipe or 1f1b here')
            pp_m = pp_microbatches(cfg.get('num_microbatches')) or pp_size
            if pp_m % min(pp_m, pp_size):
                raise ValueError(
                    f'SpmdTrainStep pipeline: num_microbatches {pp_m} '
                    f'must be a multiple of the wave size '
                    f'{min(pp_m, pp_size)} (the pp axis span)')
        self._pp_schedule = pp_sched
        self._pp_microbatches = pp_m

        entries: Dict[str, tuple] = {}
        fsdp_dim: Dict[str, Optional[int]] = {}
        kinds: Dict[str, str] = {}
        arrays = {n: jnp.asarray(v) for n, v in params.items()}
        for n, v in arrays.items():
            if n in stage_names:
                if v.shape[0] != pp_size:
                    raise ValueError(
                        f'SpmdTrainStep pipeline: stage param {n!r} has '
                        f'{v.shape[0]} stacked stages but mesh axis '
                        f'{pp_ax!r} has {pp_size} devices')
                # stacked dim rides the 'stage' rule; the PER-STAGE dims
                # still resolve through param_spec, so Megatron-marked
                # stage weights tile over tp too (pp×tp composition) —
                # fsdp entries are dropped (stage_fn sees its stage's
                # full value; there is no gather inside the schedule)
                tail_e = spec_entries(p.param_spec(n, v.shape[1:]))
                tail_e = tail_e + (None,) * (v.ndim - 1 - len(tail_e))
                tail_e = tuple(
                    x if x is not None and fsdp_ax not in (
                        (x,) if isinstance(x, str) else tuple(x))
                    else None for x in tail_e)
                e = (pp_ax,) + tail_e
            else:
                e = spec_entries(p.param_spec(n, v.shape))
                e = e + (None,) * (v.ndim - len(e))
            axes = _flat_axes(e)
            if fsdp_ax is not None and fsdp_ax in axes:
                kinds[n] = 'fsdp'
                fsdp_dim[n] = next(i for i, x in enumerate(e)
                                   if x is not None
                                   and fsdp_ax in ((x,) if isinstance(
                                       x, str) else x))
            elif axes:
                kinds[n] = 'tp'                 # device-varying tile
            else:
                kinds[n] = 'replicated'
            entries[n] = e
        self._kinds = kinds

        # sharded storage: each param placed per its spec ONCE; step
        # outputs keep the sharding (donated in-place update)
        self._params = {
            n: jax.device_put(v, NamedSharding(mesh, P(*entries[n])))
            for n, v in arrays.items()}

        # replicated-gradient buckets (PR 9 size cap, f32 elements);
        # PADDLE_TPU_ALLREDUCE_BUCKET_MB=auto sizes the cap from THESE
        # grads' predicted bytes instead of the hand-set 32 MiB default
        from ..ir.bucket_allreduce import bucket_cap_bytes
        repl = [n for n in sorted(arrays) if kinds[n] == 'replicated']
        repl_grad_bytes = sum(int(arrays[n].size) * 4 for n in repl)
        cap = (int(float(bucket_mb) * (1 << 20)) if bucket_mb is not None
               else bucket_cap_bytes(grad_bytes=repl_grad_bytes))
        buckets, cur, cur_bytes = [], [], 0
        for n in repl:
            nbytes = int(arrays[n].size) * 4
            if cur and cur_bytes + nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(n)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
        self._buckets = buckets
        tp_names = [n for n in sorted(arrays) if kinds[n] == 'tp']
        fsdp_names = [n for n in sorted(arrays) if kinds[n] == 'fsdp']
        other_axes = tuple(a for a in mesh.axis_names
                           if a not in data_axes)
        all_axes = tuple(mesh.axis_names)
        n_data = self._n_data
        comm = self._comm
        shapes = {n: arrays[n].shape for n in arrays}

        # host-side telemetry plan: one record per collective dispatched
        # inside the jitted body, per step (docs/OBSERVABILITY.md)
        recs = []
        for names in buckets:
            elems = sum(int(np.prod(shapes[n]) or 1) for n in names)
            for ax in data_axes:
                recs.append((elems, p.axis_size(ax), 2))
        for n in tp_names:
            elems = int(np.prod(shapes[n]) or 1)
            for ax in data_axes:
                recs.append((elems, p.axis_size(ax), 2))
        for n in fsdp_names:
            elems = int(np.prod(shapes[n]) or 1)
            recs.append((elems, p.axis_size(fsdp_ax), 1))  # reduce-scatter
            for ax in data_axes:
                if ax != fsdp_ax:
                    recs.append((elems // p.axis_size(fsdp_ax),
                                 p.axis_size(ax), 2))
        self._sync_records = recs

        def sync_data(g, skip=()):
            for ax in data_axes:
                if ax not in skip:
                    g = qc.qallreduce_sum(g, ax, comm_dtype=comm)
            return g

        def pp_value_and_grad(full, batch):
            """Schedule-structured (loss, grads) over the pp axis: gpipe
            runs all pp_m microbatches through one pipeline pass and one
            backward; 1f1b runs one backward per wave of pp_size
            microbatches, so only a wave of residuals is resident."""
            def pipe_loss(pf, bslice, n_mb):
                sp = {k: pf[k][0] for k in stage_names}
                x = x_fn(bslice)
                if x.shape[0] % n_mb:
                    raise ValueError(
                        f'SpmdTrainStep pipeline: local batch '
                        f'{x.shape[0]} not divisible by microbatch '
                        f'count {n_mb}')
                xm = x.reshape((n_mb, x.shape[0] // n_mb) + x.shape[1:])
                ym = pipeline_stage_scan(stage_fn, sp, xm, n_mb,
                                         axis=pp_ax, p=pp_size)
                # the tail loss is seeded on every pp device, so the
                # cotangent crossing the psum-broadcast back into the
                # schedule arrives pp_size-fold; rescale the backward
                # (forward value untouched) so stage grads are exact
                s = 1.0 / pp_size
                ym = ym * s + lax.stop_gradient(ym * (1.0 - s))
                y = ym.reshape((ym.shape[0] * ym.shape[1],)
                               + ym.shape[2:])
                return tail_fn(pf, y, bslice)

            if pp_sched == 'gpipe':
                return jax.value_and_grad(
                    lambda pf: pipe_loss(pf, batch, pp_m))(full)
            wsz = min(pp_m, pp_size)                        # 1f1b
            nw = pp_m // wsz
            gacc = jax.tree_util.tree_map(jnp.zeros_like, full)
            lacc = jnp.zeros((), jnp.float32)
            for i in range(nw):
                bi = jax.tree_util.tree_map(
                    lambda a: a.reshape((nw, a.shape[0] // nw)
                                        + a.shape[1:])[i], batch)
                li, gi = jax.value_and_grad(
                    lambda pf: pipe_loss(pf, bi, wsz))(full)
                gacc = jax.tree_util.tree_map(jnp.add, gacc, gi)
                lacc = lacc + li
            scale = 1.0 / nw                # mean of equal wave means
            return lacc * scale, jax.tree_util.tree_map(
                lambda a: a * scale, gacc)

        def body(ptiles, batch):
            full = {}
            for n, v in ptiles.items():
                if kinds[n] == 'fsdp':
                    full[n] = lax.all_gather(v, fsdp_ax,
                                             axis=fsdp_dim[n], tiled=True)
                else:
                    full[n] = v
            if pp_sched is not None:
                loss, grads = pp_value_and_grad(full, batch)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(full, batch)
            new = {}
            for n in fsdp_names:
                d = fsdp_dim[n]
                g = qc.qreduce_scatter_sum(grads[n], fsdp_ax,
                                           comm_dtype=comm,
                                           scattered_dimension=d)
                g = sync_data(g, skip=(fsdp_ax,)) / n_data
                new[n] = ptiles[n] - lr * g
            for n in tp_names:
                g = sync_data(grads[n]) / n_data
                new[n] = ptiles[n] - lr * g
            for names in buckets:
                flat = jnp.concatenate(
                    [jnp.ravel(grads[n]).astype(jnp.float32)
                     for n in names]) if len(names) > 1 else \
                    jnp.ravel(grads[names[0]]).astype(jnp.float32)
                flat = sync_data(flat) / n_data
                for ax in other_axes:
                    # correct tp formulations produce identical grads for
                    # replicated params on every tp shard; the pmean is a
                    # value no-op that establishes replication for the
                    # out-spec typing
                    flat = lax.pmean(flat, ax)
                off = 0
                for n in names:
                    sz = int(np.prod(shapes[n]) or 1)
                    seg = flat[off:off + sz]
                    g = seg.reshape(shapes[n]).astype(ptiles[n].dtype)
                    new[n] = ptiles[n] - lr * g
                    off += sz
            return new, lax.pmean(loss, all_axes)

        pspec = {n: P(*entries[n]) for n in arrays}
        bspec = P(data_axes if len(data_axes) != 1 else data_axes[0]) \
            if data_axes else P()
        fn = compat.shard_map(body, mesh=mesh, in_specs=(pspec, bspec),
                              out_specs=(pspec, P()))
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        self._step = jax.jit(fn, donate_argnums=(0,))
        self._mesh = mesh

    # ------------------------------------------------------------------
    def __call__(self, batch):
        # batch may be one array or a pytree of batch-major arrays (the
        # pipeline tail reads labels from its slice); the single bspec
        # applies to every leaf via shard_map's spec-prefix semantics
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        b0 = jax.tree_util.tree_leaves(batch)[0]
        if self._n_data > 1 and b0.shape[0] % self._n_data:
            raise ValueError(
                f'SpmdTrainStep: global batch {b0.shape[0]} is not '
                f'divisible by the data-axis span {self._n_data} '
                f'({self._data_axes})')
        for elems, axis_size, phases in self._sync_records:
            qc.record_collective('spmd_step', elems, self._comm,
                                 axis_size, phases=phases)
        self._params, loss = self._step(self._params, batch)
        return loss

    @property
    def sync_calls_per_step(self):
        """Collectives dispatched per step (buckets + per-tile syncs) —
        the bucketing win is this being << the parameter count."""
        return len(self._sync_records)

    def sharded_params(self):
        """name → the live global (possibly sharded) jax arrays."""
        return dict(self._params)

    def materialize(self):
        """name → full host numpy values (gathers fsdp/tp tiles)."""
        return {n: np.asarray(v) for n, v in self._params.items()}

    def param_kind(self, name):
        return self._kinds[name]
