"""Composed DP×TP×FSDP training step — the partitioner's pjit-style
lowering of a functional train loop onto ONE mesh.

This is the composition `DistributedStrategy` could never express before
(ROADMAP item 1): batch sharded over the data axes, Megatron-marked
parameters sharded over ``tp``, ZeRO parameters stored as 1/p tiles over
``fsdp`` (gathered just-in-time inside the step), and EVERY gradient
sync routed through the PR 9 quantized collectives
(``parallel/quant_collectives.py``) keyed by mesh axis — replicated
parameters' gradients additionally coalesce into
``PADDLE_TPU_ALLREDUCE_BUCKET_MB``-capped buckets (the PR 9 bucketing
semantics applied to the functional path).

``loss_fn(params, batch) -> scalar`` runs INSIDE the shard_map: it sees
the full (gathered) value of fsdp parameters, the LOCAL tile of
tp-sharded parameters (write the Megatron dataflow with
``lax.psum(..., tp_axis)``, or use parallel/tensor_parallel.py's
primitives), and the local batch shard; the loss must be the mean over
the local shard. Exact `comm_dtype='f32'` passthrough keeps every sync
a plain lax collective.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import compat
from ..parallel import quant_collectives as qc
from .partitioner import get_partitioner, spec_entries

__all__ = ['SpmdTrainStep']


def _flat_axes(entries):
    out = []
    for e in entries:
        if e is None:
            continue
        out.extend(e if isinstance(e, tuple) else (e,))
    return out


class SpmdTrainStep:
    """One jitted SGD step over the partitioner's mesh with composed
    data/tensor/fsdp parallelism and quantized, bucketed gradient sync.

        p = partition.configure(mesh_shape={'dp': 2, 'fsdp': 4})
        step = SpmdTrainStep(loss_fn, params, partitioner=p, lr=0.1)
        for batch in data:          # leading dim = GLOBAL batch
            loss = step(batch)
        final = step.materialize()
    """

    def __init__(self, loss_fn, params, partitioner=None, lr=0.1,
                 comm_dtype=None, bucket_mb=None):
        p = partitioner or get_partitioner()
        mesh = p.mesh
        if mesh is None:
            raise ValueError(
                'SpmdTrainStep: partitioner has no mesh (configure() a '
                'mesh_shape or set PADDLE_TPU_MESH)')
        self._p = p
        self._comm = qc.resolve_comm_dtype(comm_dtype)
        data_axes = tuple(p.data_axes())
        fsdp_axes = p.mesh_axes_for('fsdp') or ()
        fsdp_ax = fsdp_axes[0] if fsdp_axes else None
        self._n_data = max(1, p.axis_size(data_axes))
        self._data_axes = data_axes

        entries: Dict[str, tuple] = {}
        fsdp_dim: Dict[str, Optional[int]] = {}
        kinds: Dict[str, str] = {}
        arrays = {n: jnp.asarray(v) for n, v in params.items()}
        for n, v in arrays.items():
            e = spec_entries(p.param_spec(n, v.shape))
            e = e + (None,) * (v.ndim - len(e))
            axes = _flat_axes(e)
            if fsdp_ax is not None and fsdp_ax in axes:
                kinds[n] = 'fsdp'
                fsdp_dim[n] = next(i for i, x in enumerate(e)
                                   if x is not None
                                   and fsdp_ax in ((x,) if isinstance(
                                       x, str) else x))
            elif axes:
                kinds[n] = 'tp'                 # device-varying tile
            else:
                kinds[n] = 'replicated'
            entries[n] = e
        self._kinds = kinds

        # sharded storage: each param placed per its spec ONCE; step
        # outputs keep the sharding (donated in-place update)
        self._params = {
            n: jax.device_put(v, NamedSharding(mesh, P(*entries[n])))
            for n, v in arrays.items()}

        # replicated-gradient buckets (PR 9 size cap, f32 elements);
        # PADDLE_TPU_ALLREDUCE_BUCKET_MB=auto sizes the cap from THESE
        # grads' predicted bytes instead of the hand-set 32 MiB default
        from ..ir.bucket_allreduce import bucket_cap_bytes
        repl = [n for n in sorted(arrays) if kinds[n] == 'replicated']
        repl_grad_bytes = sum(int(arrays[n].size) * 4 for n in repl)
        cap = (int(float(bucket_mb) * (1 << 20)) if bucket_mb is not None
               else bucket_cap_bytes(grad_bytes=repl_grad_bytes))
        buckets, cur, cur_bytes = [], [], 0
        for n in repl:
            nbytes = int(arrays[n].size) * 4
            if cur and cur_bytes + nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(n)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
        self._buckets = buckets
        tp_names = [n for n in sorted(arrays) if kinds[n] == 'tp']
        fsdp_names = [n for n in sorted(arrays) if kinds[n] == 'fsdp']
        other_axes = tuple(a for a in mesh.axis_names
                           if a not in data_axes)
        all_axes = tuple(mesh.axis_names)
        n_data = self._n_data
        comm = self._comm
        shapes = {n: arrays[n].shape for n in arrays}

        # host-side telemetry plan: one record per collective dispatched
        # inside the jitted body, per step (docs/OBSERVABILITY.md)
        recs = []
        for names in buckets:
            elems = sum(int(np.prod(shapes[n]) or 1) for n in names)
            for ax in data_axes:
                recs.append((elems, p.axis_size(ax), 2))
        for n in tp_names:
            elems = int(np.prod(shapes[n]) or 1)
            for ax in data_axes:
                recs.append((elems, p.axis_size(ax), 2))
        for n in fsdp_names:
            elems = int(np.prod(shapes[n]) or 1)
            recs.append((elems, p.axis_size(fsdp_ax), 1))  # reduce-scatter
            for ax in data_axes:
                if ax != fsdp_ax:
                    recs.append((elems // p.axis_size(fsdp_ax),
                                 p.axis_size(ax), 2))
        self._sync_records = recs

        def sync_data(g, skip=()):
            for ax in data_axes:
                if ax not in skip:
                    g = qc.qallreduce_sum(g, ax, comm_dtype=comm)
            return g

        def body(ptiles, batch):
            full = {}
            for n, v in ptiles.items():
                if kinds[n] == 'fsdp':
                    full[n] = lax.all_gather(v, fsdp_ax,
                                             axis=fsdp_dim[n], tiled=True)
                else:
                    full[n] = v
            loss, grads = jax.value_and_grad(loss_fn)(full, batch)
            new = {}
            for n in fsdp_names:
                d = fsdp_dim[n]
                g = qc.qreduce_scatter_sum(grads[n], fsdp_ax,
                                           comm_dtype=comm,
                                           scattered_dimension=d)
                g = sync_data(g, skip=(fsdp_ax,)) / n_data
                new[n] = ptiles[n] - lr * g
            for n in tp_names:
                g = sync_data(grads[n]) / n_data
                new[n] = ptiles[n] - lr * g
            for names in buckets:
                flat = jnp.concatenate(
                    [jnp.ravel(grads[n]).astype(jnp.float32)
                     for n in names]) if len(names) > 1 else \
                    jnp.ravel(grads[names[0]]).astype(jnp.float32)
                flat = sync_data(flat) / n_data
                for ax in other_axes:
                    # correct tp formulations produce identical grads for
                    # replicated params on every tp shard; the pmean is a
                    # value no-op that establishes replication for the
                    # out-spec typing
                    flat = lax.pmean(flat, ax)
                off = 0
                for n in names:
                    sz = int(np.prod(shapes[n]) or 1)
                    seg = flat[off:off + sz]
                    g = seg.reshape(shapes[n]).astype(ptiles[n].dtype)
                    new[n] = ptiles[n] - lr * g
                    off += sz
            return new, lax.pmean(loss, all_axes)

        pspec = {n: P(*entries[n]) for n in arrays}
        bspec = P(data_axes if len(data_axes) != 1 else data_axes[0]) \
            if data_axes else P()
        fn = compat.shard_map(body, mesh=mesh, in_specs=(pspec, bspec),
                              out_specs=(pspec, P()))
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        self._step = jax.jit(fn, donate_argnums=(0,))
        self._mesh = mesh

    # ------------------------------------------------------------------
    def __call__(self, batch):
        batch = jnp.asarray(batch)
        if self._n_data > 1 and batch.shape[0] % self._n_data:
            raise ValueError(
                f'SpmdTrainStep: global batch {batch.shape[0]} is not '
                f'divisible by the data-axis span {self._n_data} '
                f'({self._data_axes})')
        for elems, axis_size, phases in self._sync_records:
            qc.record_collective('spmd_step', elems, self._comm,
                                 axis_size, phases=phases)
        self._params, loss = self._step(self._params, batch)
        return loss

    @property
    def sync_calls_per_step(self):
        """Collectives dispatched per step (buckets + per-tile syncs) —
        the bucketing win is this being << the parameter count."""
        return len(self._sync_records)

    def sharded_params(self):
        """name → the live global (possibly sharded) jax arrays."""
        return dict(self._params)

    def materialize(self):
        """name → full host numpy values (gathers fsdp/tp tiles)."""
        return {n: np.asarray(v) for n, v in self._params.items()}

    def param_kind(self, name):
        return self._kinds[name]
