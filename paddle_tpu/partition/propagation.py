"""Sharding propagation over a Program — zero tracing.

Given the partitioner's seed specs (persistables by the param rules,
data vars by the batch rule), walk the global block once and derive a
PartitionSpec entry tuple for every activation, using the PR 10
``analysis/infer.py`` shape engine for rank/shape facts and a small
per-op-category rule set mirroring how GSPMD actually propagates:

- elementwise / same-shape unary ops carry their input's spec;
- ``matmul``/``mul`` keep the row operand's batch/row sharding and take
  the column sharding from the weight;
- everything else (reshapes, reductions, concats, control flow)
  conservatively replicates — a replicated activation is always
  *correct*, just not maximally sharded, and the diagnostics in
  analysis/checks.py only ever act on positively-asserted specs.

The result is what gets stamped as ``program._partition_specs`` for the
sharding-consistency diagnostics and recorded into checkpoint manifests.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..analysis.infer import InferError, infer_op, seed_env

__all__ = ['propagate_specs', 'ELEMENTWISE_BINARY', 'SPEC_PRESERVING_UNARY']

ELEMENTWISE_BINARY = frozenset((
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min',
    'elementwise_pow', 'elementwise_mod', 'elementwise_floordiv',
    'fused_elemwise_add_activation'))

SPEC_PRESERVING_UNARY = frozenset((
    'relu', 'sigmoid', 'tanh', 'exp', 'sqrt', 'rsqrt', 'abs', 'ceil',
    'floor', 'cos', 'sin', 'round', 'reciprocal', 'log', 'square',
    'softplus', 'softsign', 'sign', 'erf', 'gelu', 'leaky_relu', 'relu6',
    'elu', 'selu', 'swish', 'scale', 'clip', 'assign', 'cast', 'dropout',
    'softmax', 'log_softmax', 'prelu', 'pow', 'l2_normalize',
    'fill_zeros_like'))

_MATMUL = frozenset(('matmul', 'mul'))


def _first(op, slot) -> Optional[str]:
    names = op.inputs.get(slot) or ()
    return names[0] if names else None


def _has_assignment(entries):
    return entries is not None and any(e is not None for e in entries)


def propagate_specs(program, partitioner, seed=None) -> Dict[str, tuple]:
    """``{var name: spec entries}`` for the program's global block:
    ``seed`` (typically the partitioner's persistable/data specs) plus
    propagated activation specs. Never raises on malformed programs —
    inference failures just stop propagation at that op (the verifier
    owns reporting them)."""
    specs: Dict[str, tuple] = dict(seed or {})
    env = seed_env(program)
    blk = program.global_block()

    def padded(name):
        """Spec entries padded with None to the var's known rank —
        PartitionSpec semantics leave trailing dims implicit, but the
        positional arithmetic below needs them explicit."""
        e = specs.get(name)
        if e is None:
            return None
        info = env.get(name)
        if info is not None and info.shape is not None \
                and len(e) < len(info.shape):
            e = tuple(e) + (None,) * (len(info.shape) - len(e))
        return tuple(e)

    for op in blk.ops:
        out = None
        if op.type in ELEMENTWISE_BINARY:
            xs = padded(_first(op, 'x'))
            ys = padded(_first(op, 'y'))
            out = xs if _has_assignment(xs) else ys
        elif op.type in SPEC_PRESERVING_UNARY:
            out = padded(_first(op, 'x'))
        elif op.type in _MATMUL:
            xs = padded(_first(op, 'x')) or ()
            ys = padded(_first(op, 'y')) or ()
            row = tuple(xs[:-1]) if len(xs) else ()
            col = tuple(ys[-1:]) if len(ys) else (None,)
            # a mesh axis may not repeat within one tensor: the
            # contraction result drops the column sharding on collision
            used = {a for e in row if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))}
            col = tuple(None if (e is not None and any(
                a in used for a in (e if isinstance(e, tuple) else (e,))))
                else e for e in col)
            if row or _has_assignment(col):
                out = row + col
        if not _has_assignment(out):
            out = None

        # shape engine keeps env current + guards the propagated rank
        infos = None
        try:
            infos = infer_op(op, env, blk)
        except InferError:
            infos = None
        out_names = op.output_names()
        ranks = {}
        if infos:
            for slot, res in infos.items():
                names = op.outputs.get(slot, [])
                vals = (list(res) if isinstance(res, (tuple, list))
                        else [res] * len(names))
                for n, info in zip(names, vals):
                    if info is not None:
                        env[n] = info
                        if info.shape is not None:
                            ranks[n] = len(info.shape)
        for n in out_names:
            if out is None:
                continue
            if n in ranks and ranks[n] != len(out):
                continue                      # rank changed: replicate
            specs[n] = tuple(out)
    return specs
