"""Unified SPMD partitioner: one mesh, logical axis rules, pjit-style
Program lowering (ROADMAP item 1; docs/PARTITIONER.md).

Public surface:

- :class:`Partitioner` + the process-global instance
  (:func:`get_partitioner` / :func:`configure` / :func:`mesh_scope`) —
  owns the device mesh and resolves PartitionSpecs through the ordered
  logical-axis rule table (rules.py);
- mesh builders (device_mesh.py) — the only sanctioned home of
  ``Mesh(`` construction (tools/lint_codebase.py enforces it);
- :func:`propagate_specs` — zero-tracing activation sharding
  propagation over a Program, driven by analysis/infer.py shapes;
- :class:`SpmdTrainStep` — the composed DP×TP×FSDP functional step with
  quantized + bucketed gradient sync (lazy import: it pulls in the
  collectives stack).
"""
from . import rules
from .rules import (AxisRules, DEFAULT_AXIS_RULES, LOGICAL_AXES, MESH_AXES,
                    parse_axis_rules, parse_mesh_shape)
from . import device_mesh
from .device_mesh import (make_mesh, make_hybrid_mesh, mesh_from_env,
                          process_mesh, topology)
from .partitioner import (Partitioner, configure, get_partitioner,
                          mesh_scope, reset_partitioner, set_partitioner,
                          spec_entries, state_spec_fn)

__all__ = ['Partitioner', 'AxisRules', 'DEFAULT_AXIS_RULES', 'LOGICAL_AXES',
           'MESH_AXES', 'parse_axis_rules', 'parse_mesh_shape', 'make_mesh',
           'make_hybrid_mesh', 'mesh_from_env', 'process_mesh', 'topology',
           'configure', 'get_partitioner', 'mesh_scope', 'reset_partitioner',
           'set_partitioner', 'spec_entries', 'state_spec_fn',
           'propagate_specs', 'SpmdTrainStep']


def __getattr__(name):
    # lazy: SpmdTrainStep/propagate_specs import the parallel/analysis
    # stacks, which import this package — deferring breaks the cycle
    if name == 'SpmdTrainStep':
        from .spmd_step import SpmdTrainStep
        return SpmdTrainStep
    if name == 'propagate_specs':
        from .propagation import propagate_specs
        return propagate_specs
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
