"""SIGTERM/SIGINT → graceful-stop flag for training loops.

Pod schedulers preempt with SIGTERM and a grace window; Ctrl-C is SIGINT.
Either way the correct move is the same: finish the in-flight step, write a
final checkpoint at the next step boundary, exit 0 — never die mid-write.
:class:`PreemptionGuard` converts the signal into a flag the
:class:`~paddle_tpu.resilience.manager.CheckpointManager` polls at step
boundaries; the handler itself does nothing slow or unsafe (signal context).

A second SIGINT while a stop is already pending restores the previous
handler and re-raises — an impatient Ctrl-C Ctrl-C still kills the process
the way users expect.
"""
from __future__ import annotations

import logging
import signal
import threading

from .. import observability as _obs
from ..log_helper import get_logger

__all__ = ['PreemptionGuard']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [resilience] %(message)s')


class PreemptionGuard:
    """Installable SIGTERM/SIGINT trap with a thread-safe `requested` flag.

    Installation only works from the main thread (a Python constraint);
    elsewhere the guard degrades to an inert flag that :meth:`request` can
    still set programmatically — so code using a CheckpointManager inside a
    worker thread keeps working, just without signal wiring."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._old = {}
        self._event = threading.Event()
        self.installed = False

    # -- lifecycle ------------------------------------------------------
    def install(self):
        try:
            for s in self._signals:
                self._old[s] = signal.signal(s, self._handler)
            self.installed = True
        except ValueError:
            # not the main thread: signal.signal refuses. Stay inert.
            self._old.clear()
            _logger.warning(
                'PreemptionGuard: not on the main thread, signal handlers '
                'not installed (preemption must be requested '
                'programmatically)')
        return self

    def uninstall(self):
        if self.installed:
            for s, old in self._old.items():
                try:
                    signal.signal(s, old)
                except (ValueError, TypeError):
                    pass
            self._old.clear()
            self.installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    # -- signal path ----------------------------------------------------
    def _handler(self, signum, frame):
        if self._event.is_set() and signum == signal.SIGINT:
            # second Ctrl-C: the user wants OUT, now
            self.uninstall()
            raise KeyboardInterrupt
        self._event.set()
        _obs.inc('preemption_requests',
                 help='SIGTERM/SIGINT preemption notices received')
        _logger.warning(
            'received signal %d: will checkpoint at the next step boundary '
            'and stop', signum)

    # -- flag -----------------------------------------------------------
    @property
    def requested(self):
        return self._event.is_set()

    def request(self):
        """Programmatic preemption (tests, cluster agents without signals)."""
        self._event.set()

    def clear(self):
        self._event.clear()
