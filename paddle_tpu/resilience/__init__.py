"""Production resilience: async checkpoints, deterministic resume,
preemption handling, fault injection, goodput accounting.

The design constraints (ROADMAP item 5, docs/RESILIENCE.md):

1. **Checkpointing must not stall the step loop.** State is captured as
   non-blocking FetchHandles at a step boundary (donation-protected through
   the executor's inflight window, or cloned on-device for the donating
   fused TrainStep); a background writer overlaps the D2H + serialization +
   atomic commit with subsequent compute. Stall per checkpoint < 1 step
   (``tools/bench_resilience.py``).
2. **A committed checkpoint is never torn.** Payload and manifest are each
   written temp-in-dir + fsync + ``os.replace``; the manifest (with payload
   size + CRC32) is the commit marker and is written last. Discovery
   (:func:`latest_checkpoint`) validates and SKIPS anything else.
3. **Resume is bitwise.** The snapshot covers params/slots/BN stats, the
   global step, the DataLoader cursor, and every RNG counter feeding the
   per-op ``_rng_salt`` streams — a resumed run replays the identical loss
   trajectory (tests/framework/test_crash_resume.py proves it through a
   literal ``kill -9``).
4. **Failures are a test fixture, not a hope.** ``PADDLE_TPU_FAULT_INJECT``
   kills/hangs the process, fails checkpoint IO, or poisons the observed
   loss on schedule; goodput (productive/wall time, lost work on restart)
   flows through the telemetry registry into ``tools/telemetry_report.py``.

PR 8 adds the **self-healing** layer on top (docs/RESILIENCE.md
"Self-healing"): :class:`TrainingSupervisor` detects non-finite and spiking
losses at step boundaries and applies the skip / rollback / escalate policy
ladder (``PADDLE_TPU_SUPERVISOR``), and the :mod:`watchdog` turns hangs —
wedged steps, stalled DataLoader producers, stuck checkpoint writers — into
stack-dumped, resumable aborts (``PADDLE_TPU_WATCHDOG``).
"""
from .fault import FaultInjector, get_injector, reset_injector  # noqa: F401
from .goodput import GoodputTracker  # noqa: F401
from .manager import CheckpointManager  # noqa: F401
from .preemption import PreemptionGuard  # noqa: F401
from .snapshot import (Checkpoint, latest_checkpoint,  # noqa: F401
                       list_checkpoints, read_checkpoint, write_checkpoint)
from .state import (capture_training_state,  # noqa: F401
                    restore_training_state, rng_state, restore_rng_state)
from .supervisor import (TrainingDiverged, TrainingSupervisor,  # noqa: F401
                         Verdict, parse_supervisor_spec)
from .watchdog import (WATCHDOG_EXIT_CODE, Watchdog,  # noqa: F401
                       active_watchdog)
from . import watchdog  # noqa: F401

__all__ = [
    'CheckpointManager', 'Checkpoint', 'FaultInjector', 'GoodputTracker',
    'PreemptionGuard', 'capture_training_state', 'restore_training_state',
    'rng_state', 'restore_rng_state', 'latest_checkpoint',
    'list_checkpoints', 'read_checkpoint', 'write_checkpoint',
    'get_injector', 'reset_injector',
    'TrainingSupervisor', 'TrainingDiverged', 'Verdict',
    'parse_supervisor_spec', 'Watchdog', 'active_watchdog', 'watchdog',
    'WATCHDOG_EXIT_CODE',
]
