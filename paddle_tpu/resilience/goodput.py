"""Goodput accounting: productive step time / wall time, across restarts.

Definitions (docs/RESILIENCE.md):

- **productive seconds** — wall time spent between step boundaries whose
  work SURVIVED (i.e. was either checkpointed or is in the live process).
  Work done after the last committed checkpoint in a run that then crashed
  is reclassified as **lost** on the next restart.
- **wall seconds** — everything since the job first started, across every
  incarnation, including checkpoint stalls, restart downtime, and replayed
  steps.
- **goodput** = productive / wall. A job that never checkpoints and never
  crashes has goodput ≈ 1; every crash subtracts the replay and the
  downtime.

The tracker itself is process-local; cross-restart continuity comes from
two places the :class:`~paddle_tpu.resilience.manager.CheckpointManager`
maintains: the checkpoint manifest (cumulative counters as of the last
COMMITTED step) and a tiny ``progress.json`` heartbeat (cumulative counters
as of the last boundary the crashed run actually reached). Their difference
is exactly the lost work.
"""
from __future__ import annotations

import time

from .. import observability as _obs

__all__ = ['GoodputTracker']


class GoodputTracker:
    def __init__(self):
        self._start_monotonic = time.monotonic()
        self._start_unix = time.time()
        # carried over from previous incarnations (restored checkpoints)
        self.prior_productive_s = 0.0
        self.prior_wall_s = 0.0
        self.prior_steps = 0
        # this incarnation
        self.productive_s = 0.0
        self.steps = 0
        # restart accounting
        self.restarts = 0
        self.lost_steps = 0
        self.lost_s = 0.0
        # elastic-resize accounting (docs/RESILIENCE.md "Elasticity"): a
        # SCHEDULED grow/shrink exit checkpoints synchronously at the
        # boundary, so its cost is pure downtime — booked here, in its
        # own bucket, never conflated with crash-restart loss
        self.resizes = 0
        self.resize_lost_s = 0.0

    # -- recording ------------------------------------------------------
    def record_step(self, seconds):
        self.productive_s += float(seconds)
        self.steps += 1

    def record_restart(self, ckpt_meta, progress):
        """Called once at restore time. `ckpt_meta` is the restored
        checkpoint's goodput block (counters at its commit); `progress` is
        the crashed run's last heartbeat (or None). Restores the cumulative
        counters and books the delta — everything the crashed run did past
        the checkpoint — as lost work, plus the crash→restart downtime."""
        self.restarts += 1
        restored = (ckpt_meta or {})
        self.prior_productive_s = float(restored.get('productive_s', 0.0))
        self.prior_wall_s = float(restored.get('wall_s', 0.0))
        self.prior_steps = int(restored.get('steps', 0))
        self.restarts += int(restored.get('restarts', 0))
        self.lost_steps += int(restored.get('lost_steps', 0))
        self.lost_s += float(restored.get('lost_s', 0.0))
        self.resizes += int(restored.get('resizes', 0))
        self.resize_lost_s += float(restored.get('resize_lost_s', 0.0))
        resize_exit = bool(progress.get('resize_exit')) if progress \
            else False
        if progress:
            lost_steps = max(0, int(progress.get('steps', 0))
                             - self.prior_steps)
            lost_s = max(0.0, float(progress.get('productive_s', 0.0))
                         - self.prior_productive_s)
            self.lost_steps += lost_steps
            self.lost_s += lost_s
            # downtime: crash (last heartbeat) → this process's start. Wall
            # time the job paid but nobody computed in.
            # the crashed run's FULL wall (not just up to the checkpoint),
            # plus the crash → restart downtime, is wall the job paid
            hb = progress.get('unix_time')
            downtime = max(0.0, self._start_unix - float(hb)) if hb else 0.0
            self.prior_wall_s = max(
                self.prior_wall_s,
                float(progress.get('wall_s', 0.0))) + downtime
            if resize_exit:
                # scheduled resize: the exit checkpointed synchronously
                # at the boundary (lost_steps should be 0 — any nonzero
                # delta still books as crash loss above); the downtime
                # between exit and relaunch is the resize's whole cost
                self.resizes += 1
                self.resize_lost_s += downtime
            if _obs._ENABLED:
                _obs.inc('restart_lost_steps', lost_steps,
                         help='steps of work lost to restarts (executed '
                              'after the restored checkpoint, replayed)')
                _obs.inc('restart_lost_seconds', lost_s,
                         help='productive seconds lost to restarts')
                if resize_exit:
                    _obs.inc('elastic_resizes_total',
                             help='scheduled fleet resizes completed '
                                  '(exit-for-resume at a step boundary, '
                                  'relaunched at the new size)')
        if _obs._ENABLED:
            _obs.inc('restarts_total',
                     help='training restarts that restored a checkpoint')

    # -- reading --------------------------------------------------------
    def wall_seconds(self):
        return self.prior_wall_s + (time.monotonic() - self._start_monotonic)

    def total_productive_seconds(self):
        return self.prior_productive_s + self.productive_s

    def total_steps(self):
        return self.prior_steps + self.steps

    def goodput(self):
        wall = self.wall_seconds()
        return self.total_productive_seconds() / wall if wall > 0 else 0.0

    def export_metrics(self):
        if _obs._ENABLED:
            _obs.set_gauge('goodput_ratio', self.goodput(),
                           help='productive step seconds / wall seconds '
                                '(cross-restart; docs/RESILIENCE.md)')
            _obs.set_gauge('goodput_productive_seconds',
                           self.total_productive_seconds(),
                           help='cumulative productive step seconds')
            _obs.set_gauge('goodput_wall_seconds', self.wall_seconds(),
                           help='cumulative wall seconds since job start')
            _obs.set_gauge('goodput_resize_lost_seconds',
                           self.resize_lost_s,
                           help='cumulative downtime from SCHEDULED fleet '
                                'resizes (grow/shrink exit -> relaunch) — '
                                'a separate bucket from crash-restart '
                                'loss')

    def meta(self):
        """Cumulative counters for the checkpoint manifest / heartbeat."""
        return {
            'productive_s': round(self.total_productive_seconds(), 6),
            'wall_s': round(self.wall_seconds(), 6),
            'steps': self.total_steps(),
            'restarts': self.restarts,
            'lost_steps': self.lost_steps,
            'lost_s': round(self.lost_s, 6),
            'resizes': self.resizes,
            'resize_lost_s': round(self.resize_lost_s, 6),
        }
