"""Hang watchdog: a monitor thread that turns a silent wedge into a
diagnosable, recoverable event.

A crashed step is cheap to survive (PR 7: kill -9 → bitwise resume); a HUNG
step is worse — the process keeps its slot, `/healthz`-style external checks
see a live pid, and the run burns wall clock producing nothing. The watchdog
covers the single-host hang modes the fleet retrospective (PAPERS.md,
arxiv 2606.15870) calls out: a wedged device step, a stalled DataLoader
producer, and a stuck checkpoint writer.

Mechanics: guarded activities hold a named **lease** (`arm`/`disarm`). Step
leases get a deadline of ``max(floor, factor × rolling-median duration)``
from that lease name's own history (the first arms, before any history —
typically the compiling cold step — use the larger ``cold`` deadline); IO
leases (checkpoint writer, DataLoader producer) use the fixed ``io``
deadline. A daemon monitor thread polls; when a lease overruns it:

1. dumps **all-thread stacks** via :mod:`faulthandler` to
   ``$PADDLE_TPU_METRICS_DIR/watchdog_stacks_<name>_<pid>.txt`` (plus a
   ``watchdog_breach.json`` record) so the wedge is diagnosable post-mortem;
2. increments ``watchdog_breaches{name=...}`` / ``watchdog_stack_dumps``
   through the telemetry registry;
3. with ``abort`` on (the default), exits the process with
   :data:`WATCHDOG_EXIT_CODE` — a supervised restart then rides PR 7's
   deterministic resume instead of hanging forever.

Enable process-wide with ``PADDLE_TPU_WATCHDOG=1`` (the Executor, TrainStep,
DataLoader producer, and checkpoint writer all self-guard when a process
watchdog is active; `TrainingSupervisor` additionally holds a
boundary-to-boundary ``train_loop`` lease), or programmatically via
:func:`enable`. Disabled, every guard site costs one module-attribute read.
"""
from __future__ import annotations

import faulthandler
import json
import logging
import os
import statistics
import threading
import time

from .. import observability as _obs
from ..log_helper import get_logger

__all__ = ['Watchdog', 'WatchdogLease', 'WATCHDOG_EXIT_CODE', 'enable',
           'disable', 'active_watchdog', 'arm_step', 'arm_io', 'disarm',
           'add_breach_hook', 'remove_breach_hook']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [watchdog] %(message)s')

ENV_ENABLE = 'PADDLE_TPU_WATCHDOG'
ENV_FLOOR = 'PADDLE_TPU_WATCHDOG_FLOOR_S'
ENV_FACTOR = 'PADDLE_TPU_WATCHDOG_FACTOR'
ENV_COLD = 'PADDLE_TPU_WATCHDOG_COLD_S'
ENV_IO = 'PADDLE_TPU_WATCHDOG_IO_S'
ENV_ABORT = 'PADDLE_TPU_WATCHDOG_ABORT'
ENV_POLL = 'PADDLE_TPU_WATCHDOG_POLL_S'

#: process exit code on an aborted breach — distinguishable from a crash
#: (nonzero, not a signal) so a supervising restarter can count hangs
#: separately from kills.
WATCHDOG_EXIT_CODE = 70

_HISTORY = 32          # rolling per-lease-name duration samples

# breach hooks: called with the breach record BEFORE any abort exit. The
# fleet runtime registers one that posts the cluster-wide poison flag
# (fleet_runtime/coordinator.py) so one wedged host turns into a
# whole-fleet exit-for-resume instead of p-1 peers hanging in a
# collective until their own deadlines. Hooks must be fast and must not
# raise (the process is already going down).
_BREACH_HOOKS = []


def add_breach_hook(fn):
    if fn not in _BREACH_HOOKS:
        _BREACH_HOOKS.append(fn)


def remove_breach_hook(fn):
    if fn in _BREACH_HOOKS:
        _BREACH_HOOKS.remove(fn)


def _env_float(name, default):
    raw = os.environ.get(name, '').strip()
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f'{name} must be a number, got {raw!r}')


class WatchdogLease:
    """One armed activity. Holding it means 'I should finish within
    deadline_s of armed_at'; `disarm` releases it and (for step leases)
    feeds the duration back into the rolling history."""

    __slots__ = ('name', 'armed_at', 'deadline_s', 'kind', 'breached',
                 '_owner')

    def __init__(self, owner, name, deadline_s, kind):
        self._owner = owner
        self.name = name
        self.armed_at = time.monotonic()
        self.deadline_s = float(deadline_s)
        self.kind = kind              # 'step' (history-fed) | 'io'
        self.breached = False


class Watchdog:
    """Deadline monitor for named activities (see module docstring).

    Parameters (env fallbacks in parentheses): `floor_s` — minimum deadline
    (``PADDLE_TPU_WATCHDOG_FLOOR_S``, 30), `factor` — deadline multiple of
    the rolling-median duration (``PADDLE_TPU_WATCHDOG_FACTOR``, 10),
    `cold_s` — deadline before any history exists, sized for a cold XLA
    compile (``PADDLE_TPU_WATCHDOG_COLD_S``, 600), `io_s` — fixed deadline
    for writer/producer leases (``PADDLE_TPU_WATCHDOG_IO_S``, 600),
    `abort` — exit the process on breach (``PADDLE_TPU_WATCHDOG_ABORT``, 1),
    `dump_dir` — stack-dump directory (``PADDLE_TPU_METRICS_DIR``, '.').
    """

    def __init__(self, floor_s=None, factor=None, cold_s=None, io_s=None,
                 abort=None, dump_dir=None, poll_s=None):
        self.floor_s = (float(floor_s) if floor_s is not None
                        else _env_float(ENV_FLOOR, 30.0))
        self.factor = (float(factor) if factor is not None
                       else _env_float(ENV_FACTOR, 10.0))
        self.cold_s = (float(cold_s) if cold_s is not None
                       else _env_float(ENV_COLD, 600.0))
        self.io_s = (float(io_s) if io_s is not None
                     else _env_float(ENV_IO, 600.0))
        self.abort = (bool(abort) if abort is not None
                      else os.environ.get(ENV_ABORT, '1') not in ('0', ''))
        self.dump_dir = dump_dir or os.environ.get(
            'PADDLE_TPU_METRICS_DIR') or '.'
        self.poll_s = (float(poll_s) if poll_s is not None
                       else _env_float(ENV_POLL,
                                       max(0.02, min(0.25,
                                                     self.floor_s / 5.0))))
        self._lock = threading.Lock()
        self._leases = {}              # name -> WatchdogLease
        self._history = {}             # name -> [durations]
        self._monitor = None
        self._stop = threading.Event()
        self.breaches = []             # breach records (non-abort mode)

    # ------------------------------------------------------------------
    # deadlines
    # ------------------------------------------------------------------
    def deadline_for(self, name):
        """Step-lease deadline: ``max(floor, factor × rolling median)`` of
        this lease name's own observed durations; `cold_s` before any
        history (first call usually carries the XLA compile)."""
        with self._lock:
            hist = self._history.get(name)
            if not hist:
                return max(self.floor_s, self.cold_s)
            return max(self.floor_s, self.factor * statistics.median(hist))

    def observe(self, name, seconds):
        """Feed one duration sample into `name`'s rolling history (leases
        disarmed with ``observe=True`` do this automatically)."""
        with self._lock:
            hist = self._history.setdefault(name, [])
            hist.append(float(seconds))
            if len(hist) > _HISTORY:
                del hist[0]

    # ------------------------------------------------------------------
    # leases
    # ------------------------------------------------------------------
    def arm(self, name, deadline_s=None, kind='step'):
        """Arm (or re-arm) the named lease; returns the
        :class:`WatchdogLease`. `deadline_s` defaults to
        :meth:`deadline_for` for step leases and `io_s` for IO leases."""
        if deadline_s is None:
            deadline_s = self.io_s if kind == 'io' else self.deadline_for(name)
        lease = WatchdogLease(self, name, deadline_s, kind)
        with self._lock:
            self._leases[name] = lease
        if _obs._ENABLED:
            _obs.set_gauge('watchdog_deadline_seconds', lease.deadline_s,
                           lease=name,
                           help='current per-lease watchdog deadline')
            _obs.set_gauge('watchdog_armed', 1, lease=name,
                           help='1 while the named activity holds a lease')
        self._ensure_monitor()
        return lease

    def disarm(self, lease, observe=True):
        """Release a lease; returns its held duration. Feeding the duration
        into the history (step leases) keeps the next deadline tracking the
        actual step time."""
        if lease is None:
            return 0.0
        dt = time.monotonic() - lease.armed_at
        with self._lock:
            if self._leases.get(lease.name) is lease:
                del self._leases[lease.name]
        if observe and lease.kind == 'step' and not lease.breached:
            self.observe(lease.name, dt)
        if _obs._ENABLED:
            _obs.set_gauge('watchdog_armed', 0, lease=lease.name,
                           help='1 while the named activity holds a lease')
        return dt

    class _Guard:
        __slots__ = ('_wd', '_name', '_deadline', '_kind', '_lease')

        def __init__(self, wd, name, deadline_s, kind):
            self._wd = wd
            self._name = name
            self._deadline = deadline_s
            self._kind = kind

        def __enter__(self):
            self._lease = self._wd.arm(self._name, self._deadline, self._kind)
            return self._lease

        def __exit__(self, *exc):
            self._wd.disarm(self._lease)

    def guard(self, name, deadline_s=None, kind='step'):
        """Context-manager form of arm/disarm."""
        return Watchdog._Guard(self, name, deadline_s, kind)

    # ------------------------------------------------------------------
    # monitor
    # ------------------------------------------------------------------
    def _ensure_monitor(self):
        if self._monitor is None or not self._monitor.is_alive():
            self._stop.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name='paddle_tpu_watchdog')
            self._monitor.start()

    def _monitor_loop(self):
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            with self._lock:
                expired = [l for l in self._leases.values()
                           if not l.breached
                           and now - l.armed_at > l.deadline_s]
            for lease in expired:
                self._breach(lease, now)

    def _breach(self, lease, now):
        lease.breached = True
        held = now - lease.armed_at
        record = {'name': lease.name, 'kind': lease.kind,
                  'held_seconds': round(held, 3),
                  'deadline_seconds': round(lease.deadline_s, 3),
                  'pid': os.getpid(), 'unix_time': time.time(),
                  'aborting': self.abort}
        _logger.error(
            'HANG: lease %r held %.1fs (deadline %.1fs) — dumping all-thread '
            'stacks%s', lease.name, held, lease.deadline_s,
            '; aborting' if self.abort else '')
        dump_path = self._dump_stacks(lease, record)
        record['stack_dump'] = dump_path
        self.breaches.append(record)
        for hook in list(_BREACH_HOOKS):
            try:
                hook(record)
            except BaseException as e:   # noqa: BLE001 — abort path
                _logger.error('watchdog breach hook failed: %s', e)
        if _obs._ENABLED:
            _obs.inc('watchdog_breaches', lease=lease.name,
                     help='watchdog deadline breaches by lease name')
            if dump_path:
                _obs.inc('watchdog_stack_dumps',
                         help='faulthandler all-thread stack dumps written '
                              'on watchdog breach')
        if self.abort:
            # hard exit (skips atexit/finally — the process is wedged; a
            # graceful unwind would hang on the same thing the watchdog
            # fired about). PR 7 resume makes this recoverable.
            os._exit(WATCHDOG_EXIT_CODE)

    def _dump_stacks(self, lease, record):
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f'watchdog_stacks_{lease.name}_{os.getpid()}.txt')
            with open(path, 'w') as f:
                f.write(f'# paddle_tpu watchdog breach: {json.dumps(record)}\n')
                faulthandler.dump_traceback(file=f, all_threads=True)
            with open(os.path.join(self.dump_dir, 'watchdog_breach.json'),
                      'w') as f:
                json.dump(record, f)
            return path
        except OSError as e:           # diagnostics must not mask the hang
            _logger.error('stack dump failed: %s', e)
            return None

    def stop(self):
        """Stop the monitor thread (tests / disable)."""
        self._stop.set()
        if self._monitor is not None and self._monitor.is_alive():
            self._monitor.join(2)


# ---------------------------------------------------------------------------
# process-wide watchdog: guard sites (executor, TrainStep, DataLoader
# producer, checkpoint writer) check `_ACTIVE` — one attribute read when off.
# ---------------------------------------------------------------------------

_ACTIVE = None


def enable(**kwargs):
    """Install a process-wide watchdog (the programmatic form of
    ``PADDLE_TPU_WATCHDOG=1``); returns it. Idempotent-replace: an existing
    watchdog is stopped first."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.stop()
    _ACTIVE = Watchdog(**kwargs)
    return _ACTIVE


def disable():
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.stop()
    _ACTIVE = None


def active_watchdog():
    """The process-wide watchdog, or None."""
    return _ACTIVE


def arm_step(name):
    """Guard-site helper: arm a history-deadline step lease on the process
    watchdog (None and free when no watchdog is active)."""
    w = _ACTIVE
    return w.arm(name, kind='step') if w is not None else None


def arm_io(name):
    """Guard-site helper: arm a fixed-IO-deadline lease."""
    w = _ACTIVE
    return w.arm(name, kind='io') if w is not None else None


def disarm(lease):
    if lease is not None:
        lease._owner.disarm(lease)


if os.environ.get(ENV_ENABLE, '0') not in ('0', ''):
    # env-enabled process: every guard site is armed with zero script changes
    enable()
