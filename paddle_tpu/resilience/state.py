"""Full-state capture/restore: everything a bitwise-identical resume needs.

A training step's output is a pure function of (persistable state, batch,
per-step PRNG key). The key for step N is ``fold_in(base_key(seed),
executor_step_counter)`` with per-op ``_rng_salt`` folds below it, and the
batch is a pure function of (reader definition, epoch, batch index). So the
complete resume state is:

- every persistable (params, optimizer slots, BN stats, lr vars) — the
  ``scope/<name>`` keys;
- the fused-TrainStep equivalents (``param/ buffer/ slot/ acc/`` keys +
  its step/accumulation counters) when training through
  :class:`~paddle_tpu.dygraph.jit.TrainStep`;
- the RNG plumbing: global seed, :class:`KeyGenerator` counter, the
  Executor's run counter (meta ``rng``), plus the host-side ``random`` /
  ``np.random`` generator states (meta ``python_rng``) for shuffling
  readers;
- the DataLoader cursor (meta ``loader``: epoch + batch index).

Capture is NON-BLOCKING: scope state is wrapped in
:class:`~paddle_tpu.core.fetch_handle.FetchHandle` s that are either
donation-protected through the executor's inflight window (zero-copy; the
executor keeps those buffers un-donated until the writer materializes them)
or cloned on-device first (`mode='copy'` — the TrainStep-with-donation
path, where per-name protection is impossible because the fused step
donates its whole pytree).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np
import jax.numpy as jnp

from ..core.random import default_generator

__all__ = ['capture_training_state', 'restore_training_state',
           'rng_state', 'restore_rng_state']

SCOPE_PREFIX = 'scope/'


# ---------------------------------------------------------------------------
# RNG
# ---------------------------------------------------------------------------

def rng_state(executor=None):
    from .. import framework
    st = {'generator': default_generator.state(),
          'global_seed': framework.get_global_seed()}
    if executor is not None:
        st['executor_steps'] = executor._step_counter
    return st


def restore_rng_state(st, executor=None):
    from .. import framework
    if 'generator' in st:
        default_generator.set_state(st['generator'])
    if 'global_seed' in st:
        framework.manual_seed(st['global_seed'])
    if executor is not None and 'executor_steps' in st:
        executor._step_counter = int(st['executor_steps'])


def _python_rng_state():
    version, internal, gauss = _pyrandom.getstate()
    alg, keys, pos, has_gauss, cached = np.random.get_state()
    return {'random': [version, list(internal), gauss],
            'numpy': {'alg': alg, 'keys': np.asarray(keys).tolist(),
                      'pos': int(pos), 'has_gauss': int(has_gauss),
                      'cached': float(cached)}}


def _restore_python_rng_state(st):
    if 'random' in st:
        version, internal, gauss = st['random']
        _pyrandom.setstate((version, tuple(internal), gauss))
    if 'numpy' in st:
        ns = st['numpy']
        np.random.set_state((ns['alg'],
                             np.asarray(ns['keys'], np.uint32),
                             ns['pos'], ns['has_gauss'], ns['cached']))


# ---------------------------------------------------------------------------
# capture / restore
# ---------------------------------------------------------------------------

def capture_training_state(executor=None, program=None, scope=None,
                           train_step=None, loader=None, extra=None,
                           mode=None):
    """→ (arrays, meta) for :meth:`CheckpointManager.save`.

    Pass the pieces the run actually uses: `executor`+`program` for the
    static spine (persistables captured zero-copy, donation-protected),
    `train_step` for the fused dygraph spine, `loader` for the DataLoader
    cursor. `mode='copy'` forces on-device clones instead of donation
    protection (e.g. capturing without an executor). `extra` merges
    caller-specific arrays in under their own keys."""
    arrays = {}
    meta = {'rng': rng_state(executor=executor),
            'python_rng': _python_rng_state()}

    if train_step is not None:
        ts_arrays, ts_meta = train_step.snapshot()
        arrays.update(ts_arrays)
        meta['train_step'] = ts_meta

    if program is not None:
        if executor is not None and mode != 'copy':
            handles = executor.snapshot_persistables(program, scope)
        else:
            from ..core.fetch_handle import FetchHandle
            from ..core.scope import global_scope
            scope_ = scope if scope is not None else global_scope()
            handles = {}
            for v in program.list_vars():
                if not v.persistable:
                    continue
                val = scope_.find(v.name)
                if val is None:
                    continue
                if hasattr(val, 'block_until_ready'):   # device array: clone
                    val = jnp.copy(val)
                handles[v.name] = FetchHandle(val, name=v.name)
        arrays.update({SCOPE_PREFIX + n: h for n, h in handles.items()})

    if program is not None:
        # partitioner-keyed spec manifest (docs/PARTITIONER.md): mesh
        # topology + rule table + per-persistable PartitionSpecs recorded
        # with every checkpoint, so a restore can re-shard state onto a
        # DIFFERENT mesh — the prerequisite for sharded per-host
        # save/load (ROADMAP item 2)
        from ..partition import get_partitioner
        part = get_partitioner()
        if part.mesh is not None:
            meta['partition'] = part.state_manifest(
                program, fsdp_axis=getattr(program, '_fsdp_axis', None))

    if loader is not None:
        meta['loader'] = loader.state_dict()
    if extra:
        arrays.update(extra)
    return arrays, meta


def restore_training_state(arrays, meta, executor=None, program=None,
                           scope=None, train_step=None, loader=None):
    """Inverse of :func:`capture_training_state`. Restore AFTER the startup
    program ran (the scope must hold every persistable's slot; restored
    values then overwrite the fresh initialization — and the RNG counters
    overwrite whatever startup consumed)."""
    meta = meta or {}
    if program is not None:
        from ..core.dtypes import to_jax_dtype
        from ..core.scope import global_scope
        scope_ = scope if scope is not None else global_scope()
        by_name = {v.name: v for v in program.list_vars() if v.persistable}
        for key, arr in arrays.items():
            if not key.startswith(SCOPE_PREFIX):
                continue
            name = key[len(SCOPE_PREFIX):]
            v = by_name.get(name)
            dtype = to_jax_dtype(v.dtype) if v is not None else None
            scope_.set(name, jnp.asarray(arr, dtype))
    if train_step is not None and 'train_step' in meta:
        train_step.set_state(arrays, meta['train_step'])
    if loader is not None and 'loader' in meta:
        loader.set_state_dict(meta['loader'])
    if 'rng' in meta:
        restore_rng_state(meta['rng'], executor=executor)
    if 'python_rng' in meta:
        _restore_python_rng_state(meta['python_rng'])
