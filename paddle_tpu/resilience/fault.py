"""Fault injection: the test harness for the resilience claims.

``PADDLE_TPU_FAULT_INJECT`` holds a comma-separated list of fault clauses;
each clause is ``<action>@<key>=<value>``:

- ``kill@step=N`` — SIGKILL this process (a literal ``kill -9``, no atexit,
  no flushing) at the step-N boundary. This is how the crash/resume tests
  create a mid-run hard failure without cooperating code paths.
- ``io_fail@times=N`` — the first N checkpoint IO attempts raise
  ``OSError`` (then IO succeeds); exercises the retry-with-backoff path
  deterministically.
- ``io_fail@prob=P`` — each checkpoint IO attempt fails independently with
  probability P, drawn from a generator seeded by ``PADDLE_TPU_FAULT_SEED``
  (default 0) so a given run is reproducible.
- ``nan@step=N`` / ``spike@step=N`` — the loss observed by the
  :class:`~paddle_tpu.resilience.supervisor.TrainingSupervisor` at step N
  is replaced by NaN / multiplied by 1e9 (once per process), driving the
  divergence-recovery paths (skip / rollback / escalate) deterministically.
- ``hang@step=N`` — the step-N boundary blocks (default: effectively
  forever; add ``hang@secs=S`` to bound it), simulating a wedged step so
  the watchdog's dump-and-abort path is subprocess-testable.
- ``slow@step=N`` — every step boundary from N onward sleeps
  ``slow@secs=S`` (default 0.25) — a *straggler*, not a wedge: the host
  keeps making progress but its step time inflates, which the fleet
  straggler monitor (docs/OBSERVABILITY.md) must flag within one window.
  Unlike ``hang`` this fires every step — real stragglers stay slow.

Unknown actions or keys raise ``ValueError`` listing the supported clauses
— a typo like ``kil@step=3`` must fail the run at injector construction,
not make a fault-injection test vacuously pass.

The hooks are called from the resilience subsystem only (step boundaries in
:meth:`CheckpointManager.end_of_step`, loss observation in the supervisor,
IO attempts in the background writer) — the training hot path never reads
the env. Injections are counted as ``fault_injections{site=...}`` through
the telemetry registry.
"""
from __future__ import annotations

import logging
import os
import random
import signal
import time

from .. import observability as _obs
from ..log_helper import get_logger

__all__ = ['FaultInjector', 'get_injector', 'reset_injector']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [resilience] %(message)s')

ENV_SPEC = 'PADDLE_TPU_FAULT_INJECT'
ENV_SEED = 'PADDLE_TPU_FAULT_SEED'


class FaultInjector:
    """Parsed fault plan. An empty/absent spec is a no-op injector whose
    hooks cost one attribute read."""

    SUPPORTED = ('kill@step=N, io_fail@times=N, io_fail@prob=P, nan@step=N, '
                 'spike@step=N, hang@step=N, hang@secs=S, slow@step=N, '
                 'slow@secs=S')

    def __init__(self, spec=None, seed=None):
        self._kill_step = None
        self._io_times = 0
        self._io_prob = 0.0
        self._nan_step = None
        self._spike_step = None
        self._hang_step = None
        self._hang_secs = None        # None = effectively forever
        self._slow_step = None
        self._slow_secs = 0.25
        self._fired = set()           # single-fire step clauses by action
        self._rng = random.Random(
            int(seed if seed is not None
                else os.environ.get(ENV_SEED, '0') or 0))
        self.active = False
        for clause in (spec or '').split(','):
            clause = clause.strip()
            if not clause:
                continue
            try:
                action, cond = clause.split('@', 1)
                key, value = cond.split('=', 1)
            except ValueError:
                raise ValueError(
                    f"{ENV_SPEC}: bad clause {clause!r} (want "
                    f"'<action>@<key>=<value>', e.g. 'kill@step=8'; "
                    f"supported: {self.SUPPORTED})")
            action, key = action.strip(), key.strip()
            if action == 'kill' and key == 'step':
                self._kill_step = int(value)
            elif action == 'io_fail' and key == 'times':
                self._io_times = int(value)
            elif action == 'io_fail' and key == 'prob':
                self._io_prob = float(value)
            elif action == 'nan' and key == 'step':
                self._nan_step = int(value)
            elif action == 'spike' and key == 'step':
                self._spike_step = int(value)
            elif action == 'hang' and key == 'step':
                self._hang_step = int(value)
            elif action == 'hang' and key == 'secs':
                self._hang_secs = float(value)
            elif action == 'slow' and key == 'step':
                self._slow_step = int(value)
            elif action == 'slow' and key == 'secs':
                self._slow_secs = float(value)
            else:
                raise ValueError(
                    f"{ENV_SPEC}: unknown clause {clause!r} (supported: "
                    f"{self.SUPPORTED})")
            self.active = True

    @classmethod
    def from_env(cls):
        return cls(os.environ.get(ENV_SPEC, ''))

    # -- hooks ----------------------------------------------------------
    def on_step(self, step):
        """Step-boundary hook: hard-kills the process when the configured
        step is reached. SIGKILL, not sys.exit — the point is that NOTHING
        below (checkpoint flush, atexit, finally blocks) gets to run.
        A configured ``hang`` blocks here instead (once), simulating a
        wedged step the watchdog must detect."""
        if self._kill_step is not None and step == self._kill_step:
            _obs.inc('fault_injections', site='kill_step',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            _logger.warning('fault injection: SIGKILL at step %d', step)
            os.kill(os.getpid(), signal.SIGKILL)
        if (self._hang_step is not None and step == self._hang_step
                and 'hang' not in self._fired):
            self._fired.add('hang')
            secs = self._hang_secs if self._hang_secs is not None else 86400.0
            _obs.inc('fault_injections', site='hang_step',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            _logger.warning('fault injection: hanging %.1fs at step %d',
                            secs, step)
            time.sleep(secs)
        if self._slow_step is not None and step >= self._slow_step:
            # straggler: EVERY boundary from here on pays the sleep —
            # on_step runs before end_of_step's record_step stamp, so the
            # inflation lands in this step's recorded duration
            _obs.inc('fault_injections', site='slow_step',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            time.sleep(self._slow_secs)

    def wants_loss(self, step):
        """Whether :meth:`on_loss` would alter the loss at `step` — lets the
        supervisor materialize a pending FetchHandle early only when an
        injection actually targets this step."""
        return (self._nan_step == step and 'nan' not in self._fired) or \
               (self._spike_step == step and 'spike' not in self._fired)

    def on_loss(self, step, value):
        """Loss-observation hook (called by the supervisor with the
        materialized host value): returns the possibly-poisoned loss.
        Single-fire — after a rollback the replayed window is clean, so a
        recovery cannot loop on its own injection."""
        if self._nan_step == step and 'nan' not in self._fired:
            self._fired.add('nan')
            _obs.inc('fault_injections', site='nan_step',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            _logger.warning('fault injection: NaN loss at step %d', step)
            return float('nan')
        if self._spike_step == step and 'spike' not in self._fired:
            self._fired.add('spike')
            _obs.inc('fault_injections', site='spike_step',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            _logger.warning('fault injection: loss spike at step %d', step)
            return float(value) * 1e9 + 1e9
        return value

    def on_io(self, what='checkpoint'):
        """Checkpoint-IO hook: raises OSError per the io_fail clauses."""
        if self._io_times > 0:
            self._io_times -= 1
            _obs.inc('fault_injections', site='io_fail',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            raise OSError(f'fault injection: {what} IO failed '
                          f'({self._io_times} more scripted failures)')
        if self._io_prob > 0.0 and self._rng.random() < self._io_prob:
            _obs.inc('fault_injections', site='io_fail',
                     help='injected faults by site (PADDLE_TPU_FAULT_INJECT)')
            raise OSError(f'fault injection: {what} IO failed '
                          f'(prob={self._io_prob})')


_injector = None


def get_injector():
    """Process-wide injector parsed once from the environment."""
    global _injector
    if _injector is None:
        _injector = FaultInjector.from_env()
    return _injector


def reset_injector():
    """Re-read the env on next use (tests that mutate PADDLE_TPU_FAULT_INJECT
    in-process)."""
    global _injector
    _injector = None
