"""Checkpoint on-disk format: torn-write-proof payload + manifest commit.

Layout inside a checkpoint directory::

    ckpt-00000042.npz    payload — every state array, flat string keys
    ckpt-00000042.json   manifest — written LAST via temp + os.replace

The manifest is the commit marker. A checkpoint exists iff its manifest
parses AND the payload it names matches the recorded byte size and CRC32 —
so a ``kill -9`` at ANY instant leaves either a fully committed checkpoint
or something :func:`list_checkpoints` skips (with a logged warning), never
a loadable torn file. Both files are themselves written to a temp name in
the target directory and atomically ``os.replace``d, so a crash mid-write
leaves only ``.tmp-*`` litter (cleaned opportunistically by the manager's
GC), never a half-written final name.

Arrays are stored as raw numpy; dtypes numpy cannot serialize natively
(bf16 & friends from ml_dtypes) are widened to float32 for the file — an
exact, information-preserving widening — and the original dtype is recorded
in the manifest so restore casts back bitwise.
"""
from __future__ import annotations

import io
import json
import logging
import os
import tempfile
import zlib

import numpy as np

from ..log_helper import get_logger

__all__ = ['Checkpoint', 'write_checkpoint', 'read_checkpoint',
           'list_checkpoints', 'latest_checkpoint', 'atomic_write_bytes',
           'FORMAT_VERSION']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [resilience] %(message)s')

FORMAT_VERSION = 1
_PREFIX = 'ckpt-'

# dtypes np.save round-trips without pickle; anything else is widened to
# float32 (exact for the 16-bit float family) and cast back at restore
_SAVEZ_KINDS = frozenset('fiub')


def _payload_name(step):
    return f'{_PREFIX}{int(step):08d}.npz'


def _manifest_name(step):
    return f'{_PREFIX}{int(step):08d}.json'


def atomic_write_bytes(path, data):
    """Write bytes to `path` via temp-in-same-dir + fsync + os.replace: a
    reader can never observe a partially written `path`."""
    directory = os.path.dirname(os.path.abspath(path)) or '.'
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + '.tmp-', dir=directory)
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class Checkpoint:
    """One committed checkpoint (a validated manifest + payload pair)."""

    __slots__ = ('step', 'directory', 'manifest')

    def __init__(self, step, directory, manifest):
        self.step = int(step)
        self.directory = directory
        self.manifest = manifest

    @property
    def payload_path(self):
        return os.path.join(self.directory, self.manifest['payload'])

    @property
    def sharded(self):
        """Fleet checkpoint: per-host shard files + a fleet manifest
        (fleet_runtime/sharded_ckpt.py) instead of one payload."""
        return bool(self.manifest.get('sharded'))

    @property
    def payload_paths(self):
        """Every payload file this checkpoint owns (GC deletes these
        after decommitting the manifest): the single payload, or one
        payload + one shard manifest per host for fleet checkpoints."""
        if not self.sharded:
            return [self.payload_path]
        out = []
        for sh in self.manifest.get('shards', []):
            out.append(os.path.join(self.directory, sh['payload']))
            out.append(os.path.join(self.directory, sh['manifest']))
        return out

    @property
    def manifest_path(self):
        return os.path.join(self.directory, _manifest_name(self.step))

    @property
    def meta(self):
        return self.manifest.get('meta', {})

    def __repr__(self):
        return (f"Checkpoint(step={self.step}, "
                f"bytes={self.manifest.get('payload_bytes')})")


def write_checkpoint(directory, step, arrays, meta=None, saved_unix_time=None):
    """Serialize `arrays` ({flat_key: ndarray-like}) + commit the manifest.
    Returns the :class:`Checkpoint`. `arrays` values must already be host
    numpy (the async writer materializes FetchHandles before calling this).
    """
    os.makedirs(directory, exist_ok=True)
    meta = dict(meta or {})
    narrow = {}
    stored = {}
    for key, value in arrays.items():
        arr = np.asarray(value)
        if arr.dtype.kind not in _SAVEZ_KINDS:
            narrow[key] = str(arr.dtype)
            arr = arr.astype(np.float32)
        stored[key] = arr
    if narrow:
        meta['_widened_dtypes'] = narrow

    buf = io.BytesIO()
    np.savez(buf, **stored)
    payload = buf.getvalue()

    payload_path = os.path.join(directory, _payload_name(step))
    atomic_write_bytes(payload_path, payload)

    manifest = {
        'format': FORMAT_VERSION,
        'step': int(step),
        'payload': _payload_name(step),
        'payload_bytes': len(payload),
        'payload_crc32': zlib.crc32(payload) & 0xFFFFFFFF,
        'keys': sorted(stored),
        'saved_unix_time': saved_unix_time,
        'meta': meta,
    }
    atomic_write_bytes(os.path.join(directory, _manifest_name(step)),
                       json.dumps(manifest, indent=1).encode())
    return Checkpoint(step, directory, manifest)


def _validate(directory, manifest):
    """→ error string, or None when the payload matches the manifest."""
    if manifest.get('sharded'):
        return _validate_sharded(directory, manifest)
    payload_path = os.path.join(directory, manifest.get('payload', ''))
    if not os.path.isfile(payload_path):
        return 'payload missing'
    size = os.path.getsize(payload_path)
    if size != manifest.get('payload_bytes'):
        return (f"payload is {size} bytes, manifest recorded "
                f"{manifest.get('payload_bytes')} (torn write?)")
    with open(payload_path, 'rb') as f:
        crc = zlib.crc32(f.read()) & 0xFFFFFFFF
    if crc != manifest.get('payload_crc32'):
        return 'payload CRC mismatch (corrupt write?)'
    return None


def _validate_sharded(directory, manifest):
    """Fleet-manifest validation: EVERY host shard it lists must exist
    with the recorded byte size and CRC32 — a missing or torn host shard
    (one host died mid-write, partial rsync, bit rot) makes the whole
    fleet checkpoint invisible to discovery, exactly like a torn
    single-host payload."""
    shards = manifest.get('shards')
    if not shards:
        return 'fleet manifest lists no shards'
    for sh in shards:
        spath = os.path.join(directory, sh.get('payload', ''))
        if not os.path.isfile(spath):
            return f"host shard {sh.get('payload')!r} missing"
        size = os.path.getsize(spath)
        if size != sh.get('payload_bytes'):
            return (f"host shard {sh.get('payload')!r} is {size} bytes, "
                    f"fleet manifest recorded {sh.get('payload_bytes')} "
                    f"(torn shard write?)")
        with open(spath, 'rb') as f:
            crc = zlib.crc32(f.read()) & 0xFFFFFFFF
        if crc != sh.get('payload_crc32'):
            return (f"host shard {sh.get('payload')!r} CRC mismatch "
                    f"(corrupt shard?)")
        if not os.path.isfile(os.path.join(directory,
                                           sh.get('manifest', ''))):
            return f"shard manifest {sh.get('manifest')!r} missing"
    return None


def list_checkpoints(directory):
    """All VALID checkpoints in `directory`, oldest first. Manifests that
    fail to parse, or whose payload is missing/truncated/corrupt, are
    skipped with a logged warning — a torn checkpoint must never crash (or
    win) discovery."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in sorted(os.listdir(directory)):
        if not (name.startswith(_PREFIX) and name.endswith('.json')):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                manifest = json.load(f)
            step = int(manifest['step'])
        except (OSError, ValueError, KeyError, TypeError) as e:
            _logger.warning('skipping unreadable checkpoint manifest %s: %s',
                            path, e)
            continue
        if name != _manifest_name(step):
            # per-host SHARD manifests (ckpt-N.shardKofP.json) are not
            # commit markers — only the fleet manifest is
            continue
        err = _validate(directory, manifest)
        if err:
            _logger.warning('skipping checkpoint step %d at %s: %s',
                            step, directory, err)
            continue
        out.append(Checkpoint(step, directory, manifest))
    out.sort(key=lambda c: c.step)
    return out


def latest_checkpoint(directory):
    """Newest valid checkpoint, or None."""
    ckpts = list_checkpoints(directory)
    return ckpts[-1] if ckpts else None


def read_checkpoint(ckpt):
    """Checkpoint → ({flat_key: np.ndarray}, meta dict). Widened dtypes are
    cast back to their recorded originals (bitwise — the widening was
    exact). Fleet checkpoints reassemble full values from the per-host
    shards (fleet_runtime/sharded_ckpt.py)."""
    if ckpt.sharded:
        from ..fleet_runtime.sharded_ckpt import read_sharded_checkpoint
        return read_sharded_checkpoint(ckpt)
    with np.load(ckpt.payload_path) as data:
        arrays = {k: data[k] for k in data.files}
    meta = dict(ckpt.meta)
    narrow = meta.pop('_widened_dtypes', None) or {}
    if narrow:
        import ml_dtypes  # noqa: F401 — registers bf16 etc. with numpy
        for key, dtype in narrow.items():
            if key in arrays:
                arrays[key] = arrays[key].astype(np.dtype(dtype))
    return arrays, meta
