"""Training supervisor: divergence detection + skip/rollback/escalate.

PR 7 made the training stack survive *death* (kill -9, SIGTERM, torn
writes); this module makes it survive *sickness*. At every step boundary
the supervisor judges the observed loss:

- **non-finite** — NaN/Inf loss (the check_nan_inf machinery's host-side
  scan, honored at FetchHandle materialization time under the PR 5 async
  window, so supervision does not re-serialize a pipelined loop);
- **spike** — a robust z-score over a rolling window: ``z = 0.6745 ×
  (loss − median) / MAD``; only an UPWARD excursion past ``zmax`` counts
  (loss collapsing toward zero is progress, not divergence).

An unhealthy step is quarantined (one JSONL record per event: step, reason,
loss, z-score, batch descriptor) and handled by the configured **policy
ladder** (``PADDLE_TPU_SUPERVISOR``):

- ``off`` — detect, count, and quarantine only (monitoring mode);
- ``skip`` — drop the poisoned update: the supervisor re-captures the state
  at every *healthy* boundary (zero-copy donation-protected FetchHandles on
  the Executor spine, on-device clones on the donating TrainStep spine) and
  writes that capture back, then training continues on the next batch;
- ``rollback`` — restore the last good checkpoint bitwise (PR 7's
  ``restore_training_state``) while the DataLoader cursor keeps moving
  FORWARD, so the poisoned data window is skipped, not replayed; after
  ``max_rollbacks`` rollbacks within ``escalate_window`` observed steps the
  supervisor raises :class:`TrainingDiverged`;
- ``escalate`` — raise :class:`TrainingDiverged` on first detection.

AMP dynamic-loss-scaling overflow skips
(:mod:`paddle_tpu.contrib.mixed_precision`) are recognized as **benign**:
the optimizer already dropped that update by design, so an overflow step
never triggers rollback.

Wiring: pass ``loss=`` to :meth:`CheckpointManager.end_of_step` (the
supervisor attaches itself to its manager), or construct
``TrainStep(..., supervisor=sup)``, or call :meth:`end_of_step` directly.
The supervisor also holds the watchdog's boundary-to-boundary
``train_loop`` lease when a watchdog is active (watchdog.py).

Spec grammar (strict — unknown policies/keys raise ``ValueError``)::

    PADDLE_TPU_SUPERVISOR=rollback,window=64,zmax=8,max_rollbacks=3
"""
from __future__ import annotations

import collections
import json
import logging
import math
import os
import statistics
import time

import numpy as np

from .. import observability as _obs
from ..core.fetch_handle import FetchHandle
from ..log_helper import get_logger
from . import watchdog as _wdg
from .fault import get_injector

__all__ = ['TrainingSupervisor', 'TrainingDiverged', 'Verdict',
           'parse_supervisor_spec']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [supervisor] %(message)s')

ENV_SPEC = 'PADDLE_TPU_SUPERVISOR'

POLICIES = ('off', 'skip', 'rollback', 'escalate')

#: tunables and their types/defaults; every key is overridable from the
#: env spec or constructor kwargs.
DEFAULTS = {
    'window': 64,            # rolling-loss window for the spike detector
    'zmax': 8.0,             # robust z-score threshold (upward only)
    'min_history': 8,        # samples required before spikes can fire
    'max_rollbacks': 3,      # N rollbacks ...
    'escalate_window': 200,  # ... within M observed steps → TrainingDiverged
    'max_skips': 16,         # consecutive skips → TrainingDiverged (0 = ∞)
}


class TrainingDiverged(RuntimeError):
    """Training health degraded past what the configured policy may absorb:
    escalate policy hit a detection, rollback exceeded its budget, or a
    recovery had nothing to restore."""


class Verdict(collections.namedtuple(
        'Verdict', ('action', 'reason', 'step', 'resume_step', 'loss',
                    'zscore'))):
    """Outcome of one supervised step boundary.

    `action`: ``ok`` (healthy, or evaluation deferred on a pending async
    handle), ``benign`` (AMP overflow skip), ``record`` (detected under
    policy=off), ``skip`` (update dropped), ``rollback`` (checkpoint
    restored — the loop must reset its step counter to `resume_step` and
    restart its DataLoader iteration). Escalations raise instead."""
    __slots__ = ()


def parse_supervisor_spec(spec):
    """``'rollback,window=64,zmax=8'`` → (policy | None, options). Strict:
    unknown policies or option keys raise ValueError naming what IS
    supported — a typo must not silently disable supervision."""
    spec = (spec or '').strip()
    policy, opts = None, {}
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        if '=' in part:
            key, value = (s.strip() for s in part.split('=', 1))
            if key not in DEFAULTS:
                raise ValueError(
                    f"{ENV_SPEC}: unknown option {key!r} (supported: "
                    f"{', '.join(sorted(DEFAULTS))})")
            try:
                opts[key] = type(DEFAULTS[key])(value)
            except ValueError:
                raise ValueError(
                    f'{ENV_SPEC}: bad value for {key}: {value!r}')
        else:
            if part not in POLICIES:
                raise ValueError(
                    f"{ENV_SPEC}: unknown policy {part!r} (supported: "
                    f"{', '.join(POLICIES)})")
            if policy is not None:
                raise ValueError(
                    f'{ENV_SPEC}: two policies given ({policy!r}, {part!r})')
            policy = part
    return policy, opts


class TrainingSupervisor:
    """Step-boundary health judge + recovery executor (module docstring).

    Pass the pieces the run actually uses: `manager` (required for
    rollback; the supervisor attaches itself so
    ``manager.end_of_step(..., loss=...)`` supervises transparently),
    `executor`+`program` (+`scope`) for the static spine, `train_step` for
    the fused dygraph spine, `loader` for quarantine descriptors and the
    skip-forward cursor, `amp_optimizer` for static-graph AMP benignity.
    `policy`/kwargs override ``PADDLE_TPU_SUPERVISOR``."""

    def __init__(self, policy=None, manager=None, executor=None, program=None,
                 scope=None, train_step=None, loader=None, watchdog=None,
                 amp_optimizer=None, quarantine_path=None, **options):
        env_policy, env_opts = parse_supervisor_spec(
            os.environ.get(ENV_SPEC, ''))
        cfg = dict(DEFAULTS)
        cfg.update(env_opts)
        for key, value in options.items():
            if key not in DEFAULTS:
                raise ValueError(
                    f"TrainingSupervisor: unknown option {key!r} (supported: "
                    f"{', '.join(sorted(DEFAULTS))})")
            cfg[key] = type(DEFAULTS[key])(value)
        policy = policy if policy is not None else env_policy
        if policy is None:
            policy = 'rollback' if manager is not None else 'skip'
        if policy not in POLICIES:
            raise ValueError(
                f"TrainingSupervisor: unknown policy {policy!r} "
                f"(supported: {', '.join(POLICIES)})")
        if policy == 'rollback' and manager is None:
            raise ValueError(
                "policy 'rollback' needs a CheckpointManager (pass "
                "manager=...)")
        self.policy = policy
        self.window = int(cfg['window'])
        self.zmax = float(cfg['zmax'])
        self.min_history = int(cfg['min_history'])
        self.max_rollbacks = int(cfg['max_rollbacks'])
        self.escalate_window = int(cfg['escalate_window'])
        self.max_skips = int(cfg['max_skips'])

        self._manager = manager
        self._executor = executor
        self._program = program
        self._scope = scope
        self._train_step = train_step
        self._loader = loader
        self._amp_optimizer = amp_optimizer
        self._fault = get_injector()
        self._watchdog = (watchdog if watchdog is not None
                          else _wdg.active_watchdog())
        self._lease = None

        self._history = collections.deque(maxlen=self.window)
        self._pending = collections.deque()   # (step, handle, batch_desc)
        self._steps_seen = 0                  # monotonic, survives rollbacks
        self._rollback_marks = collections.deque()
        self._consecutive_skips = 0
        self._capture_state = None            # ('scope'|'train_step', ...)
        self._amp_seen = self._amp_total()
        self._amp_static_seen = None
        self.last_verdict = None

        if quarantine_path is not None:
            self._quarantine_path = quarantine_path
        elif manager is not None:
            self._quarantine_path = os.path.join(manager.directory,
                                                 'quarantine.jsonl')
        elif _obs.metrics_dir():
            self._quarantine_path = os.path.join(_obs.metrics_dir(),
                                                 'quarantine.jsonl')
        else:
            self._quarantine_path = None

        if manager is not None:
            manager._supervisor = self
        _logger.info(
            'supervising: policy=%s window=%d zmax=%.1f quarantine=%s '
            'watchdog=%s', self.policy, self.window, self.zmax,
            self._quarantine_path or '<disabled>',
            'armed' if self._watchdog is not None else 'off')

    # ------------------------------------------------------------------
    # the step-boundary hook
    # ------------------------------------------------------------------
    def end_of_step(self, step, loss, batch_desc=None):
        """Judge one completed step; returns (and stores as `last_verdict`)
        a :class:`Verdict`. `loss` may be a host scalar/array, a jax array,
        or a :class:`FetchHandle` — pending handles are evaluated when
        their device computation finishes (up to K steps late under the
        async window) unless the policy needs a synchronous value.
        Raises :class:`TrainingDiverged` per the escalation rules."""
        self._steps_seen += 1
        self._rearm_watchdog()
        if (isinstance(loss, FetchHandle) and not loss.materialized
                and not loss.done and not self._needs_sync(step)):
            self._pending.append((step, loss, batch_desc))
            verdict = self._drain_pending(block=False)
            if verdict is None:
                verdict = Verdict('ok', 'deferred', step, None, None, None)
        else:
            self._pending.append((step, loss, batch_desc))
            verdict = self._drain_pending(block=True)
        self.last_verdict = verdict
        return verdict

    def flush(self):
        """Evaluate every still-pending async loss (blocking); the verdict
        for the worst of them. Call once after the loop drains."""
        verdict = self._drain_pending(block=True)
        self.last_verdict = verdict or self.last_verdict
        return self.last_verdict

    def close(self):
        """Release the watchdog lease (the loop is over, not hung)."""
        if self._watchdog is not None and self._lease is not None:
            self._watchdog.disarm(self._lease, observe=False)
            self._lease = None

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _needs_sync(self, step):
        # skip must act before the next update lands, and a loss-targeting
        # fault injection has to observe its own step
        return self.policy == 'skip' or (self._fault.active
                                         and self._fault.wants_loss(step))

    def _drain_pending(self, block):
        """Evaluate pending losses in FIFO order; → the most significant
        verdict (an unhealthy one wins over trailing 'ok's), or None when
        nothing was ready."""
        unhealthy, last = None, None
        while self._pending:
            step, loss, batch_desc = self._pending[0]
            if (not block and isinstance(loss, FetchHandle)
                    and not loss.materialized and not loss.done):
                break
            self._pending.popleft()
            value = self._materialize(loss)
            if self._fault.active:
                value = self._fault.on_loss(step, value)
            last = self._judge(step, value, batch_desc)
            if last.action != 'ok':
                unhealthy = last
                if last.action == 'rollback':
                    break              # later pending losses are now stale
        return unhealthy or last

    @staticmethod
    def _materialize(loss):
        """→ host float. A check_nan-armed FetchHandle raises
        FloatingPointError at materialization; supervision absorbs that
        into a non-finite observation instead of killing the loop."""
        try:
            arr = np.asarray(loss)
        except FloatingPointError:
            return float('nan')
        if arr.size == 0:
            return float('nan')
        return float(np.asarray(arr, np.float64).ravel()[0]) if arr.size == 1 \
            else float(np.asarray(arr, np.float64).mean())

    def _zscore(self, value):
        if len(self._history) < self.min_history:
            return None
        med = statistics.median(self._history)
        mad = statistics.median(abs(x - med) for x in self._history)
        scale = max(mad, 1e-12 * max(1.0, abs(med)))
        return 0.6745 * (value - med) / scale

    def _judge(self, step, value, batch_desc):
        amp_delta = self._amp_delta_dygraph()
        z = None
        if math.isfinite(value):
            z = self._zscore(value)
            detection = ('spike', z) if (z is not None and z > self.zmax) \
                else None
        else:
            detection = ('nonfinite', None)
        if detection is None:
            self._history.append(value)
            self._consecutive_skips = 0
            if self.policy == 'skip':
                self._capture()
            if _obs._ENABLED and z is not None:
                _obs.set_gauge('supervisor_last_zscore', z,
                               help='robust z-score of the most recent '
                                    'loss vs the rolling window')
            return Verdict('ok', None, step, None, value, z)

        kind, z = detection
        if amp_delta > 0 or self._amp_delta_static() > 0:
            # the AMP optimizer already dropped this update by design —
            # an overflow step must never look like divergence
            _obs.inc('supervisor_amp_benign_skips',
                     help='detections absorbed as benign AMP '
                          'overflow-skip steps (never rolled back)')
            _logger.info('step %d: %s absorbed as benign AMP overflow skip',
                         step, kind)
            return Verdict('benign', 'amp_overflow_skip', step, None, value,
                           z)

        _obs.inc('supervisor_detections', kind=kind,
                 help='unhealthy steps by detector '
                      '(nonfinite | spike)')
        _logger.warning('step %d: %s loss %r%s → policy=%s', step, kind,
                        value, f' (z={z:.1f})' if z is not None else '',
                        self.policy)

        if self.policy == 'off':
            self._quarantine(step, kind, value, z, batch_desc, 'record')
            return Verdict('record', kind, step, None, value, z)
        if self.policy == 'escalate':
            self._quarantine(step, kind, value, z, batch_desc, 'escalate')
            self._escalate(f'{kind} loss at step {step} (policy=escalate)')
        if self.policy == 'skip':
            self._quarantine(step, kind, value, z, batch_desc, 'skip')
            self._skip_update(step, kind)
            return Verdict('skip', kind, step, None, value, z)
        self._quarantine(step, kind, value, z, batch_desc, 'rollback')
        resume_step = self._rollback(step, kind)
        return Verdict('rollback', kind, step, resume_step, value, z)

    # ------------------------------------------------------------------
    # AMP benignity
    # ------------------------------------------------------------------
    @staticmethod
    def _amp_total():
        from ..contrib import mixed_precision as mp
        return mp.total_overflow_skips()

    def _amp_delta_dygraph(self):
        cur = self._amp_total()
        delta, self._amp_seen = cur - self._amp_seen, cur
        return delta

    def _amp_delta_static(self):
        """Static-graph AMP skips live in a scope counter var; read it only
        when a detection fired (a host read is a device sync)."""
        if self._amp_optimizer is None:
            return 0
        try:
            cur = self._amp_optimizer.overflow_steps(scope=self._scope)
        except Exception:
            return 0
        if self._amp_static_seen is None:
            self._amp_static_seen = 0
        delta, self._amp_static_seen = cur - self._amp_static_seen, cur
        return delta

    # ------------------------------------------------------------------
    # policies
    # ------------------------------------------------------------------
    def _capture(self):
        """Refresh the post-healthy-boundary state capture the skip policy
        restores. Executor spine: donation-protected FetchHandles over the
        live scope buffers (zero-copy; the executor keeps exactly those
        buffers un-donated while the capture is live). TrainStep spine:
        ``snapshot()`` — on-device clones under donation."""
        if self._train_step is not None:
            arrays, meta = self._train_step.snapshot()
            self._capture_state = ('train_step', arrays, meta)
        elif self._executor is not None and self._program is not None:
            handles = self._executor.snapshot_persistables(
                self._program, self._scope)
            self._capture_state = ('scope', handles, None)

    def _skip_update(self, step, kind):
        if self._capture_state is None:
            if self._manager is not None:
                _logger.warning('skip at step %d has no captured state yet; '
                                'falling back to rollback', step)
                self._rollback(step, kind)
                return
            self._escalate(
                f'{kind} loss at step {step} before any state was captured '
                f'(skip policy needs one healthy boundary first)')
        where, arrays, meta = self._capture_state
        if where == 'train_step':
            self._train_step.set_state(
                {k: h.device_array() for k, h in arrays.items()}, meta)
        else:
            from ..core.scope import global_scope
            scope = self._scope if self._scope is not None else global_scope()
            for name, handle in arrays.items():
                scope.set(name, handle.device_array())
        self._consecutive_skips += 1
        _obs.inc('supervisor_skipped_updates',
                 help='poisoned updates dropped by the skip policy')
        _logger.warning('step %d: update dropped (%s), state restored to '
                        'last healthy boundary', step, kind)
        if self.max_skips and self._consecutive_skips >= self.max_skips:
            self._escalate(
                f'{self._consecutive_skips} consecutive skipped updates '
                f'(max_skips={self.max_skips})')

    def _rollback(self, step, kind):
        if self._manager is None:
            self._escalate(f'{kind} loss at step {step} and no '
                           f'CheckpointManager to roll back with')
        try:
            # flush the in-flight async save: a checkpoint captured at the
            # previous (healthy) boundary may still be on the writer
            # thread, and it is strictly better to resume from it than
            # from one cadence earlier
            self._manager.wait()
        except OSError as e:
            _logger.warning('in-flight checkpoint failed during rollback '
                            '(%s); using the last committed one', e)
        ckpt = self._manager.latest()
        if ckpt is None:
            self._escalate(
                f'{kind} loss at step {step} before any checkpoint existed')
        cursor = (self._loader.state_dict()
                  if self._loader is not None else None)
        t0 = time.perf_counter()
        arrays, meta = self._manager.restore(ckpt)
        from .state import restore_training_state
        restore_training_state(arrays, meta, executor=self._executor,
                               program=self._program, scope=self._scope,
                               train_step=self._train_step,
                               loader=self._loader)
        if self._loader is not None and cursor is not None:
            # the poisoned data window is SKIPPED, not replayed: state and
            # RNG rewind to the checkpoint, the cursor keeps moving forward
            self._loader.set_state_dict(cursor)
        self._history.clear()
        self._pending.clear()
        resume_step = int(meta['step'])
        self.last_recovery_seconds = time.perf_counter() - t0
        _obs.inc('supervisor_rollbacks',
                 help='checkpoint restores triggered by divergence '
                      'detection')
        if _obs._ENABLED:
            _obs.observe('supervisor_recovery_seconds',
                         self.last_recovery_seconds,
                         help='checkpoint-restore wall time per rollback')
        _logger.warning(
            'step %d: rolled back to checkpoint step %d in %.3fs '
            '(poisoned window steps %d..%d skipped)', step, resume_step,
            self.last_recovery_seconds, resume_step + 1, step)
        self._rollback_marks.append(self._steps_seen)
        while self._rollback_marks and \
                self._steps_seen - self._rollback_marks[0] > \
                self.escalate_window:
            self._rollback_marks.popleft()
        if len(self._rollback_marks) >= self.max_rollbacks:
            self._escalate(
                f'{len(self._rollback_marks)} rollbacks within the last '
                f'{self.escalate_window} steps '
                f'(max_rollbacks={self.max_rollbacks}); state is restored '
                f'to checkpoint step {resume_step}')
        return resume_step

    def _escalate(self, message):
        _obs.inc('supervisor_escalations',
                 help='TrainingDiverged raises (policy=escalate, rollback '
                      'budget exhausted, or nothing to restore)')
        self.close()
        raise TrainingDiverged(message)

    # ------------------------------------------------------------------
    # quarantine + watchdog
    # ------------------------------------------------------------------
    def _quarantine(self, step, kind, value, z, batch_desc, action):
        if batch_desc is None and self._loader is not None:
            cursor = self._loader.state_dict()
            batch_desc = {'epoch': cursor['epoch'], 'batch': cursor['batch']}
        _obs.inc('supervisor_quarantined_batches',
                 help='batch descriptors written to quarantine.jsonl')
        if self._quarantine_path is None:
            return
        record = {'step': int(step), 'reason': kind, 'action': action,
                  'loss': float(value),
                  'zscore': None if z is None else round(float(z), 3),
                  'batch': batch_desc, 'unix_time': time.time()}
        try:
            with open(self._quarantine_path, 'a') as f:
                f.write(json.dumps(record) + '\n')
                f.flush()
        except OSError as e:
            _logger.warning('quarantine write failed: %s', e)

    def _rearm_watchdog(self):
        if self._watchdog is None:
            return
        if self._lease is not None:
            # disarm feeds the boundary-to-boundary duration into the
            # 'train_loop' history, so the deadline tracks real step time
            self._watchdog.disarm(self._lease)
        self._lease = self._watchdog.arm('train_loop', kind='step')

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
