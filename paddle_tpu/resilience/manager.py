"""CheckpointManager: async non-stalling saves, keep-N, preemption, goodput.

The train-loop contract (docs/RESILIENCE.md)::

    mgr = resilience.CheckpointManager('ckpts', every_n_steps=100)
    ck = mgr.latest()
    if ck is not None:
        arrays, meta = mgr.restore(ck)
        resilience.restore_training_state(arrays, meta, executor=exe,
                                          program=main, loader=loader)
        step = meta['step']
    for batch in loader():
        ...run one step...
        step += 1
        if mgr.end_of_step(step, lambda: resilience.capture_training_state(
                executor=exe, program=main, loader=loader)):
            break            # preempted: final checkpoint committed, exit 0
    mgr.close()

Why the step loop never stalls: ``end_of_step`` captures state as
NON-BLOCKING :class:`~paddle_tpu.core.fetch_handle.FetchHandle` s (the
capture helpers either register donation protection with the executor's
inflight window or clone on-device — both are dispatch-cost-only) and hands
them to a background writer thread, which performs the device→host
materialization, the ``np.savez``, the CRC, and the atomic
temp→``os.replace``→manifest commit while the main thread is already
dispatching the next steps. The only synchronous cost at a checkpoint
boundary is handle creation plus — if a previous checkpoint is somehow
still in flight — waiting for it; both are recorded as
``checkpoint_stall_seconds`` and asserted < 1 step by
``tools/bench_resilience.py``.
"""
from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time

import numpy as np

from .. import observability as _obs
from ..log_helper import get_logger
from . import snapshot as _snap
from . import watchdog as _wdg
from .fault import get_injector
from .goodput import GoodputTracker
from .preemption import PreemptionGuard

__all__ = ['CheckpointManager']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [resilience] %(message)s')

ENV_DIR = 'PADDLE_TPU_CKPT_DIR'
ENV_EVERY = 'PADDLE_TPU_CKPT_EVERY_N_STEPS'
ENV_KEEP = 'PADDLE_TPU_CKPT_KEEP'
ENV_RETRIES = 'PADDLE_TPU_CKPT_RETRIES'

PROGRESS_FILE = 'progress.json'
_TMP_MAX_AGE_S = 600.0


def _env_int(name, default):
    raw = os.environ.get(name, '').strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f'{name} must be an integer, got {raw!r}')


class _SaveJob:
    __slots__ = ('step', 'arrays', 'meta', 'done', 'error')

    def __init__(self, step, arrays, meta):
        self.step = step
        self.arrays = arrays        # {flat_key: FetchHandle | array}
        self.meta = meta
        self.done = threading.Event()
        self.error = None


class CheckpointManager:
    """Rolling async checkpointer with preemption + goodput accounting.

    Parameters (env fallbacks in parentheses): `directory`
    (``PADDLE_TPU_CKPT_DIR``), `every_n_steps` — periodic-save cadence for
    :meth:`end_of_step` (``PADDLE_TPU_CKPT_EVERY_N_STEPS``), `keep` — last-N
    retention (``PADDLE_TPU_CKPT_KEEP``, default 3), `retries` — attempts
    per checkpoint IO failure with exponential backoff
    (``PADDLE_TPU_CKPT_RETRIES``, default 3). ``async_save=False`` commits
    on the calling thread (simplest-possible mode, and the bench baseline
    the stall numbers are measured against)."""

    def __init__(self, directory=None, every_n_steps=None, keep=None,
                 async_save=True, retries=None, backoff_s=0.05,
                 install_signal_handlers=True):
        directory = directory or os.environ.get(ENV_DIR)
        if not directory:
            raise ValueError(
                f'CheckpointManager needs a directory (argument or {ENV_DIR})')
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.every_n_steps = (every_n_steps if every_n_steps is not None
                              else _env_int(ENV_EVERY, 0)) or None
        self.keep = max(1, keep if keep is not None else _env_int(ENV_KEEP, 3))
        self.retries = max(0, retries if retries is not None
                           else _env_int(ENV_RETRIES, 3))
        self.backoff_s = float(backoff_s)
        self.async_save = bool(async_save)
        self.goodput = GoodputTracker()
        self._fault = get_injector()
        self._preemption = PreemptionGuard()
        if install_signal_handlers:
            self._preemption.install()
        self._queue = queue.Queue(maxsize=1)
        self._inflight = None         # last submitted _SaveJob
        self._writer = None
        self._error = None            # first unrecovered write failure
        self._last_boundary = None
        self._last_saved_step = None
        self._closed = False
        # runtime-health integration (supervisor.py): a TrainingSupervisor
        # constructed with manager=self attaches here; end_of_step(...,
        # loss=) then judges the step before any save decision, and the
        # verdict is readable as `last_verdict`
        self._supervisor = None
        self.last_verdict = None
        # fleet integration (fleet_runtime/): when another host poisons
        # the fleet, end_of_step returns True (exit-for-resume) and the
        # observed record lands here so the loop can exit with
        # FLEET_EXIT_CODE instead of 0
        self.fleet_poisoned = None
        self._rank = None              # resolved lazily (post-bootstrap)
        # fleet telemetry (docs/OBSERVABILITY.md "Training fleet"): host 0
        # folds every host's published snapshot and runs the straggler
        # monitor; built lazily the first boundary the KV is configured
        self._straggler = None
        # elastic scheduled resize (elastic/schedule.py): armed from
        # PADDLE_TPU_ELASTIC_RESIZE. At the first due boundary
        # end_of_step commits a SYNCHRONOUS checkpoint, rank 0 writes
        # resize.json, and the call returns True with `resize_requested`
        # set — the loop exits through the exit-for-resume ladder and
        # the restarter relaunches at the new size
        from ..elastic.schedule import parse_resize_env
        self._resize_plan = parse_resize_env()
        self.resize_requested = None
        self._resize_exit = False

    # ------------------------------------------------------------------
    # fleet plumbing (fleet_runtime/)
    # ------------------------------------------------------------------
    def _rank_index(self):
        if self._rank is None:
            import jax
            self._rank = jax.process_index()
        return self._rank

    @staticmethod
    def _fleet_world():
        import jax
        return jax.process_count()

    def _sharded(self):
        from ..fleet_runtime.sharded_ckpt import sharded_save_enabled
        return sharded_save_enabled()

    def _sentinel(self):
        from ..fleet_runtime.coordinator import active_sentinel
        return active_sentinel()

    # ------------------------------------------------------------------
    # discovery / restore
    # ------------------------------------------------------------------
    def latest(self):
        """Newest VALID checkpoint (torn/corrupt ones are skipped with a
        logged warning), or None on a fresh directory."""
        return _snap.latest_checkpoint(self.directory)

    def all_checkpoints(self):
        return _snap.list_checkpoints(self.directory)

    def restore(self, ckpt=None):
        """→ (arrays, meta) from `ckpt` (default: latest). Books restart +
        lost-work accounting from the previous incarnation's heartbeat.
        Returns None when there is nothing to restore.

        Fleet restore contract (docs/RESILIENCE.md "Fleet"): on a
        multi-host fleet every host must restore the SAME checkpoint —
        the hosts first agree on the discovered step (a shared-FS race or
        a half-synced directory raises instead of silently diverging),
        then barrier so no host starts stepping against peers still
        loading; sharded checkpoints additionally reassemble full values
        from every host's validated shard and overlay this host's own
        local meta (RNG/loader cursor) from its shard manifest."""
        fleet = self._fleet_world() > 1
        ckpt = ckpt if ckpt is not None else self.latest()
        if fleet:
            from ..elastic.reshard import current_mesh_axes
            from ..fleet_runtime.bootstrap import (all_hosts_agree,
                                                   fleet_barrier)
            step = -1 if ckpt is None else int(ckpt.step)
            # the resize restore barrier: a (possibly resized) fleet must
            # agree on BOTH the step and the mesh it restores onto before
            # any host starts re-laying tiles — a half-updated launch
            # config (one host still at the old world size) fails here,
            # typed, instead of diverging inside the first collective
            if not all_hosts_agree({'restore_step': step,
                                    'mesh_axes': current_mesh_axes()},
                                   tag='ckpt_restore'):
                raise RuntimeError(
                    f'fleet restore: hosts disagree on the checkpoint '
                    f'step or the restoring mesh (this host found step '
                    f'{step}, mesh {current_mesh_axes()}); checkpoint '
                    f'directory {self.directory} is not consistently '
                    f'visible, or the fleet was relaunched with '
                    f'mismatched sizes')
            fleet_barrier(f'ckpt_restore_{step}')
        if ckpt is None:
            return None
        arrays, meta = _snap.read_checkpoint(ckpt)
        saved_part = meta.get('partition')
        if saved_part:
            # reshard-manifest check (elastic/reshard.py): the saved
            # mesh/specs must be re-layable onto THIS fleet's mesh —
            # divisibility validated up front, ReshardError instead of a
            # device_put shape error after minutes of bring-up
            from ..elastic.reshard import check_reshard
            info = check_reshard(
                saved_part,
                shapes={k: np.shape(v) for k, v in arrays.items()},
                step=ckpt.step)
            if info['resharded']:
                _logger.info(
                    'reshard-on-restore: checkpoint step %d saved on '
                    'mesh %s, re-laying onto %s', ckpt.step,
                    info['saved_axes'], info['current_axes'])
                if _obs._ENABLED:
                    _obs.inc('elastic_reshard_restores',
                             help='restores that re-laid checkpoint '
                                  'tiles onto a different mesh than '
                                  'they were saved under')
        host_meta = meta.get('host_meta')
        if host_meta:
            # this host's own RNG / loader cursor (falls back to host 0's
            # when the fleet SHRANK and this rank is new... which cannot
            # happen — rank < world — but a GROWN fleet's extra hosts do
            # take host 0's meta: same lockstep cursor, fresh host RNG)
            mine = host_meta.get(str(self._rank_index())) \
                or host_meta.get('0') or {}
            for key in ('rng', 'python_rng', 'loader'):
                if key in mine:
                    meta[key] = mine[key]
        self.goodput.record_restart(meta.get('goodput'),
                                    self._read_progress())
        self.goodput.export_metrics()
        self._last_saved_step = ckpt.step
        _logger.info('restored checkpoint step %d from %s (lost work: '
                     '%d step(s))', ckpt.step, self.directory,
                     self.goodput.lost_steps)
        return arrays, meta

    # ------------------------------------------------------------------
    # saving
    # ------------------------------------------------------------------
    def save(self, step, arrays, meta=None, block=False):
        """Queue one checkpoint. `arrays` values may be FetchHandles (the
        non-stalling path — D2H happens on the writer thread), jax arrays,
        or numpy. Raises the previous save's error, if any, rather than
        silently dropping checkpoints after the writer broke."""
        if self._closed:
            raise RuntimeError('CheckpointManager is closed')
        self._raise_pending_error()
        meta = dict(meta or {})
        meta.setdefault('step', int(step))
        job = _SaveJob(int(step), dict(arrays), meta)
        t0 = time.perf_counter()
        if not self.async_save or block:
            # commit on the calling thread (final/preemption checkpoints
            # must be durable before the process exits)
            if self._inflight is not None:
                self._inflight.done.wait()
            self._write(job)
            if job.error is not None:
                self._error = None
                raise job.error
        else:
            self._ensure_writer()
            if self._inflight is not None and not self._inflight.done.is_set():
                # one checkpoint in flight at a time bounds host memory to
                # 1× state; waiting here (rare: save cadence outpacing disk)
                # is counted as stall
                self._inflight.done.wait()
                self._raise_pending_error()
            self._inflight = job
            self._queue.put(job)
        stall = time.perf_counter() - t0
        if _obs._ENABLED:
            _obs.observe('checkpoint_stall_seconds', stall,
                         help='time the step loop was blocked per '
                              'checkpoint request (capture + enqueue; the '
                              'write itself is off-thread)')
        self._last_saved_step = int(step)
        return job

    def wait(self):
        """Block until the in-flight save (if any) committed; re-raise its
        failure."""
        if self._inflight is not None:
            self._inflight.done.wait()
        self._raise_pending_error()

    def _raise_pending_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _ensure_writer(self):
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True,
                name='paddle_tpu_checkpoint_writer')
            self._writer.start()

    def _writer_loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            self._write(job)

    def _write(self, job):
        t0 = time.perf_counter()
        # a wedged write (dead NFS mount, stuck D2H) must not silently stop
        # all future checkpoints: the process watchdog, when armed, holds an
        # IO lease over the materialize+commit (watchdog.py)
        lease = _wdg.arm_io('checkpoint_writer')
        try:
            if self._sharded():
                return self._write_fleet(job, lease, t0)
            # materialize: for FetchHandles this is the device→host wait +
            # copy, overlapped with the main thread's subsequent steps
            arrays = {k: np.asarray(v) for k, v in job.arrays.items()}
            job.arrays = None          # drop handles → donation unblocks
            nbytes = None
            for attempt in range(self.retries + 1):
                try:
                    self._fault.on_io()
                    ck = _snap.write_checkpoint(
                        self.directory, job.step, arrays, job.meta,
                        saved_unix_time=time.time())
                    nbytes = ck.manifest['payload_bytes']
                    break
                except OSError as e:
                    if attempt >= self.retries:
                        raise
                    delay = self.backoff_s * (2 ** attempt)
                    _logger.warning(
                        'checkpoint step %d attempt %d/%d failed (%s); '
                        'retrying in %.3fs', job.step, attempt + 1,
                        self.retries + 1, e, delay)
                    if _obs._ENABLED:
                        _obs.inc('checkpoint_retries',
                                 help='checkpoint IO attempts retried '
                                      'after a failure')
                    time.sleep(delay)
            self._gc()
            if _obs._ENABLED:
                _obs.inc('checkpoint_saves',
                         help='checkpoints committed (manifest written)')
                _obs.inc('checkpoint_bytes', nbytes,
                         help='checkpoint payload bytes written')
                _obs.observe('checkpoint_save_seconds',
                             time.perf_counter() - t0,
                             help='materialize + write + commit time per '
                                  'checkpoint (background thread)')
                _obs.set_gauge('checkpoint_last_step', job.step,
                               help='step of the newest committed '
                                    'checkpoint')
        except BaseException as e:      # surface on the next save()/wait()
            job.error = e
            self._error = e
            _logger.error('checkpoint step %d FAILED after %d attempt(s): '
                          '%s: %s', job.step, self.retries + 1,
                          type(e).__name__, e)
            if _obs._ENABLED:
                _obs.inc('checkpoint_failures',
                         help='checkpoints abandoned after exhausting '
                              'retries')
        finally:
            _wdg.disarm(lease)
            job.done.set()

    def _write_fleet(self, job, lease, t0):
        """Sharded fleet save (fleet_runtime/sharded_ckpt.py): this host
        materializes + commits ONLY the tiles it owns; host 0 then waits
        on the coordinator-KV shard barrier and commits the fleet
        manifest — the single global marker — LAST. Runs on the writer
        thread; any raise is surfaced by _write's error handling."""
        from ..fleet_runtime import sharded_ckpt as _shard
        rank, world = self._rank_index(), self._fleet_world()
        meta = dict(job.meta)
        host_meta = {k: meta[k] for k in ('rng', 'python_rng', 'loader')
                     if k in meta}
        arrays, job.arrays = job.arrays, None
        for attempt in range(self.retries + 1):
            try:
                self._fault.on_io()
                sm = _shard.write_host_shard(
                    self.directory, job.step, arrays,
                    host_meta=host_meta, rank=rank, world=world)
                break
            except OSError as e:
                if attempt >= self.retries:
                    raise
                delay = self.backoff_s * (2 ** attempt)
                _logger.warning(
                    'fleet shard step %d attempt %d/%d failed (%s); '
                    'retrying in %.3fs', job.step, attempt + 1,
                    self.retries + 1, e, delay)
                if _obs._ENABLED:
                    _obs.inc('checkpoint_retries',
                             help='checkpoint IO attempts retried after '
                                  'a failure')
                time.sleep(delay)
        arrays = None                  # drop handles → donation unblocks
        if rank == 0:
            _shard.commit_fleet_manifest(
                self.directory, job.step, world, meta=meta,
                saved_unix_time=time.time())
            self._gc()
        if _obs._ENABLED:
            _obs.inc('checkpoint_saves',
                     help='checkpoints committed (manifest written)')
            _obs.inc('checkpoint_bytes', sm['payload_bytes'],
                     help='checkpoint payload bytes written')
            _obs.inc('checkpoint_shard_bytes', sm['payload_bytes'],
                     help='bytes this host wrote into its own fleet '
                          'checkpoint shards (owned tiles only)')
            _obs.observe('checkpoint_save_seconds',
                         time.perf_counter() - t0,
                         help='materialize + write + commit time per '
                              'checkpoint (background thread)')
            _obs.set_gauge('checkpoint_last_step', job.step,
                           help='step of the newest committed checkpoint')

    def _gc(self):
        """Keep the newest `keep` valid checkpoints; delete manifest FIRST
        (decommit), then payloads — a crash mid-gc can only leave orphan
        payloads, never a manifest pointing at nothing valid. Fleet
        checkpoints are GC'd by host 0 only (the manifest committer);
        stale temp litter from crashed writers is swept too."""
        ckpts = _snap.list_checkpoints(self.directory)
        for ck in ckpts[:-self.keep] if len(ckpts) > self.keep else []:
            if ck.sharded and self._rank_index() != 0:
                continue
            try:
                os.unlink(ck.manifest_path)
                for p in ck.payload_paths:
                    os.unlink(p)
            except OSError:
                pass
        now = time.time()
        for name in os.listdir(self.directory):
            if '.tmp-' in name:
                p = os.path.join(self.directory, name)
                try:
                    if now - os.path.getmtime(p) > _TMP_MAX_AGE_S:
                        os.unlink(p)
                except OSError:
                    pass

    # ------------------------------------------------------------------
    # the step-boundary hook
    # ------------------------------------------------------------------
    @property
    def preemption_requested(self):
        return self._preemption.requested

    def request_preemption(self):
        """Programmatic SIGTERM equivalent (tests, external agents)."""
        self._preemption.request()

    def end_of_step(self, step, state_fn, meta=None, loss=None,
                    batch_desc=None):
        """Call once per completed training step. Runs the fault-injection
        step hook, judges health when a supervisor is attached and `loss`
        is given, books goodput, saves when the cadence is due — and, on a
        pending SIGTERM/SIGINT, saves a FINAL checkpoint synchronously and
        returns True (the loop should exit cleanly).

        `state_fn` is called only when a save actually happens; it returns
        either an arrays dict or an ``(arrays, meta)`` tuple (the shape
        :func:`~paddle_tpu.resilience.state.capture_training_state`
        produces).

        Supervision (docs/RESILIENCE.md "Self-healing"): pass the step's
        `loss` (host value or FetchHandle). The attached
        :class:`~paddle_tpu.resilience.supervisor.TrainingSupervisor` runs
        FIRST — a quarantined boundary never checkpoints the poisoned
        state — and its verdict lands in ``self.last_verdict``; on
        ``action == 'rollback'`` the caller must reset its step counter to
        ``last_verdict.resume_step`` and restart its DataLoader iteration.
        Escalations raise ``TrainingDiverged`` out of this call."""
        self._fault.on_step(step)      # may SIGKILL or hang (that's the point)
        now = time.perf_counter()
        # the first boundary has no prior timestamp: the step still COUNTS
        # (lost-work deltas are in steps), its duration is just unknown
        step_time = (now - self._last_boundary
                     if self._last_boundary is not None else None)
        self.goodput.record_step(step_time if step_time is not None else 0.0)
        if step_time is not None:
            from ..observability import distributed as _dobs
            _dobs.series('step_time').observe(step_time)
        sentinel = self._sentinel()
        if sentinel is not None:
            # fleet poison poll (docs/RESILIENCE.md "Fleet propagation"):
            # another host failed — exit for resume NOW, before
            # dispatching a step into a collective with a dead peer. No
            # save: a partial fleet cannot commit a fleet checkpoint; the
            # restart resumes from the last committed one.
            rec = sentinel.check()
            if rec is not None:
                self.fleet_poisoned = rec
                _logger.error(
                    'fleet poisoned by host %s (%s) — exiting for resume '
                    'at step %d', rec.get('source'), rec.get('reason'),
                    step)
                self._write_progress(step)
                self.goodput.export_metrics()
                return True
        self.last_verdict = None
        if self._supervisor is not None and loss is not None:
            try:
                verdict = self._supervisor.end_of_step(step, loss,
                                                       batch_desc)
            except BaseException as e:
                # supervisor escalation (TrainingDiverged) on THIS host
                # must take the whole fleet down for resume, not leave
                # p-1 peers blocked in the next collective
                if sentinel is not None:
                    sentinel.post(f'supervisor escalation: '
                                  f'{type(e).__name__}: {e}',
                                  step=step, kind='supervisor')
                raise
            self.last_verdict = verdict
            if verdict.action == 'rollback':
                # state/RNG/step are back at the restored checkpoint: no
                # save, no heartbeat at the now-bogus step number
                self.goodput.export_metrics()
                self._last_boundary = time.perf_counter()
                return False
        preempt = self._preemption.requested
        # scheduled elastic resize (elastic/schedule.py): at the first
        # boundary >= the planned step, checkpoint SYNCHRONOUSLY and exit
        # for relaunch at the new size — exactly the preemption shape,
        # plus the resize.json handoff for the restarter
        resize = (self._resize_plan is not None
                  and self.resize_requested is None
                  and self._resize_plan.due(step))
        due = (self.every_n_steps is not None
               and step % self.every_n_steps == 0)
        if self.last_verdict is not None and \
                self.last_verdict.action == 'skip':
            due = False                # never checkpoint a dropped update
        if due or preempt or resize:
            got = state_fn()
            arrays, cap_meta = got if isinstance(got, tuple) else (got, {})
            cap_meta = dict(cap_meta)
            if meta:
                cap_meta.update(meta)
            cap_meta['step'] = int(step)
            cap_meta['goodput'] = self.goodput.meta()
            cap_meta['preempted'] = bool(preempt)
            self.save(step, arrays, cap_meta, block=preempt or resize)
        if resize:
            self._begin_resize(step)
        self._publish_fleet_telemetry(step, step_time)
        self._write_progress(step)
        self.goodput.export_metrics()
        self._last_boundary = time.perf_counter()
        if preempt:
            self.wait()
            _logger.info('preemption checkpoint committed at step %d; '
                         'stopping', step)
            return True
        if resize:
            return True
        return False

    def _begin_resize(self, step):
        """The resize checkpoint is committed (save was synchronous);
        record the handoff. Rank 0 writes ``resize.json`` beside the
        checkpoints so the restarter knows the target size; every rank
        stamps ``resize_exit`` into its heartbeat so the NEXT incarnation
        books the downtime into the resize bucket, not crash loss."""
        plan = self._resize_plan
        self.wait()                    # surface a failed resize save HERE
        from ..elastic import schedule as _sched
        if self._rank_index() == 0:
            _sched.write_resize_request(self.directory, step, plan.nproc,
                                        from_nproc=self._fleet_world())
        self._resize_exit = True
        self.resize_requested = {'step': int(step),
                                 'target_nproc': int(plan.nproc)}
        if _obs._ENABLED:
            _obs.inc('elastic_resize_exits',
                     help='scheduled resize exits taken at a step '
                          'boundary (checkpoint committed, relaunch '
                          'pending)')
        _logger.info('scheduled resize at step %d: checkpoint committed, '
                     'exiting for relaunch at nproc=%d', step, plan.nproc)

    # ------------------------------------------------------------------
    # fleet telemetry (docs/OBSERVABILITY.md "Training fleet")
    # ------------------------------------------------------------------
    def _publish_fleet_telemetry(self, step, step_time_s):
        """Per-host metric snapshot through the coordinator KV at each
        step boundary; host 0 folds the fleet aggregate + straggler
        verdict into ``fleet_metrics.json`` beside the checkpoints.
        Gated on the KV being configured — one env read when it isn't —
        and never allowed to fail a training step."""
        from ..fleet_runtime.coordinator import ENV_FLEET_DIR
        if not os.environ.get(ENV_FLEET_DIR):
            return
        from ..observability import distributed as _dobs
        try:
            rank = self._rank_index()
            _dobs.publish_host_snapshot(rank, step,
                                        step_time_s=step_time_s)
            if rank == 0:
                if self._straggler is None:
                    self._straggler = _dobs.StragglerMonitor(
                        out_dir=self.directory)
                _dobs.aggregate_fleet_snapshots(
                    straggler=self._straggler,
                    out_path=os.path.join(self.directory,
                                          'fleet_metrics.json'),
                    step=step)
        except Exception as e:   # noqa: broad — telemetry must not kill a step
            _logger.warning('fleet telemetry publish failed: %s', e)

    # ------------------------------------------------------------------
    # heartbeat
    # ------------------------------------------------------------------
    def _progress_path(self):
        """Per-host heartbeat file: on a fleet the hosts share the
        checkpoint directory, and p writers clobbering ONE progress.json
        would corrupt the lost-work delta (booked from each host's own
        heartbeat — once per host, and the fleet-level counters are host
        0's, whose steps ARE the fleet's steps in lockstep training)."""
        rank = self._rank_index()
        if rank == 0:
            return os.path.join(self.directory, PROGRESS_FILE)
        return os.path.join(self.directory, f'progress-{rank:04d}.json')

    def _write_progress(self, step):
        """Tiny atomic heartbeat: how far THIS incarnation actually got.
        On restart, (heartbeat − restored checkpoint) is the lost work."""
        doc = {'step': int(step),
               'last_checkpoint_step': self._last_saved_step,
               'unix_time': time.time()}
        doc.update(self.goodput.meta())
        if self._resize_exit:
            # next incarnation's record_restart routes the downtime into
            # the resize bucket instead of crash loss
            doc['resize_exit'] = True
        try:
            _snap.atomic_write_bytes(self._progress_path(),
                                     json.dumps(doc).encode())
        except OSError as e:
            _logger.warning('progress heartbeat failed: %s', e)

    def _read_progress(self):
        try:
            with open(self._progress_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # ------------------------------------------------------------------
    def close(self):
        """Flush the writer, uninstall signal handlers. Idempotent."""
        if self._closed:
            return
        self._closed = True
        try:
            if self._inflight is not None:
                self._inflight.done.wait()
        finally:
            if self._writer is not None and self._writer.is_alive():
                self._queue.put(None)
                self._writer.join(5)
            self._preemption.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
