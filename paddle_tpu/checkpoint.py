"""Orbax-backed checkpointing for large/sharded state (SURVEY §2.7).

Parity target: the reference's save_persistables/load_persistables for
training state, upgraded the TPU way: orbax handles sharded arrays (each
host writes its shards), atomic step directories, and async save so the
train loop overlaps checkpoint IO with compute.

NOTE: the production train-loop checkpointing path is
``paddle_tpu/resilience/`` (docs/RESILIENCE.md) — non-stalling FetchHandle
capture, torn-write-proof manifest commit, SIGTERM handling, bitwise
deterministic resume, fault injection, goodput. This module remains the
low-level orbax surface for MULTI-HOST sharded pytrees (each host writes
its shards), which the resilience manager will key off the unified
partitioner once ROADMAP item 1 lands.
"""
from __future__ import annotations

import os

import numpy as np
import jax


def _checkpointer(use_async=False):
    import orbax.checkpoint as ocp
    if use_async:
        return ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return ocp.PyTreeCheckpointer()


def save_checkpoint(state, directory, step=None, use_async=False,
                    overwrite=True):
    """state: pytree (e.g. {name: array} param dict, optimizer slots, …).
    Writes to directory[/step]. With use_async=True returns immediately;
    call wait_until_finished(ckptr) (returned) before exiting."""
    path = os.path.join(os.path.abspath(directory),
                        str(step)) if step is not None \
        else os.path.abspath(directory)
    ckptr = _checkpointer(use_async)
    ckptr.save(path, state, force=overwrite)
    return ckptr


def load_checkpoint(directory, step=None, target=None):
    """Restore a pytree. `target` (optional) provides structure/shardings —
    pass the current state pytree to restore sharded arrays in place."""
    import orbax.checkpoint as ocp
    path = os.path.join(os.path.abspath(directory),
                        str(step)) if step is not None \
        else os.path.abspath(directory)
    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        restore_args = jax.tree_util.tree_map(
            lambda x: ocp.ArrayRestoreArgs(sharding=getattr(x, 'sharding',
                                                            None))
            if hasattr(x, 'sharding') else ocp.RestoreArgs(), target)
        return ckptr.restore(path, item=target, restore_args=restore_args)
    return ckptr.restore(path)


def latest_step(directory):
    """Largest numeric subdirectory (checkpoint step layout)."""
    if not os.path.isdir(directory):
        return None
    steps = [int(d) for d in os.listdir(directory) if d.isdigit()]
    return max(steps) if steps else None


class CheckpointManager:
    """Rolling checkpoint manager (keep last N, async-capable)."""

    def __init__(self, directory, max_to_keep=3, use_async=False):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.use_async = use_async
        self._pending = None
        # steps tracked in-memory: an async save's directory may not be
        # visible on disk yet, so gc can't rely on listdir alone
        self._steps = [] if not os.path.isdir(self.directory) else \
            sorted(int(d) for d in os.listdir(self.directory) if d.isdigit())

    def save(self, step, state):
        if self._pending is not None:
            self._pending.wait_until_finished()
            self._pending = None
        ck = save_checkpoint(state, self.directory, step,
                             use_async=self.use_async)
        if self.use_async:
            self._pending = ck
        self._steps = sorted(set(self._steps) | {int(step)})
        self._gc()
        return ck

    def restore(self, step=None, target=None):
        step = step if step is not None else latest_step(self.directory)
        if step is None:
            return None
        return load_checkpoint(self.directory, step, target)

    def wait(self):
        if self._pending is not None:
            self._pending.wait_until_finished()
            self._pending = None

    def _gc(self):
        import shutil
        # merge with a fresh listdir so step dirs created after construction
        # (another process / second manager on the same dir) are collected
        # too, instead of being retained forever
        on_disk = set()
        if os.path.isdir(self.directory):
            on_disk = {int(d) for d in os.listdir(self.directory)
                       if d.isdigit()}
        newest = self._steps[-1] if self._steps else None
        merged = sorted(set(self._steps) | on_disk)
        keep = set(merged[-self.max_to_keep:])
        if newest is not None:
            # this manager's latest (possibly in-flight async) save is never
            # dropped, even if another writer raced ahead of it
            keep.add(newest)
        for s in merged:
            if s not in keep:
                shutil.rmtree(os.path.join(self.directory, str(s)),
                              ignore_errors=True)
        self._steps = sorted(keep)
