"""Composite networks (ref: python/paddle/fluid/nets.py)."""
from __future__ import annotations

from . import layers

__all__ = ['simple_img_conv_pool', 'img_conv_group', 'sequence_conv_pool',
           'glu', 'scaled_dot_product_attention']


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type='max',
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, stride=conv_stride,
                             padding=conv_padding, dilation=conv_dilation,
                             groups=conv_groups, param_attr=param_attr,
                             bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type='max', use_cudnn=True):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(v):
        return v if isinstance(v, (list, tuple)) else [v] * len(conv_num_filter)

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(tmp, num_filters=conv_num_filter[i],
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act='sigmoid', pool_type='max', bias_attr=None):
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act,
                                    bias_attr=bias_attr)
    return layers.sequence_pool(conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.common.apply_op_layer(
        'sigmoid', {'x': b}))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """ref: nets.py:scaled_dot_product_attention. Multi-head attention built
    on matmul+softmax — XLA fuses this into an MXU-friendly schedule."""
    d = queries.shape[-1]

    def _split_heads(x):
        if num_heads == 1:
            return x
        b, t, dd = x.shape
        x = layers.reshape(x, shape=[b if b > 0 else -1, t, num_heads,
                                     dd // num_heads])
        return layers.transpose(x, perm=[0, 2, 1, 3])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    scaled = layers.scale(q, scale=(d // num_heads) ** -0.5)
    logits = layers.matmul(scaled, k, transpose_y=True)
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 dropout_implementation='upscale_in_train')
    ctx = layers.matmul(weights, v)
    if num_heads > 1:
        b = ctx.shape[0]
        ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
        ctx = layers.reshape(ctx, shape=[b if b > 0 else -1, ctx.shape[1],
                                         num_heads * (d // num_heads)])
    return ctx
