"""Config/env dump (ref: python/paddle/utils/dump_config.py) — prints the
effective runtime configuration for bug reports."""
import os
import sys

__all__ = ['dump_config']


def dump_config():
    """Print python/jax/devices/env configuration."""
    import jax
    print('python:', sys.version.split()[0])
    print('jax:', jax.__version__)
    print('backend:', jax.default_backend())
    for d in jax.devices():
        print('device:', d.id, getattr(d, 'device_kind', ''))
    for k, v in sorted(os.environ.items()):
        if k.startswith(('PADDLE_', 'JAX_', 'XLA_', 'TPU_')):
            print(f'{k}={v}')
