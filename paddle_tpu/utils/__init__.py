"""paddle.utils parity (ref: python/paddle/utils/): training-curve Ploter
and env-config dump."""
from .plot import Ploter, PlotData
from .dump_config import dump_config

__all__ = ['Ploter', 'PlotData', 'dump_config']
