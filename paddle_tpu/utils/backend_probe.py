"""Bounded jax backend init.

A dead axon tunnel makes the first backend touch (`jax.devices()`) block
forever inside the remote handshake — the failure mode that turned an infra
outage into rc=124 with zero output at r4 driver-capture time.

Two modes:

- `probe_backend(isolated=True)` (default): a SUBPROCESS touches the
  backend first, under a timeout. If the child hangs or errors, the PARENT
  has never touched the dead backend, so the caller can still pin the CPU
  platform and carry on (an in-process watchdog thread cannot offer that —
  a stuck thread holds jax's backend lock and poisons every later device
  query in the process). After the child proves the backend answers, the
  parent initializes in-process under its own watchdog (the tunnel can die
  in the gap; a fast clear error still beats an infinite hang). Costs one
  extra interpreter+backend init on success — use it where a fallback
  matters (driver entry points).
- `probe_backend(isolated=False)`: the in-process watchdog thread only.
  Cheaper (single init), but on a hang the process's jax backend state is
  poisoned — right for callers that exit on failure anyway (bench).

Raises BackendInitTimeout on a hang and BackendInitError on a fast init
failure; both mean "infra, not code".
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading

DEFAULT_TIMEOUT_ENV = 'PADDLE_TPU_BACKEND_TIMEOUT'
# isolated mode spends part of its budget on interpreter startup + the jax
# import in the fresh child; grant that separately so a tuned-low timeout
# keeps meaning "time for the BACKEND to answer"
_CHILD_STARTUP_GRACE_S = 30.0

_CHILD = """
import os, sys
import jax
env = os.environ.get('JAX_PLATFORMS', '')
if env and jax.config.jax_platforms != env:
    jax.config.update('jax_platforms', env)
print('PROBE_OK', jax.default_backend(), len(jax.devices()), flush=True)
"""


class BackendInitTimeout(RuntimeError):
    """Backend init did not answer within the budget (likely dead tunnel).

    `parent_clean` is True when THIS process has not touched the backend
    (child-probe phase) — a CPU fallback is possible; False when the
    in-process init hung (a stuck thread holds jax's backend lock — the
    process cannot fall back, only exit with this clear error)."""

    def __init__(self, msg, parent_clean=False):
        super().__init__(msg)
        self.parent_clean = parent_clean


class BackendInitError(RuntimeError):
    """Backend init failed fast (refused connection, bad platform, ...).

    `parent_clean` as on BackendInitTimeout (fast failures leave the
    process backend-free in both phases, so it is True unless the raw
    in-process error proved otherwise)."""

    def __init__(self, msg, parent_clean=True):
        super().__init__(msg)
        self.parent_clean = parent_clean


def _timeout_msg(timeout):
    return (
        f"jax backend init did not answer within {timeout:.0f}s "
        f"(JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '')!r}); "
        "if this is an axon session the remote TPU tunnel is down — "
        "re-run when it is back, or set JAX_PLATFORMS=cpu for a "
        "CPU-shape run.")


def _init_in_process(timeout):
    """Touch the backend under a daemon-thread watchdog. On timeout the
    stuck thread keeps jax's backend lock — callers must not retry in this
    process (`parent_clean=False`) — but the caller gets a clear, fast
    error. Fast failures are wrapped in BackendInitError so the
    documented contract (only the two BackendInit* types) holds."""
    probe = {}

    def _touch():
        try:
            import jax
            env = os.environ.get('JAX_PLATFORMS', '')
            if env and jax.config.jax_platforms != env:
                jax.config.update('jax_platforms', env)
            probe['devices'] = jax.devices()
            probe['backend'] = jax.default_backend()
        except BaseException as e:  # surfaced to the caller's thread
            probe['error'] = e

    t = threading.Thread(target=_touch, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise BackendInitTimeout(_timeout_msg(timeout), parent_clean=False)
    if 'error' in probe:
        e = probe['error']
        raise BackendInitError(
            f"jax backend init failed: {type(e).__name__}: {e}",
            parent_clean=False) from e
    return probe['devices'], probe['backend']


def probe_backend(timeout=None, isolated=True):
    """Return (devices, backend_name) with the backend initialized
    in-process, or raise BackendInitTimeout / BackendInitError (see module
    docstring for the isolated-vs-in-process trade).

    `timeout` defaults to $PADDLE_TPU_BACKEND_TIMEOUT or 120 (seconds the
    backend gets to answer; isolated mode adds a fixed startup grace for
    the child interpreter on top). An explicit JAX_PLATFORMS env var beats
    the axon sitecustomize platform pin in either mode.
    """
    if timeout is None:
        timeout = float(os.environ.get(DEFAULT_TIMEOUT_ENV, '120'))
    if not isolated:
        return _init_in_process(timeout)
    try:
        out = subprocess.run([sys.executable, '-c', _CHILD],
                             capture_output=True, text=True,
                             timeout=timeout + _CHILD_STARTUP_GRACE_S)
    except subprocess.TimeoutExpired:
        raise BackendInitTimeout(_timeout_msg(timeout), parent_clean=True)
    if out.returncode != 0 or 'PROBE_OK' not in out.stdout:
        detail = (out.stderr or out.stdout).strip()
        raise BackendInitError(
            "jax backend init failed in the probe subprocess "
            f"(rc={out.returncode}); child output tail:\n{detail[-2000:]}",
            parent_clean=True)
    # the backend answers — initialize in-process, still bounded (the
    # tunnel can die in the gap; no fallback is possible past this point,
    # but a fast error beats an indefinite hang)
    return _init_in_process(timeout)
