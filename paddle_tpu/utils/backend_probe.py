"""Bounded jax backend init.

A dead axon tunnel makes the first backend touch (`jax.devices()`) block
forever inside the remote handshake — the failure mode that turned an infra
outage into rc=124 with zero output at r4 driver-capture time. `probe_backend`
touches the backend from a daemon thread under a watchdog so callers get a
clear, fast error instead of an indefinite hang.
"""
from __future__ import annotations

import os
import threading

DEFAULT_TIMEOUT_ENV = 'PADDLE_TPU_BACKEND_TIMEOUT'


class BackendInitTimeout(RuntimeError):
    pass


def probe_backend(timeout=None):
    """Return (devices, backend_name) or raise.

    Raises BackendInitTimeout after `timeout` seconds (default
    $PADDLE_TPU_BACKEND_TIMEOUT or 120) if backend init hangs, and
    re-raises any exception the init itself threw. An explicit
    JAX_PLATFORMS env var beats the axon sitecustomize platform pin.
    """
    if timeout is None:
        timeout = float(os.environ.get(DEFAULT_TIMEOUT_ENV, '120'))
    probe = {}

    def _touch():
        try:
            import jax
            env = os.environ.get('JAX_PLATFORMS', '')
            if env and jax.config.jax_platforms != env:
                jax.config.update('jax_platforms', env)
            probe['devices'] = jax.devices()
            probe['backend'] = jax.default_backend()
        except BaseException as e:  # surfaced to the caller's thread
            probe['error'] = e

    t = threading.Thread(target=_touch, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise BackendInitTimeout(
            f"jax backend init did not answer within {timeout:.0f}s "
            f"(JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '')!r}); "
            "if this is an axon session the remote TPU tunnel is down — "
            "re-run when it is back, or set JAX_PLATFORMS=cpu for a "
            "CPU-shape run.")
    if 'error' in probe:
        raise probe['error']
    return probe['devices'], probe['backend']
