"""Training-curve plotting (ref: python/paddle/utils/plot.py).

Headless-safe: with matplotlib available it renders (Agg backend off-tty),
otherwise it still records data and `plot(path)` writes a CSV next to the
requested path so curves are never lost."""
import os

__all__ = ['Ploter', 'PlotData']


class PlotData:
    """ref plot.py:20 — one curve's (step, value) series."""

    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    """ref plot.py:33 — multi-curve live plot:

        ploter = Ploter('train cost', 'test cost')
        ploter.append('train cost', step, loss)
        ploter.plot('curve.png')
    """

    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}
        self.__disable_plot__ = os.environ.get('DISABLE_PLOT', 'False')

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == 'True'

    def append(self, title, step, value):
        """ref plot.py:62."""
        if title not in self.__plot_data__:
            raise ValueError(f'{title} is not a curve of this Ploter '
                             f'(curves: {list(self.__plot_data__)})')
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        """ref plot.py:82 — render to `path` (or show); falls back to a
        CSV dump when matplotlib is unavailable."""
        if self.__plot_is_disabled__():
            return
        try:
            import matplotlib
            matplotlib.use('Agg')
            import matplotlib.pyplot as plt
            titles = []
            for title in self.__args__:
                data = self.__plot_data__[title]
                if len(data.step) > 0:
                    titles.append(title)
                    plt.plot(data.step, data.value)
            plt.legend(titles, loc='upper left')
            if path is not None:
                plt.savefig(path)
            plt.clf()
        except ImportError:
            if path is not None:
                with open(str(path) + '.csv', 'w') as f:
                    for title in self.__args__:
                        data = self.__plot_data__[title]
                        for s, v in zip(data.step, data.value):
                            f.write(f'{title},{s},{v}\n')

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
