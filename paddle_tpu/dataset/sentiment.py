"""paddle.dataset.sentiment parity (ref: python/paddle/dataset/
sentiment.py — NLTK movie_reviews). get_word_dict + train/test readers
yielding ([word ids], 0|1). NLTK corpora can't be fetched offline, so a
cached `movie_reviews` directory under DATA_HOME is used when present
(pos/ and neg/ subdirs of .txt files) and the deterministic synthetic
corpus otherwise."""
import os

from .common import DATA_HOME, WORDS, synthetic_text_corpus, synthetic_warn

__all__ = ['train', 'test', 'get_word_dict']

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_DIR = os.path.join(DATA_HOME, 'sentiment', 'movie_reviews')


def _docs():
    """All (tokens, label) docs, pos first then neg (ref ordering), then
    interleaved for the train/test split the ref applies."""
    docs = []
    if os.path.isdir(_DIR):
        for label, sub in ((0, 'pos'), (1, 'neg')):
            d = os.path.join(_DIR, sub)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), errors='ignore') as f:
                    docs.append((f.read().lower().split(), label))
    else:
        synthetic_warn('sentiment', _DIR)
        base = synthetic_text_corpus(WORDS, NUM_TOTAL_INSTANCES, 31)
        for i, sent in enumerate(base):
            label = i % 2
            docs.append((sent + (['good'] if label == 0 else ['bad']),
                         label))
    # ref shuffles pos/neg together deterministically; interleave instead
    pos = [d for d in docs if d[1] == 0]
    neg = [d for d in docs if d[1] == 1]
    out = []
    for p, n in zip(pos, neg):
        out += [p, n]
    out += pos[len(neg):] + neg[len(pos):]
    return out


_word_dict = None


def get_word_dict():
    """ref sentiment.py:get_word_dict — frequency-sorted {word: idx}."""
    global _word_dict
    if _word_dict is None:
        freq = {}
        for tokens, _ in _docs():
            for w in tokens:
                freq[w] = freq.get(w, 0) + 1
        words = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
        _word_dict = {w: i for i, (w, _) in enumerate(words)}
    return _word_dict


def _reader_creator(lo, hi):
    def reader():
        wd = get_word_dict()
        for tokens, label in _docs()[lo:hi]:
            yield [wd[w] for w in tokens if w in wd], label
    reader.is_synthetic = not os.path.isdir(_DIR)
    return reader


def train():
    """ref sentiment.py:train — first 1600 instances."""
    return _reader_creator(0, NUM_TRAINING_INSTANCES)


def test():
    """ref sentiment.py:test — last 400 instances."""
    return _reader_creator(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES)
