"""paddle.dataset.uci_housing parity (ref: python/paddle/dataset/
uci_housing.py). Samples are (13-float32 normalized features,
[float32 price])."""
import os

import numpy as np

from .common import DATA_HOME, synthetic_warn

__all__ = ['train', 'test']

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']
FEATURE_NUM = len(feature_names) + 1   # + target
UCI_TEST_RATIO = 0.2

_cache = {}


def _load():
    if 'data' in _cache:
        return _cache['data']
    path = os.path.join(DATA_HOME, 'uci_housing', 'housing.data')
    if os.path.exists(path):
        data = np.fromfile(path, sep=' ').reshape(-1, FEATURE_NUM)
        synthetic = False
    else:
        synthetic_warn('uci_housing', path)
        rng = np.random.RandomState(7)
        feats = rng.rand(506, FEATURE_NUM - 1).astype('float64')
        w = rng.randn(FEATURE_NUM - 1)
        target = feats @ w + 0.1 * rng.randn(506) + 22.0
        data = np.concatenate([feats, target[:, None]], axis=1)
        synthetic = True
    # ref normalization: per-feature (x - mean) / (max - min)
    maxs, mins, means = (data.max(0), data.min(0), data.mean(0))
    for i in range(FEATURE_NUM - 1):
        data[:, i] = (data[:, i] - means[i]) / (maxs[i] - mins[i])
    _cache['data'] = (data, synthetic)
    return _cache['data']


def _reader_creator(is_test):
    def reader():
        data, _ = _load()
        n_test = int(len(data) * UCI_TEST_RATIO)
        rows = data[-n_test:] if is_test else data[:-n_test]
        for row in rows:
            yield row[:-1].astype('float32'), \
                row[-1:].astype('float32')
    reader.is_synthetic = _load()[1]
    return reader


def train():
    """ref uci_housing.py:train."""
    return _reader_creator(is_test=False)


def test():
    """ref uci_housing.py:test."""
    return _reader_creator(is_test=True)
