"""paddle.dataset.movielens parity (ref: python/paddle/dataset/
movielens.py — ML-1M). Yields per-rating feature rows
[user_id, gender, age, job, movie_id, title ids, category ids, score].
Real ml-1m.zip when cached; a deterministic synthetic catalogue
otherwise."""
import os
import re
import zipfile

import numpy as np

from .common import DATA_HOME, synthetic_warn

__all__ = ['train', 'test', 'get_movie_title_dict', 'max_movie_id',
           'max_user_id', 'age_table', 'movie_categories', 'max_job_id',
           'user_info', 'movie_info']

age_table = [1, 18, 25, 35, 45, 50, 56]

_ZIP = os.path.join(DATA_HOME, 'movielens', 'ml-1m.zip')


class MovieInfo:
    """ref movielens.py:48."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        """[movie_id, [category ids], [title word ids]]"""
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __str__(self):
        return (f'<MovieInfo id({self.index}), title({self.title}), '
                f'categories({self.categories})>')

    __repr__ = __str__


class UserInfo:
    """ref movielens.py:75."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        """[user_id, gender, age bucket, job]"""
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __str__(self):
        return (f'<UserInfo id({self.index}), '
                f'gender({"M" if self.is_male else "F"}), '
                f'age({age_table[self.age]}), job({self.job_id})>')

    __repr__ = __str__


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None
RATINGS = None
_IS_SYNTHETIC = False


def _init():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, \
        RATINGS, _IS_SYNTHETIC
    if MOVIE_INFO is not None:
        return
    categories, titles = set(), set()
    MOVIE_INFO, USER_INFO, RATINGS = {}, {}, []
    if os.path.exists(_ZIP):
        pat = re.compile(r'^(.*)\((\d+)\)$')
        with zipfile.ZipFile(_ZIP) as z:
            with z.open('ml-1m/movies.dat') as f:
                for line in f.read().decode('latin-1').splitlines():
                    mid, title, cats = line.strip().split('::')
                    cats = cats.split('|')
                    title = pat.match(title).group(1).strip()
                    MOVIE_INFO[int(mid)] = MovieInfo.__new__(MovieInfo)
                    MOVIE_INFO[int(mid)].__dict__.update(
                        index=int(mid), categories=cats, title=title)
                    categories.update(cats)
                    titles.update(w.lower() for w in title.split())
            with z.open('ml-1m/users.dat') as f:
                for line in f.read().decode('latin-1').splitlines():
                    uid, gender, age, job, _ = line.strip().split('::')
                    USER_INFO[int(uid)] = UserInfo(uid, gender, age, job)
            with z.open('ml-1m/ratings.dat') as f:
                for line in f.read().decode('latin-1').splitlines():
                    uid, mid, rating, _ = line.strip().split('::')
                    RATINGS.append((int(uid), int(mid), float(rating)))
    else:
        synthetic_warn('movielens', _ZIP)
        _IS_SYNTHETIC = True
        rng = np.random.RandomState(41)
        cat_names = ['Action', 'Comedy', 'Drama', 'Horror', 'Romance']
        title_words = ['the', 'movie', 'of', 'night', 'day', 'star', 'love',
                       'war', 'king', 'girl']
        for mid in range(1, 201):
            cats = [cat_names[j]
                    for j in rng.choice(len(cat_names),
                                        rng.randint(1, 3), replace=False)]
            title = ' '.join(title_words[j]
                             for j in rng.randint(0, len(title_words), 3))
            MOVIE_INFO[mid] = MovieInfo.__new__(MovieInfo)
            MOVIE_INFO[mid].__dict__.update(index=mid, categories=cats,
                                            title=title)
            categories.update(cats)
            titles.update(title.split())
        for uid in range(1, 101):
            USER_INFO[uid] = UserInfo(
                uid, 'M' if rng.randint(2) else 'F',
                age_table[rng.randint(len(age_table))], rng.randint(0, 21))
        for _ in range(4000):
            RATINGS.append((int(rng.randint(1, 101)),
                            int(rng.randint(1, 201)),
                            float(rng.randint(1, 6))))
    CATEGORIES_DICT = {c: i for i, c in enumerate(sorted(categories))}
    MOVIE_TITLE_DICT = {w: i for i, w in enumerate(sorted(titles))}


def _reader(rand_seed=0, test_ratio=0.1, is_test=False):
    _init()
    rng = np.random.RandomState(rand_seed)
    for uid, mid, rating in RATINGS:
        if (rng.rand() < test_ratio) == is_test:
            if uid in USER_INFO and mid in MOVIE_INFO:
                yield USER_INFO[uid].value() + MOVIE_INFO[mid].value() + \
                    [[rating]]


def _creator(**kw):
    def reader():
        yield from _reader(**kw)
    _init()
    reader.is_synthetic = _IS_SYNTHETIC
    return reader


def train():
    """ref movielens.py:train."""
    return _creator(is_test=False)


def test():
    """ref movielens.py:test."""
    return _creator(is_test=True)


def get_movie_title_dict():
    """ref movielens.py:178."""
    _init()
    return MOVIE_TITLE_DICT


def max_movie_id():
    """ref movielens.py:193."""
    _init()
    return max(MOVIE_INFO)


def max_user_id():
    """ref movielens.py:201."""
    _init()
    return max(USER_INFO)


def max_job_id():
    """ref movielens.py:216."""
    _init()
    return max(u.job_id for u in USER_INFO.values())


def movie_categories():
    """ref movielens.py:225."""
    _init()
    return CATEGORIES_DICT


def user_info():
    """ref movielens.py:233."""
    _init()
    return USER_INFO


def movie_info():
    """ref movielens.py:241."""
    _init()
    return MOVIE_INFO
