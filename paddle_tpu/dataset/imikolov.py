"""paddle.dataset.imikolov parity (ref: python/paddle/dataset/imikolov.py).
PTB language-model data: build_dict + N-gram / sequence readers."""
import collections
import os
import tarfile

from .common import DATA_HOME, WORDS, synthetic_text_corpus, synthetic_warn

__all__ = ['train', 'test', 'build_dict']

_TAR = os.path.join(DATA_HOME, 'imikolov', 'simple-examples.tgz')
_TRAIN_MEMBER = './simple-examples/data/ptb.train.txt'
_TEST_MEMBER = './simple-examples/data/ptb.valid.txt'


class DataType:
    """ref imikolov.py:DataType."""
    NGRAM = 1
    SEQ = 2


def _sentences(member, n_synth, seed):
    if os.path.exists(_TAR):
        with tarfile.open(_TAR) as tf:
            for line in tf.extractfile(member).read().decode().splitlines():
                yield line.strip().split()
    else:
        synthetic_warn('imikolov', _TAR)
        for sent in synthetic_text_corpus(WORDS, n_synth, seed):
            yield sent


def word_count(sents, word_freq=None):
    """ref imikolov.py:word_count."""
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for words in sents:
        for w in words:
            word_freq[w] += 1
        word_freq['<s>'] += 1
        word_freq['<e>'] += 1
    return word_freq


def build_dict(min_word_freq=50):
    """ref imikolov.py:build_dict — train∪test vocab above the frequency
    floor, plus <unk>."""
    word_freq = word_count(_sentences(_TEST_MEMBER, 100, 21),
                           word_count(_sentences(_TRAIN_MEMBER, 400, 20)))
    if '<unk>' in word_freq:
        del word_freq['<unk>']
    # synthetic corpora are small — scale the floor so the dict is non-empty
    if not os.path.exists(_TAR):
        min_word_freq = min(min_word_freq, 1)
    word_freq = [x for x in word_freq.items() if x[1] >= min_word_freq]
    word_freq_sorted = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*word_freq_sorted))
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx['<unk>'] = len(words)
    return word_idx


def reader_creator(member, word_idx, n, data_type, n_synth, seed):
    def reader():
        UNK = word_idx['<unk>']
        for sent in _sentences(member, n_synth, seed):
            if DataType.NGRAM == data_type:
                assert n > -1, 'Invalid gram length'
                sent = ['<s>'] + sent + ['<e>']
                if len(sent) >= n:
                    sent = [word_idx.get(w, UNK) for w in sent]
                    for i in range(n, len(sent) + 1):
                        yield tuple(sent[i - n:i])
            elif DataType.SEQ == data_type:
                sent = [word_idx.get(w, UNK) for w in sent]
                src_seq = [word_idx['<s>']] + sent
                trg_seq = sent + [word_idx['<e>']]
                if n > 0 and len(sent) > n:
                    continue
                yield src_seq, trg_seq
            else:
                assert False, 'Unknown data type'
    reader.is_synthetic = not os.path.exists(_TAR)
    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    """ref imikolov.py:train."""
    return reader_creator(_TRAIN_MEMBER, word_idx, n, data_type, 400, 20)


def test(word_idx, n, data_type=DataType.NGRAM):
    """ref imikolov.py:test."""
    return reader_creator(_TEST_MEMBER, word_idx, n, data_type, 100, 21)
