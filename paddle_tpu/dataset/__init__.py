"""Merged dataset namespace.

Two reference surfaces live here (paddle and fluid share one namespace in
this build — `paddle_tpu.fluid is paddle_tpu`):

- `paddle.dataset.*` zoo (ref: python/paddle/dataset/): mnist, cifar,
  uci_housing, imdb, imikolov, movielens, mq2007, sentiment, conll05,
  flowers, voc2012, wmt14, wmt16, image, common. Real files when staged
  under the local cache (no network egress here), deterministic synthetic
  corpora with identical sample structure otherwise (readers carry
  `.is_synthetic`).
- `fluid.dataset` (ref: python/paddle/fluid/dataset.py): DatasetFactory /
  InMemoryDataset / QueueDataset — MultiSlot-file training input for
  Executor.train_from_dataset.
"""
from .fluid_dataset import (DatasetFactory, InMemoryDataset, QueueDataset,
                            FileInstantDataset, DatasetBase)
from . import common
from . import image
from . import mnist
from . import cifar
from . import uci_housing
from . import imdb
from . import imikolov
from . import movielens
from . import mq2007
from . import sentiment
from . import conll05
from . import flowers
from . import voc2012
from . import wmt14
from . import wmt16

__all__ = ['DatasetFactory', 'InMemoryDataset', 'QueueDataset',
           'FileInstantDataset', 'common', 'image', 'mnist', 'cifar',
           'uci_housing', 'imdb', 'imikolov', 'movielens', 'mq2007',
           'sentiment', 'conll05', 'flowers', 'voc2012', 'wmt14', 'wmt16']
