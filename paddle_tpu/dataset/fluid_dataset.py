"""fluid.dataset — high-performance file-backed training input
(ref: python/paddle/fluid/dataset.py).

The reference feeds MultiSlot-format text files through C++ DataFeed
readers into per-thread scopes. The TPU formulation parses the same
MultiSlot format in Python/numpy, batches on host, and hands batches to
the jitted executor step (Executor.train_from_dataset); `pipe_command`
preprocessing runs for real via a subprocess pipe, matching the
reference's semantics of piping each file through a shell command.

MultiSlot line format (one sample per line, slots in `set_use_var` order):
    <n1> v1 ... vn1  <n2> v1 ... vn2  ...
Each slot starts with its value count. Dense slots (lod_level==0) must
have count == prod(var.shape[1:]); sparse slots batch as LoDTensors.
"""
from __future__ import annotations

import subprocess

import numpy as np

from ..core.lod import LoDTensor

__all__ = ['DatasetFactory', 'InMemoryDataset', 'QueueDataset',
           'FileInstantDataset', 'DatasetBase']


class DatasetFactory:
    """ref dataset.py:23 — create a dataset by class name."""

    def create_dataset(self, datafeed_class='QueueDataset'):
        try:
            return globals()[datafeed_class]()
        except KeyError:
            raise ValueError(
                f'datafeed class {datafeed_class} does not exist')


class DatasetBase:
    """ref dataset.py:64 — shared config surface."""

    def __init__(self):
        self.proto_desc = {'name': 'MultiSlotDataFeed', 'batch_size': 1,
                           'pipe_command': 'cat'}
        self.filelist = []
        self.use_vars = []
        self.thread_num = 1
        self.queue_num = None
        self.fleet_send_batch_size = 1024
        self.merge_size = -1
        self.parse_ins_id = False
        self.parse_content = False

    # -- config setters (ref dataset.py:77-254) --
    def set_pipe_command(self, pipe_command):
        """Shell command each data file is piped through before parsing."""
        self.proto_desc['pipe_command'] = pipe_command

    def set_batch_size(self, batch_size):
        self.proto_desc['batch_size'] = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_hdfs_config(self, fs_name, fs_ugi):
        """Accepted for API parity; TPU pods read from mounted/GCS paths, so
        there is no HDFS client to configure."""
        self.hdfs_config = (fs_name, fs_ugi)

    def set_download_cmd(self, download_cmd):
        self.download_cmd = download_cmd

    def set_fea_eval(self, record_candidate_size, fea_eval=True):
        self.fea_eval = (record_candidate_size, fea_eval)

    def desc(self):
        """ref dataset.py:269 — text-proto description."""
        from ..data_feed_desc import _to_text_proto
        d = dict(self.proto_desc)
        d['multi_slot_desc'] = {'slots': [
            {'name': v.name, 'type': str(v.dtype),
             'is_dense': getattr(v, 'lod_level', 0) == 0, 'is_used': True}
            for v in self.use_vars]}
        return _to_text_proto(d)

    # -- parsing core --
    def _read_lines(self, path):
        cmd = self.proto_desc.get('pipe_command', 'cat')
        if cmd and cmd != 'cat':
            with open(path, 'rb') as f:
                out = subprocess.run(cmd, shell=True, stdin=f,
                                     capture_output=True, check=True)
            return out.stdout.decode().splitlines()
        with open(path) as f:
            return f.read().splitlines()

    def _parse_line(self, line):
        """One MultiSlot line → list of 1-D numpy arrays (slot order)."""
        toks = line.split()
        vals, i = [], 0
        for v in self.use_vars:
            if i >= len(toks):
                raise ValueError(
                    f'line has too few slots for {len(self.use_vars)} vars: '
                    f'{line[:80]!r}')
            n = int(toks[i]); i += 1
            dtype = np.int64 if 'int' in str(v.dtype) else np.float32
            vals.append(np.array(toks[i:i + n], dtype=dtype))
            i += n
        return vals

    def _records(self):
        """Iterate parsed samples over the filelist."""
        for path in self.filelist:
            for line in self._read_lines(path):
                if line.strip():
                    yield self._parse_line(line)

    def _batches(self, records=None):
        """Yield {var_name: ndarray|LoDTensor} feed dicts of batch_size."""
        bs = self.proto_desc['batch_size']
        buf = []
        for rec in (records if records is not None else self._records()):
            buf.append(rec)
            if len(buf) == bs:
                yield self._pack(buf)
                buf = []
        if buf:
            yield self._pack(buf)

    def _pack(self, rows):
        feed = {}
        for si, v in enumerate(self.use_vars):
            cols = [r[si] for r in rows]
            if getattr(v, 'lod_level', 0) == 0:
                tail = list((v.shape or [])[1:])
                if tail and -1 not in tail:
                    want = int(np.prod(tail))
                    bad = [len(c) for c in cols if len(c) != want]
                    if bad:
                        raise ValueError(
                            f'dense slot {v.name} expects {want} values '
                            f'per sample (shape {tail}), got {bad[0]}')
                    feed[v.name] = np.stack([c.reshape(tail) for c in cols])
                else:
                    feed[v.name] = np.stack(cols)
            else:
                lens = [len(c) for c in cols]
                t = max(lens) if lens else 1
                pad = np.zeros((len(cols), max(t, 1)), cols[0].dtype)
                for i, c in enumerate(cols):
                    pad[i, :len(c)] = c
                feed[v.name] = LoDTensor(pad, [lens])
        return feed


class QueueDataset(DatasetBase):
    """ref dataset.py:684 — streaming dataset: files are read and parsed
    on the fly at train time; nothing is materialized."""

    def local_shuffle(self):
        raise NotImplementedError(
            'QueueDataset does not support local shuffle; '
            'use InMemoryDataset')

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            'QueueDataset does not support global shuffle; '
            'use InMemoryDataset')


class InMemoryDataset(DatasetBase):
    """ref dataset.py:302 — load_into_memory + local/global shuffle."""

    def __init__(self):
        super().__init__()
        self.memory = None
        self._rng = np.random.RandomState(0)

    def set_queue_num(self, queue_num):
        self.queue_num = int(queue_num)

    def set_parse_ins_id(self, parse_ins_id):
        self.parse_ins_id = bool(parse_ins_id)

    def set_parse_content(self, parse_content):
        self.parse_content = bool(parse_content)

    def set_fleet_send_batch_size(self, fleet_send_batch_size=1024):
        self.fleet_send_batch_size = int(fleet_send_batch_size)

    def set_fleet_send_sleep_seconds(self, seconds=0):
        self.fleet_send_sleep_seconds = seconds

    def set_merge_by_lineid(self, merge_size=2):
        self.merge_size = int(merge_size)

    def load_into_memory(self):
        """ref dataset.py:457 — parse every file into host memory."""
        self.memory = list(self._records())

    def preload_into_memory(self, thread_num=None):
        """ref dataset.py:473 — same as load (no async host threads needed:
        parsing is not on the device-step critical path)."""
        self.load_into_memory()

    def wait_preload_done(self):
        if self.memory is None:
            self.load_into_memory()

    def local_shuffle(self):
        """ref dataset.py:514."""
        if self.memory is None:
            raise RuntimeError('call load_into_memory() before local_shuffle')
        self._rng.shuffle(self.memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        """ref dataset.py:530 — shard by sample hash across workers, then
        shuffle locally. With fleet=None this equals local_shuffle."""
        if self.memory is None:
            raise RuntimeError('call load_into_memory() before global_shuffle')
        if fleet is not None:
            n = max(1, fleet.worker_num())
            i = fleet.worker_index()
            self.memory = [r for k, r in enumerate(self.memory)
                           if k % n == i]
        self._rng.shuffle(self.memory)

    def release_memory(self):
        """ref dataset.py:575."""
        self.memory = None

    def get_memory_data_size(self, fleet=None):
        """ref dataset.py:597 — total sample count (summed over workers)."""
        local = len(self.memory or ())
        if fleet is not None:
            return local * max(1, fleet.worker_num())
        return local

    def get_shuffle_data_size(self, fleet=None):
        """ref dataset.py:633."""
        return self.get_memory_data_size(fleet)

    def slots_shuffle(self, slots):
        """ref dataset.py:118 — permute the values of named slots across
        samples (feature-importance evaluation)."""
        if self.memory is None:
            raise RuntimeError('call load_into_memory() before slots_shuffle')
        name_to_idx = {v.name: i for i, v in enumerate(self.use_vars)}
        for name in slots:
            si = name_to_idx[name]
            perm = self._rng.permutation(len(self.memory))
            vals = [self.memory[p][si] for p in perm]
            for r, val in zip(self.memory, vals):
                r[si] = val

    def _batches(self, records=None):
        if records is None and self.memory is not None:
            records = self.memory
        return super()._batches(records)


class FileInstantDataset(DatasetBase):
    """ref dataset.py:766 — file-instant variant (streams like
    QueueDataset on TPU)."""

    def local_shuffle(self):
        raise NotImplementedError(
            'FileInstantDataset does not support local shuffle')

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            'FileInstantDataset does not support global shuffle')
