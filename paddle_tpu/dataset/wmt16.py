"""paddle.dataset.wmt16 parity (ref: python/paddle/dataset/wmt16.py) —
WMT16 en↔de with on-the-fly vocab building. Readers yield
(src ids, trg ids, trg-next ids)."""
import collections
import os
import tarfile

from .common import DATA_HOME, WORDS, synthetic_text_corpus, synthetic_warn

__all__ = ['train', 'test', 'validation', 'get_dict', 'fetch']

_DIR = os.path.join(DATA_HOME, 'wmt16')
_TAR = os.path.join(_DIR, 'wmt16.tar.gz')

START_MARK = '<s>'
END_MARK = '<e>'
UNK_MARK = '<unk>'


def _synth_pairs(n, seed):
    src = synthetic_text_corpus(WORDS[:40], n, seed, min_len=3, max_len=8)
    return [(s, list(reversed(s))) for s in src]


def __build_dict(tar_file, dict_size, save_path, lang):
    word_dict = collections.defaultdict(int)
    with tarfile.open(tar_file) as f:
        for line in f.extractfile('wmt16/train').read().decode() \
                .splitlines():
            line_split = line.strip().split('\t')
            if len(line_split) != 2:
                continue
            sen = line_split[0] if lang == 'en' else line_split[1]
            for w in sen.split():
                word_dict[w] += 1
    with open(save_path, 'w', encoding='utf-8') as fout:
        fout.write(f'{START_MARK}\n{END_MARK}\n{UNK_MARK}\n')
        for word, _ in sorted(word_dict.items(),
                              key=lambda x: x[1], reverse=True)[
                :dict_size - 3]:
            fout.write(word + '\n')


def __load_dict(tar_file, dict_size, lang, reverse=False):
    dict_path = os.path.join(_DIR, f'{lang}.dict')
    if os.path.exists(tar_file) and (not os.path.exists(dict_path) or (
            len(open(dict_path, 'rb').readlines()) != dict_size)):
        os.makedirs(_DIR, exist_ok=True)
        __build_dict(tar_file, dict_size, dict_path, lang)
    word_dict = {}
    if os.path.exists(dict_path):
        with open(dict_path, encoding='utf-8') as fdict:
            for idx, line in enumerate(fdict):
                if reverse:
                    word_dict[idx] = line.strip()
                else:
                    word_dict[line.strip()] = idx
    else:
        vocab = [START_MARK, END_MARK, UNK_MARK] + WORDS[:40]
        vocab = vocab[:dict_size] if dict_size > 3 else vocab
        for i, w in enumerate(vocab):
            word_dict[i if reverse else w] = w if reverse else i
    return word_dict


def _reader_creator(split, src_dict_size, trg_dict_size, src_lang,
                    n_synth, seed):
    src_dict_size = min(src_dict_size, 10**6) if src_dict_size > 0 else 3
    trg_dict_size = min(trg_dict_size, 10**6) if trg_dict_size > 0 else 3

    def reader():
        src_dict = __load_dict(_TAR, src_dict_size, src_lang)
        trg_lang = 'de' if src_lang == 'en' else 'en'
        trg_dict = __load_dict(_TAR, trg_dict_size, trg_lang)
        start, end, unk = (src_dict[START_MARK], src_dict[END_MARK],
                           src_dict[UNK_MARK])
        t_start, t_end, t_unk = (trg_dict[START_MARK], trg_dict[END_MARK],
                                 trg_dict[UNK_MARK])
        if os.path.exists(_TAR):
            with tarfile.open(_TAR) as f:
                lines = f.extractfile(f'wmt16/{split}').read().decode() \
                    .splitlines()
            pairs = []
            for line in lines:
                ls = line.strip().split('\t')
                if len(ls) == 2:
                    en, de = ls[0].split(), ls[1].split()
                    pairs.append((en, de) if src_lang == 'en' else (de, en))
        else:
            pairs = _synth_pairs(n_synth, seed)
        for s, t in pairs:
            src_ids = [start] + [src_dict.get(w, unk) for w in s] + [end]
            trg_ids = [trg_dict.get(w, t_unk) for w in t]
            yield src_ids, [t_start] + trg_ids, trg_ids + [t_end]
    reader.is_synthetic = not os.path.exists(_TAR)
    return reader


def train(src_dict_size, trg_dict_size, src_lang='en'):
    """ref wmt16.py:train."""
    if src_lang not in ('en', 'de'):
        raise ValueError("src_lang must be 'en' or 'de'")
    if not os.path.exists(_TAR):
        synthetic_warn('wmt16', _TAR)
    return _reader_creator('train', src_dict_size, trg_dict_size, src_lang,
                           300, 95)


def test(src_dict_size, trg_dict_size, src_lang='en'):
    """ref wmt16.py:test."""
    if src_lang not in ('en', 'de'):
        raise ValueError("src_lang must be 'en' or 'de'")
    return _reader_creator('test', src_dict_size, trg_dict_size, src_lang,
                           60, 96)


def validation(src_dict_size, trg_dict_size, src_lang='en'):
    """ref wmt16.py:validation."""
    if src_lang not in ('en', 'de'):
        raise ValueError("src_lang must be 'en' or 'de'")
    return _reader_creator('val', src_dict_size, trg_dict_size, src_lang,
                           60, 97)


def get_dict(lang, dict_size, reverse=False):
    """ref wmt16.py:get_dict."""
    dict_size = min(dict_size, 10**6)
    return __load_dict(_TAR, dict_size, lang, reverse)


def fetch():
    """ref wmt16.py:fetch — no egress; points at the cache location."""
    from .common import download
    return download('http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz',
                    'wmt16', None)
