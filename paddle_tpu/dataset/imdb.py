"""paddle.dataset.imdb parity (ref: python/paddle/dataset/imdb.py).
build_dict → {word: idx}; train/test readers yield ([word ids], 0|1).
Real aclImdb tarball when cached; deterministic synthetic corpus with a
sentiment-correlated signal word otherwise (so models can actually fit)."""
import collections
import os
import re
import string
import tarfile

from .common import DATA_HOME, WORDS, synthetic_text_corpus, synthetic_warn

__all__ = ['build_dict', 'train', 'test']

_TAR = os.path.join(DATA_HOME, 'imdb', 'aclImdb_v1.tar.gz')


def _synth_docs(is_test):
    """(tokens, label) pairs; 'good'/'bad' marker words carry the label."""
    base = synthetic_text_corpus(WORDS, 400 if not is_test else 100,
                                 11 if not is_test else 12)
    out = []
    for i, sent in enumerate(base):
        label = i % 2
        sent = sent + (['good', 'like'] if label == 0 else ['bad', 'not'])
        out.append((sent, label))
    return out


def tokenize(pattern):
    """ref imdb.py:tokenize — lowercased, punctuation-stripped token
    streams from tar members matching `pattern`."""
    if not os.path.exists(_TAR):
        synthetic_warn('imdb', _TAR)
        is_test = 'test' in pattern.pattern if hasattr(pattern, 'pattern') \
            else 'test' in str(pattern)
        for sent, _ in _synth_docs(is_test):
            yield sent
        return
    pattern = re.compile(pattern) if isinstance(pattern, str) else pattern
    with tarfile.open(_TAR) as tf:
        for m in tf.getmembers():
            if bool(pattern.match(m.name)):
                data = tf.extractfile(m).read().decode('latin-1')
                yield data.translate(
                    str.maketrans('', '', string.punctuation)).lower().split()


def build_dict(pattern, cutoff):
    """ref imdb.py:build_dict — frequency-cutoff vocab + <unk>."""
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] += 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary)) if dictionary else ((), ())
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx['<unk>'] = len(words)
    return word_idx


def _reader_creator(pos_pattern, neg_pattern, word_idx, is_test):
    unk = word_idx['<unk>']

    def reader():
        if not os.path.exists(_TAR):
            for sent, label in _synth_docs(is_test):
                yield [word_idx.get(w, unk) for w in sent], label
            return
        for label, pattern in ((0, pos_pattern), (1, neg_pattern)):
            for doc in tokenize(pattern):
                yield [word_idx.get(w, unk) for w in doc], label
    reader.is_synthetic = not os.path.exists(_TAR)
    return reader


def train(word_idx):
    """ref imdb.py:train — label 0 = positive, 1 = negative."""
    return _reader_creator(
        re.compile(r'aclImdb/train/pos/.*\.txt$'),
        re.compile(r'aclImdb/train/neg/.*\.txt$'), word_idx, False)


def test(word_idx):
    """ref imdb.py:test."""
    return _reader_creator(
        re.compile(r'aclImdb/test/pos/.*\.txt$'),
        re.compile(r'aclImdb/test/neg/.*\.txt$'), word_idx, True)


def word_dict():
    """ref imdb.py:word_dict (used by some ref configs)."""
    return build_dict(re.compile(r'aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$'), 150)
