"""paddle.dataset.conll05 parity (ref: python/paddle/dataset/conll05.py) —
CoNLL-2005 semantic role labeling. Each sample is the 9-feature SRL tuple
(words, 5 predicate-context features, predicate, mark, labels). Real
conll05st files when cached; synthetic tagged sentences otherwise."""
import gzip
import os
import tarfile

import numpy as np

from .common import DATA_HOME, WORDS, synthetic_text_corpus, synthetic_warn

__all__ = ['test', 'get_dict', 'get_embedding']

UNK_IDX = 0

_DIR = os.path.join(DATA_HOME, 'conll05st')
_TAR = os.path.join(_DIR, 'conll05st-tests.tar.gz')
_LABELS = ['B-A0', 'I-A0', 'B-A1', 'I-A1', 'B-A2', 'I-A2', 'B-V', 'O']


def load_dict(filename):
    """ref conll05.py:68 — one token per line → {token: idx}."""
    d = {}
    opener = gzip.open if filename.endswith('.gz') else open
    with opener(filename, 'rt') as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def load_label_dict(filename):
    """ref conll05.py:48 — expand B-/I- prefixed argument labels."""
    d = {}
    tag_dict = set()
    opener = gzip.open if filename.endswith('.gz') else open
    with opener(filename, 'rt') as f:
        for line in f:
            line = line.strip()
            if line.startswith('B-'):
                tag_dict.add(line[2:])
            elif line.startswith('I-'):
                tag_dict.add(line[2:])
    index = 0
    for tag in sorted(tag_dict):
        d['B-' + tag] = index
        index += 1
        d['I-' + tag] = index
        index += 1
    d['O'] = index
    return d


def _synthetic_corpus(seed=61, n=120):
    """(sentence tokens, predicate, labels) triples with one B-V verb."""
    rng = np.random.RandomState(seed)
    out = []
    for sent in synthetic_text_corpus(WORDS, n, seed, min_len=5, max_len=9):
        vi = rng.randint(1, len(sent) - 1)
        labels = []
        for i in range(len(sent)):
            if i == vi:
                labels.append('B-V')
            elif i == vi - 1:
                labels.append('B-A0')
            elif i == vi + 1:
                labels.append('B-A1')
            else:
                labels.append('O')
        out.append((sent, sent[vi], labels))
    return out


def get_dict():
    """ref conll05.py:205 — (word_dict, verb_dict, label_dict)."""
    wd_path = os.path.join(_DIR, 'wordDict.txt')
    vd_path = os.path.join(_DIR, 'verbDict.txt')
    td_path = os.path.join(_DIR, 'targetDict.txt')
    if all(os.path.exists(p) for p in (wd_path, vd_path, td_path)):
        return (load_dict(wd_path), load_dict(vd_path),
                load_label_dict(td_path))
    corpus = _synthetic_corpus()
    words = sorted({w for sent, _, _ in corpus for w in sent}
                   | {'bos', 'eos'})
    verbs = sorted({v for _, v, _ in corpus})
    word_dict = {w: i for i, w in enumerate(words)}
    verb_dict = {v: i for i, v in enumerate(verbs)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """ref conll05.py:218 — path to the pretrained embedding table; a
    deterministic table is generated when the download cache is empty."""
    path = os.path.join(_DIR, 'emb')
    if not os.path.exists(path):
        os.makedirs(_DIR, exist_ok=True)
        word_dict, _, _ = get_dict()
        rng = np.random.RandomState(62)
        emb = rng.uniform(-1, 1, (len(word_dict), 32)).astype('float32')
        np.savetxt(path, emb)
    return path


def corpus_reader(data_path, words_name, props_name):
    """ref conll05.py:76 — yields (sentence, predicate, labels)."""
    if not os.path.exists(data_path):
        synthetic_warn('conll05', data_path)

        def reader():
            yield from _synthetic_corpus()
        return reader

    def reader():
        with tarfile.open(data_path) as tf:
            words = gzip.decompress(
                tf.extractfile(words_name).read()).decode().splitlines()
            props = gzip.decompress(
                tf.extractfile(props_name).read()).decode().splitlines()
        sentence, labels_rows = [], []
        for w, p in zip(words, props):
            w, p = w.strip(), p.strip()
            if w == '':
                cols = list(zip(*labels_rows)) if labels_rows else []
                for col in cols[1:]:
                    lbls, cur = [], None
                    for t in col:
                        if t.startswith('('):
                            cur = t.strip('()*').rstrip(')')
                            lbls.append('B-' + cur)
                            if t.endswith(')'):
                                cur = None
                        elif cur is not None:
                            lbls.append('I-' + cur)
                            if t.endswith(')'):
                                cur = None
                        else:
                            lbls.append('O')
                    if 'B-V' in lbls:
                        verb = sentence[lbls.index('B-V')]
                        yield sentence, verb, lbls
                sentence, labels_rows = [], []
            else:
                sentence.append(w)
                labels_rows.append(p.split())
    return reader


def reader_creator(corpus_reader, word_dict=None, predicate_dict=None,
                   label_dict=None):
    """ref conll05.py:150 — build the 9-feature SRL sample."""

    def reader():
        for sentence, predicate, labels in corpus_reader():
            sen_len = len(sentence)
            if 'B-V' not in labels or predicate not in predicate_dict:
                continue
            verb_index = labels.index('B-V')
            mark = [0] * len(labels)
            ctx_n2 = sentence[verb_index - 2] if verb_index > 1 else 'bos'
            ctx_n1 = sentence[verb_index - 1] if verb_index > 0 else 'bos'
            ctx_0 = sentence[verb_index]
            ctx_p1 = sentence[verb_index + 1] \
                if verb_index < len(labels) - 1 else 'eos'
            ctx_p2 = sentence[verb_index + 2] \
                if verb_index < len(labels) - 2 else 'eos'
            for i in range(max(0, verb_index - 2),
                           min(len(labels), verb_index + 3)):
                mark[i] = 1
            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            ctx = [[word_dict.get(c, UNK_IDX)] * sen_len
                   for c in (ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2)]
            pred_idx = [predicate_dict[predicate]] * sen_len
            label_idx = [label_dict.get(l, label_dict.get('O'))
                         for l in labels]
            yield (word_idx, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4],
                   pred_idx, mark, label_idx)
    return reader


def test():
    """ref conll05.py:225 — the (free) test split used for training."""
    word_dict, verb_dict, label_dict = get_dict()
    reader = corpus_reader(
        _TAR,
        words_name='conll05st-release/test.wsj/words/test.wsj.words.gz',
        props_name='conll05st-release/test.wsj/props/test.wsj.props.gz')
    r = reader_creator(reader, word_dict, verb_dict, label_dict)
    r.is_synthetic = not os.path.exists(_TAR)
    return r
