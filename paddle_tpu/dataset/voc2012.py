"""paddle.dataset.voc2012 parity (ref: python/paddle/dataset/voc2012.py) —
Pascal VOC 2012 segmentation. Yields (CHW float32 image, HW int32 label
mask). Real VOCtrainval tar when cached, synthetic masks otherwise."""
import os
import tarfile

import numpy as np

from .common import DATA_HOME, synthetic_warn
from .image import load_image_bytes

__all__ = ['train', 'test', 'val']

_TAR = os.path.join(DATA_HOME, 'voc2012',
                    'VOCtrainval_11-May-2012.tar')
SET_FILE = 'VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt'
DATA_FILE = 'VOCdevkit/VOC2012/JPEGImages/{}.jpg'
LABEL_FILE = 'VOCdevkit/VOC2012/SegmentationClass/{}.png'


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            img = rng.rand(3, 128, 128).astype('float32')
            lab = rng.randint(0, 21, (128, 128)).astype('int32')
            yield img, lab
    reader.is_synthetic = True
    return reader


def _creator(split, n_synth, seed):
    if not os.path.exists(_TAR):
        synthetic_warn('voc2012', _TAR)
        return _synthetic(n_synth, seed)

    def reader():
        with tarfile.open(_TAR) as tf:
            names = tf.extractfile(SET_FILE.format(split)) \
                .read().decode().split()
            for name in names:
                img = load_image_bytes(
                    tf.extractfile(DATA_FILE.format(name)).read())
                lab = load_image_bytes(
                    tf.extractfile(LABEL_FILE.format(name)).read(),
                    is_color=False)
                yield img.transpose(2, 0, 1).astype('float32'), \
                    lab[..., 0].astype('int32')
    reader.is_synthetic = False
    return reader


def train():
    """ref voc2012.py:train."""
    return _creator('trainval', 128, 81)


def test():
    """ref voc2012.py:test."""
    return _creator('train', 32, 82)


def val():
    """ref voc2012.py:val."""
    return _creator('val', 32, 83)
