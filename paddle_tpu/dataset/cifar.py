"""paddle.dataset.cifar parity (ref: python/paddle/dataset/cifar.py).
Samples are (3072-float32 in [-1,1], int label)."""
import os

from .common import DATA_HOME
from ..datasets import _cifar_reader

__all__ = ['train100', 'test100', 'train10', 'test10']


def _flat(reader_chw):
    def reader():
        for img, lab in reader_chw():
            yield img.reshape(-1), lab
    reader.is_synthetic = getattr(reader_chw, 'is_synthetic', False)
    return reader


def _path(name):
    return os.path.join(DATA_HOME, 'cifar', name)


def train10():
    """ref cifar.py:train10."""
    return _flat(_cifar_reader(_path('cifar-10-python.tar.gz'),
                               'data_batch', b'labels', 1024, 2))


def test10():
    """ref cifar.py:test10."""
    return _flat(_cifar_reader(_path('cifar-10-python.tar.gz'),
                               'test_batch', b'labels', 256, 3))


def train100():
    """ref cifar.py:train100 — fine labels (100 classes)."""
    return _flat(_cifar_reader(_path('cifar-100-python.tar.gz'),
                               'train', b'fine_labels', 1024, 4))


def test100():
    """ref cifar.py:test100."""
    return _flat(_cifar_reader(_path('cifar-100-python.tar.gz'),
                               'test', b'fine_labels', 256, 5))
