"""paddle.dataset.wmt14 parity (ref: python/paddle/dataset/wmt14.py) —
WMT14 en→fr. Readers yield (src ids, trg ids, trg-next ids); get_dict
returns (src_dict, trg_dict) id→word mappings. Real wmt_shrinked_data
tarball when cached, a deterministic parallel toy corpus otherwise."""
import os
import tarfile

import numpy as np

from .common import DATA_HOME, WORDS, synthetic_text_corpus, synthetic_warn

__all__ = ['train', 'test', 'get_dict']

URL_TRAIN = ('http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz')
_TAR = os.path.join(DATA_HOME, 'wmt14', 'wmt14.tgz')

START = '<s>'
END = '<e>'
UNK = '<unk>'
UNK_IDX = 2


def _synth_pairs(n, seed):
    """Parallel 'translation' pairs: target = reversed source (a structure
    a seq2seq model can actually learn)."""
    src = synthetic_text_corpus(WORDS[:30], n, seed, min_len=3, max_len=8)
    return [(s, list(reversed(s))) for s in src]


def _synth_dict(dict_size):
    vocab = [START, END, UNK] + WORDS[:30]
    vocab = vocab[:dict_size] if dict_size > 3 else vocab
    word_to_id = {w: i for i, w in enumerate(vocab)}
    return word_to_id


def _tar_reader_creator(tar_file, file_name, dict_size):
    def reader():
        src_dict, trg_dict = __read_to_dict(tar_file, dict_size)
        with tarfile.open(tar_file) as f:
            names = [n for n in f.getnames() if n.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name).read().decode().splitlines():
                    line_split = line.strip().split('\t')
                    if len(line_split) != 2:
                        continue
                    src_words = line_split[0].split()
                    src_ids = [src_dict.get(START)] + [
                        src_dict.get(w, UNK_IDX) for w in src_words
                    ] + [src_dict.get(END)]
                    trg_words = line_split[1].split()
                    trg_ids = [trg_dict.get(w, UNK_IDX) for w in trg_words]
                    trg_ids_next = trg_ids + [trg_dict.get(END)]
                    trg_ids = [trg_dict.get(START)] + trg_ids
                    yield src_ids, trg_ids, trg_ids_next
    reader.is_synthetic = False
    return reader


def __read_to_dict(tar_file, dict_size):
    def __to_dict(fd, size):
        out_dict = {}
        for line_count, line in enumerate(fd.read().decode().splitlines()):
            if line_count < size:
                out_dict[line.strip()] = line_count
            else:
                break
        return out_dict

    with tarfile.open(tar_file) as f:
        src_name = [n for n in f.getnames() if n.endswith('src.dict')][0]
        trg_name = [n for n in f.getnames() if n.endswith('trg.dict')][0]
        src_dict = __to_dict(f.extractfile(src_name), dict_size)
        trg_dict = __to_dict(f.extractfile(trg_name), dict_size)
    return src_dict, trg_dict


def _synth_reader_creator(n, seed, dict_size):
    def reader():
        d = _synth_dict(dict_size)
        for s, t in _synth_pairs(n, seed):
            src_ids = [d[START]] + [d.get(w, UNK_IDX) for w in s] + [d[END]]
            trg_ids = [d.get(w, UNK_IDX) for w in t]
            yield src_ids, [d[START]] + trg_ids, trg_ids + [d[END]]
    reader.is_synthetic = True
    return reader


def train(dict_size):
    """ref wmt14.py:train."""
    if os.path.exists(_TAR):
        return _tar_reader_creator(_TAR, 'train/train', dict_size)
    synthetic_warn('wmt14', _TAR)
    return _synth_reader_creator(300, 91, dict_size)


def test(dict_size):
    """ref wmt14.py:test."""
    if os.path.exists(_TAR):
        return _tar_reader_creator(_TAR, 'test/test', dict_size)
    synthetic_warn('wmt14', _TAR)
    return _synth_reader_creator(60, 92, dict_size)


def get_dict(dict_size, reverse=True):
    """ref wmt14.py:get_dict — (src, trg) id→word (or word→id when
    reverse=False)."""
    if os.path.exists(_TAR):
        src_dict, trg_dict = __read_to_dict(_TAR, dict_size)
    else:
        src_dict = trg_dict = _synth_dict(dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
