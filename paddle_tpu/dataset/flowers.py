"""paddle.dataset.flowers parity (ref: python/paddle/dataset/flowers.py) —
Oxford 102 flowers. Yields (CHW float32 image, int label). Real
102flowers.tgz + setid.mat/imagelabels.mat when cached (scipy ships in
this image for .mat), synthetic stream otherwise."""
import os
import tarfile

import numpy as np

from .common import DATA_HOME, synthetic_warn
from .image import load_image_bytes, simple_transform

__all__ = ['train', 'test', 'valid']

_DIR = os.path.join(DATA_HOME, 'flowers')
_TAR = os.path.join(_DIR, '102flowers.tgz')
_LABELS = os.path.join(_DIR, 'imagelabels.mat')
_SETID = os.path.join(_DIR, 'setid.mat')


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            yield rng.rand(3, 224, 224).astype('float32'), \
                int(rng.randint(0, 102))
    reader.is_synthetic = True
    return reader


def _real_reader(set_key, mapper=None):
    from scipy.io import loadmat
    labels = loadmat(_LABELS)['labels'][0]
    ids = loadmat(_SETID)[set_key][0]
    id_set = {int(i) for i in ids}

    def reader():
        with tarfile.open(_TAR) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if not base.startswith('image_'):
                    continue
                img_id = int(base[6:11])
                if img_id not in id_set:
                    continue
                data = tf.extractfile(m).read()
                img = load_image_bytes(data)
                img = simple_transform(img, 256, 224, is_train=False)
                yield img.astype('float32'), int(labels[img_id - 1]) - 1
    reader.is_synthetic = False
    return reader


def _creator(set_key, n_synth, seed):
    if all(os.path.exists(p) for p in (_TAR, _LABELS, _SETID)):
        try:
            return _real_reader(set_key)
        except Exception:
            pass
    synthetic_warn('flowers', _TAR)
    return _synthetic(n_synth, seed)


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    """ref flowers.py:train (trnid split)."""
    return _creator('trnid', 256, 71)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    """ref flowers.py:test (tstid split)."""
    return _creator('tstid', 64, 72)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    """ref flowers.py:valid (valid split)."""
    return _creator('valid', 64, 73)
