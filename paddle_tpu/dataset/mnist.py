"""paddle.dataset.mnist parity (ref: python/paddle/dataset/mnist.py).
Samples are (784-float32 in [-1,1], int label); real IDX files when cached
(shared loader with paddle_tpu.datasets), deterministic synthetic stream
otherwise."""
import os

from .common import DATA_HOME
from ..datasets import _mnist_reader

__all__ = ['train', 'test']


def _flat(reader28):
    def reader():
        for img, lab in reader28():
            yield img.reshape(-1), lab
    reader.is_synthetic = getattr(reader28, 'is_synthetic', False)
    return reader


def train():
    """ref mnist.py:train — 784-dim image, label in [0,9]."""
    d = os.path.join(DATA_HOME, 'mnist')
    return _flat(_mnist_reader(
        os.path.join(d, 'train-images-idx3-ubyte.gz'),
        os.path.join(d, 'train-labels-idx1-ubyte.gz'), 1024, 0))


def test():
    """ref mnist.py:test."""
    d = os.path.join(DATA_HOME, 'mnist')
    return _flat(_mnist_reader(
        os.path.join(d, 't10k-images-idx3-ubyte.gz'),
        os.path.join(d, 't10k-labels-idx1-ubyte.gz'), 256, 1))
