"""paddle.dataset.mq2007 parity (ref: python/paddle/dataset/mq2007.py) —
LETOR learning-to-rank data. Query/QueryList containers + pointwise /
pairwise / listwise generators; real Fold files when present, synthetic
ranked lists otherwise."""
import functools
import os
import random

import numpy as np

from .common import DATA_HOME, synthetic_warn

__all__ = ['Query', 'QueryList', 'gen_plain_txt', 'gen_point', 'gen_pair',
           'gen_list', 'query_filter', 'load_from_text', 'train', 'test']

FEATURE_DIM = 46


class Query:
    """ref mq2007.py:50 — one judged (query, document) row."""

    def __init__(self, query_id=-1, relevance_score=-1, feature_vector=None,
                 description=""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = list(feature_vector or [])
        self.description = description

    def __str__(self):
        feats = ' '.join(f'{i + 1}:{v}'
                         for i, v in enumerate(self.feature_vector))
        return f'{self.relevance_score} qid:{self.query_id} {feats}'

    __repr__ = __str__

    def _parse_line(self, raw, fill_missing=-1):
        parts = raw.split('#')[0].strip().split()
        self.relevance_score = int(parts[0])
        self.query_id = int(parts[1].split(':')[1])
        fv = {}
        for tok in parts[2:]:
            k, v = tok.split(':')
            fv[int(k)] = float(v) if v else fill_missing
        self.feature_vector = [fv.get(i + 1, fill_missing)
                               for i in range(max(fv) if fv else 0)]
        return self


class QueryList:
    """ref mq2007.py:106 — all judged docs of one query id."""

    def __init__(self, querylist=None):
        self.query_list = list(querylist or [])

    def __iter__(self):
        return iter(self.query_list)

    def __len__(self):
        return len(self.query_list)

    def __getitem__(self, i):
        return self.query_list[i]

    def _correct_ranking_(self):
        self.query_list.sort(key=lambda q: -q.relevance_score)

    def _add_query(self, query):
        self.query_list.append(query)


def load_from_text(filepath, shuffle=False, fill_missing=-1):
    """ref mq2007.py:269 — parse a LETOR text file into QueryLists."""
    lists = {}
    with open(filepath) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            q = Query()._parse_line(line, fill_missing)
            lists.setdefault(q.query_id, QueryList())._add_query(q)
    out = list(lists.values())
    if shuffle:
        random.shuffle(out)
    return out


def query_filter(querylists):
    """ref mq2007.py:251 — drop queries whose docs all share one score."""
    out = []
    for ql in querylists:
        scores = {q.relevance_score for q in ql}
        if len(scores) > 1:
            out.append(ql)
    return out


def gen_plain_txt(querylist):
    """ref mq2007.py:148 — (query_id, score, feature_vector) rows."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    for q in querylist:
        yield q.query_id, q.relevance_score, np.array(q.feature_vector)


def gen_point(querylist):
    """ref mq2007.py:169 — pointwise (score, feature_vector)."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    for q in querylist:
        yield q.relevance_score, np.array(q.feature_vector)


def gen_pair(querylist, partial_order='full'):
    """ref mq2007.py:188 — pairwise (1, better_vec, worse_vec)."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    qs = sorted(querylist, key=lambda q: -q.relevance_score)
    for i, a in enumerate(qs):
        for b in qs[i + 1:]:
            if a.relevance_score > b.relevance_score:
                yield 1, np.array(a.feature_vector), \
                    np.array(b.feature_vector)


def gen_list(querylist):
    """ref mq2007.py:231 — listwise (all scores, all feature vectors)."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    labels = [q.relevance_score for q in querylist]
    features = [q.feature_vector for q in querylist]
    yield np.array(labels), np.array(features)


def _synthetic_querylists(n_queries, seed):
    rng = np.random.RandomState(seed)
    out = []
    for qid in range(n_queries):
        ql = QueryList()
        for _ in range(rng.randint(4, 10)):
            ql._add_query(Query(qid, int(rng.randint(0, 3)),
                                rng.rand(FEATURE_DIM).tolist()))
        out.append(ql)
    return out


def __reader__(filepath, format='pairwise', shuffle=False, fill_missing=-1):
    """ref mq2007.py:294."""
    if os.path.exists(filepath):
        querylists = query_filter(
            load_from_text(filepath, shuffle=shuffle,
                           fill_missing=fill_missing))
    else:
        synthetic_warn('mq2007', filepath)
        querylists = query_filter(_synthetic_querylists(
            50, 51 if 'train' in filepath else 52))
    for querylist in querylists:
        if format == 'plain_txt':
            yield next(gen_plain_txt(querylist))
        elif format == 'pointwise':
            yield next(gen_point(querylist))
        elif format == 'pairwise':
            yield from gen_pair(querylist)
        elif format == 'listwise':
            yield next(gen_list(querylist))


train = functools.partial(
    __reader__,
    filepath=os.path.join(DATA_HOME, 'MQ2007', 'MQ2007', 'Fold1',
                          'train.txt'))
test = functools.partial(
    __reader__,
    filepath=os.path.join(DATA_HOME, 'MQ2007', 'MQ2007', 'Fold1',
                          'test.txt'))
