"""paddle.dataset.common parity (ref: python/paddle/dataset/common.py):
DATA_HOME, download, md5file, split, cluster_files_reader.

This environment has no network egress, so `download` resolves against the
local cache (DATA_HOME, same layout as the reference) and raises a clear
error when the file is absent instead of fetching.
"""
import glob
import hashlib
import os
import pickle

import numpy as np

__all__ = ['DATA_HOME', 'download', 'md5file', 'split',
           'cluster_files_reader']

DATA_HOME = os.environ.get('PADDLE_TPU_DATA_HOME',
                           os.path.expanduser('~/.cache/paddle/dataset'))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


def md5file(fname):
    """ref common.py:md5file."""
    hash_md5 = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """ref common.py:download — here: locate the file in the local cache
    (~/.cache/paddle/dataset/<module_name>/<filename>); no egress."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, url.split('/')[-1] if save_name is None else save_name)
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise IOError(
                f'{filename} exists but its md5 does not match {md5sum}; '
                'delete the corrupt file and re-stage it')
        return filename
    raise IOError(
        f'dataset file for {url} not found at {filename} and this '
        'environment has no network egress; stage the file there manually '
        '(or rely on the dataset module\'s synthetic fallback readers)')


def split(reader, line_count, suffix='%05d.pickle', dumper=pickle.dump):
    """ref common.py:split — chunk a reader into pickled files."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if (i + 1) % line_count == 0:
            with open(suffix % indx_f, 'wb') as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, 'wb') as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """ref common.py:cluster_files_reader — round-robin shard of pickled
    chunk files across trainers."""

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [f for i, f in enumerate(file_list)
                    if i % trainer_count == trainer_id]
        for fn in my_files:
            with open(fn, 'rb') as f:
                for line in loader(f):
                    yield line
    return reader


# shared synthetic-corpus helpers for the zero-egress fallbacks ------------

def synthetic_warn(module, missing):
    import logging
    logging.getLogger('paddle_tpu.dataset').warning(
        'paddle_tpu.dataset.%s: cache files missing (%s) — serving a '
        'deterministic SYNTHETIC corpus (reader.is_synthetic=True). '
        'Accuracy numbers are meaningless; stage real files under %s.',
        module, missing, DATA_HOME)


def synthetic_text_corpus(vocab, n_sentences, seed, min_len=3, max_len=12):
    """Deterministic fake sentences over `vocab` (a list of words) — used
    by the text datasets so build_dict/train/test stay mutually
    consistent."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_sentences):
        n = rng.randint(min_len, max_len + 1)
        out.append([vocab[j] for j in rng.randint(0, len(vocab), n)])
    return out


WORDS = [
    'the', 'of', 'and', 'a', 'to', 'in', 'is', 'you', 'that', 'it', 'he',
    'was', 'for', 'on', 'are', 'as', 'with', 'his', 'they', 'I', 'at',
    'be', 'this', 'have', 'from', 'or', 'one', 'had', 'by', 'word', 'but',
    'not', 'what', 'all', 'were', 'we', 'when', 'your', 'can', 'said',
    'there', 'use', 'an', 'each', 'which', 'she', 'do', 'how', 'their',
    'if', 'will', 'up', 'other', 'about', 'out', 'many', 'then', 'them',
    'these', 'so', 'some', 'her', 'would', 'make', 'like', 'him', 'into',
    'time', 'has', 'look', 'two', 'more', 'write', 'go', 'see', 'number',
    'no', 'way', 'could', 'people', 'my', 'than', 'first', 'water', 'been',
    'call', 'who', 'oil', 'its', 'now', 'find', 'long', 'down', 'day',
    'did', 'get', 'come', 'made', 'may', 'part']
