"""paddle.dataset.image parity (ref: python/paddle/dataset/image.py).

The reference shells into cv2 for decode/resize; this build is
numpy-native: .npy/.npz images load directly, raw encoded bytes decode via
PIL when available (torch ships it in this image), and the geometric
transforms (resize_short, crops, flips, CHW) are pure numpy, so the
augmentation pipeline runs anywhere without an OpenCV dependency.
"""
import tarfile

import numpy as np

__all__ = ['load_image_bytes', 'load_image', 'resize_short', 'to_chw',
           'center_crop', 'random_crop', 'left_right_flip',
           'simple_transform', 'load_and_transform', 'batch_images_from_tar']


def _decode_bytes(data, is_color):
    import io
    try:
        from PIL import Image
    except ImportError:
        raise RuntimeError(
            'decoding encoded image bytes needs PIL, which is unavailable; '
            'pre-decode to .npy arrays instead')
    img = Image.open(io.BytesIO(data))
    img = img.convert('RGB' if is_color else 'L')
    arr = np.asarray(img)
    return arr if is_color else arr[..., None]


def load_image_bytes(data, is_color=True):
    """ref image.py:load_image_bytes — decode encoded bytes to HWC uint8."""
    return _decode_bytes(data, is_color)


def load_image(file, is_color=True):
    """ref image.py:load_image — load from file (.npy/.npz or encoded)."""
    if file.endswith('.npy'):
        arr = np.load(file)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr
    with open(file, 'rb') as f:
        return _decode_bytes(f.read(), is_color)


def _resize_bilinear(img, h, w):
    """Pure-numpy bilinear resize of an HWC array."""
    H, W = img.shape[:2]
    if (H, W) == (h, w):
        return img
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out


def resize_short(im, size):
    """ref image.py:resize_short — scale so the short side equals size."""
    h, w = im.shape[:2]
    if h > w:
        h = int(round(h * size / w))
        w = size
    else:
        w = int(round(w * size / h))
        h = size
    return _resize_bilinear(im, h, w)


def to_chw(im, order=(2, 0, 1)):
    """ref image.py:to_chw."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """ref image.py:center_crop."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    """ref image.py:random_crop."""
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    """ref image.py:left_right_flip."""
    return im[:, ::-1, :] if im.ndim == 3 else im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """ref image.py:simple_transform — resize-short, crop (+flip when
    training), CHW, mean-subtract."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """ref image.py:load_and_transform."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """ref image.py:batch_images_from_tar — pre-batch tar members into
    pickled (data, label) block files; returns the meta file path."""
    import os
    import pickle
    out_path = f'{data_file}_{dataset_name}_batch'
    meta = os.path.join(out_path, 'batch_meta')
    if os.path.exists(meta):
        return meta
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id, names = [], [], 0, []
    with tarfile.open(data_file) as tf:
        for m in tf.getmembers():
            if m.name not in img2label:
                continue
            data.append(tf.extractfile(m).read())
            labels.append(img2label[m.name])
            if len(data) == num_per_batch:
                name = os.path.join(out_path, f'batch_{file_id}')
                with open(name, 'wb') as f:
                    pickle.dump({'data': data, 'label': labels}, f,
                                protocol=2)
                names.append(name)
                data, labels, file_id = [], [], file_id + 1
    if data:
        name = os.path.join(out_path, f'batch_{file_id}')
        with open(name, 'wb') as f:
            pickle.dump({'data': data, 'label': labels}, f, protocol=2)
        names.append(name)
    with open(meta, 'w') as f:
        f.write('\n'.join(names))
    return meta
