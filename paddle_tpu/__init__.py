"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: /root/reference, Sand3r-/Paddle).

Not a port: the static-graph Program lowers to ONE jitted XLA computation
(executor.py), dygraph records a jax.vjp tape, distribution is jax.sharding
meshes + XLA collectives over ICI. See SURVEY.md for the design map.

The `paddle_tpu.fluid` alias mirrors `paddle.fluid` so reference training
scripts map 1:1.
"""
from . import core
from .core import (CPUPlace, TPUPlace, CUDAPlace, XLAPlace, CUDAPinnedPlace,
                   cuda_places, cpu_places, tpu_places, is_compiled_with_cuda,
                   Scope, global_scope, scope_guard)
from .core import unique_name
from .core.random import seed
from . import framework
from .core.lod import (LoDTensor, create_lod_tensor,
                       create_random_int_lodtensor)
from .core.places import cuda_pinned_places
from .framework import (name_scope, device_guard, load_op_library,
                        require_version)
from .framework import (Program, Variable, default_main_program,
                        default_startup_program, program_guard,
                        in_dygraph_mode, manual_seed)
from . import ops
from . import initializer
from .param_attr import ParamAttr, WeightNormParamAttr
from . import layers
from .layers.io import fluid_data as data
from . import regularizer
from . import clip
from .backward import append_backward, gradients
from . import optimizer
from .executor import Executor
from .core.fetch_handle import FetchHandle
from . import metrics
from . import nets
from .compiler import CompiledProgram
from .parallel_executor import ParallelExecutor
from . import dygraph
from .dygraph.base import enable_dygraph, disable_dygraph, enabled
from . import io
from .io import (save_params, save_persistables, load_params, load_persistables,
                 save_inference_model, load_inference_model, save_dygraph,
                 load_dygraph, save, load, load_program_state,
                 set_program_state)
from . import reader
from .reader import DataLoader
from .data_feeder import DataFeeder
from . import partition
from . import parallel
from . import distributed
from . import contrib
from . import observability
from . import serving
from . import resilience
from . import analysis
from . import profiler
from . import debugger
from . import log_helper
from . import annotations
from . import average
from . import evaluator
from . import install_check
from . import dygraph_grad_clip
from . import input
from . import default_scope_funcs
from . import op
from . import net_drawer
from . import data_feed_desc
from .data_feed_desc import DataFeedDesc
from . import communicator
from .communicator import Communicator
from . import device_worker
from . import trainer_desc
from . import trainer_factory
from . import distribute_lookup_table
from . import dataset
from .dataset import (DatasetFactory, InMemoryDataset, QueueDataset)
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from . import incubate
from . import utils

# `import paddle_tpu.fluid as fluid` parity: fluid IS this module's namespace.
import sys as _sys
fluid = _sys.modules[__name__]
_sys.modules[__name__ + '.fluid'] = fluid

__version__ = '1.7.0'  # fluid API level this framework tracks (scripts gate on it)


# fluid.install_check is the module imported above (run_check lives there,
# delegating to debugging.install_check's tiny train-step self-test)
