"""Weighted running average (ref: python/paddle/fluid/average.py:40)."""
import numpy as np

__all__ = ['WeightedAverage']


def _is_number_or_matrix(x):
    return isinstance(x, (int, float, np.ndarray)) or np.isscalar(x)


class WeightedAverage:
    """Accumulate `add(value, weight)` pairs; `eval()` returns the
    weighted mean (ref average.py:40)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            value = np.asarray(value)
        if not np.isscalar(weight) and not isinstance(weight, (int, float)):
            raise ValueError('weight must be a number')
        self.numerator += np.mean(value) * weight
        self.denominator += weight

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                'there is no data to be averaged in WeightedAverage')
        return self.numerator / self.denominator
