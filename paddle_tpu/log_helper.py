"""ref: python/paddle/fluid/log_helper.py — per-module logger that does not
touch logging.basicConfig (so importing the framework never hijacks the
application's logging setup)."""
from __future__ import annotations

import logging

__all__ = ['get_logger']


def get_logger(name, level, fmt=None):
    """Logger with its own handler/level, basicConfig untouched."""
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:     # idempotent: repeat calls add no handlers
        handler = logging.StreamHandler()
        if fmt:
            handler.setFormatter(logging.Formatter(fmt=fmt))
        logger.addHandler(handler)
    logger.propagate = False
    return logger
