"""Failure detection: NaN/Inf checks + error clip + env report (SURVEY
§2.11).

Parity target: the reference's check_nan_inf machinery
(paddle/fluid/framework/details/nan_inf_utils*, FLAGS_check_nan_inf) and
fluid's debugger/device report. On TPU the check compiles INTO the step
(jnp.isfinite reductions are nearly free next to the matmuls) instead of
the reference's post-kernel host scans; jax's native debug_nans is also
wired through for eager paths.
"""
from __future__ import annotations

import logging
import os

import numpy as np
import jax
import jax.numpy as jnp

from .log_helper import get_logger

_logger = get_logger(__name__, logging.INFO,
                     fmt='%(asctime)s-%(levelname)s: %(message)s')

_check_enabled = os.environ.get('FLAGS_check_nan_inf', '0') not in ('0', '')


def enable_check_nan_inf(enable=True):
    """Also enables jax_debug_nans so eager/dygraph ops raise at the
    producing op, like the reference's per-op scan. The instrumented
    Executor additionally scans fetched values each step and reports
    detections as the `nonfinite_detections` telemetry counter plus an
    `executor/check_nan_inf` trace span (docs/OBSERVABILITY.md).

    Interaction with the async pipeline (PADDLE_TPU_ASYNC /
    num_inflight_steps / TrainStep(async_fetch=True)): a per-step host
    scan would force a device→host sync each step and silently
    re-serialize the pipelined loop, so in async mode the scan runs at
    FetchHandle MATERIALIZATION time instead — the raise surfaces where
    the value is first read (up to K steps after the producing dispatch),
    and the `nonfinite_detections` counter still increments per detection.
    `jax_debug_nans` remains step-accurate in either mode (it raises from
    inside the computation). Set PADDLE_TPU_ASYNC=0 to pin the per-step
    fetch scan while hunting a NaN.

    A supervised loop (resilience/supervisor.py) rides the same machinery:
    the supervisor materializes the loss it judges, ABSORBS the
    FloatingPointError a check_nan-armed handle raises, and converts it
    into a non-finite detection handled by the configured skip/rollback
    policy instead of a dead run."""
    global _check_enabled
    _check_enabled = enable
    jax.config.update('jax_debug_nans', bool(enable))


def check_nan_inf_enabled():
    return _check_enabled


def nonfinite_summary(value):
    """→ ``{'nan': n, 'inf': n, 'size': n}`` for a host array, or None when
    every element is finite (or the dtype is non-float). The shared
    detection primitive behind :func:`check_numerics`, the executor's fetch
    scan, and the supervisor's quarantine records."""
    arr = np.asarray(value)
    if arr.dtype.kind != 'f' or np.isfinite(arr).all():
        return None
    return {'nan': int(np.isnan(arr).sum()),
            'inf': int(np.isinf(arr).sum()),
            'size': int(arr.size)}


def check_numerics(value, name='tensor'):
    """Raise if `value` (array or pytree) has NaN/Inf. Usable on fetched
    numpy results or inside eager code."""
    bad = []

    def visit(path, v):
        arr = np.asarray(v)
        summary = nonfinite_summary(arr)
        if summary is not None:
            bad.append(f"{path}: {summary['nan']} NaN, {summary['inf']} Inf "
                       f"(shape {arr.shape})")

    leaves = jax.tree_util.tree_leaves_with_path(value) \
        if not hasattr(value, 'shape') else [((name,), value)]
    for path, v in leaves:
        visit('/'.join(str(p) for p in path) or name, v)
    if bad:
        raise FloatingPointError(
            f"check_nan_inf: non-finite values in {name}:\n  "
            + "\n  ".join(bad))
    return value


def assert_all_finite(x, message='tensor'):
    """In-graph check: poisons the whole tensor to NaN when any value is
    non-finite so the failure is unmissable on fetch (branchless)."""
    finite = jnp.all(jnp.isfinite(x))
    return jnp.where(finite, x, jnp.full_like(x, jnp.nan))


def device_report():
    """Environment/device summary (ref: fluid's install-time env report)."""
    lines = [
        f"jax {jax.__version__}, backend {jax.default_backend()}",
        f"devices: {[str(d) for d in jax.devices()]}",
        f"process {jax.process_index()}/{jax.process_count()}",
        f"x64: {jax.config.read('jax_enable_x64')}",
    ]
    return '\n'.join(lines)


def install_check():
    """Self-test (ref: fluid.install_check.run_check): build and run one
    tiny train step end to end on the active backend."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        l0, = exe.run(main, feed={'x': np.ones((8, 4), 'float32'),
                                  'y': np.zeros((8, 1), 'float32')},
                      fetch_list=[loss])
        check_numerics(l0, 'install_check loss')
    _logger.info('paddle_tpu install check passed — %s',
                 device_report().split('\n')[0])
    return True
