"""Pod-scale fleet runtime: multi-host bring-up, cross-host primitives,
partitioner-sharded checkpoints, and fleet-wide resilience (ROADMAP item 2;
docs/DISTRIBUTED.md "Multi-host runtime", docs/RESILIENCE.md "Fleet").

Every other subsystem stays single-process-correct; this package is the
layer that turns one process into one *host* of a fleet:

- :mod:`bootstrap` — strict-parse fleet-env discovery
  (``PADDLE_TRAINERS_NUM`` / ``PADDLE_TRAINER_ID`` / endpoints),
  ``jax.distributed`` bring-up wired into the Partitioner's mesh, a
  ``local_fleet(nproc)`` subprocess spawner for tests/benches, and the
  cross-host primitive set (``fleet_barrier`` / ``broadcast_from_host0``
  / ``all_hosts_agree``).
- :mod:`coordinator` — the coordinator KV store (jax.distributed client,
  shared-directory fallback) and the :class:`FleetSentinel` poison flag
  that propagates one host's failure fleet-wide.
- :mod:`sharded_ckpt` — per-host checkpoint shards keyed by the
  partitioner's spec manifest: each host persists only the tiles it owns,
  host 0 commits the fleet manifest last, restore validates every shard
  and reassembles (resharding when the mesh changed).
"""
from .bootstrap import (FleetSpec, discover_fleet_env, bootstrap,
                        process_index, process_count, is_host0,
                        local_fleet, LocalFleet, fleet_barrier,
                        broadcast_from_host0, all_hosts_agree,
                        fleet_allreduce_scalars)
from .coordinator import (FleetSentinel, FleetPoisoned, FLEET_EXIT_CODE,
                          kv_set, kv_get, kv_dir, active_sentinel,
                          install_sentinel, clear_sentinel, check_poisoned,
                          exit_for_resume)
from .sharded_ckpt import (write_host_shard, commit_fleet_manifest,
                           read_sharded_checkpoint, owned_tiles,
                           sharded_save_enabled)

__all__ = [
    'FleetSpec', 'discover_fleet_env', 'bootstrap', 'process_index',
    'process_count', 'is_host0', 'local_fleet', 'LocalFleet',
    'fleet_barrier', 'broadcast_from_host0', 'all_hosts_agree',
    'fleet_allreduce_scalars',
    'FleetSentinel', 'FleetPoisoned', 'FLEET_EXIT_CODE', 'kv_set',
    'kv_get', 'kv_dir', 'active_sentinel', 'install_sentinel',
    'clear_sentinel', 'check_poisoned', 'exit_for_resume',
    'write_host_shard', 'commit_fleet_manifest', 'read_sharded_checkpoint',
    'owned_tiles', 'sharded_save_enabled',
]
