"""Partitioner-sharded checkpoints: each host persists only the tiles it
owns; host 0 commits the fleet manifest last.

Layout for step 42 on a 2-host fleet (inside the shared checkpoint dir)::

    ckpt-00000042.shard00of02.npz    host 0's owned tiles
    ckpt-00000042.shard00of02.json   shard manifest (bytes+CRC, tile index
                                     map, host-local meta) — committed
                                     AFTER its payload
    ckpt-00000042.shard01of02.npz    host 1's owned tiles
    ckpt-00000042.shard01of02.json
    ckpt-00000042.json               FLEET manifest — committed LAST by
                                     host 0, after the coordinator-KV
                                     shard-commit barrier

The fleet manifest is the one global commit marker: discovery
(:func:`~paddle_tpu.resilience.snapshot.list_checkpoints`) validates every
listed shard (existence, byte size, CRC32) before a fleet checkpoint is
eligible — a host that died mid-shard-write, or a torn shard file, makes
the WHOLE checkpoint invisible (skipped with a logged warning), exactly
like a torn single-host payload. ``kill -9`` at any instant on any host
leaves either a fully committed fleet checkpoint or an older one.

**Ownership** is derived from the arrays' actual shardings, not re-derived
from rules (the partitioner's spec manifest is recorded alongside for
reshard validation): for every tile index of
``sharding.devices_indices_map``, the owner is the LOWEST process index
holding a replica. So fsdp/tp tiles land exactly once across the fleet
(Σ shard bytes ≈ state bytes, not p× state bytes) and replicated
variables are saved by host 0 only. Host-local numpy (RNG states, step
counters) is host-0-owned unless passed through ``host_meta``.

**Restore** reassembles every variable to its FULL global value from the
tiles across all shard files — which makes reshard-on-restore free: the
restored full array is simply re-placed under whatever mesh the NEW fleet
configured (the spec manifest travels in the fleet manifest so callers can
check/compare). The cross-host shard-COMMIT barrier runs through the
coordinator KV store — never through device collectives — so the
background writer thread can commit while the main thread keeps
dispatching steps.
"""
from __future__ import annotations

import json
import logging
import os
import time
import zlib

import numpy as np
import jax

from ..log_helper import get_logger
from ..resilience import snapshot as _snap

__all__ = ['owned_tiles', 'materialize_owned', 'write_host_shard',
           'commit_fleet_manifest', 'wait_for_shards',
           'read_sharded_checkpoint', 'sharded_save_enabled',
           'shard_name', 'ENV_FORCE_SHARDED', 'ENV_COMMIT_TIMEOUT']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [fleet] %(message)s')

ENV_FORCE_SHARDED = 'PADDLE_TPU_FLEET_SHARDED'
ENV_COMMIT_TIMEOUT = 'PADDLE_TPU_FLEET_CKPT_TIMEOUT_S'

_KV_PREFIX = 'paddle_tpu/ckpt/'


def sharded_save_enabled():
    """Sharded per-host saves are on for real multi-process fleets, or
    when forced via ``PADDLE_TPU_FLEET_SHARDED=1`` (single-process
    multi-device meshes — how tier-1 exercises the tile layout). Strict
    parse: values outside {'', '0', '1'} raise."""
    raw = os.environ.get(ENV_FORCE_SHARDED, '').strip()
    if raw not in ('', '0', '1'):
        raise ValueError(
            f'{ENV_FORCE_SHARDED} must be 0 or 1, got {raw!r}')
    if raw == '1':
        return True
    return jax.process_count() > 1


def shard_name(step, rank, world, ext):
    return f'ckpt-{int(step):08d}.shard{rank:02d}of{world:02d}.{ext}'


def _norm_index(index, shape):
    """Tile index (tuple of slices) → JSON-safe [[start, stop], ...]."""
    out = []
    for s, dim in zip(index, shape):
        start = 0 if s.start is None else int(s.start)
        stop = int(dim) if s.stop is None else int(s.stop)
        out.append([start, stop])
    return out


def _device_value(value):
    """Unwrap FetchHandles → the on-device array (no host copy, and no
    np.asarray — which would throw on a non-fully-addressable global
    array)."""
    if hasattr(value, 'device_array'):        # FetchHandle
        return value.device_array()
    return value


def owned_tiles(value, rank=None):
    """→ list of ``(index_norm, np.ndarray)`` tiles of `value` that THIS
    process owns (owner = lowest process index holding the tile). Host
    numpy / scalars / fully-replicated arrays are one full tile owned by
    host 0."""
    rank = jax.process_index() if rank is None else int(rank)
    value = _device_value(value)
    shardingless = not hasattr(value, 'sharding') \
        or not hasattr(value, 'addressable_shards')
    if shardingless:
        if rank == 0:
            arr = np.asarray(value)
            return [(_norm_index((slice(None),) * arr.ndim, arr.shape),
                     arr)]
        return []
    index_owner = {}
    for dev, idx in value.sharding.devices_indices_map(
            value.shape).items():
        key = tuple(map(tuple, _norm_index(idx, value.shape)))
        p = dev.process_index
        if key not in index_owner or p < index_owner[key]:
            index_owner[key] = p
    tiles, seen = [], set()
    for shard in value.addressable_shards:
        norm = _norm_index(shard.index, value.shape)
        key = tuple(map(tuple, norm))
        if key in seen or index_owner.get(key) != rank:
            continue
        seen.add(key)
        tiles.append((norm, np.asarray(shard.data)))
    return tiles


def materialize_owned(arrays, rank=None):
    """{key: array|FetchHandle} → ({npz_key: np tile}, tile manifest).
    The device→host copy happens here, per owned tile — on the writer
    thread, overlapped with the main thread's next steps."""
    stored, manifest = {}, {}
    for key, value in arrays.items():
        dev = _device_value(value)
        shape = tuple(int(d) for d in np.shape(dev))
        dtype = str(np.dtype(getattr(dev, 'dtype', np.float64)))
        tiles = owned_tiles(dev, rank=rank)
        if not tiles and not shape:
            continue
        recs = []
        for i, (index, tile) in enumerate(tiles):
            npz_key = f'{key}::t{i}'
            stored_dtype = str(tile.dtype)
            if tile.dtype.kind not in _snap._SAVEZ_KINDS:
                tile = tile.astype(np.float32)   # exact widening (bf16 &co)
                stored_dtype = 'float32'
            stored[npz_key] = tile
            recs.append({'npz': npz_key, 'index': index,
                         'stored_dtype': stored_dtype})
        manifest[key] = {'global_shape': list(shape), 'dtype': dtype,
                         'tiles': recs}
    return stored, manifest


def write_host_shard(directory, step, arrays, host_meta=None, rank=None,
                     world=None):
    """Materialize this host's owned tiles and commit its shard (payload
    npz, then shard manifest — both atomic). Announces the commit on the
    coordinator KV store and returns the shard manifest dict."""
    import io as _io
    rank = jax.process_index() if rank is None else int(rank)
    world = jax.process_count() if world is None else int(world)
    os.makedirs(directory, exist_ok=True)
    stored, tile_manifest = materialize_owned(arrays, rank=rank)
    buf = _io.BytesIO()
    # in-memory serialize; the bytes land via atomic_write_bytes below
    # (temp+fsync+os.replace — the PR 7 commit protocol)
    np.savez(buf, **stored)      # lint: allow-io (BytesIO, committed atomically)
    payload = buf.getvalue()
    payload_name = shard_name(step, rank, world, 'npz')
    _snap.atomic_write_bytes(os.path.join(directory, payload_name), payload)
    manifest = {
        'format': _snap.FORMAT_VERSION,
        'step': int(step), 'rank': rank, 'world': world,
        'payload': payload_name,
        'payload_bytes': len(payload),
        'payload_crc32': zlib.crc32(payload) & 0xFFFFFFFF,
        'arrays': tile_manifest,
        'host_meta': dict(host_meta or {}),
    }
    _snap.atomic_write_bytes(
        os.path.join(directory, shard_name(step, rank, world, 'json')),
        json.dumps(manifest, indent=1).encode())
    from .coordinator import kv_set
    kv_set(f'{_KV_PREFIX}{int(step)}/{rank}',
           json.dumps({'rank': rank, 'bytes': len(payload),
                       'crc32': manifest['payload_crc32']}))
    return manifest


def _commit_timeout():
    raw = os.environ.get(ENV_COMMIT_TIMEOUT, '').strip()
    if not raw:
        return 600.0
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f'{ENV_COMMIT_TIMEOUT} must be a number, got {raw!r}')


def wait_for_shards(directory, step, world, timeout_s=None, poll_s=0.05):
    """Host 0's shard-commit barrier: poll the coordinator KV (file
    fallback: the shard manifests themselves) until every rank announced
    its shard for `step`. Runs on the WRITER thread — KV RPCs and stat
    calls only, never device collectives."""
    from .coordinator import kv_dir
    timeout_s = _commit_timeout() if timeout_s is None else timeout_s
    deadline = time.monotonic() + timeout_s
    want = set(range(world))
    while True:
        have = set()
        for key in kv_dir(f'{_KV_PREFIX}{int(step)}/'):
            try:
                have.add(int(key.rsplit('/', 1)[-1]))
            except ValueError:
                pass
        for r in want - have:          # file-system fallback / restarts
            if os.path.isfile(os.path.join(
                    directory, shard_name(step, r, world, 'json'))):
                have.add(r)
        if want <= have:
            return
        if time.monotonic() >= deadline:
            raise OSError(
                f'fleet checkpoint step {step}: shard-commit barrier '
                f'timed out after {timeout_s:.0f}s (have ranks '
                f'{sorted(have)} of {world})')
        time.sleep(poll_s)


def commit_fleet_manifest(directory, step, world, meta=None,
                          saved_unix_time=None, wait=True):
    """Host 0 only: after every shard committed (KV barrier), validate
    the shard manifests and write the FLEET manifest — the atomic global
    commit marker discovery keys on. Returns a
    :class:`~paddle_tpu.resilience.snapshot.Checkpoint`."""
    if wait:
        wait_for_shards(directory, step, world)
    shards, keys = [], set()
    for r in range(world):
        mname = shard_name(step, r, world, 'json')
        with open(os.path.join(directory, mname)) as f:
            sm = json.load(f)
        shards.append({'manifest': mname, 'payload': sm['payload'],
                       'payload_bytes': sm['payload_bytes'],
                       'payload_crc32': sm['payload_crc32'],
                       'rank': r})
        keys.update(sm['arrays'])
    manifest = {
        'format': _snap.FORMAT_VERSION,
        'step': int(step),
        'sharded': True,
        'world': int(world),
        'shards': shards,
        'keys': sorted(keys),
        'saved_unix_time': saved_unix_time,
        'meta': dict(meta or {}),
    }
    _snap.atomic_write_bytes(
        os.path.join(directory, f'ckpt-{int(step):08d}.json'),
        json.dumps(manifest, indent=1).encode())
    return _snap.Checkpoint(step, directory, manifest)


def read_sharded_checkpoint(ckpt):
    """Fleet checkpoint → ``(arrays, meta)`` with every variable
    reassembled to its FULL global value from the tiles across all shard
    files (validated against the fleet manifest by discovery already).
    ``meta['host_meta']`` maps rank → that host's local meta (RNG,
    loader cursor); the restoring manager overlays its own rank's entry.
    Because full values come back, the read itself is mesh-agnostic —
    inspection tooling can read any checkpoint from any process.
    Restoring onto a DIFFERENT mesh shape (reshard-on-restore) is a
    property of the RESTORE path: ``CheckpointManager.restore`` runs the
    reshard-manifest legality check (``elastic/reshard.py``) against the
    restoring fleet's mesh up front, and the new placement then happens
    wherever the state is next consumed."""
    directory = ckpt.directory
    manifest = ckpt.manifest
    specs = {}          # key -> (shape, dtype)
    pieces = {}         # key -> list[(index, np tile)]
    host_meta = {}
    for sh in manifest['shards']:
        with open(os.path.join(directory, sh['manifest'])) as f:
            sm = json.load(f)
        host_meta[str(sm.get('rank', 0))] = sm.get('host_meta', {})
        with np.load(os.path.join(directory, sm['payload'])) as data:
            for key, rec in sm['arrays'].items():
                shape = tuple(rec['global_shape'])
                prev = specs.get(key)
                if prev is not None and prev != (shape, rec['dtype']):
                    raise ValueError(
                        f'fleet checkpoint step {ckpt.step}: {key!r} '
                        f'declared as {prev} and '
                        f'{(shape, rec["dtype"])} in different shards')
                specs[key] = (shape, rec['dtype'])
                for t in rec['tiles']:
                    tile = data[t['npz']]
                    if t['stored_dtype'] != rec['dtype']:
                        import ml_dtypes  # noqa: F401 — registers bf16
                        tile = tile.astype(np.dtype(rec['dtype']))
                    pieces.setdefault(key, []).append((t['index'], tile))
    arrays = {}
    for key, (shape, dtype) in specs.items():
        tiles = pieces.get(key, [])
        if len(tiles) == 1 and all(
                (a, b) == (0, d) for (a, b), d in zip(tiles[0][0], shape)):
            arrays[key] = tiles[0][1]
            continue
        full = np.empty(shape, np.dtype(dtype))
        covered = 0
        for index, tile in tiles:
            sl = tuple(slice(a, b) for a, b in index)
            full[sl] = tile
            covered += int(tile.size)
        if covered != int(np.prod(shape, dtype=np.int64)):
            raise ValueError(
                f'fleet checkpoint step {ckpt.step}: {key!r} tiles cover '
                f'{covered} of {int(np.prod(shape, dtype=np.int64))} '
                f'elements (shard set incomplete?)')
        arrays[key] = full
    meta = dict(ckpt.meta)
    meta['host_meta'] = host_meta
    return arrays, meta
