"""Coordinator KV store + the fleet poison flag (FleetSentinel).

The TPU-supercomputer retrospective's availability lesson (PAPERS.md,
arxiv 2606.15870) is that at pod scale *any*-host failure must translate
into *fleet* resume, not a half-dead job burning wall clock. The ladder
here (docs/RESILIENCE.md "Fleet propagation"):

1. a host detects its own failure — watchdog deadline breach (hang),
   supervisor escalation (divergence), or an unrecoverable exception;
2. it **posts a poison flag** through the coordinator KV store (plus a
   shared-directory file when a fleet dir is configured — the file
   survives whole-fleet death for post-mortem) and exits for resume;
3. every other host polls the flag at its next step boundary
   (:meth:`FleetSentinel.check`) and exits with
   :data:`FLEET_EXIT_CODE` — *exit-for-resume*, the restarter relaunches
   the whole fleet which resumes from the last committed fleet
   checkpoint;
4. a host that never reaches a boundary because it is blocked inside a
   collective whose peer died is covered by its own watchdog lease (the
   PR 8 machinery) — the ladder needs no healthy-path synchronization.

The KV store is ``jax.distributed``'s built-in client (living on the
coordinator process); :func:`kv_set`/:func:`kv_get`/:func:`kv_dir` wrap it
with the shared-directory fallback so single-process tests and tools can
exercise the same code paths. Keys are namespaced ``paddle_tpu/...``.
"""
from __future__ import annotations

import json
import logging
import os
import time

import jax

from ..log_helper import get_logger
from ..resilience import watchdog as _wdg
from ..resilience.snapshot import atomic_write_bytes

__all__ = ['FleetSentinel', 'FleetPoisoned', 'FLEET_EXIT_CODE', 'kv_set',
           'kv_get', 'kv_dir', 'active_sentinel', 'install_sentinel',
           'clear_sentinel', 'check_poisoned', 'exit_for_resume',
           'ENV_FLEET_DIR', 'ENV_POISON_GRACE']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [fleet] %(message)s')

#: exit code for a healthy host leaving because ANOTHER host poisoned the
#: fleet — distinct from a crash (signal), a watchdog abort (70), and a
#: clean exit (0), so the restarter can account the three separately.
FLEET_EXIT_CODE = 75

ENV_FLEET_DIR = 'PADDLE_TPU_FLEET_DIR'
ENV_POISON_GRACE = 'PADDLE_TPU_FLEET_POISON_GRACE_S'

_POISON_PREFIX = 'paddle_tpu/poison/'
_POISON_FILE = 'fleet_poison.json'


class FleetPoisoned(RuntimeError):
    """Raised (optionally) when the fleet poison flag is set: some host
    posted a failure and every host must exit for resume."""

    def __init__(self, record):
        self.record = record
        super().__init__(
            f"fleet poisoned by host {record.get('source')}: "
            f"{record.get('reason')} (step {record.get('step')})")


def _client():
    try:
        from jax._src.distributed import global_state
        return global_state.client
    except Exception:
        return None


def kv_set(key, value):
    """Set `key` → `value` (str) in the coordinator KV store; mirrored to
    the fleet directory when configured. Returns True if at least one
    backend accepted the write."""
    ok = False
    c = _client()
    if c is not None:
        try:
            c.key_value_set(key, value)
            ok = True
        except Exception as e:       # noqa: BLE001 — dying host, best effort
            _logger.warning('kv_set(%s) failed: %s', key, e)
    d = os.environ.get(ENV_FLEET_DIR)
    if d:
        try:
            os.makedirs(d, exist_ok=True)
            atomic_write_bytes(
                os.path.join(d, key.replace('/', '__')), value.encode())
            ok = True
        except OSError as e:
            _logger.warning('kv_set(%s) file mirror failed: %s', key, e)
    return ok


def kv_get(key, timeout_s=5.0):
    """Blocking get → str, or None on timeout/no-backend."""
    c = _client()
    if c is not None:
        try:
            return c.blocking_key_value_get(key, int(timeout_s * 1000))
        except Exception:
            pass
    d = os.environ.get(ENV_FLEET_DIR)
    if d:
        path = os.path.join(d, key.replace('/', '__'))
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                with open(path) as f:
                    return f.read()
            except OSError:
                if time.monotonic() >= deadline:
                    return None
                time.sleep(0.05)
    return None


def kv_dir(prefix):
    """Non-blocking directory listing → {key: value} for keys under
    `prefix` (the poison poll uses this: one RPC, no timeout games)."""
    out = {}
    c = _client()
    if c is not None:
        try:
            for k, v in c.key_value_dir_get(prefix):
                out[k] = v
        except Exception:
            pass
    d = os.environ.get(ENV_FLEET_DIR)
    if d and os.path.isdir(d):
        want = prefix.replace('/', '__')
        for name in os.listdir(d):
            if name.startswith(want):
                try:
                    with open(os.path.join(d, name)) as f:
                        out.setdefault(name.replace('__', '/'), f.read())
                except OSError:
                    pass
    return out


class FleetSentinel:
    """The poison flag. One per process (installed by
    ``bootstrap()``/``install_sentinel()``); the CheckpointManager polls
    it at every step boundary, the watchdog posts through it on breach,
    and the supervisor posts on escalation.

    `grace_s` (``PADDLE_TPU_FLEET_POISON_GRACE_S``, default 0): extra
    dwell at each boundary poll — poll, sleep, poll again — giving a
    just-posted flag time to land before this host commits to dispatching
    the next step into a collective with a dead peer. Zero keeps the
    healthy path free; tests/restarts that must observe the KV path
    deterministically set ~1s."""

    def __init__(self, source=None, grace_s=None):
        self.source = (source if source is not None
                       else jax.process_index())
        raw = os.environ.get(ENV_POISON_GRACE, '').strip()
        if grace_s is None and raw:
            try:
                grace_s = float(raw)
            except ValueError:
                raise ValueError(
                    f'{ENV_POISON_GRACE} must be a number, got {raw!r}')
        self.grace_s = float(grace_s or 0.0)
        self._posted = None

    # -- posting -------------------------------------------------------
    def post(self, reason, step=None, kind='error'):
        """Poison the fleet: record WHO failed, WHY, and WHERE in the
        step stream. Idempotent per process; best-effort by design (the
        poster is usually about to die)."""
        if self._posted is not None:
            return self._posted
        record = {'source': int(self.source), 'reason': str(reason),
                  'kind': kind, 'step': step, 'pid': os.getpid(),
                  'unix_time': time.time()}
        self._posted = record
        kv_set(f'{_POISON_PREFIX}{self.source}', json.dumps(record))
        d = os.environ.get(ENV_FLEET_DIR)
        if d:
            try:
                atomic_write_bytes(os.path.join(d, _POISON_FILE),
                                   json.dumps(record).encode())
            except OSError:
                pass
        _logger.error('fleet POISONED by this host: %s (step %s)',
                      reason, step)
        from .. import observability as _obs
        if _obs._ENABLED:
            _obs.inc('fleet_poison_posted',
                     help='fleet poison flags posted by this host')
        return record

    # -- polling -------------------------------------------------------
    def check(self):
        """→ the poison record posted by ANOTHER host, or None. One
        non-blocking KV poll (+ the grace re-poll when configured) —
        the per-boundary cost on the healthy path is a single local RPC."""
        rec = self._poll_once()
        if rec is None and self.grace_s > 0:
            time.sleep(self.grace_s)
            rec = self._poll_once()
        if rec is not None:
            from .. import observability as _obs
            if _obs._ENABLED:
                _obs.inc('fleet_poison_observed',
                         help='poison flags observed from other hosts')
        return rec

    def _poll_once(self):
        for key, val in kv_dir(_POISON_PREFIX).items():
            try:
                rec = json.loads(val)
            except ValueError:
                continue
            if int(rec.get('source', -1)) != int(self.source):
                return rec
        d = os.environ.get(ENV_FLEET_DIR)
        if d:
            try:
                with open(os.path.join(d, _POISON_FILE)) as f:
                    rec = json.loads(f.read())
                if int(rec.get('source', -1)) != int(self.source):
                    return rec
            except (OSError, ValueError):
                pass
        return None

    def raise_if_poisoned(self):
        rec = self.check()
        if rec is not None:
            raise FleetPoisoned(rec)

    def clear(self):
        """Remove stale poison flags (host 0, at bring-up, BEFORE the
        restore barrier — otherwise a restarted fleet would instantly
        re-observe last incarnation's flag and exit again)."""
        c = _client()
        if c is not None:
            try:
                for k, _ in c.key_value_dir_get(_POISON_PREFIX):
                    c.key_value_delete(k)
            except Exception:
                pass
        d = os.environ.get(ENV_FLEET_DIR)
        if d and os.path.isdir(d):
            for name in list(os.listdir(d)):
                if name == _POISON_FILE or \
                        name.startswith(_POISON_PREFIX.replace('/', '__')):
                    try:
                        os.unlink(os.path.join(d, name))
                    except OSError:
                        pass
        self._posted = None


# ---------------------------------------------------------------------------
# process-wide sentinel + watchdog integration
# ---------------------------------------------------------------------------

_ACTIVE = None


def active_sentinel():
    return _ACTIVE


def install_sentinel(**kwargs):
    """Install the process sentinel and hook the watchdog: a breach on
    this host now poisons the fleet BEFORE the abort exit, so every other
    host follows within one step boundary instead of hanging in a
    collective until its own deadline."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = FleetSentinel(**kwargs)
        _wdg.add_breach_hook(_on_watchdog_breach)
    return _ACTIVE


def clear_sentinel():
    global _ACTIVE
    _ACTIVE = None


def check_poisoned():
    """The poison record another host posted, or None. Train loops call
    this when a STEP FAILS (a collective error is how a dead peer
    surfaces on the survivors — gloo closes the connection the instant
    the peer exits): poisoned → the failure is the fleet going down for
    resume, exit with FLEET_EXIT_CODE instead of crashing."""
    s = _ACTIVE
    return s.check() if s is not None else None


def exit_for_resume(record=None, code=FLEET_EXIT_CODE):
    """Leave the process for a fleet restart: flush stdio and hard-exit
    with `code`. This is ``os._exit`` ON PURPOSE — the normal interpreter
    teardown runs jax.distributed's atexit shutdown barrier, which can
    never complete once a peer died hard (the coordination service
    aborts the survivor with SIGABRT after its heartbeat timeout instead
    of letting it exit with our code). Callers flush their own state
    (CheckpointManager.close()) BEFORE calling."""
    if record is not None:
        _logger.error('exiting for fleet resume (code %d): poisoned by '
                      'host %s: %s', code, record.get('source'),
                      record.get('reason'))
    try:
        import sys
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os._exit(code)


def _on_watchdog_breach(record):
    # NOTE: runs on the watchdog monitor thread moments before a hard
    # exit — must not touch backend initialization (jax.process_count()
    # can re-enter platform init mid-teardown); the presence of the
    # distributed client / a fleet dir is the fleet signal
    s = _ACTIVE
    if s is not None and (_client() is not None
                          or os.environ.get(ENV_FLEET_DIR)):
        s.post(f"watchdog breach: lease {record.get('name')!r} held "
               f"{record.get('held_seconds')}s", kind='watchdog')
