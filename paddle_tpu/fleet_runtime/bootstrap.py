"""Multi-host bring-up: fleet-env discovery, jax.distributed init, and the
cross-host primitive set.

The Fluid reference's ``distributed/launch.py`` spawned one process per GPU
and wired NCCL env vars; on a TPU pod each host runs ONE process and the
runtime needs exactly three facts: how many trainers, which one am I, and
where the coordinator lives. :func:`discover_fleet_env` reads those from the
reference's env-var contract — **strict-parse**: a malformed or internally
contradictory environment raises immediately, listing every expected var,
instead of silently training single-host while the rest of the pod waits in
a collective (the classic fleet bring-up failure mode).

Recognized variables (docs/DISTRIBUTED.md "Multi-host runtime")::

    PADDLE_TRAINERS_NUM        world size (int >= 1)
    PADDLE_TRAINER_ID          this host's rank in [0, num)
    PADDLE_TRAINER_ENDPOINTS   comma list "host:port,..." (len == num)
    PADDLE_CURRENT_ENDPOINT    this host's entry of the list
    PADDLE_TPU_FLEET_COORDINATOR  coordinator addr override (defaults to
                               endpoint[0], the reference convention)

Bring-up order (each step idempotent): parse env → ``jax.distributed
.initialize`` (gloo CPU collectives for the test/bench fleets) → wire the
Partitioner's mesh from the now-GLOBAL device list → install the
:class:`~paddle_tpu.fleet_runtime.coordinator.FleetSentinel`.

``local_fleet(nproc)`` is the test/bench spawner: it launches ``nproc``
REAL ``jax.distributed`` CPU worker processes (one device each) with the
full fleet env wired — generalizing what ``bench_collectives --nproc``
hand-rolled — so multi-host behavior is exercised by actual multi-process
rendezvous, not simulation.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import time

import numpy as np
import jax

from ..log_helper import get_logger

__all__ = ['FleetSpec', 'discover_fleet_env', 'bootstrap', 'process_index',
           'process_count', 'is_host0', 'local_fleet', 'LocalFleet',
           'fleet_barrier', 'broadcast_from_host0', 'all_hosts_agree',
           'fleet_allreduce_scalars', 'ENV_NUM', 'ENV_ID', 'ENV_ENDPOINTS',
           'ENV_CURRENT', 'ENV_COORDINATOR']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [fleet] %(message)s')

ENV_NUM = 'PADDLE_TRAINERS_NUM'
ENV_ID = 'PADDLE_TRAINER_ID'
ENV_ENDPOINTS = 'PADDLE_TRAINER_ENDPOINTS'
ENV_CURRENT = 'PADDLE_CURRENT_ENDPOINT'
ENV_COORDINATOR = 'PADDLE_TPU_FLEET_COORDINATOR'

_EXPECTED = (ENV_NUM, ENV_ID, ENV_ENDPOINTS, ENV_CURRENT, ENV_COORDINATOR)

_BOOTSTRAPPED = False


def _distributed_client_up():
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:
        return False


def _fail(problem):
    raise ValueError(
        f'fleet env: {problem}. Expected variables: '
        f'{ENV_NUM} (int >= 1), {ENV_ID} (int in [0, {ENV_NUM})), '
        f'{ENV_ENDPOINTS} (comma list of host:port, one per trainer), '
        f'{ENV_CURRENT} (this host\'s endpoint, member of the list), '
        f'{ENV_COORDINATOR} (optional coordinator host:port; defaults to '
        f'the first endpoint)')


def _parse_int(environ, name):
    raw = environ.get(name, '').strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        _fail(f'{name} must be an integer, got {raw!r}')


class FleetSpec:
    """Parsed + validated fleet topology. ``num_trainers == 1`` is a valid
    single-host fleet (bring-up becomes a no-op)."""

    __slots__ = ('num_trainers', 'trainer_id', 'endpoints',
                 'coordinator_address')

    def __init__(self, num_trainers, trainer_id, endpoints=None,
                 coordinator_address=None):
        num_trainers = int(num_trainers)
        trainer_id = int(trainer_id)
        if num_trainers < 1:
            _fail(f'{ENV_NUM} must be >= 1, got {num_trainers}')
        if not (0 <= trainer_id < num_trainers):
            _fail(f'{ENV_ID}={trainer_id} outside [0, '
                  f'{ENV_NUM}={num_trainers})')
        if endpoints is not None:
            if len(endpoints) != num_trainers:
                _fail(f'{ENV_ENDPOINTS} lists {len(endpoints)} endpoints '
                      f'but {ENV_NUM}={num_trainers}')
            if len(set(endpoints)) != len(endpoints):
                _fail(f'{ENV_ENDPOINTS} has duplicate entries')
        if coordinator_address is None and endpoints:
            coordinator_address = endpoints[0]
        if num_trainers > 1 and not coordinator_address:
            _fail(f'{ENV_NUM}={num_trainers} > 1 but neither '
                  f'{ENV_COORDINATOR} nor {ENV_ENDPOINTS} is set (no way '
                  f'to rendezvous)')
        self.num_trainers = num_trainers
        self.trainer_id = trainer_id
        self.endpoints = list(endpoints) if endpoints else None
        self.coordinator_address = coordinator_address

    def __repr__(self):
        return (f'FleetSpec(num={self.num_trainers}, id={self.trainer_id}, '
                f'coordinator={self.coordinator_address!r})')


def discover_fleet_env(environ=None):
    """→ :class:`FleetSpec` from the environment, or None when NO fleet
    vars are set (plain single-process run). A partially/contradictorily
    set environment raises (strict parse — see module docstring)."""
    environ = environ if environ is not None else os.environ
    num = _parse_int(environ, ENV_NUM)
    tid = _parse_int(environ, ENV_ID)
    eps_raw = environ.get(ENV_ENDPOINTS, '').strip()
    cur = environ.get(ENV_CURRENT, '').strip()
    coord = environ.get(ENV_COORDINATOR, '').strip() or None
    if num is None and tid is None and not eps_raw and not cur \
            and coord is None:
        return None
    if num is None:
        _fail(f'{ENV_ID}/{ENV_ENDPOINTS} set but {ENV_NUM} is missing')
    if tid is None:
        tid = 0 if num == 1 else _fail(
            f'{ENV_NUM}={num} set but {ENV_ID} is missing')
    endpoints = None
    if eps_raw:
        endpoints = [e.strip() for e in eps_raw.split(',') if e.strip()]
        for e in endpoints:
            if ':' not in e:
                _fail(f'{ENV_ENDPOINTS} entry {e!r} is not host:port')
    spec = FleetSpec(num, tid, endpoints, coord)
    if cur:
        if spec.endpoints is None:
            _fail(f'{ENV_CURRENT} set but {ENV_ENDPOINTS} is missing')
        if cur not in spec.endpoints:
            _fail(f'{ENV_CURRENT}={cur!r} not in {ENV_ENDPOINTS}')
        if spec.endpoints.index(cur) != spec.trainer_id:
            _fail(f'{ENV_CURRENT}={cur!r} is endpoint '
                  f'#{spec.endpoints.index(cur)} but {ENV_ID}='
                  f'{spec.trainer_id} (contradictory rank)')
    return spec


def bootstrap(spec=None, configure_mesh=True, install_sentinel_flag=True):
    """Multi-host bring-up (idempotent). Order matters and is part of the
    documented contract (docs/DISTRIBUTED.md):

    1. parse/validate the fleet env (strict) unless `spec` is given;
    2. ``jax.distributed.initialize`` against the coordinator — after
       this, ``jax.devices()`` is the GLOBAL device list (gloo CPU
       collectives are configured first so test fleets work off-TPU);
    3. wire the Partitioner's owned mesh from the global devices when it
       is still unconfigured (``{'dp': jax.device_count()}`` — the fleet
       default; strategies/env can override before or after);
    4. install the process :class:`FleetSentinel` so one host's failure
       propagates (skippable for tools that only want the mesh).

    Returns the effective :class:`FleetSpec` (or None for a plain
    single-process run with no fleet env)."""
    global _BOOTSTRAPPED
    spec = spec if spec is not None else discover_fleet_env()
    if spec is not None and spec.num_trainers > 1 and not _BOOTSTRAPPED \
            and not _distributed_client_up():
        try:
            # the CPU backend needs the gloo collectives implementation
            # for cross-process computations (no-op when unavailable)
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
        except Exception:
            pass
        t0 = time.perf_counter()
        jax.distributed.initialize(
            coordinator_address=spec.coordinator_address,
            num_processes=spec.num_trainers,
            process_id=spec.trainer_id)
        _logger.info(
            'jax.distributed up: process %d/%d, coordinator %s, '
            '%d global device(s), %.2fs',
            spec.trainer_id, spec.num_trainers, spec.coordinator_address,
            jax.device_count(), time.perf_counter() - t0)
        _BOOTSTRAPPED = True
    if configure_mesh:
        from ..partition import configure, get_partitioner
        if get_partitioner().mesh is None:
            configure(mesh_shape={'dp': jax.device_count()})
    if install_sentinel_flag:
        from . import coordinator as _coord
        sentinel = _coord.install_sentinel()
        if jax.process_index() == 0:
            # a restarted fleet must not instantly re-observe LAST
            # incarnation's poison flag: host 0 clears stale flags, and
            # the barrier below keeps every other host from polling
            # before the clear landed
            sentinel.clear()
        fleet_barrier('fleet_bootstrap')
    from .. import observability as _obs
    # name this process in distributed span records (trace_merge.py shows
    # 'host<rank>' lanes) — a no-op unless PADDLE_TPU_TRACE_DIR is set
    _obs.distributed.set_process_label('host%d' % process_index())
    if _obs._ENABLED:
        _obs.set_gauge('fleet_world_size', process_count(),
                       help='number of trainer processes in the fleet')
        _obs.set_gauge('fleet_process_index', process_index(),
                       help='this process\'s trainer id')
    return spec


def process_index():
    return jax.process_index()


def process_count():
    return jax.process_count()


def is_host0():
    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# cross-host primitives
# ---------------------------------------------------------------------------

def fleet_barrier(tag='fleet_barrier'):
    """Block until every host reached this `tag` (device-collective
    barrier; no-op single-host). Use only from the MAIN thread — the
    checkpoint writer's cross-host commit uses the coordinator KV store
    instead, precisely so a background barrier can never interleave with
    the step stream's collectives."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def broadcast_from_host0(value):
    """Host 0's pytree of arrays, replicated to every host (no-op
    single-host)."""
    if jax.process_count() <= 1:
        return value
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(value)


def all_hosts_agree(value, tag='fleet_agree'):
    """True iff every host passed an identical `value` (JSON-serialized
    comparison — meshes, steps, manifest digests). Single-host: True."""
    if jax.process_count() <= 1:
        return True
    import zlib
    from jax.experimental import multihost_utils
    digest = zlib.crc32(
        json.dumps(value, sort_keys=True, default=str).encode()) \
        & 0xFFFFFFFF
    all_digests = multihost_utils.process_allgather(
        np.asarray(digest, np.uint32))
    return bool((np.asarray(all_digests) == digest).all())


def fleet_allreduce_scalars(values, op='sum'):
    """Reduce a list of host-local python scalars across all hosts — the
    cross-host eval-metric reduction (``run_eval_graph`` sums per-host
    metric accumulators and batch counts through this). Identity
    single-host. `op` ∈ {'sum', 'mean', 'max', 'min'}."""
    ops = {'sum': np.sum, 'mean': np.mean, 'max': np.max, 'min': np.min}
    if op not in ops:
        raise ValueError(f'fleet_allreduce_scalars: unknown op {op!r} '
                         f'(supported: {", ".join(sorted(ops))})')
    vals = [float(v) for v in values]
    if jax.process_count() <= 1:
        return vals
    from jax.experimental import multihost_utils
    gathered = np.asarray(multihost_utils.process_allgather(
        np.asarray(vals, np.float64)))       # (num_hosts, len(values))
    return [float(v) for v in ops[op](gathered, axis=0)]


# ---------------------------------------------------------------------------
# local_fleet: the test/bench spawner (real jax.distributed CPU workers)
# ---------------------------------------------------------------------------

class LocalFleet:
    """Handle on a spawned local fleet: one subprocess per trainer, each a
    REAL ``jax.distributed`` CPU worker (one device per process, gloo
    collectives, full fleet env wired)."""

    def __init__(self, procs, spec_envs):
        self.procs = procs
        self.spec_envs = spec_envs

    def wait(self, timeout=600):
        """→ list of return codes (one per rank); kills stragglers on
        timeout rather than hanging the caller."""
        deadline = time.monotonic() + timeout
        rcs = []
        for p in self.procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                rcs.append(p.wait(timeout=left))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                rcs.append(None)
        return rcs

    def poll(self):
        return [p.poll() for p in self.procs]

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def free_port():
    with socket.socket() as s:
        s.bind(('localhost', 0))
        return s.getsockname()[1]


def local_fleet(nproc, script, args=(), env=None, rank_env=None,
                stdout=None, cwd=None):
    """Spawn `nproc` real ``jax.distributed`` CPU workers running
    ``python script args...`` with the complete fleet env wired
    (endpoints on free localhost ports, coordinator = endpoint 0,
    ``JAX_PLATFORMS=cpu``, ``XLA_FLAGS`` stripped so each process owns
    exactly one device). This is the generalization of what
    ``bench_collectives --nproc`` hand-rolled, shared by the fleet tests
    and ``tools/bench_fleet.py``.

    `env` merges extra vars into every rank; `rank_env` is
    ``{rank: {var: value}}`` per-rank overrides (fault injection on ONE
    worker). `stdout` may be a callable ``rank -> file object``.
    Returns a :class:`LocalFleet`."""
    ports = [free_port() for _ in range(nproc)]
    endpoints = [f'localhost:{p}' for p in ports]
    procs, envs = [], []
    for r in range(nproc):
        e = dict(os.environ, JAX_PLATFORMS='cpu')
        e.pop('XLA_FLAGS', None)            # one device per process
        e.pop('PADDLE_TPU_FAULT_INJECT', None)
        e[ENV_NUM] = str(nproc)
        e[ENV_ID] = str(r)
        e[ENV_ENDPOINTS] = ','.join(endpoints)
        e[ENV_CURRENT] = endpoints[r]
        if env:
            e.update(env)
        if rank_env and r in rank_env:
            e.update(rank_env[r])
        out = stdout(r) if callable(stdout) else stdout
        procs.append(subprocess.Popen(
            [sys.executable, str(script)] + [str(a) for a in args],
            env=e, cwd=cwd, stdout=out,
            stderr=subprocess.STDOUT if out is not None else None))
        envs.append(e)
    return LocalFleet(procs, envs)
