"""Collective communication API (ref: python/paddle/fluid/layers/collective.py
+ paddle/fluid/operators/collective/c_*_op.cc).

Two forms:
- inside shard_map/pjit-traced code: jax.lax collectives over mesh axes
  (the production path — XLA schedules them on ICI);
- eager on host: operates on the addressable shards of a sharded array.
The c_* names mirror the reference ops so transpiled programs map 1:1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.registry import register_op


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


def allreduce_sum(x, axis='dp'):
    return lax.psum(x, axis)


def allreduce_mean(x, axis='dp'):
    return lax.pmean(x, axis)


def allreduce_max(x, axis='dp'):
    return lax.pmax(x, axis)


def allreduce_min(x, axis='dp'):
    return lax.pmin(x, axis)


def allgather(x, axis='dp'):
    return lax.all_gather(x, axis)


def reduce_scatter(x, axis='dp'):
    return lax.psum_scatter(x, axis)


def broadcast(x, root=0, axis='dp'):
    """Broadcast shard `root`'s value along the mesh axis."""
    idx = lax.axis_index(axis)
    n = lax.psum(jnp.ones((), jnp.int32), axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def alltoall(x, axis='dp'):
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0)


def ppermute(x, perm, axis='dp'):
    return lax.ppermute(x, axis, perm)


def barrier(axis='dp'):
    return lax.psum(jnp.zeros((), jnp.float32), axis)


# graph-op registrations (c_* parity): usable from static programs that are
# lowered inside shard_map contexts (parallel/fleet.py wires this).
@register_op('c_allreduce_sum')
def c_allreduce_sum(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    return lax.psum(jnp.asarray(x), axis)


@register_op('c_allreduce_max')
def c_allreduce_max(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    return lax.pmax(jnp.asarray(x), axis)


@register_op('c_allreduce_min')
def c_allreduce_min(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    return lax.pmin(jnp.asarray(x), axis)


@register_op('c_allreduce_prod')
def c_allreduce_prod(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    # no lax.pprod; log-space for positive, fallback via all_gather product
    g = lax.all_gather(jnp.asarray(x), axis)
    return jnp.prod(g, axis=0)


@register_op('c_allgather')
def c_allgather(x, *, nranks=1, ring_id=0, use_calc_stream=True, axis='dp'):
    g = lax.all_gather(jnp.asarray(x), axis)
    return g.reshape((-1,) + g.shape[2:])


@register_op('c_broadcast')
def c_broadcast(x, *, root=0, ring_id=0, use_calc_stream=True, axis='dp'):
    return broadcast(jnp.asarray(x), root, axis)


@register_op('c_reducescatter')
def c_reducescatter(x, *, nranks=1, ring_id=0, use_calc_stream=True,
                    axis='dp'):
    return lax.psum_scatter(jnp.asarray(x), axis)


@register_op('c_sync_calc_stream')
def c_sync_calc_stream(x):
    return jnp.asarray(x)  # XLA orders effects; sync is a no-op


@register_op('c_sync_comm_stream')
def c_sync_comm_stream(x):
    return jnp.asarray(x)
