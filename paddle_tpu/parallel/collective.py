"""Collective communication API (ref: python/paddle/fluid/layers/collective.py
+ paddle/fluid/operators/collective/c_*_op.cc).

Two forms:
- inside shard_map/pjit-traced code: jax.lax collectives over mesh axes
  (the production path — XLA schedules them on ICI);
- eager on host: operates on the addressable shards of a sharded array.
The c_* names mirror the reference ops so transpiled programs map 1:1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.registry import register_op


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


def allreduce_sum(x, axis='dp'):
    return lax.psum(x, axis)


def allreduce_mean(x, axis='dp'):
    return lax.pmean(x, axis)


def allreduce_max(x, axis='dp'):
    return lax.pmax(x, axis)


def allreduce_min(x, axis='dp'):
    return lax.pmin(x, axis)


def allgather(x, axis='dp'):
    return lax.all_gather(x, axis)


def reduce_scatter(x, axis='dp'):
    return lax.psum_scatter(x, axis)


def broadcast(x, root=0, axis='dp'):
    """Broadcast shard `root`'s value along the mesh axis."""
    idx = lax.axis_index(axis)
    n = lax.psum(jnp.ones((), jnp.int32), axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def alltoall(x, axis='dp'):
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0)


def ppermute(x, perm, axis='dp'):
    return lax.ppermute(x, axis, perm)


def barrier(axis='dp'):
    return lax.psum(jnp.zeros((), jnp.float32), axis)


def _axis_bound(axis):
    """Whether `axis` is a live mesh axis of the surrounding trace. Static
    programs run through the plain (non-shard_map) Executor jit have NO
    bound axes — the gradient c_allreduce ops fleet inserts then lower to
    identity (single-replica semantics: XLA already derives the AllReduce
    from the GSPMD sharded-batch formulation; the explicit ops carry the
    sync-point STRUCTURE the bucketing pass ir/bucket_allreduce.py and the
    bytes accounting operate on, and become real collectives the moment
    the program lowers inside a shard_map)."""
    try:
        lax.psum(1, axis)
        return True
    except NameError:
        return False


# graph-op registrations (c_* parity): real lax collectives when lowered
# inside a shard_map context binding their axis; identity/single-replica
# lowering otherwise (see _axis_bound).
@register_op('c_allreduce_sum')
def c_allreduce_sum(x, *, ring_id=0, use_calc_stream=True, axis='dp',
                    comm_dtype=None):
    """AllReduce-sum; `comm_dtype` (f32/bf16/int8, stamped by fleet from
    DistributedStrategy.comm_dtype) block-quantizes the payload via
    parallel/quant_collectives.py — exact lax.psum at f32."""
    if not _axis_bound(axis):
        return jnp.asarray(x)
    from . import quant_collectives as qc
    return qc.qallreduce_sum(jnp.asarray(x), axis, comm_dtype=comm_dtype)


@register_op('c_allreduce_sum_bucket', variadic=('xs',))
def c_allreduce_sum_bucket(xs, *, ring_id=0, use_calc_stream=True,
                           axis='dp', comm_dtype=None):
    """One size-capped bucket of gradient AllReduces fused by the
    ir/bucket_allreduce.py pass: members flatten into one contiguous
    bundle, ONE collective moves it, and the results split back to the
    members' shapes. Concat/slice/reshape only around the collective —
    bucketed vs per-grad reduction is bitwise-identical at f32 (elementwise
    psum over the same values), which the pass parity suite asserts."""
    arrs = [jnp.asarray(x) for x in xs]
    shapes = [a.shape for a in arrs]
    sizes = [int(a.size) for a in arrs]
    flat = jnp.concatenate([a if a.ndim == 1 else jnp.ravel(a)
                            for a in arrs]) if len(arrs) > 1 else \
        jnp.ravel(arrs[0])
    if _axis_bound(axis):
        from . import quant_collectives as qc
        flat = qc.qallreduce_sum(flat, axis, comm_dtype=comm_dtype)
    out, off = [], 0
    for shp, sz in zip(shapes, sizes):
        seg = flat[off:off + sz]
        out.append(seg if shp == (sz,) else jnp.reshape(seg, shp))
        off += sz
    return out


@register_op('c_allreduce_max')
def c_allreduce_max(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    return lax.pmax(jnp.asarray(x), axis)


@register_op('c_allreduce_min')
def c_allreduce_min(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    return lax.pmin(jnp.asarray(x), axis)


@register_op('c_allreduce_prod')
def c_allreduce_prod(x, *, ring_id=0, use_calc_stream=True, axis='dp'):
    # no lax.pprod; log-space for positive, fallback via all_gather product
    g = lax.all_gather(jnp.asarray(x), axis)
    return jnp.prod(g, axis=0)


@register_op('c_allgather')
def c_allgather(x, *, nranks=1, ring_id=0, use_calc_stream=True, axis='dp'):
    g = lax.all_gather(jnp.asarray(x), axis)
    return g.reshape((-1,) + g.shape[2:])


@register_op('c_broadcast')
def c_broadcast(x, *, root=0, ring_id=0, use_calc_stream=True, axis='dp'):
    return broadcast(jnp.asarray(x), root, axis)


@register_op('c_reducescatter')
def c_reducescatter(x, *, nranks=1, ring_id=0, use_calc_stream=True,
                    axis='dp'):
    return lax.psum_scatter(jnp.asarray(x), axis)


@register_op('c_sync_calc_stream')
def c_sync_calc_stream(x):
    return jnp.asarray(x)  # XLA orders effects; sync is a no-op


@register_op('c_sync_comm_stream')
def c_sync_comm_stream(x):
    return jnp.asarray(x)
