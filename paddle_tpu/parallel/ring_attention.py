"""Ring attention: sequence/context parallelism over a mesh axis.

The reference scales long sequences with its NCCL sendrecv pipelines; the TPU
design shards the SEQUENCE dim over a mesh axis ('sp') and rotates K/V blocks
around the ring with lax.ppermute while each device accumulates its queries'
attention with an online (flash-style) softmax. Peak memory per chip is
O(S/p · S/p) per block instead of O(S²), and the ppermute rides ICI
neighbor links — the canonical TPU long-context formulation
(Liu et al., Ring Attention; jax-ml scaling-book ch. 'sharding').

Differentiable end-to-end: the VJP of ppermute is the reverse rotation, so
jax.grad through a ring_attention call yields the ring-parallel backward.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import compat
from .mesh import get_default_mesh

_BIG_NEG = -1e30


def _ring_attention_local(q, k, v, *, axis_name, causal, scale):
    """Per-shard body (inside shard_map). q/k/v: (B, S_loc, H, D) — the
    local sequence block. Returns (B, S_loc, H, D)."""
    p = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    qt = q.transpose(0, 2, 1, 3)                        # (B, H, S, D)

    q_pos = idx * S + jnp.arange(S)                     # global query rows

    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(carry, r):
        o, m, l, kc, vc = carry
        kt = kc.transpose(0, 2, 1, 3)                   # (B, H, S, D)
        vt = vc.transpose(0, 2, 1, 3)
        s = jnp.einsum('bhqd,bhkd->bhqk', qt, kt,
                       preferred_element_type=jnp.float32) * sc
        # the block held after r rotations came from device (idx - r) mod p
        src = (idx - r) % p
        k_pos = src * S + jnp.arange(S)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _BIG_NEG)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)                      # (B, H, S)
        pexp = jnp.exp(s - m_new[..., None])
        if causal:
            pexp = jnp.where(mask[None, None], pexp, 0.0)
        l_new = l * alpha + pexp.sum(-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            'bhqk,bhkd->bhqd', pexp, vt.astype(pexp.dtype))
        k_next = lax.ppermute(kc, axis_name, perm)
        v_next = lax.ppermute(vc, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    # carries become device-varying (masks depend on axis_index): mark the
    # constant inits as varying over the ring axis for shard_map's vma typing
    o0 = compat.pcast(jnp.zeros((B, H, S, D), jnp.float32), axis_name,
                   to='varying')
    m0 = compat.pcast(jnp.full((B, H, S), _BIG_NEG, jnp.float32), axis_name,
                   to='varying')
    l0 = compat.pcast(jnp.zeros((B, H, S), jnp.float32), axis_name,
                   to='varying')
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(p))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis='sp', causal=False, scale=None):
    """Sequence-parallel attention. q/k/v: (B, S, H, D) GLOBAL shapes with S
    sharded over mesh axis `axis` (S must divide evenly). Batch/head dims
    stay as-is (shard them with dp/tp shardings upstream)."""
    mesh = mesh or get_default_mesh()
    if mesh is None or axis not in mesh.axis_names:
        # no mesh / axis → plain attention on one device
        return _full_attention(q, k, v, causal=causal, scale=scale)
    body = functools.partial(_ring_attention_local, axis_name=axis,
                             causal=causal, scale=scale)
    spec = P(None, axis, None, None)
    fn = compat.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)


def _full_attention(q, k, v, causal=False, scale=None):
    """Single-device reference path (also the numeric oracle in tests)."""
    B, S, H, D = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * sc
    if causal:
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, _BIG_NEG)
    a = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhqk,bkhd->bqhd', a, v.astype(a.dtype))
    return out.astype(q.dtype)
