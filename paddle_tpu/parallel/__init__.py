"""Distribution: the unified SPMD partitioner (paddle_tpu.partition) plus
collectives, fleet, and model/pipeline/sequence parallelism (SURVEY
§2.8, docs/PARTITIONER.md). The mesh module is a compatibility shim —
the partitioner owns the device mesh."""
from . import mesh
from .mesh import (make_mesh, make_hybrid_mesh, set_default_mesh,
                   get_default_mesh, mesh_guard, data_sharding, replicated,
                   topology)
from ..partition import (Partitioner, get_partitioner, configure,
                         mesh_scope)
from . import fsdp
from .fsdp import (fsdp_shardings, fsdp_sharding, fsdp_spec,
                   reduce_scatter_grads)
from . import collective
from . import quant_collectives
from .quant_collectives import (qallreduce_sum, qallreduce_mean,
                                qreduce_scatter_sum, block_quantize,
                                block_dequantize, resolve_comm_dtype)
from .fleet import (fleet, Fleet, DistributedStrategy, DistributedOptimizer,
                    PaddleCloudRoleMaker, UserDefinedRoleMaker)
from .ring_attention import ring_attention
from .tensor_parallel import (megatron_param_spec, shard_params,
                              column_parallel_matmul, row_parallel_matmul,
                              vocab_parallel_embedding)
from .pipeline import gpipe, stack_stage_params
from .local_sgd import LocalSGDStep
from .geo_sgd import GeoSGDStep
