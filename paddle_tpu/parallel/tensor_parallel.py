"""Megatron-style tensor/model parallelism over a mesh axis ('tp').

Two complementary surfaces, both riding ICI collectives:

1. GSPMD annotations (`megatron_param_spec`, `shard_params`) — annotate
   parameter shardings and let XLA insert the all-reduces. This is the
   default path (the dryrun/fleet path) because the compiler overlaps the
   collectives with compute.
2. Explicit shard_map primitives (`column_parallel_matmul`,
   `row_parallel_matmul`, `vocab_parallel_embedding`) — for code that wants
   the Megatron dataflow spelled out (e.g. custom pipelines), matching the
   reference's c_allreduce-after-row-matmul pattern
   (ref: paddle/fluid/operators/collective/c_allreduce_op.h usage in its
   model-parallel fleet mode).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import compat
from .mesh import get_default_mesh

__all__ = ['megatron_param_spec', 'shard_params', 'column_parallel_matmul',
           'row_parallel_matmul', 'vocab_parallel_embedding']


def megatron_param_spec(name, arr, axis='tp', col_markers=('ffn1', 'q_proj',
                        'k_proj', 'v_proj', '.q.', '.k.', '.v.'),
                        row_markers=('ffn2', 'out_proj', '.out.')):
    """PartitionSpec for a parameter by Megatron rules: up-projections /
    QKV shard columns, down-projections shard rows, everything else
    replicated over `axis`."""
    if getattr(arr, 'ndim', len(getattr(arr, 'shape', ()))) == 2:
        if any(m in name for m in col_markers):
            return P(None, axis)
        if any(m in name for m in row_markers):
            return P(axis, None)
    return P()


def shard_params(params, mesh=None, axis='tp', spec_fn=None):
    """device_put a {name: array} parameter dict with Megatron shardings."""
    mesh = mesh or get_default_mesh()
    spec_fn = spec_fn or (lambda n, a: megatron_param_spec(n, a, axis))
    return {n: jax.device_put(v, NamedSharding(mesh, spec_fn(n, v)))
            for n, v in params.items()}


def _smap(body, mesh, in_specs, out_specs):
    return compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def column_parallel_matmul(x, w, b=None, mesh=None, axis='tp',
                           gather_output=False):
    """y = x @ w with w column-sharded: each device computes its slice of
    the output features; no collective unless gather_output."""
    mesh = mesh or get_default_mesh()

    def body(xs, ws, bs):
        y = xs @ ws
        if bs is not None:
            y = y + bs
        return y

    in_specs = (P(), P(None, axis), P(axis) if b is not None else P())
    out = _smap(lambda xs, ws, bs: body(xs, ws, bs), mesh, in_specs,
                P(None, axis))(x, w, b if b is not None
                               else jnp.zeros((), x.dtype))
    if gather_output:
        return jax.device_put(out, NamedSharding(mesh, P()))
    return out


def row_parallel_matmul(x, w, b=None, mesh=None, axis='tp'):
    """y = x @ w with w row-sharded and x feature-sharded: partial products
    all-reduce over `axis` (the Megatron down-projection; the reference's
    c_allreduce_sum after the split matmul)."""
    mesh = mesh or get_default_mesh()

    def body(xs, ws, bs):
        part = xs @ ws
        y = lax.psum(part, axis)
        if bs is not None:
            y = y + bs
        return y

    in_specs = (P(None, axis), P(axis, None), P())
    return _smap(body, mesh, in_specs, P())(
        x, w, b if b is not None else jnp.zeros((), x.dtype))


def vocab_parallel_embedding(ids, table, mesh=None, axis='tp'):
    """Embedding with the vocab dim sharded: each device looks up only ids
    in its shard (others contribute zero), then psum combines — one small
    AllReduce instead of gathering the full table."""
    mesh = mesh or get_default_mesh()

    def body(ids_s, tab_s):
        idx = lax.axis_index(axis)
        V_local = tab_s.shape[0]
        lo = idx * V_local
        local = ids_s - lo
        in_range = (local >= 0) & (local < V_local)
        safe = jnp.clip(local, 0, V_local - 1)
        emb = tab_s[safe]
        emb = jnp.where(in_range[..., None], emb, 0.0)
        return lax.psum(emb, axis)

    return _smap(body, mesh, (P(), P(axis, None)), P())(ids, table)
