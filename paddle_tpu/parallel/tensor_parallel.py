"""Megatron-style tensor/model parallelism over a mesh axis ('tp').

Two complementary surfaces, both riding ICI collectives:

1. GSPMD annotations (`megatron_param_spec`, `shard_params`) — annotate
   parameter shardings and let XLA insert the all-reduces. This is the
   default path (the dryrun/fleet path) because the compiler overlaps the
   collectives with compute.
2. Explicit shard_map primitives (`column_parallel_matmul`,
   `row_parallel_matmul`, `vocab_parallel_embedding`) — for code that wants
   the Megatron dataflow spelled out (e.g. custom pipelines), matching the
   reference's c_allreduce-after-row-matmul pattern
   (ref: paddle/fluid/operators/collective/c_allreduce_op.h usage in its
   model-parallel fleet mode).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import compat
from .mesh import get_default_mesh

__all__ = ['megatron_param_spec', 'shard_params', 'column_parallel_matmul',
           'row_parallel_matmul', 'vocab_parallel_embedding', 'mp_copy',
           'mp_allreduce']


@functools.lru_cache(maxsize=None)
def _mp_pair(axis):
    """Megatron's (f, g) conjugate collectives over ``axis``:

    - ``f`` (mp_copy): identity forward, all-reduce backward — placed at
      the ENTRY of a tensor-parallel region so upstream (replicated)
      parameters receive the full gradient, summed over the tp shards'
      partial contributions;
    - ``g`` (mp_allreduce): all-reduce forward, identity backward —
      placed at the EXIT (after a row-parallel matmul). A plain
      ``lax.psum`` is wrong there under autodiff: its transpose is psum
      again, so a replicated cotangent comes back multiplied by the axis
      size (the classic n× gradient bug the custom VJP removes).
    """
    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis)

    g.defvjp(lambda x: (lax.psum(x, axis), None),
             lambda _, ct: (compat.pcast(ct, axis, to='varying'),))

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None),
             lambda _, ct: (lax.psum(ct, axis),))
    return f, g


def mp_copy(x, axis='tp'):
    """Identity forward / psum backward (Megatron 'f') — wrap the input
    of a tensor-parallel region with it."""
    return _mp_pair(axis)[0](x)


def mp_allreduce(x, axis='tp'):
    """psum forward / identity backward (Megatron 'g') — reduce the
    partial products of a row-parallel matmul with it."""
    return _mp_pair(axis)[1](x)


def megatron_param_spec(name, arr, axis='tp', col_markers=None,
                        row_markers=None):
    """PartitionSpec for a parameter by Megatron rules: up-projections /
    QKV shard columns, down-projections shard rows, everything else
    replicated over `axis`. The marker tables live on the partitioner
    (partition/partitioner.py) — the same rules drive
    ``Partitioner.param_spec`` so the explicit-shard_map surface and the
    Program-lowering surface can never disagree."""
    from ..partition.partitioner import (COLUMN_PARALLEL_MARKERS,
                                         ROW_PARALLEL_MARKERS)
    col_markers = (COLUMN_PARALLEL_MARKERS if col_markers is None
                   else col_markers)
    row_markers = (ROW_PARALLEL_MARKERS if row_markers is None
                   else row_markers)
    if getattr(arr, 'ndim', len(getattr(arr, 'shape', ()))) == 2:
        if any(m in name for m in col_markers):
            return P(None, axis)
        if any(m in name for m in row_markers):
            return P(axis, None)
    return P()


def shard_params(params, mesh=None, axis='tp', spec_fn=None):
    """device_put a {name: array} parameter dict with Megatron shardings."""
    mesh = mesh or get_default_mesh()
    spec_fn = spec_fn or (lambda n, a: megatron_param_spec(n, a, axis))
    return {n: jax.device_put(v, NamedSharding(mesh, spec_fn(n, v)))
            for n, v in params.items()}


def _smap(body, mesh, in_specs, out_specs):
    return compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def column_parallel_matmul(x, w, b=None, mesh=None, axis='tp',
                           gather_output=False):
    """y = x @ w with w column-sharded: each device computes its slice of
    the output features; no collective unless gather_output."""
    mesh = mesh or get_default_mesh()

    def body(xs, ws, bs):
        y = xs @ ws
        if bs is not None:
            y = y + bs
        return y

    in_specs = (P(), P(None, axis), P(axis) if b is not None else P())
    out = _smap(lambda xs, ws, bs: body(xs, ws, bs), mesh, in_specs,
                P(None, axis))(x, w, b if b is not None
                               else jnp.zeros((), x.dtype))
    if gather_output:
        return jax.device_put(out, NamedSharding(mesh, P()))
    return out


def row_parallel_matmul(x, w, b=None, mesh=None, axis='tp'):
    """y = x @ w with w row-sharded and x feature-sharded: partial products
    all-reduce over `axis` (the Megatron down-projection; the reference's
    c_allreduce_sum after the split matmul)."""
    mesh = mesh or get_default_mesh()

    def body(xs, ws, bs):
        part = xs @ ws
        # mp_allreduce, not bare psum: psum's transpose is psum, so a
        # replicated cotangent would come back ×axis_size (see _mp_pair)
        y = mp_allreduce(part, axis)
        if bs is not None:
            y = y + bs
        return y

    in_specs = (P(None, axis), P(axis, None), P())
    return _smap(body, mesh, in_specs, P())(
        x, w, b if b is not None else jnp.zeros((), x.dtype))


def vocab_parallel_embedding(ids, table, mesh=None, axis='tp'):
    """Embedding with the vocab dim sharded: each device looks up only ids
    in its shard (others contribute zero), then psum combines — one small
    AllReduce instead of gathering the full table."""
    mesh = mesh or get_default_mesh()

    def body(ids_s, tab_s):
        idx = lax.axis_index(axis)
        V_local = tab_s.shape[0]
        lo = idx * V_local
        local = ids_s - lo
        in_range = (local >= 0) & (local < V_local)
        safe = jnp.clip(local, 0, V_local - 1)
        emb = tab_s[safe]
        emb = jnp.where(in_range[..., None], emb, 0.0)
        return lax.psum(emb, axis)

    return _smap(body, mesh, (P(), P(axis, None)), P())(ids, table)
