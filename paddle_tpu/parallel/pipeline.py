"""DEPRECATED shim: pipeline parallelism moved onto the partitioner.

The GPipe schedule this module owned lives in
:mod:`paddle_tpu.partition.pipeline` now — on the partitioner's owned
mesh, next to the 1F1B and interleaved schedules, the ``('stage','pp')``
logical-axis rule, and the strict-parse ``PADDLE_TPU_PP_SCHEDULE`` /
``PADDLE_TPU_PP_MICROBATCHES`` knobs. Everything here delegates
(bitwise-identical — same code, new home) behind a one-per-process
deprecation warning, the ``parallel.mesh.set_default_mesh`` pattern.
"""
from __future__ import annotations

from ..partition.pipeline import gpipe as _gpipe
from ..partition.pipeline import stack_stage_params  # noqa: F401  (re-export)

__all__ = ['gpipe', 'stack_stage_params']


def gpipe(stage_fn, stacked_params, x_micro, mesh=None, axis='pp'):
    """DEPRECATED: use ``partition.pipeline.gpipe`` (or the schedule-aware
    executor lowering / ``SpmdTrainStep(pipeline=...)``)."""
    from ..partition.partitioner import warn_once
    warn_once(
        'parallel.pipeline.gpipe',
        'parallel.pipeline.gpipe is deprecated: pipeline schedules are '
        'owned by the partitioner (paddle_tpu.partition.pipeline). Import '
        'gpipe from there, or drive schedules through '
        'PipelineOptimizer(schedule=...) / DistributedStrategy.pp_schedule '
        '/ PADDLE_TPU_PP_SCHEDULE.')
    return _gpipe(stage_fn, stacked_params, x_micro, mesh=mesh, axis=axis)
