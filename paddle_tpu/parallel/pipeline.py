"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis ('pp').

The reference's PipelineOptimizer splits the Program across devices and
streams batches through section workers
(ref: python/paddle/fluid/optimizer.py:PipelineOptimizer +
paddle/fluid/framework/pipeline_trainer.cc). The TPU formulation keeps ONE
SPMD program: every device holds its own stage's parameters (stacked pytree,
leading dim = n_stages, sharded over 'pp'), and a lax.scan steps the GPipe
schedule — each tick computes the local stage and ppermutes activations to
the neighbor over ICI. Autodiff through the scan+ppermute gives the 1F1B-
equivalent backward without a separate scheduler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import compat
from .mesh import get_default_mesh

__all__ = ['gpipe', 'stack_stage_params']


def stack_stage_params(per_stage_params):
    """[{name: arr} per stage] → {name: arr[n_stages, ...]} for sharding
    over 'pp' (all stages must be isomorphic — the transformer-block case)."""
    keys = per_stage_params[0].keys()
    return {k: jnp.stack([p[k] for p in per_stage_params]) for k in keys}


def gpipe(stage_fn, stacked_params, x_micro, mesh=None, axis='pp'):
    """Run `stage_fn(params, x) -> y` as a pipeline.

    stacked_params: pytree with leading dim n_stages (sharded over `axis`).
    x_micro: (n_micro, mb, ...) microbatched input (replicated).
    Returns (n_micro, mb, ...) outputs of the LAST stage (replicated).
    Stage input/output shapes must match (uniform stages)."""
    mesh = mesh or get_default_mesh()
    n_micro = x_micro.shape[0]
    p = mesh.shape[axis]                                # static stage count
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != p:
        raise ValueError(
            f"gpipe: {n_stages} stacked stages but mesh axis {axis!r} has "
            f"{p} devices — one stage per device is required")

    def body(params_s, xm):
        # params_s leaves: (1, ...) local stage slice → squeeze
        params = jax.tree_util.tree_map(lambda a: a[0], params_s)
        idx = lax.axis_index(axis)
        T = n_micro + p - 1
        fwd_perm = [(i, i + 1) for i in range(p - 1)]
        # activations are device-varying (each stage computes differently):
        # mark the zero init for shard_map's vma typing
        zero = compat.pcast(jnp.zeros_like(xm[0]), axis, to='varying')

        def step(carry, t):
            prev_y = carry
            recv = lax.ppermute(prev_y, axis, fwd_perm)
            mb = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(idx == 0, xm[mb], recv)
            active = (t >= idx) & (t - idx < n_micro)
            y = stage_fn(params, x_in)
            y = jnp.where(active, y, zero)
            return y, y

        _, ys = lax.scan(step, zero, jnp.arange(T))     # (T, mb, ...)
        # device p-1 finishes microbatch i at tick i + p - 1
        outs = ys[p - 1:p - 1 + n_micro] if p > 1 else ys[:n_micro]
        # only the last stage's values are real; broadcast them to all
        outs = jnp.where(idx == p - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, axis)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    fn = compat.shard_map(body, mesh=mesh,
                       in_specs=(param_specs, P()), out_specs=P())
    return fn(stacked_params, x_micro)
