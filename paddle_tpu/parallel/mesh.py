"""Device-mesh compatibility shims over the unified SPMD partitioner.

This module used to own a module-global default mesh that every
``parallel/`` module mutated and read around each other — exactly the
hand-rolled plumbing the partitioner retired (ROADMAP item 1,
docs/PARTITIONER.md). The mesh is now OWNED by
:mod:`paddle_tpu.partition`: built once from a ``DistributedStrategy`` /
``PADDLE_TPU_MESH`` topology, resolved through the logical axis rules.

Everything here is a delegating alias kept for API compatibility:

- ``make_mesh`` / ``make_hybrid_mesh`` / ``topology`` re-export
  partition.device_mesh (the only sanctioned ``Mesh(`` construction
  site — tools/lint_codebase.py enforces it);
- ``get_default_mesh`` / ``mesh_guard`` read/scope the partitioner's
  owned mesh;
- ``set_default_mesh`` still works but is DEPRECATED (one warning per
  process through log_helper): configure the partitioner instead
  (``partition.configure(mesh_shape=...)`` or ``fleet.init``).
"""
from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from ..partition.device_mesh import make_mesh, make_hybrid_mesh, topology

__all__ = ['make_mesh', 'make_hybrid_mesh', 'set_default_mesh',
           'get_default_mesh', 'mesh_guard', 'data_sharding', 'replicated',
           'topology']


def set_default_mesh(mesh: Optional[Mesh]):
    """DEPRECATED: mutate the partitioner's owned mesh. Prefer
    ``partition.configure(mesh_shape=...)`` (builds it once from a
    topology) or the scoped ``partition.mesh_scope``."""
    from ..partition import get_partitioner
    from ..partition.partitioner import warn_once
    warn_once(
        'set_default_mesh',
        'parallel.mesh.set_default_mesh is deprecated: the device mesh is '
        'owned by the partitioner (paddle_tpu.partition). Use '
        'partition.configure(mesh_shape=...) / fleet.init(mesh_shape=...) '
        'or the scoped partition.mesh_scope(mesh) instead.')
    get_partitioner().set_mesh(mesh)


def get_default_mesh() -> Optional[Mesh]:
    from ..partition import get_partitioner
    return get_partitioner().mesh


def mesh_guard(mesh: Mesh):
    """Scoped mesh override (delegates to partition.mesh_scope)."""
    from ..partition import mesh_scope
    return mesh_scope(mesh)


def data_sharding(mesh=None, axis=None):
    """Sharding for a batch tensor: leading dim over the data axes the
    rule table resolves (or an explicit ``axis``), rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec
    from ..partition import get_partitioner
    p = get_partitioner()
    if mesh is None and axis is None:
        return p.data_sharding()
    mesh = mesh if mesh is not None else p.mesh
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(axis if axis is not None
                                             else 'dp'))


def replicated(mesh=None):
    from jax.sharding import NamedSharding, PartitionSpec
    from ..partition import get_partitioner
    mesh = mesh if mesh is not None else get_partitioner().mesh
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec())
