"""Device mesh management — the TPU-native backbone of all distribution.

Replaces the reference's NCCL communicator bootstrap
(/root/reference/paddle/fluid/operators/collective/c_comm_init_op.cc,
c_gen_nccl_id_op.cc): instead of exchanging NCCL unique ids over RPC, we
build a jax.sharding.Mesh over the ICI/DCN topology; XLA lowers collectives
onto it. Axes convention (SURVEY §2.8): dp (data), fsdp (sharded params),
tp (tensor), pp (pipeline), sp (sequence).
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_default_mesh: Optional[Mesh] = None


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({'dp': 4, 'tp': 2}).
    Uses mesh_utils for ICI-aware device ordering when available."""
    devices = devices if devices is not None else jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh {axes} needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices[:n])
    except Exception:
        dev_array = np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def set_default_mesh(mesh: Optional[Mesh]):
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    global _default_mesh
    old = _default_mesh
    _default_mesh = mesh
    try:
        yield mesh
    finally:
        _default_mesh = old


def data_sharding(mesh=None, axis='dp'):
    """Sharding for a batch tensor: leading dim over `axis`, rest replicated."""
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh=None):
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec())


def topology():
    """Slice/pod topology report (ref: fleet's role maker endpoints)."""
    devs = jax.devices()
    info = {
        'process_index': jax.process_index(),
        'process_count': jax.process_count(),
        'local_device_count': jax.local_device_count(),
        'device_count': len(devs),
        'platform': devs[0].platform if devs else 'none',
    }
    if hasattr(devs[0], 'coords'):
        info['coords'] = [tuple(d.coords) for d in devs]
    return info
