"""Device mesh management — the TPU-native backbone of all distribution.

Replaces the reference's NCCL communicator bootstrap
(/root/reference/paddle/fluid/operators/collective/c_comm_init_op.cc,
c_gen_nccl_id_op.cc): instead of exchanging NCCL unique ids over RPC, we
build a jax.sharding.Mesh over the ICI/DCN topology; XLA lowers collectives
onto it. Axes convention (SURVEY §2.8): dp (data), fsdp (sharded params),
tp (tensor), pp (pipeline), sp (sequence).
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_default_mesh: Optional[Mesh] = None


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({'dp': 4, 'tp': 2}).
    Uses mesh_utils for ICI-aware device ordering when available."""
    devices = devices if devices is not None else jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh {axes} needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices[:n])
    except Exception:
        dev_array = np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def make_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int],
                     devices=None) -> Mesh:
    """Multi-slice/pod mesh: `dcn_axes` span the data-center network
    (slices), `ici_axes` the in-slice interconnect. This is the TPU
    analogue of the reference's hierarchical allreduce
    (ref: incubate/fleet DistributedStrategy.use_hierarchical_allreduce +
    NCCL hierarchical comms): laying dp over DCN and tp/fsdp over ICI makes
    XLA emit the two-level collective automatically. Uses
    mesh_utils.create_hybrid_device_mesh when slice topology is available;
    otherwise (single slice / CPU test mesh) falls back to a flat
    ICI-ordered mesh with the same named axes."""
    devices = devices if devices is not None else jax.devices()
    overlap = set(dcn_axes) & set(ici_axes)
    if overlap:
        raise ValueError(
            f"axis names {sorted(overlap)} appear in both dcn_axes and "
            f"ici_axes")
    dcn_shape = tuple(dcn_axes.values())
    ici_shape = tuple(ici_axes.values())
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    n_dcn = int(np.prod(dcn_shape))
    n_ici = int(np.prod(ici_shape))
    if n_dcn * n_ici > len(devices):
        raise ValueError(
            f"hybrid mesh {dcn_axes}x{ici_axes} needs {n_dcn * n_ici} "
            f"devices, have {len(devices)}")
    by_slice: Dict[int, list] = {}
    for d in devices:
        by_slice.setdefault(getattr(d, 'slice_index', 0), []).append(d)
    if len(by_slice) > 1:
        # pick WHOLE slices (n_dcn of them × n_ici devices each) so the
        # dcn axes really span DCN — a flat device prefix could land
        # entirely inside one slice
        usable = [ds[:n_ici] for ds in by_slice.values()
                  if len(ds) >= n_ici]
        if len(usable) < n_dcn:
            raise ValueError(
                f"hybrid mesh needs {n_dcn} slices with ≥{n_ici} devices "
                f"each; have {[len(v) for v in by_slice.values()]}")
        chosen = [d for ds in usable[:n_dcn] for d in ds]
        # create_hybrid_device_mesh wants same-rank shapes and returns
        # their ELEMENTWISE product; padding with 1s yields exactly
        # dcn_shape + ici_shape in (dcn..., ici...) order
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_shape) + ici_shape,
            dcn_shape + (1,) * len(ici_shape), chosen)
        return Mesh(dev_array, names)
    # single slice / CPU test mesh: flat ICI-ordered mesh, same named axes
    return make_mesh({**dcn_axes, **ici_axes}, devices[:n_dcn * n_ici])


def set_default_mesh(mesh: Optional[Mesh]):
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    global _default_mesh
    old = _default_mesh
    _default_mesh = mesh
    try:
        yield mesh
    finally:
        _default_mesh = old


def data_sharding(mesh=None, axis='dp'):
    """Sharding for a batch tensor: leading dim over `axis`, rest replicated."""
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh=None):
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec())


def topology():
    """Slice/pod topology report (ref: fleet's role maker endpoints)."""
    devs = jax.devices()
    info = {
        'process_index': jax.process_index(),
        'process_count': jax.process_count(),
        'local_device_count': jax.local_device_count(),
        'device_count': len(devs),
        'platform': devs[0].platform if devs else 'none',
    }
    if hasattr(devs[0], 'coords'):
        info['coords'] = [tuple(d.coords) for d in devs]
    return info
