"""Block-quantized collectives: bytes-on-wire reduction for gradient sync.

Gradient all-reduce is the scale-out bottleneck (ROADMAP item 3): every
DP/FSDP/local-SGD/geo-SGD sync point ran a full-precision ``lax.psum``.
Following EQuARX (PAPERS.md, arxiv 2506.17615), this module provides
block-quantized all-reduce variants that cut wire bytes ~4x (int8) or 2x
(bf16) with a bounded, documented error, expressed entirely in lax
collectives so XLA schedules them on ICI like any other comm:

    quantize local chunks -> all-to-all (the reduce-scatter phase)
    -> dequantize + sum partials in f32 -> requantize
    -> all-gather -> dequantize

Two properties are load-bearing:

- the partial-sum arithmetic is EXACT f32 — only the two codec stages
  lose bits, so the elementwise error is bounded by
  ``sum_i absmax_i(block)/254 + absmax_reduced(block)/254`` (int8,
  round-to-nearest symmetric; see docs/DISTRIBUTED.md for the contract);
- when the mesh axis has size 1, or ``comm_dtype`` resolves to ``f32``,
  every entry point is an EXACT passthrough to the plain lax collective —
  bitwise-identical to the pre-quantization code paths.

Selection is one knob: ``PADDLE_TPU_COMM_DTYPE`` (env, wins) /
``DistributedStrategy.comm_dtype`` / a per-call ``comm_dtype=`` argument,
each in {f32, bf16, int8} — unknown values raise ``ValueError`` naming
the supported set (the PR 8 strict-parse convention).

Telemetry (``PADDLE_TPU_TELEMETRY``): host-side call sites record
``collective_sync_calls`` / ``collective_bytes_on_wire`` /
``collective_bytes_f32_equiv`` counters and a
``collective_quant_rel_error`` round-trip error histogram — the
jit-traced collectives themselves stay pure (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp
from jax import lax

from .. import observability as _obs

__all__ = ['SUPPORTED_COMM_DTYPES', 'resolve_comm_dtype', 'block_quantize',
           'block_dequantize', 'qallreduce_sum', 'qallreduce_mean',
           'qreduce_scatter_sum', 'wire_bytes', 'record_collective',
           'quant_error_stats', 'DEFAULT_BLOCK_SIZE', 'rowwise_quantize',
           'rowwise_dequantize', 'sparse_allgather', 'sparse_wire_bytes',
           'record_sparse_collective']

SUPPORTED_COMM_DTYPES = ('f32', 'bf16', 'int8')
DEFAULT_BLOCK_SIZE = 256
ENV_COMM_DTYPE = 'PADDLE_TPU_COMM_DTYPE'


def _validate(value, source):
    if value not in SUPPORTED_COMM_DTYPES:
        raise ValueError(
            f"{source}: unknown comm_dtype {value!r} "
            f"(supported: {', '.join(SUPPORTED_COMM_DTYPES)})")
    return value


def resolve_comm_dtype(value=None):
    """One comm-dtype knob for every sync point. Precedence:
    ``PADDLE_TPU_COMM_DTYPE`` env > the ``value`` argument (a per-call
    override or ``DistributedStrategy.comm_dtype``) > ``'f32'``. Unknown
    names raise ValueError listing the supported set."""
    env = os.environ.get(ENV_COMM_DTYPE)
    if env is not None and env != '':
        return _validate(env, ENV_COMM_DTYPE)
    if value is not None:
        return _validate(value, 'comm_dtype')
    return 'f32'


# ---------------------------------------------------------------------------
# codec: symmetric per-block int8 / plain bf16
# ---------------------------------------------------------------------------

def _padded_size(size, block_size):
    return -(-size // block_size) * block_size


def block_quantize(x, block_size=DEFAULT_BLOCK_SIZE):
    """Symmetric round-to-nearest int8 quantization with one f32 scale per
    ``block_size`` contiguous elements of the flattened input.

    Returns ``(q, scales)``: ``q`` is int8 of shape ``(padded,)`` where
    ``padded`` rounds ``x.size`` up to a whole number of blocks (the tail
    pads with zeros — exact under the zero-maps-to-zero codec), ``scales``
    is f32 of shape ``(padded // block_size,)``. An all-zero block gets
    scale 0 and decodes to exact zeros; a single-element tensor is exact
    (its own absmax maps to ±127)."""
    f = jnp.ravel(x).astype(jnp.float32)
    size = f.shape[0]
    padded = _padded_size(max(size, 1), block_size)
    if padded != size:
        f = jnp.pad(f, (0, padded - size))
    b = f.reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(b), axis=1)
    scale = absmax / 127.0
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.clip(jnp.round(b * inv[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale


def block_dequantize(q, scales, shape=None, block_size=DEFAULT_BLOCK_SIZE):
    """Inverse of :func:`block_quantize`. ``shape`` (when given) slices the
    padding tail off and reshapes to the original tensor shape."""
    f = (q.reshape(-1, block_size).astype(jnp.float32)
         * jnp.asarray(scales, jnp.float32)[:, None]).reshape(-1)
    if shape is not None:
        size = int(np.prod(shape, dtype=np.int64)) if len(shape) else 1
        f = f[:size].reshape(shape)
    return f


def _encode(flat, comm_dtype, block_size):
    """flat f32 (block-aligned) -> (payload, scales or None)."""
    if comm_dtype == 'int8':
        return block_quantize(flat, block_size)
    # bf16 carries its own exponent; no block scales needed
    return flat.astype(jnp.bfloat16), None


def _decode(payload, scales, comm_dtype, block_size):
    if comm_dtype == 'int8':
        return block_dequantize(payload, scales, block_size=block_size)
    return payload.astype(jnp.float32)


def rowwise_quantize(vals):
    """Symmetric int8 with ONE f32 scale per embedding row — the sparse
    push codec (docs/SPARSE.md). Unlike :func:`block_quantize`, scales
    align with COO rows so a gathered (rows, vals, scales) triple stays
    row-addressable; an all-zero row (COO padding) gets scale 0 and
    decodes to exact zeros."""
    v = jnp.asarray(vals, jnp.float32)
    absmax = jnp.max(jnp.abs(v), axis=-1)
    scale = absmax / 127.0
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.clip(jnp.round(v * inv[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def rowwise_dequantize(q, scales):
    return q.astype(jnp.float32) * jnp.asarray(scales, jnp.float32)[..., None]


def sparse_allgather(rows, vals, axis='dp', comm_dtype=None):
    """The DP sparse gradient push: every device contributes its padded
    COO (rows, vals); each gets the CONCATENATION of all peers' entries
    back — O(n·K·D) bytes at the comm dtype instead of the O(V·D) dense
    all-reduce it replaces. Call inside shard_map/pjit with ``axis``
    bound; the caller coalesces (duplicate rows across peers sum there,
    which IS the gradient reduction). int8 payloads cross the wire with
    per-row f32 scales (exact-zero padding rows survive)."""
    comm = resolve_comm_dtype(comm_dtype)
    n = _axis_size(axis)
    rows = jnp.asarray(rows).astype(jnp.int32)
    vals = jnp.asarray(vals)
    if n == 1:
        return rows, vals.astype(jnp.float32)
    rows_all = lax.all_gather(rows, axis).reshape(-1)
    if comm == 'int8':
        q, s = rowwise_quantize(vals)
        qg = lax.all_gather(q, axis).reshape(-1, vals.shape[-1])
        sg = lax.all_gather(s, axis).reshape(-1)
        return rows_all, rowwise_dequantize(qg, sg)
    if comm == 'bf16':
        vg = lax.all_gather(vals.astype(jnp.bfloat16), axis)
        return rows_all, vg.reshape(-1, vals.shape[-1]).astype(jnp.float32)
    vg = lax.all_gather(vals.astype(jnp.float32), axis)
    return rows_all, vg.reshape(-1, vals.shape[-1])


def sparse_wire_bytes(num_rows, dim, comm_dtype, axis_size):
    """Logical payload bytes one device's COO contribution puts on the
    wire in a :func:`sparse_allgather`: int32 row ids + vals at the codec
    width (+ per-row f32 scales for int8). Axis size 1 moves nothing."""
    comm = resolve_comm_dtype(comm_dtype)
    if axis_size <= 1:
        return 0
    r, d = int(num_rows), int(dim)
    ids = r * 4
    if comm == 'int8':
        return ids + r * d + r * 4
    if comm == 'bf16':
        return ids + r * d * 2
    return ids + r * d * 4


def record_sparse_collective(path, num_rows, dim, comm_dtype, axis_size,
                             dense_elems):
    """Count one sparse push: bytes on wire at the COO+codec size, f32
    equivalent = the dense all-reduce of the ``dense_elems``-element
    table this push replaced — their ratio is the headline sparse win
    (tools/bench_sparse.py measures it). No-op with telemetry off."""
    if not _obs._ENABLED:
        return
    comm = resolve_comm_dtype(comm_dtype)
    _obs.inc('collective_sync_calls', 1,
             help='gradient/param sync collectives by path and comm dtype',
             path=path, dtype=comm)
    _obs.inc('collective_bytes_on_wire',
             sparse_wire_bytes(num_rows, dim, comm, axis_size),
             help='logical collective payload bytes at the wire dtype',
             path=path, dtype=comm)
    _obs.inc('collective_bytes_f32_equiv',
             wire_bytes(dense_elems, 'f32', axis_size, phases=2),
             help='f32-equivalent bytes for the same syncs (ratio = '
                  'compression)',
             path=path)


# ---------------------------------------------------------------------------
# collectives (call inside shard_map/pjit-traced code, axis bound)
# ---------------------------------------------------------------------------

def _axis_size(axis):
    # psum of a concrete scalar is folded to the axis size at trace time
    return int(lax.psum(1, axis))


def qallreduce_sum(x, axis='dp', comm_dtype=None, block_size=None):
    """All-reduce-sum of ``x`` over mesh axis ``axis`` with the comm payload
    block-quantized to ``comm_dtype``.

    EQuARX two-phase decomposition: each device quantizes its local copy in
    chunks, an all-to-all routes chunk i of every peer to device i (the
    reduce-scatter phase at 1/4 or 1/2 the f32 bytes), partials dequantize
    and sum EXACTLY in f32, the reduced chunk requantizes, and an
    all-gather rebuilds the full tensor everywhere. Exact f32 passthrough
    (plain ``lax.psum``, bitwise-identical to pre-quantization code) when
    the axis size is 1 or ``comm_dtype`` resolves to ``'f32'``."""
    comm = resolve_comm_dtype(comm_dtype)
    block_size = int(block_size or DEFAULT_BLOCK_SIZE)
    n = _axis_size(axis)
    if comm == 'f32' or n == 1:
        return lax.psum(x, axis)
    x = jnp.asarray(x)
    shape, dtype = x.shape, x.dtype
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    # pad so every device-destined chunk is a whole number of blocks
    chunk = _padded_size(-(-size // n), block_size)
    padded = chunk * n
    f = jnp.ravel(x).astype(jnp.float32)
    if padded != size:
        f = jnp.pad(f, (0, padded - size))
    # phase 1 — reduce-scatter: quantize, all-to-all, exact f32 partial sum
    payload, scales = _encode(f, comm, block_size)
    pc = lax.all_to_all(payload.reshape(n, chunk), axis,
                        split_axis=0, concat_axis=0)
    if scales is not None:
        sc = lax.all_to_all(scales.reshape(n, chunk // block_size), axis,
                            split_axis=0, concat_axis=0)
        part = (pc.reshape(n, chunk // block_size, block_size)
                .astype(jnp.float32) * sc[:, :, None]).reshape(n, chunk)
    else:
        part = pc.astype(jnp.float32)
    reduced = jnp.sum(part, axis=0)
    # phase 2 — all-gather the requantized reduced chunk
    payload2, scales2 = _encode(reduced, comm, block_size)
    pg = lax.all_gather(payload2, axis)
    if scales2 is not None:
        sg = lax.all_gather(scales2, axis)
        out = (pg.reshape(padded // block_size, block_size)
               .astype(jnp.float32)
               * sg.reshape(-1)[:, None]).reshape(-1)
    else:
        out = pg.reshape(-1).astype(jnp.float32)
    if padded != size:
        out = out[:size]
    return out.reshape(shape).astype(dtype)


def qallreduce_mean(x, axis='dp', comm_dtype=None, block_size=None):
    """All-reduce-mean counterpart of :func:`qallreduce_sum` (exact
    ``lax.pmean`` passthrough at f32 / axis size 1)."""
    comm = resolve_comm_dtype(comm_dtype)
    n = _axis_size(axis)
    if comm == 'f32' or n == 1:
        return lax.pmean(x, axis)
    s = qallreduce_sum(x, axis, comm_dtype=comm, block_size=block_size)
    return (s / n).astype(jnp.asarray(x).dtype)


def qreduce_scatter_sum(x, axis='dp', comm_dtype=None, block_size=None,
                        scattered_dimension=0):
    """Reduce-scatter-sum with a quantized payload: phase 1 of the EQuARX
    decomposition alone — each device ends with its 1/n tile of the sum
    along ``scattered_dimension`` (``lax.psum_scatter(..., tiled=True)``
    semantics; exact f32 passthrough at f32 / axis size 1). This is the
    gradient half of ZeRO/FSDP sync: the summed partials never exist in
    full precision on the wire, only the local tile does."""
    comm = resolve_comm_dtype(comm_dtype)
    block_size = int(block_size or DEFAULT_BLOCK_SIZE)
    n = _axis_size(axis)
    d = scattered_dimension
    if comm == 'f32' or n == 1:
        return lax.psum_scatter(x, axis, scatter_dimension=d, tiled=True)
    x = jnp.asarray(x)
    if x.shape[d] % n:
        raise ValueError(
            f"qreduce_scatter_sum: dim {d} of shape {x.shape} is not "
            f"divisible by the axis size {n}")
    dtype = x.dtype
    moved = jnp.moveaxis(x, d, 0)
    tile_shape = (moved.shape[0] // n,) + moved.shape[1:]
    piece = int(np.prod(tile_shape, dtype=np.int64))
    padded = _padded_size(piece, block_size)
    flat = moved.reshape(n, piece).astype(jnp.float32)
    if padded != piece:
        flat = jnp.pad(flat, ((0, 0), (0, padded - piece)))
    # block boundaries stay inside one device-destined piece (padded is a
    # whole number of blocks), so per-piece scales survive the all-to-all
    payload, scales = _encode(flat.reshape(-1), comm, block_size)
    pc = lax.all_to_all(payload.reshape(n, padded), axis,
                        split_axis=0, concat_axis=0)
    if scales is not None:
        sc = lax.all_to_all(scales.reshape(n, padded // block_size), axis,
                            split_axis=0, concat_axis=0)
        part = (pc.reshape(n, padded // block_size, block_size)
                .astype(jnp.float32) * sc[:, :, None]).reshape(n, padded)
    else:
        part = pc.astype(jnp.float32)
    tile = jnp.sum(part, axis=0)[:piece].reshape(tile_shape)
    return jnp.moveaxis(tile, 0, d).astype(dtype)


# ---------------------------------------------------------------------------
# bytes-on-wire accounting + quantization-error telemetry (host side)
# ---------------------------------------------------------------------------

def wire_bytes(num_elements, comm_dtype, axis_size, block_size=None,
               phases=2):
    """Logical payload bytes a collective over ``num_elements`` puts on the
    wire per device: ``phases`` passes over the (block-padded) tensor at
    the codec's width, plus the f32 scale sidecar for int8. The f32
    baseline is the same two-pass (reduce-scatter + all-gather) accounting
    so the int8/f32 ratio is the EQuARX compression, not a phase-count
    artifact. Axis size 1 moves zero bytes (the passthrough is local)."""
    comm = resolve_comm_dtype(comm_dtype)
    if axis_size <= 1:
        return 0
    block_size = int(block_size or DEFAULT_BLOCK_SIZE)
    n = int(num_elements)
    if comm == 'f32':
        return phases * n * 4
    padded = _padded_size(n, block_size)
    if comm == 'bf16':
        return phases * padded * 2
    return phases * (padded + (padded // block_size) * 4)       # int8


def record_collective(path, num_elements, comm_dtype, axis_size,
                      block_size=None, phases=2):
    """Count one sync call into the telemetry registry: actual bytes on
    wire at ``comm_dtype`` plus the f32-equivalent bytes the same sync
    would have moved — their ratio is the measured compression
    (tools/telemetry_report.py prints it). No-op with telemetry off."""
    if not _obs._ENABLED:
        return
    comm = resolve_comm_dtype(comm_dtype)
    _obs.inc('collective_sync_calls', 1,
             help='gradient/param sync collectives by path and comm dtype',
             path=path, dtype=comm)
    _obs.inc('collective_bytes_on_wire',
             wire_bytes(num_elements, comm, axis_size,
                        block_size=block_size, phases=phases),
             help='logical collective payload bytes at the wire dtype',
             path=path, dtype=comm)
    _obs.inc('collective_bytes_f32_equiv',
             wire_bytes(num_elements, 'f32', axis_size, phases=phases),
             help='f32-equivalent bytes for the same syncs (ratio = '
                  'compression)',
             path=path)


def quant_error_stats(x, comm_dtype=None, block_size=None):
    """Local codec round-trip error of ``x``: ``(max_abs_err,
    max_rel_err)`` where rel is against the tensor absmax. This is the
    per-stage term of the documented error contract (each of the two
    phases contributes one such round trip); call sites record it into the
    ``collective_quant_rel_error`` histogram when telemetry is on."""
    comm = resolve_comm_dtype(comm_dtype)
    x = jnp.asarray(x)
    f = jnp.ravel(x).astype(jnp.float32)
    if comm == 'f32':
        return 0.0, 0.0
    block_size = int(block_size or DEFAULT_BLOCK_SIZE)
    if comm == 'int8':
        q, s = block_quantize(f, block_size)
        rt = block_dequantize(q, s, block_size=block_size)[:f.shape[0]]
    else:
        rt = f.astype(jnp.bfloat16).astype(jnp.float32)
    err = float(jnp.max(jnp.abs(rt - f))) if f.size else 0.0
    amax = float(jnp.max(jnp.abs(f))) if f.size else 0.0
    return err, (err / amax if amax > 0 else 0.0)


def record_quant_error(path, x, comm_dtype=None, block_size=None):
    """Observe the local round-trip relative error of one synced tensor
    (telemetry on only — costs one codec pass over ``x``)."""
    if not _obs._ENABLED:
        return
    comm = resolve_comm_dtype(comm_dtype)
    if comm == 'f32':
        return
    _, rel = quant_error_stats(x, comm, block_size)
    _obs.observe('collective_quant_rel_error', rel,
                 help='per-call codec round-trip error relative to tensor '
                      'absmax (one phase of the two-phase contract)',
                 path=path, dtype=comm)
