"""FSDP: fully-sharded data parallelism over an 'fsdp' mesh axis.

SURVEY §2.8 names fsdp as a first-class mesh axis; the reference's closest
surface is the sharding knob on the collective DistributedStrategy
(ref: python/paddle/fluid/incubate/fleet/collective/__init__.py:134). The
TPU-native formulation is pure GSPMD: parameters (and their optimizer
slots) carry NamedShardings that split the largest divisible dim over
'fsdp'; XLA inserts the all-gather before use and the reduce-scatter on the
gradient — ZeRO-3 semantics without a partitioning runtime. Batch feeds
shard over the same axis, so 'fsdp' doubles as the data axis (the
scaling-book recipe).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ['fsdp_spec', 'fsdp_sharding', 'fsdp_shardings', 'shard_params',
           'param_shard_bytes']


def fsdp_spec(shape, mesh: Mesh, axis: str = 'fsdp') -> PartitionSpec:
    """PartitionSpec sharding the LARGEST dim divisible by the axis size
    (replicated if none divides). Largest-dim wins: it maximizes the bytes
    saved per device and keeps the all-gather contiguous."""
    if axis not in mesh.shape:
        return PartitionSpec()
    p = mesh.shape[axis]
    best, best_size = None, 0
    for d, s in enumerate(shape):
        if s % p == 0 and s >= p and s > best_size:
            best, best_size = d, s
    if best is None:
        return PartitionSpec()
    spec = [None] * len(shape)
    spec[best] = axis
    return PartitionSpec(*spec)


def fsdp_sharding(shape, mesh: Mesh = None, axis: str = 'fsdp'):
    from .mesh import get_default_mesh
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, fsdp_spec(shape, mesh, axis))


def fsdp_shardings(params, mesh: Mesh = None, axis: str = 'fsdp'):
    """Pytree of params → pytree of NamedShardings (None without a mesh,
    like fsdp_sharding)."""
    from .mesh import get_default_mesh
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return jax.tree_util.tree_map(
        lambda a: NamedSharding(mesh, fsdp_spec(np.shape(a), mesh, axis)),
        params)


def shard_params(params, mesh: Mesh = None, axis: str = 'fsdp'):
    """device_put the pytree with FSDP shardings (no-op copies when already
    placed). Per-device bytes for a sharded param ≈ total/axis_size.
    Without a mesh, returns the params unchanged."""
    shardings = fsdp_shardings(params, mesh, axis)
    if shardings is None:
        return params
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def param_shard_bytes(arr) -> int:
    """Bytes of `arr` held on ONE device (diagnostic for the 1/p check)."""
    shards = arr.addressable_shards
    return int(np.prod(shards[0].data.shape)) * arr.dtype.itemsize
