"""FSDP: fully-sharded data parallelism over an 'fsdp' mesh axis.

SURVEY §2.8 names fsdp as a first-class mesh axis; the reference's closest
surface is the sharding knob on the collective DistributedStrategy
(ref: python/paddle/fluid/incubate/fleet/collective/__init__.py:134). The
TPU-native formulation is pure GSPMD: parameters (and their optimizer
slots) carry NamedShardings that split the largest divisible dim over
'fsdp'; XLA inserts the all-gather before use and the reduce-scatter on the
gradient — ZeRO-3 semantics without a partitioning runtime. Batch feeds
shard over the same axis, so 'fsdp' doubles as the data axis (the
scaling-book recipe).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ['fsdp_spec', 'fsdp_sharding', 'fsdp_shardings', 'shard_params',
           'param_shard_bytes', 'reduce_scatter_grads']


def fsdp_spec(shape, mesh: Mesh, axis: str = 'fsdp') -> PartitionSpec:
    """PartitionSpec sharding the LARGEST dim divisible by the axis size
    (replicated if none divides). Largest-dim wins: it maximizes the bytes
    saved per device and keeps the all-gather contiguous. This is the
    partitioner's 'fsdp' placement rule (partition/rules.py); kept as a
    module function because the explicit (mesh, axis) form is this
    module's sharding contract."""
    if axis not in mesh.shape:
        return PartitionSpec()
    p = mesh.shape[axis]
    from ..partition.rules import largest_divisible_dim
    best = largest_divisible_dim(shape, p) if p > 1 else None
    if best is None:
        return PartitionSpec()
    spec = [None] * len(shape)
    spec[best] = axis
    return PartitionSpec(*spec)


def fsdp_sharding(shape, mesh: Mesh = None, axis: str = 'fsdp'):
    from .mesh import get_default_mesh
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, fsdp_spec(shape, mesh, axis))


def fsdp_shardings(params, mesh: Mesh = None, axis: str = 'fsdp'):
    """Pytree of params → pytree of NamedShardings (None without a mesh,
    like fsdp_sharding)."""
    from .mesh import get_default_mesh
    mesh = mesh or get_default_mesh()
    if mesh is None:
        return None
    return jax.tree_util.tree_map(
        lambda a: NamedSharding(mesh, fsdp_spec(np.shape(a), mesh, axis)),
        params)


def shard_params(params, mesh: Mesh = None, axis: str = 'fsdp'):
    """device_put the pytree with FSDP shardings (no-op copies when already
    placed). Per-device bytes for a sharded param ≈ total/axis_size.
    Without a mesh, returns the params unchanged."""
    shardings = fsdp_shardings(params, mesh, axis)
    if shardings is None:
        return params
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def param_shard_bytes(arr) -> int:
    """Bytes of `arr` held on ONE device (diagnostic for the 1/p check)."""
    shards = arr.addressable_shards
    return int(np.prod(shards[0].data.shape)) * arr.dtype.itemsize


def reduce_scatter_grads(stacked_grads, mesh: Mesh = None, axis: str = 'fsdp',
                         comm_dtype=None, block_size=None):
    """Gradient reduce-scatter: per-device full gradients -> each device's
    1/p tile of their SUM, laid out exactly like :func:`fsdp_spec` shards
    the parameter (the ZeRO gradient sync, made explicit).

    ``stacked_grads`` is a dict name -> (p, *shape) array whose leading dim
    stacks the per-device local gradients over ``axis`` (sharded or host —
    device_put happens here). The payload quantizes per ``comm_dtype``
    (quant_collectives; env `PADDLE_TPU_COMM_DTYPE` wins): at int8/bf16 the
    summed gradient never crosses the wire in full precision — only each
    device's tile is materialized from exact-f32 partial sums. A shape with
    no ``axis``-divisible dim falls back to a (quantized) full all-reduce,
    replicated like its parameter. Exact ``lax.psum_scatter``/``psum`` at
    f32. Telemetry: one ``collective_*`` record per call (path ``fsdp``)."""
    from jax.sharding import PartitionSpec as P
    from .mesh import get_default_mesh
    from ..core import compat
    from . import quant_collectives as qc
    mesh = mesh or get_default_mesh()
    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"reduce_scatter_grads: no mesh axis {axis!r}")
    p = mesh.shape[axis]
    comm = qc.resolve_comm_dtype(comm_dtype)
    stacked_grads = {k: jax.device_put(
        jax.numpy.asarray(v),
        NamedSharding(mesh, P(axis, *([None] * (np.ndim(v) - 1)))))
        for k, v in stacked_grads.items()}
    shapes = {k: tuple(v.shape[1:]) for k, v in stacked_grads.items()}
    specs = {k: fsdp_spec(s, mesh, axis) for k, s in shapes.items()}
    scatter_dim = {}
    for k, spec in specs.items():
        entries = tuple(spec)
        scatter_dim[k] = entries.index(axis) if axis in entries else None
    in_specs = {k: P(axis, *([None] * len(shapes[k])))
                for k in stacked_grads}

    def body(stacked):
        out = {}
        for k, v in stacked.items():
            g = v[0]                      # this device's local gradient
            d = scatter_dim[k]
            if d is None:
                out[k] = compat.pcast(
                    qc.qallreduce_sum(g, axis, comm_dtype=comm,
                                      block_size=block_size),
                    axis, to='varying')
            else:
                out[k] = qc.qreduce_scatter_sum(
                    g, axis, comm_dtype=comm, block_size=block_size,
                    scattered_dimension=d)
        return out

    fn = compat.shard_map(body, mesh=mesh, in_specs=(in_specs,),
                          out_specs=specs)
    qc.record_collective(
        'fsdp',
        sum(int(np.prod(s, dtype=np.int64)) if s else 1
            for s in shapes.values()),
        comm, p, block_size=block_size,
        phases=1)       # reduce-scatter is phase 1 only (no all-gather)
    if _qc_err_enabled(comm):
        for k, v in stacked_grads.items():
            qc.record_quant_error('fsdp', v, comm, block_size)
    return fn(stacked_grads)


def _qc_err_enabled(comm):
    from .. import observability as _obs
    return _obs._ENABLED and comm != 'f32'
