"""Fleet collective training API (ref: python/paddle/fluid/incubate/fleet/
collective/__init__.py + base/fleet_base.py + base/role_maker.py).

TPU redesign: init() discovers the pod topology from the jax runtime (slice
metadata) instead of gloo/NCCL rendezvous; distributed_optimizer wraps an
optimizer so that feeds are sharded over the mesh 'dp' axis and XLA emits the
gradient AllReduce over ICI — existing `fleet.init(); fleet.distributed_
optimizer(opt).minimize(loss)` scripts run unmodified.
"""
from __future__ import annotations

import jax

from .mesh import topology


class Fleet:
    def __init__(self, mode='collective'):
        self._role_maker = None
        self._inited = False
        self._strategy = None
        self._mode = mode

    # ---- lifecycle ----
    def init(self, role_maker=None, is_collective=True, mesh_shape=None,
             dcn_mesh_shape=None, axis_rules=None):
        """Accepts both collective and parameter-server role makers (ref:
        incubate/fleet/base/fleet_base.py:Fleet.init). PS roles lower to
        collective DP on TPU: there are no parameter servers — every process
        is a worker and parameter state is replicated over the mesh, with XLA
        AllReduce replacing the send/recv to pservers (SURVEY 2.8).

        mesh_shape (TPU extension): mesh axes for the PARTITIONER's owned
        device mesh, e.g. {'dp': 4, 'tp': 2} or "dp=4,tp=2" — strict
        parse, unknown axis names raise. `dcn_mesh_shape` lays those axes
        over the data-center network (hybrid ICI×DCN mesh —
        partition.make_hybrid_mesh); `axis_rules` overrides the logical
        axis rule table (docs/PARTITIONER.md). Every parallel helper
        (tensor_parallel, fsdp, local/geo SGD, ring_attention, the
        Executor's Program lowering) resolves through that one
        partitioner."""
        from ..partition import configure, get_partitioner
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._role_maker.generate_role()
        # multi-host bring-up (fleet_runtime/bootstrap.py): when the role
        # maker carries a fleet topology (PADDLE_TRAINERS_NUM et al.),
        # init jax.distributed against the coordinator BEFORE any mesh is
        # built, so the partitioner's mesh spans the GLOBAL device list
        # and the fleet sentinel is armed. Single-host: no-op.
        from ..fleet_runtime import bootstrap as _fleet_bootstrap
        _fleet_bootstrap(spec=getattr(self._role_maker, 'fleet_spec', None),
                         configure_mesh=False)
        if mesh_shape or dcn_mesh_shape or axis_rules:
            configure(mesh_shape=mesh_shape, dcn_mesh_shape=dcn_mesh_shape,
                      axis_rules=axis_rules)
        elif get_partitioner().mesh is None:
            n = len(jax.devices())
            configure(mesh_shape={'dp': n})
        self._inited = True
        return self

    @property
    def worker_index(self):
        rm = self._role_maker
        return rm.worker_index() if rm is not None else jax.process_index()

    def worker_num(self):
        rm = self._role_maker
        return rm.worker_num() if rm is not None else jax.process_count()

    def worker_endpoints(self, to_string=False):
        rm = self._role_maker
        if rm is not None and hasattr(rm, 'worker_endpoints'):
            eps = rm.worker_endpoints()
        else:
            eps = [f"process:{i}" for i in range(self.worker_num())]
        return ','.join(eps) if to_string else eps

    def is_first_worker(self):
        rm = self._role_maker
        return rm.is_first_worker() if rm is not None \
            else jax.process_index() == 0

    def is_worker(self):
        rm = self._role_maker
        return rm.is_worker() if hasattr(rm, 'is_worker') else True

    def is_server(self):
        # PS lowering: no process acts as a parameter server on TPU; scripts
        # branching on is_server() fall through to the worker/training path
        # unless the user pinned Role.SERVER explicitly in the role maker.
        rm = self._role_maker
        return rm.is_server() if hasattr(rm, 'is_server') else False

    def barrier_worker(self):
        # collective barrier across processes via a tiny psum
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('fleet_barrier')

    # PS-mode lifecycle API (ref: incubate/fleet/parameter_server/
    # distribute_transpiler/__init__.py) — accepted; all are no-ops or
    # collective equivalents since there are no pserver processes.
    def init_worker(self):
        pass

    def init_server(self, model_dir=None):
        pass

    def run_server(self):
        # Returns immediately: parameter state lives replicated on the mesh
        # and syncs via XLA AllReduce, so there is nothing to serve. A
        # launcher need not spawn server processes at all; one that does gets
        # a clean exit instead of a hang.
        import logging
        logging.getLogger(__name__).warning(
            "fleet.run_server(): parameter servers are lowered to collective "
            "DP on TPU; returning immediately (nothing to serve)")

    def stop_worker(self):
        pass

    # ---- optimizer ----
    def distributed_optimizer(self, optimizer, strategy=None):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(optimizer, self._strategy)

    # ---- save ----
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ..io import save_inference_model
        if self.is_first_worker():
            save_inference_model(dirname, feeded_var_names, target_vars,
                                 executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ..io import save_persistables
        if self.is_first_worker():
            save_persistables(executor, dirname, main_program)


class DistributedStrategy:
    """ref: incubate/fleet/collective DistributedStrategy knobs.

    Honored by DistributedOptimizer.minimize: recompute, amp,
    gradient_merge_steps (wraps GradientMergeOptimizer), use_local_sgd +
    local_sgd_steps (lowered to the sync-every-k-steps schedule — see
    DistributedOptimizer.minimize for why replicas cannot diverge inside one
    SPMD program; parallel/local_sgd.py provides true divergent-replica
    LocalSGD for the functional path).

    LIVE comm knobs (ROADMAP item 3):

    - ``fuse_all_reduce_ops`` — drives the ``bucket_allreduce`` IR pass
      (ir/bucket_allreduce.py): per-gradient ``c_allreduce_sum`` ops that
      ``minimize`` emits are split into size-capped buckets
      (``PADDLE_TPU_ALLREDUCE_BUCKET_MB``), each dispatched right after
      its gradients' producer so XLA overlaps bucket comm with the
      remaining backward compute instead of one tail-synchronous
      reduction;
    - ``comm_dtype`` ∈ {f32, bf16, int8} — block-quantizes every gradient
      sync payload (parallel/quant_collectives.py, EQuARX two-phase
      decomposition; ``PADDLE_TPU_COMM_DTYPE`` overrides). Unknown names
      raise ValueError. ``f32`` (default) is exact/bitwise;
    - ``use_hierarchical_allreduce`` — lowered through the hybrid device
      mesh (parallel/mesh.make_hybrid_mesh): dp over DCN × tp/fsdp over
      ICI makes XLA emit the two-level reduction the reference built from
      hierarchical NCCL comms.

    Still accepted-for-compat only: nccl_comm_num (one XLA comm world)."""

    def __init__(self):
        self.fuse_all_reduce_ops = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = True
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.gradient_merge_steps = 1
        self.recompute = False
        self.recompute_checkpoints = []
        self.amp = False
        self.amp_loss_scale = 2. ** 15
        self.exec_strategy = None
        self.forward_recompute = False
        # FSDP (SURVEY §2.8): shard params + optimizer slots over the
        # 'fsdp' mesh axis via GSPMD (parallel/fsdp.py)
        self.sharding = False
        self.sharding_axis = 'fsdp'
        self._comm_dtype = 'f32'
        # partitioner topology (docs/PARTITIONER.md): mesh_shape builds
        # the owned device mesh at minimize/init time ("dp=2,tp=4" or a
        # dict; dcn_mesh_shape lays axes over DCN), axis_rules overrides
        # the logical-axis rule table. All strict-parse: unknown mesh
        # axis / logical names raise ValueError listing the supported
        # set (the PR 8/9 knob-hygiene contract).
        self._mesh_shape = None
        self._dcn_mesh_shape = None
        self._axis_rules = None
        # pipeline parallelism (docs/DISTRIBUTED.md): pipeline_stages
        # turns on the cost-model auto-cut; pp_schedule/pp_microbatches
        # pick the schedule and microbatch count (strict-parse; the
        # PADDLE_TPU_PP_* env knobs win at lowering time)
        self._pipeline_stages = None
        self._pp_schedule = None
        self._pp_microbatches = None

    @property
    def mesh_shape(self):
        """Partitioner mesh topology, e.g. {'dp': 2, 'tp': 4} or
        "dp=2,tp=4". Unknown axis names raise ValueError."""
        return self._mesh_shape

    @mesh_shape.setter
    def mesh_shape(self, value):
        from ..partition.rules import parse_mesh_shape
        self._mesh_shape = parse_mesh_shape(
            value, source='DistributedStrategy.mesh_shape')

    @property
    def dcn_mesh_shape(self):
        """Axes spanning the data-center network (hybrid ICI×DCN mesh)."""
        return self._dcn_mesh_shape

    @dcn_mesh_shape.setter
    def dcn_mesh_shape(self, value):
        from ..partition.rules import parse_mesh_shape
        self._dcn_mesh_shape = parse_mesh_shape(
            value, source='DistributedStrategy.dcn_mesh_shape')

    @property
    def axis_rules(self):
        """Logical-axis rule overrides, e.g. "batch=dp,mlp=tp,kv=" or a
        sequence of (logical, mesh) pairs. Unknown names raise."""
        return self._axis_rules

    @axis_rules.setter
    def axis_rules(self, value):
        from ..partition.rules import parse_axis_rules
        self._axis_rules = parse_axis_rules(
            value, source='DistributedStrategy.axis_rules')

    @property
    def comm_dtype(self):
        """Gradient-sync wire dtype: 'f32' (exact), 'bf16', or 'int8'
        (block-quantized, parallel/quant_collectives.py). The
        ``PADDLE_TPU_COMM_DTYPE`` env var overrides at every sync point."""
        return self._comm_dtype

    @comm_dtype.setter
    def comm_dtype(self, value):
        from .quant_collectives import SUPPORTED_COMM_DTYPES
        if value not in SUPPORTED_COMM_DTYPES:
            raise ValueError(
                f"DistributedStrategy.comm_dtype: unknown comm_dtype "
                f"{value!r} (supported: "
                f"{', '.join(SUPPORTED_COMM_DTYPES)})")
        self._comm_dtype = value

    @property
    def pipeline_stages(self):
        """Pipeline stage count (>= 2 enables pp): the cut is computed
        by the cost-model solver (analysis/stage.solve_stage_cuts)."""
        return self._pipeline_stages

    @pipeline_stages.setter
    def pipeline_stages(self, value):
        if value is not None:
            try:
                value = int(value)
            except (TypeError, ValueError):
                raise ValueError(
                    f'DistributedStrategy.pipeline_stages: expected an '
                    f'integer stage count >= 2, got {value!r}')
            if value < 2:
                raise ValueError(
                    f'DistributedStrategy.pipeline_stages: must be >= 2 '
                    f'to pipeline, got {value!r}')
        self._pipeline_stages = value

    @property
    def pp_schedule(self):
        """Pipeline schedule ∈ {gpipe, 1f1b, interleaved}; the
        ``PADDLE_TPU_PP_SCHEDULE`` env var overrides at lowering time."""
        return self._pp_schedule

    @pp_schedule.setter
    def pp_schedule(self, value):
        if value is not None:
            from ..partition.pipeline import PP_SCHEDULES
            if value not in PP_SCHEDULES:
                raise ValueError(
                    f'DistributedStrategy.pp_schedule: unknown schedule '
                    f"{value!r} (supported: {', '.join(PP_SCHEDULES)})")
        self._pp_schedule = value

    @property
    def pp_microbatches(self):
        """Microbatch count: a positive int, or 'auto' (default) to solve
        the smallest count fitting ``PADDLE_TPU_HBM_BUDGET_MB``;
        ``PADDLE_TPU_PP_MICROBATCHES`` overrides at lowering time."""
        return self._pp_microbatches

    @pp_microbatches.setter
    def pp_microbatches(self, value):
        if value is not None and value != 'auto':
            try:
                value = int(value)
            except (TypeError, ValueError):
                raise ValueError(
                    f"DistributedStrategy.pp_microbatches: expected a "
                    f"positive integer or 'auto', got {value!r}")
            if value <= 0:
                raise ValueError(
                    f'DistributedStrategy.pp_microbatches: must be > 0, '
                    f'got {value!r}')
        self._pp_microbatches = value


class DistributedOptimizer:
    """Wraps an optimizer; minimize() behaves like the inner one, but the
    program/scope produced is meant to be run through a data-sharded
    CompiledProgram (Executor handles it when fleet is inited — feeds get
    NamedSharding(mesh, P('dp'))). Grad averaging falls out of the mean-loss +
    sharded-batch formulation (XLA inserts the AllReduce)."""

    def __init__(self, optimizer, strategy):
        self._inner = optimizer
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        inner = self._inner
        strat = self._strategy
        if strat.recompute:
            from ..optimizer import RecomputeOptimizer
            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(strat.recompute_checkpoints)
        if strat.amp:
            from ..contrib.mixed_precision import decorate
            inner = decorate(inner,
                             init_loss_scaling=strat.amp_loss_scale)
        merge_k = int(strat.gradient_merge_steps or 1)
        if strat.use_local_sgd:
            # Inside ONE jitted SPMD program, replicated parameters cannot
            # hold per-device values, so replicas can never diverge — true
            # LocalSGD is representable only with an explicit replica axis
            # (parallel/local_sgd.py). What the knob CAN honor here is
            # LocalSGD's communication schedule: one global parameter sync
            # per local_sgd_steps instead of a per-step gradient AllReduce,
            # i.e. accumulate k steps locally, apply once — GradientMerge.
            merge_k = max(merge_k, int(strat.local_sgd_steps or 1))
        if merge_k > 1:
            from ..optimizer import GradientMergeOptimizer
            inner = GradientMergeOptimizer(inner, k_steps=merge_k, avg=True)
        result = inner.minimize(loss, startup_program, parameter_list,
                                no_grad_set)
        program = loss.block.program
        if strat.pipeline_stages or strat.pp_schedule \
                or strat.pp_microbatches:
            # one dist_strategy drives pp like every other axis: auto-cut
            # from the cost model, schedule + microbatch count stamped on
            # the backward marker (executor resolves env overrides and
            # the HBM-budget microbatch solve at lowering time)
            if not strat.pipeline_stages:
                raise ValueError(
                    'DistributedStrategy: pp_schedule/pp_microbatches '
                    'need pipeline_stages >= 2 to enable pipelining')
            mm = strat.pp_microbatches
            from ..optimizer import _stamp_pipeline
            _stamp_pipeline(
                program, [], 0 if mm in (None, 'auto') else int(mm),
                strat.pp_schedule, num_stages=strat.pipeline_stages,
                loss_name=loss.name)
        from ..partition import configure, get_partitioner
        if strat.mesh_shape or strat.axis_rules:
            # strategy-declared topology: build the partitioner's owned
            # mesh here so `minimize` is the single bring-up point
            configure(mesh_shape=strat.mesh_shape,
                      dcn_mesh_shape=strat.dcn_mesh_shape,
                      axis_rules=strat.axis_rules)
        part = get_partitioner()
        if strat.sharding:
            # Executor.run places persistable state with FSDP shardings
            # before each jitted step (a no-op once placed)
            program._fsdp_axis = strat.sharding_axis
        mesh_axes = part.axis_sizes()
        composed = sum(1 for s in mesh_axes.values() if s > 1) > 1 \
            or any(mesh_axes.get(a, 1) > 1 for a in ('tp', 'sp', 'pp'))
        if strat.sharding or composed:
            # full rule-table resolution when lowering: the Executor
            # consults the partitioner for every persistable's sharding
            # (tp Megatron specs + fsdp tiles compose on one mesh), and
            # the stamped specs feed the analysis/checks.py
            # sharding-consistency diagnostics
            program._partition_params = True
            part.stamp_program(
                program,
                fsdp_axis=strat.sharding_axis if strat.sharding else None)
        if merge_k == 1:
            # per-step DP gradient sync points (ref: the collective
            # transpiler's per-grad c_allreduce_sum insertion). On the
            # GSPMD executor these lower to identity — XLA derives the
            # AllReduce from the sharded-batch formulation — but they make
            # the sync STRUCTURE explicit: the bucket_allreduce IR pass
            # groups them into overlap-friendly size-capped buckets, and
            # comm_dtype rides on them into any shard_map lowering.
            # Skipped for k-step schedules (gradient merge / local SGD):
            # those sync once per k steps, not per gradient per step.
            self._insert_grad_allreduce(loss.block.program, strat)
        return result

    @staticmethod
    def _insert_grad_allreduce(program, strat):
        from ..framework import BACKWARD_OP_TYPE, Operator
        from ..partition import get_partitioner
        blk = program.global_block()
        bwd = next((i for i, op in enumerate(blk.ops)
                    if op.type == BACKWARD_OP_TYPE), None)
        if bwd is None:
            return
        grads = blk.ops[bwd].outputs.get('Grads', [])
        comm = getattr(strat, 'comm_dtype', 'f32')
        # gradient sync axis comes from the partitioner's rule table —
        # the axes 'batch' shards over ARE the axes gradients reduce
        # over (a dp×fsdp mesh stamps the tuple; shard_map lowerings
        # then psum over both, the GSPMD executor keeps identity)
        data_axes = get_partitioner().data_axes()
        axis = ('dp' if not data_axes
                else data_axes[0] if len(data_axes) == 1
                else tuple(data_axes))
        for j, g in enumerate(grads):
            blk.ops.insert(bwd + 1 + j, Operator(
                blk, 'c_allreduce_sum', inputs={'x': g},
                outputs={'Out': g},
                attrs={'ring_id': 0, 'use_calc_stream': True, 'axis': axis,
                       'comm_dtype': comm}))
        program._bump_version()
        # carry the bucketing decision for programs run WITHOUT a
        # CompiledProgram BuildStrategy (ir/bucket_allreduce.py reads it)
        program._dist_fuse_all_reduce_ops = bool(
            getattr(strat, 'fuse_all_reduce_ops', True))


class Role:
    """ref: incubate/fleet/base/role_maker.py:Role."""
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self, is_collective=True):
        self._is_collective = is_collective

    def generate_role(self):
        pass

    def worker_num(self):
        return jax.process_count()

    def worker_index(self):
        return jax.process_index()

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self.worker_index() == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    """ref: role_maker.py:PaddleCloudRoleMaker — reads the PADDLE_* fleet
    env vars, for real: topology comes from the STRICT-PARSE bootstrap
    (fleet_runtime/bootstrap.py) — ``PADDLE_TRAINERS_NUM`` /
    ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINER_ENDPOINTS`` /
    ``PADDLE_CURRENT_ENDPOINT`` — and an unknown or internally
    contradictory environment raises at :meth:`generate_role`, listing
    every expected variable, instead of silently running single-host
    while the rest of the pod waits in a collective. With NO fleet env
    set, topology falls back to the live jax runtime (the Cloud-TPU
    path, where the TPU metadata server already initialized it).

    In PS mode (is_collective=False), TRAINING_ROLE=PSERVER processes
    report as servers so PS launch scripts behave (nothing is served —
    see Fleet.run_server); collective jobs ignore the env var, like the
    reference."""

    def __init__(self, is_collective=True):
        super().__init__(is_collective)
        self._generated = False
        self._spec = None

    def generate_role(self):
        """Parse + validate the fleet environment (idempotent). This is
        where a malformed env fails loudly — fleet.init() calls it before
        any distributed bring-up."""
        if self._generated:
            return self
        from ..fleet_runtime.bootstrap import discover_fleet_env
        self._spec = discover_fleet_env()
        self._generated = True
        return self

    @property
    def fleet_spec(self):
        """The validated FleetSpec from env, or None (jax-runtime
        topology). fleet.init() hands this to fleet_runtime.bootstrap."""
        self.generate_role()
        return self._spec

    def worker_num(self):
        self.generate_role()
        if self._spec is not None:
            return self._spec.num_trainers
        return jax.process_count()

    def worker_index(self):
        self.generate_role()
        if self._spec is not None:
            return self._spec.trainer_id
        return jax.process_index()

    def worker_endpoints(self):
        self.generate_role()
        if self._spec is not None and self._spec.endpoints:
            return list(self._spec.endpoints)
        return [f'process:{i}' for i in range(self.worker_num())]

    def is_server(self):
        if self._is_collective:
            return False
        import os
        return os.environ.get('TRAINING_ROLE', 'TRAINER').upper() == 'PSERVER'

    def is_worker(self):
        return not self.is_server()


class UserDefinedRoleMaker(RoleMakerBase):
    """ref: role_maker.py:UserDefinedRoleMaker (same validation rules)."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, **kw):
        super().__init__()
        if not isinstance(server_endpoints, list) or not server_endpoints:
            raise TypeError("server_endpoints must be a non-empty list")
        if len(server_endpoints) != len(set(server_endpoints)):
            raise ValueError("server_endpoints can't have duplicate elements")
        if role not in (Role.WORKER, Role.SERVER):
            raise TypeError("role must be Role.WORKER or Role.SERVER")
        if current_id < 0:
            raise ValueError("current_id must be >= 0")
        if worker_num <= 0:
            raise ValueError("worker_num must be greater than 0")
        self._server_endpoints = server_endpoints
        self._role = role
        self._current_id = current_id
        self._worker_num = worker_num

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self._role == Role.WORKER and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """ref: role_maker.py:UserDefinedCollectiveRoleMaker (same validation)."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        if not isinstance(worker_endpoints, list) or not worker_endpoints:
            raise TypeError("worker_endpoints must be a non-empty list")
        if len(worker_endpoints) != len(set(worker_endpoints)):
            raise ValueError("worker_endpoints can't have duplicate elements")
        if not isinstance(current_id, int) or current_id < 0:
            raise ValueError("current_id must be an int >= 0")
        if current_id >= len(worker_endpoints):
            raise ValueError("current_id must be less than len(worker_"
                             "endpoints)")
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints

    def is_first_worker(self):
        return self._current_id == 0

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return len(self._worker_endpoints)


fleet = Fleet()
