"""Fleet collective training API (ref: python/paddle/fluid/incubate/fleet/
collective/__init__.py + base/fleet_base.py + base/role_maker.py).

TPU redesign: init() discovers the pod topology from the jax runtime (slice
metadata) instead of gloo/NCCL rendezvous; distributed_optimizer wraps an
optimizer so that feeds are sharded over the mesh 'dp' axis and XLA emits the
gradient AllReduce over ICI — existing `fleet.init(); fleet.distributed_
optimizer(opt).minimize(loss)` scripts run unmodified.
"""
from __future__ import annotations

import jax

from .mesh import get_default_mesh, make_mesh, set_default_mesh, topology


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._inited = False
        self._strategy = None

    # ---- lifecycle ----
    def init(self, role_maker=None, is_collective=True):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        if get_default_mesh() is None:
            n = len(jax.devices())
            set_default_mesh(make_mesh({'dp': n}))
        self._inited = True
        return self

    @property
    def worker_index(self):
        return jax.process_index()

    def worker_num(self):
        return jax.process_count()

    def worker_endpoints(self, to_string=False):
        eps = [f"process:{i}" for i in range(jax.process_count())]
        return ','.join(eps) if to_string else eps

    def is_first_worker(self):
        return jax.process_index() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def barrier_worker(self):
        # collective barrier across processes via a tiny psum
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('fleet_barrier')

    def stop_worker(self):
        pass

    # ---- optimizer ----
    def distributed_optimizer(self, optimizer, strategy=None):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(optimizer, self._strategy)

    # ---- save ----
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ..io import save_inference_model
        if self.is_first_worker():
            save_inference_model(dirname, feeded_var_names, target_vars,
                                 executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ..io import save_persistables
        if self.is_first_worker():
            save_persistables(executor, dirname, main_program)


class DistributedStrategy:
    """ref: incubate/fleet/collective DistributedStrategy knobs. XLA subsumes
    fuse_allreduce (bucketing) and overlap; gradient-merge / localsgd / remat
    are honored by DistributedOptimizer."""

    def __init__(self):
        self.fuse_all_reduce_ops = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = True
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.gradient_merge_steps = 1
        self.recompute = False
        self.recompute_checkpoints = []
        self.amp = False
        self.amp_loss_scale = 2. ** 15
        self.exec_strategy = None
        self.forward_recompute = False


class DistributedOptimizer:
    """Wraps an optimizer; minimize() behaves like the inner one, but the
    program/scope produced is meant to be run through a data-sharded
    CompiledProgram (Executor handles it when fleet is inited — feeds get
    NamedSharding(mesh, P('dp'))). Grad averaging falls out of the mean-loss +
    sharded-batch formulation (XLA inserts the AllReduce)."""

    def __init__(self, optimizer, strategy):
        self._inner = optimizer
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        inner = self._inner
        if self._strategy.recompute:
            from ..optimizer import RecomputeOptimizer
            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(self._strategy.recompute_checkpoints)
        if self._strategy.amp:
            from ..contrib.mixed_precision import decorate
            inner = decorate(inner,
                             init_loss_scaling=self._strategy.amp_loss_scale)
        return inner.minimize(loss, startup_program, parameter_list,
                              no_grad_set)


class RoleMakerBase:
    def __init__(self, is_collective=True):
        self._is_collective = is_collective

    def worker_num(self):
        return jax.process_count()

    def worker_index(self):
        return jax.process_index()


class PaddleCloudRoleMaker(RoleMakerBase):
    pass


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=None, worker_num=1,
                 server_endpoints=None, **kw):
        super().__init__()


fleet = Fleet()
