"""LocalSGD with truly divergent replicas (ref: the LocalSGD strategy in
python/paddle/fluid/incubate/fleet/collective/__init__.py, which patches the
transpiled program so each trainer updates with local gradients and
parameters all-reduce every `local_sgd_steps`).

TPU-first formulation: parameters carry an explicit leading replica axis
sharded over the mesh `dp` axis. Under shard_map each device updates its own
replica with gradients from its own batch shard only — no per-step
collective — and every k-th step replicas are averaged with ONE pmean
(AllReduce) over ICI. This is the only way divergent replicas can exist
inside an SPMD program: a replicated array holds one value by construction,
so the static-graph fleet path lowers `use_local_sgd` to the
sync-every-k-steps GradientMerge schedule instead (parallel/fleet.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import compat
from . import quant_collectives as qc


class LocalSGDStep:
    """Builds a jitted LocalSGD training step over `mesh` axis `axis`.

    loss_fn(params: dict, batch) -> scalar (mean over the LOCAL shard).
    params: dict name -> array (un-replicated values; broadcast to
    (n_replicas, *shape) internally and sharded over `axis`).

        step = LocalSGDStep(loss_fn, params, mesh, k_steps=4, lr=0.1)
        for batch in data:           # batch leading dim sharded over `axis`
            loss = step(batch)
        final = step.averaged_params()

    `comm_dtype` quantizes the k-step parameter-averaging AllReduce
    (quant_collectives; env `PADDLE_TPU_COMM_DTYPE` wins) — `f32` (default)
    keeps the exact `lax.pmean` bitwise.

    `mesh` may be omitted when a partitioner owns one
    (`partition.configure(...)` / `fleet.init`): the replica layout and
    the sync axis then come from the partitioner instead of hand-rolled
    per-module plumbing.
    """

    def __init__(self, loss_fn, params, mesh=None, k_steps=1, lr=0.1,
                 axis='dp', comm_dtype=None, partitioner=None):
        # k/lr/axis/comm_dtype are baked into the compiled step below —
        # rebuild the LocalSGDStep to change them
        from ..partition import Partitioner, get_partitioner
        p = partitioner or get_partitioner()
        if mesh is not None and mesh is not p.mesh:
            p = Partitioner(mesh=mesh, axis_rules=p.rules)
        mesh = p.mesh
        if mesh is None or axis not in mesh.shape:
            raise ValueError(
                f"LocalSGDStep: no mesh axis {axis!r} (pass mesh= or "
                f"configure the partitioner)")
        self._k = int(k_steps)
        self._comm = qc.resolve_comm_dtype(comm_dtype)
        self._sync_elems = sum(
            int(jnp.size(jnp.asarray(v))) for v in params.values())
        n = self._n = mesh.shape[axis]
        self._params = {name: p.replica_put(v, axis)
                        for name, v in params.items()}
        self._t = 0
        k = self._k
        comm = self._comm

        def body(stacked, batch, t):
            local = {m: v[0] for m, v in stacked.items()}
            loss, grads = jax.value_and_grad(loss_fn)(local, batch)
            new = {m: v - lr * grads[m] for m, v in local.items()}

            def sync(p):
                # collective output is replication-invariant; pcast back to
                # varying so both cond branches type-match under shard_map
                return {m: compat.pcast(
                    qc.qallreduce_mean(v, axis, comm_dtype=comm),
                    axis, to='varying')
                        for m, v in p.items()}

            new = lax.cond((t % k) == (k - 1), sync, lambda p: p, new)
            return ({m: v[None] for m, v in new.items()},
                    lax.pmean(loss, axis))

        pspec = {name: P(axis, *([None] * jnp.ndim(v)))
                 for name, v in params.items()}
        fn = compat.shard_map(body, mesh=mesh,
                           in_specs=(pspec, P(axis), P()),
                           out_specs=(pspec, P()))
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        self._step = jax.jit(fn, donate_argnums=(0,))

    def __call__(self, batch):
        if (self._t % self._k) == (self._k - 1):
            # host-side bytes-on-wire accounting for the sync this step
            # performs inside the jitted body (no-op with telemetry off);
            # the error histogram samples the codec on the values entering
            # the boundary (pre-step params — a per-call estimate)
            qc.record_collective('local_sgd', self._sync_elems, self._comm,
                                 self._n)
            if self._comm != 'f32':
                for v in self._params.values():
                    qc.record_quant_error('local_sgd', v[0], self._comm)
        self._params, loss = self._step(self._params,
                                        jnp.asarray(batch),
                                        jnp.int32(self._t))
        self._t += 1
        return loss

    def replica_params(self):
        """dict name -> (n_replicas, *shape) array of per-replica values."""
        return dict(self._params)

    def averaged_params(self):
        return {m: jnp.mean(v, axis=0) for m, v in self._params.items()}

    def replicas_in_sync(self, rtol=1e-6):
        return all(
            bool(jnp.allclose(v, jnp.broadcast_to(v[:1], v.shape), rtol=rtol))
            for v in self._params.values())
