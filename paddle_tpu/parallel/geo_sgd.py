"""Geo-SGD: delayed delta-sum synchronization (ref: python/paddle/fluid/
transpiler/geo_sgd_transpiler.py + the geo async-PS runtime).

Reference semantics: each trainer updates a LOCAL copy of the parameters;
every `need_push_nums` steps it pushes the accumulated DELTA (local - base)
to the parameter server, which applies the sum of trainer deltas to the
global base; trainers pull the fresh base and continue. Unlike LocalSGD's
parameter averaging, geo-SGD SUMS deltas — k local steps on n workers move
the base by the total of all workers' progress.

TPU-first formulation (same trick as parallel/local_sgd.py): parameters
carry an explicit leading replica axis sharded over the mesh axis, plus a
carried `base` copy. Under shard_map each device steps its own replica with
its own batch shard; every k-th step ONE psum over ICI aggregates the
deltas, the base advances by their sum, and every replica resets to the new
base. No per-step collective — the k-step window trades staleness for an
ICI round, exactly the reference's trade.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import compat
from . import quant_collectives as qc


class GeoSGDStep:
    """Jitted geo-SGD training step over `mesh` axis `axis`.

        step = GeoSGDStep(loss_fn, params, mesh, need_push_nums=4, lr=0.1)
        for batch in data:            # leading dim sharded over `axis`
            loss = step(batch)
        final = step.base_params()    # the synchronized base

    `comm_dtype` quantizes the k-step delta-sum AllReduce — deltas are the
    natural quantization target (small dynamic range vs the params
    themselves); `f32` (default) keeps the exact `lax.psum` bitwise.

    `mesh` may be omitted when a partitioner owns one — replica layout
    and sync axis come from it (docs/PARTITIONER.md).
    """

    def __init__(self, loss_fn, params, mesh=None, need_push_nums=1, lr=0.1,
                 axis='dp', comm_dtype=None, partitioner=None):
        from ..partition import Partitioner, get_partitioner
        p = partitioner or get_partitioner()
        if mesh is not None and mesh is not p.mesh:
            p = Partitioner(mesh=mesh, axis_rules=p.rules)
        mesh = p.mesh
        if mesh is None or axis not in mesh.shape:
            raise ValueError(
                f"GeoSGDStep: no mesh axis {axis!r} (pass mesh= or "
                f"configure the partitioner)")
        self._k = int(need_push_nums)
        self._comm = qc.resolve_comm_dtype(comm_dtype)
        self._sync_elems = sum(
            int(jnp.size(jnp.asarray(v))) for v in params.values())
        n = self._n = mesh.shape[axis]
        rep_spec = {name: P(axis, *([None] * jnp.ndim(v)))
                    for name, v in params.items()}
        stacked = {name: p.replica_put(v, axis)
                   for name, v in params.items()}
        # local replicas and the base start identical — DISTINCT buffers
        # (both arguments are donated; aliasing them would donate twice)
        self._state = (stacked,
                       jax.tree_util.tree_map(
                           lambda x: jax.device_put(jnp.array(x), x.sharding),
                           stacked))
        self._t = 0
        k = self._k
        comm = self._comm

        def body(local_stacked, base_stacked, batch, t):
            local = {m: v[0] for m, v in local_stacked.items()}
            base = {m: v[0] for m, v in base_stacked.items()}
            loss, grads = jax.value_and_grad(loss_fn)(local, batch)
            local = {m: v - lr * grads[m] for m, v in local.items()}

            def push_pull(operand):
                local, base = operand
                # sum of per-replica deltas moves the base (geo semantics);
                # adding the varying `base` keeps the result 'varying', so
                # both cond branches type-match under shard_map
                new_base = {
                    m: base[m] + qc.qallreduce_sum(local[m] - base[m], axis,
                                                   comm_dtype=comm)
                    for m in base}
                return new_base, new_base

            def keep(operand):
                return operand

            local, base = lax.cond((t % k) == (k - 1), push_pull, keep,
                                   (local, base))
            return ({m: v[None] for m, v in local.items()},
                    {m: v[None] for m, v in base.items()},
                    lax.pmean(loss, axis))

        fn = compat.shard_map(body, mesh=mesh,
                           in_specs=(rep_spec, rep_spec, P(axis), P()),
                           out_specs=(rep_spec, rep_spec, P()))
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        self._step = jax.jit(fn, donate_argnums=(0, 1))

    def __call__(self, batch):
        if (self._t % self._k) == (self._k - 1):
            # bytes + codec-error telemetry for the delta psum this step
            # runs inside the jitted body; the error samples the current
            # local-base delta (the quantization target) per call
            qc.record_collective('geo_sgd', self._sync_elems, self._comm,
                                 self._n)
            if self._comm != 'f32':
                local, base = self._state
                for m in local:
                    qc.record_quant_error('geo_sgd',
                                          local[m][0] - base[m][0],
                                          self._comm)
        local, base = self._state
        local, base, loss = self._step(local, base, jnp.asarray(batch),
                                       jnp.int32(self._t))
        self._state = (local, base)
        self._t += 1
        return loss

    def replica_params(self):
        """name → (n_replicas, *shape): the divergent local copies."""
        return dict(self._state[0])

    def base_params(self):
        """name → array: the synchronized base (row 0 — identical rows
        after a push/pull boundary)."""
        return {m: v[0] for m, v in self._state[1].items()}

    def replicas_in_sync(self, rtol=1e-6):
        return all(
            bool(jnp.allclose(v, jnp.broadcast_to(v[:1], v.shape),
                              rtol=rtol))
            for v in self._state[0].values())
