"""Gradient clipping (ref: python/paddle/fluid/clip.py)."""
from __future__ import annotations

import jax.numpy as jnp


class BaseGradientClipAttr:
    def process(self, params_grads):
        """Static mode: return new params_grads with clip ops appended."""
        raise NotImplementedError

    def apply_tree(self, grads: dict):
        """Functional form over a name→grad dict (dygraph/jit paths)."""
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def process(self, params_grads):
        from .layers.common import apply_op_layer
        return [(p, apply_op_layer('clip', {'x': g},
                                   {'min': self.min, 'max': self.max}))
                for p, g in params_grads]

    def apply_tree(self, grads):
        return {k: jnp.clip(g, self.min, self.max) for k, g in grads.items()}


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def process(self, params_grads):
        from .layers.common import apply_op_layer
        return [(p, apply_op_layer('clip_by_norm', {'x': g},
                                   {'max_norm': self.clip_norm}))
                for p, g in params_grads]

    def apply_tree(self, grads):
        out = {}
        for k, g in grads.items():
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            out[k] = jnp.where(n > self.clip_norm, g * (self.clip_norm / n), g)
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def process(self, params_grads):
        from .layers.common import apply_op_layer
        sq = [apply_op_layer('reduce_sum', {'x': apply_op_layer(
            'square', {'x': g})}) for _, g in params_grads]
        total = apply_op_layer('sum', {'xs': sq})
        gn = apply_op_layer('sqrt', {'x': total})
        # scale = clip / max(gn, clip)
        denom = apply_op_layer('elementwise_max', {
            'x': gn, 'y': _const_like(gn, self.clip_norm)})
        out = []
        for p, g in params_grads:
            scaled = apply_op_layer('elementwise_div', {'x': apply_op_layer(
                'scale', {'x': g}, {'scale': self.clip_norm}), 'y': denom})
            out.append((p, scaled))
        return out

    def apply_tree(self, grads):
        total = sum(jnp.sum(jnp.square(g)) for g in grads.values())
        gn = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        return {k: g * scale for k, g in grads.items()}


def _const_like(var, value):
    from .layers.tensor import fill_constant
    return fill_constant([1], var.dtype, value)


class ErrorClipByValue:
    """Accepted for parity; activation-grad error clip is folded into value
    clipping of gradients under the single-vjp backward design."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max


def set_gradient_clip(clip, param_list=None, program=None):
    from .framework import default_main_program
    program = program or default_main_program()
    program._gradient_clip = clip
    if param_list:
        for p in param_list:
            (p if not isinstance(p, str) else
             program.global_block().var(p)).gradient_clip = clip


def append_gradient_clip_ops(params_grads, program=None):
    from .framework import default_main_program
    program = program or default_main_program()
    clip = getattr(program, '_gradient_clip', None)
    if clip is None:
        return params_grads
    return clip.process(params_grads)
