"""Legacy fluid.evaluator surface (ref: python/paddle/fluid/evaluator.py).

The reference deprecates these in favor of fluid.metrics; here they are
thin aliases over the metrics implementations so old scripts import-run.
"""
import warnings

from .metrics import ChunkEvaluator as _ChunkEvaluator
from .metrics import EditDistance as _EditDistance
from .metrics import DetectionMAP as _DetectionMAP

__all__ = ['ChunkEvaluator', 'EditDistance', 'DetectionMAP']


def _deprecated(cls, name):
    class Wrapped(cls):
        def __init__(self, *args, **kwargs):
            warnings.warn(
                f'fluid.evaluator.{name} is deprecated; '
                f'use fluid.metrics.{name}', DeprecationWarning, stacklevel=2)
            super().__init__(*args, **kwargs)
    Wrapped.__name__ = name
    Wrapped.__qualname__ = name
    return Wrapped


ChunkEvaluator = _deprecated(_ChunkEvaluator, 'ChunkEvaluator')
EditDistance = _deprecated(_EditDistance, 'EditDistance')
DetectionMAP = _deprecated(_DetectionMAP, 'DetectionMAP')
