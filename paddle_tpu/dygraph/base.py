"""Dygraph mode switches (ref: python/paddle/fluid/dygraph/base.py)."""
from __future__ import annotations

import contextlib

import numpy as np

from .. import framework
from .tape import Tensor, no_grad, no_grad_guard


class Tracer:
    """ref: fluid/dygraph/tracer.py — the imperative op tracer, held by
    framework._dygraph_tracer_ while dygraph mode is on. Tracing IS the
    tape here (dygraph/tape.py): every dispatched op eagerly runs its jax
    functional and records a vjp node; the class carries the reference's
    train/eval flag and trace_op entry point."""

    def __init__(self, block=None):
        self._train_mode = True

    def train_mode(self):
        self._train_mode = True

    def eval_mode(self):
        self._train_mode = False

    def trace_op(self, type, inputs, outputs=None, attrs=None,
                 stop_gradient=False):
        from .tape import dispatch_op
        if stop_gradient:
            with no_grad_guard():
                out = dispatch_op(type, inputs, attrs or {})
            for t in (out if isinstance(out, (list, tuple)) else [out]):
                if hasattr(t, 'stop_gradient'):
                    t.stop_gradient = True
            return out
        return dispatch_op(type, inputs, attrs or {})


_Tracer = Tracer  # legacy internal alias


def enabled():
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    framework._dygraph_tracer_ = Tracer()


def disable_dygraph():
    framework._dygraph_tracer_ = None


@contextlib.contextmanager
def guard(place=None):
    enable_dygraph(place)
    try:
        yield
    finally:
        disable_dygraph()


def set_eager_kernel_cache(enabled, maxsize=None):
    """Toggle the eager per-op jitted-kernel cache (tape.kernel_cache) at
    runtime — the programmatic form of the PADDLE_TPU_EAGER_CACHE env hatch.
    `maxsize` rebounds the LRU (PADDLE_TPU_EAGER_CACHE_SIZE at import)."""
    from .tape import kernel_cache
    kernel_cache.enabled = bool(enabled)
    if maxsize is not None:
        kernel_cache.maxsize = max(int(maxsize), 1)
        while len(kernel_cache._entries) > kernel_cache.maxsize:
            kernel_cache._entries.popitem(last=False)
            kernel_cache.evictions += 1


@contextlib.contextmanager
def eager_kernel_cache_guard(enabled):
    """Scope the eager kernel cache on/off (e.g. A/B numerics checks)."""
    from .tape import kernel_cache
    old = kernel_cache.enabled
    kernel_cache.enabled = bool(enabled)
    try:
        yield
    finally:
        kernel_cache.enabled = old


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, Tensor):
        return value
    value = np.asarray(value)
    if value.dtype == np.int64:
        # int64 computes as int32 on device; out-of-range ids must raise,
        # not wrap (core/dtypes.py int64 boundary contract)
        from ..core.dtypes import check_int32_bounds
        check_int32_bounds(value, name or '<to_variable>')
    return Tensor(value, name=name, stop_gradient=True)
