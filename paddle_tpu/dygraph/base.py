"""Dygraph mode switches (ref: python/paddle/fluid/dygraph/base.py)."""
from __future__ import annotations

import contextlib

import numpy as np

from .. import framework
from .tape import Tensor, no_grad, no_grad_guard


class _Tracer:
    """Marker object; framework.in_dygraph_mode() keys off its presence
    (ref: the C++ imperative::Tracer held by framework._dygraph_tracer_)."""
    pass


def enabled():
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    framework._dygraph_tracer_ = _Tracer()


def disable_dygraph():
    framework._dygraph_tracer_ = None


@contextlib.contextmanager
def guard(place=None):
    enable_dygraph(place)
    try:
        yield
    finally:
        disable_dygraph()


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, Tensor):
        return value
    return Tensor(np.asarray(value), name=name, stop_gradient=True)
