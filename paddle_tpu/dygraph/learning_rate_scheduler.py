"""Dygraph LR schedulers (ref: python/paddle/fluid/dygraph/
learning_rate_scheduler.py)."""
from __future__ import annotations

import math


class LearningRateDecay:
    def __init__(self, begin=0, step=1, dtype='float32'):
        self.step_num = begin
        self.step_size = step

    def __call__(self):
        return self.create_lr_var(self.step_num)

    def step(self):
        self.step_num += self.step_size

    def create_lr_var(self, step_num):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1, dtype='float32'):
        super().__init__(begin, step)
        self.boundaries = boundaries
        self.values = values

    def create_lr_var(self, n):
        for b, v in zip(self.boundaries, self.values):
            if n < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate, staircase=False,
                 begin=0, step=1, dtype='float32'):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def create_lr_var(self, n):
        t = n / self.decay_steps
        if self.staircase:
            t = math.floor(t)
        return self.lr * math.exp(-self.decay_rate * t)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate, staircase=False,
                 begin=0, step=1, dtype='float32'):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def create_lr_var(self, n):
        t = n / self.decay_steps
        if self.staircase:
            t = math.floor(t)
        return self.lr * (self.decay_rate ** t)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate, staircase=False,
                 begin=0, step=1, dtype='float32'):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def create_lr_var(self, n):
        t = n / self.decay_steps
        if self.staircase:
            t = math.floor(t)
        return self.lr / (1 + self.decay_rate * t)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1, dtype='float32'):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.end_lr, self.power, self.cycle = end_learning_rate, power, cycle

    def create_lr_var(self, n):
        ds = self.decay_steps
        if self.cycle:
            mult = max(1.0, math.ceil(n / ds))
            ds = ds * mult
        else:
            n = min(n, ds)
        return (self.lr - self.end_lr) * ((1 - n / ds) ** self.power) + self.end_lr


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype='float32'):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def create_lr_var(self, n):
        cur_epoch = math.floor(n / self.step_each_epoch)
        return self.lr * 0.5 * (math.cos(cur_epoch * math.pi / self.epochs) + 1)


class LinearLrWarmup(LearningRateDecay):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype='float32'):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr, self.end_lr = start_lr, end_lr

    def step(self):
        super().step()
        if isinstance(self.lr, LearningRateDecay):
            self.lr.step()

    def create_lr_var(self, n):
        if n < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * (
                n / self.warmup_steps)
        lr = self.lr
        return lr.create_lr_var(lr.step_num) if isinstance(
            lr, LearningRateDecay) else lr


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1, dtype='float32',
                 learning_rate=1.0):
        super().__init__(begin, step)
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        self.base_lr = learning_rate

    def create_lr_var(self, n):
        n = max(n, 1)
        a = n ** -0.5
        b = self.warmup_steps ** -1.5 * n
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)
