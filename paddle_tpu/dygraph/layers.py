"""dygraph.Layer base (ref: python/paddle/fluid/dygraph/layers.py)."""
from __future__ import annotations

from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ..core import unique_name
from ..core.dtypes import convert_dtype, to_jax_dtype
from ..initializer import ConstantInitializer, XavierInitializer
from ..param_attr import ParamAttr
from .tape import Parameter, Tensor


class Layer:
    def __init__(self, name_scope=None, dtype='float32'):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._dtype = convert_dtype(dtype)
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self.training = True

    def full_name(self):
        return self._full_name

    # ---- params / sublayers ----
    def create_parameter(self, shape, attr=None, dtype='float32',
                         is_bias=False, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = attr.initializer or default_initializer or (
            ConstantInitializer(0.0) if is_bias else XavierInitializer())
        value = init.compute([int(s) for s in shape], convert_dtype(dtype))
        name = attr.name or unique_name.generate(self._full_name + '.w')
        p = Parameter(value, name=name, trainable=attr.trainable,
                      regularizer=attr.regularizer,
                      learning_rate=attr.learning_rate)
        return p

    def create_buffer(self, shape, dtype='float32', fill=0.0):
        t = Tensor(jnp.full(tuple(shape), fill, to_jax_dtype(dtype)),
                   stop_gradient=True, persistable=True)
        return t

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor):
        self._buffers[name] = tensor
        return tensor

    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self.__dict__.setdefault('_parameters', OrderedDict())[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault('_sub_layers', OrderedDict())[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    # ---- traversal ----
    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def named_parameters(self, prefix=''):
        for n, p in self._parameters.items():
            yield (prefix + n if not prefix else prefix + '.' + n), p
        for ln, l in self._sub_layers.items():
            sub_prefix = ln if not prefix else prefix + '.' + ln
            yield from l.named_parameters(sub_prefix)

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def named_sublayers(self, prefix=''):
        for n, l in self._sub_layers.items():
            name = n if not prefix else prefix + '.' + n
            yield name, l
            yield from l.named_sublayers(name)

    def buffers(self, include_sublayers=True):
        out = list(self._buffers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.buffers())
        return out

    def named_buffers(self, prefix=''):
        for n, b in self._buffers.items():
            yield (prefix + '.' + n if prefix else n), b
        for ln, l in self._sub_layers.items():
            yield from l.named_buffers(ln if not prefix else prefix + '.' + ln)

    # ---- modes ----
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ---- state ----
    def state_dict(self, destination=None, include_sublayers=True,
                   prefix=''):
        dest = destination if destination is not None else OrderedDict()
        for n, p in self.named_parameters():
            dest[n] = p
        for n, b in self.named_buffers():
            dest[n] = b
        return dest

    def set_dict(self, state, include_sublayers=True, use_structured_name=True):
        own = self.state_dict()
        for n, t in own.items():
            if n in state:
                src = state[n]
                arr = src.value if isinstance(src, Tensor) else jnp.asarray(src)
                t.value = arr.astype(t.value.dtype).reshape(t.value.shape)

    load_dict = set_dict
    set_state_dict = set_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookRemover(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return _HookRemover(self._forward_post_hooks, key)

    # ---- call ----
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out


class _HookRemover:
    def __init__(self, store, key):
        self._store, self._key = store, key

    def remove(self):
        self._store.pop(self._key, None)
