"""Imperative (dygraph) mode — ref: python/paddle/fluid/dygraph/."""
from .base import (guard, enable_dygraph, disable_dygraph, enabled,
                   to_variable, set_eager_kernel_cache,
                   eager_kernel_cache_guard)
from .tape import (Tensor, Parameter, no_grad, no_grad_guard, dispatch_op,
                   grad)
from .layers import Layer
from .container import Sequential, LayerList, ParameterList
from .nn import (Conv2D, Conv3D, Pool2D, Linear, BatchNorm, Embedding,
                 GRUUnit, LayerNorm, NCE, PRelu, BilinearTensorProduct,
                 Conv2DTranspose, Conv3DTranspose, GroupNorm, SpectralNorm,
                 TreeConv, Dropout)
from . import jit
from .jit import (TracedLayer, declarative, to_static, ProgramTranslator,
                  StaticFunction, InputSpec)
from .parallel import DataParallel, ParallelEnv, prepare_context
from .checkpoint import save_dygraph, load_dygraph
from .learning_rate_scheduler import (LearningRateDecay, PiecewiseDecay,
                                      NaturalExpDecay, ExponentialDecay,
                                      InverseTimeDecay, PolynomialDecay,
                                      CosineDecay, NoamDecay)


class BackwardStrategy:
    """ref: imperative/backward_strategy.h — sort_sum_gradient accepted for
    parity; the tape already accumulates deterministically."""

    def __init__(self):
        self.sort_sum_gradient = False


# legacy to_static aliases (ref dygraph/jit.py 1.x names)
from .jit import to_static as dygraph_to_static_graph          # noqa: E402
from .jit import to_static as dygraph_to_static_output         # noqa: E402


def start_gperf_profiler():
    """ref: dygraph.start_gperf_profiler — lowered to jax.profiler."""
    from ..profiler import start_profiler
    start_profiler()


def stop_gperf_profiler():
    from ..profiler import stop_profiler
    stop_profiler()


from .base import Tracer  # noqa: E402  (the tracer guard() installs)


# ref: fluid/dygraph/layer_object_helper.py — parameter-creation helper
# bound to a Layer; the static LayerHelper serves both modes here.
from ..layer_helper import LayerHelper as LayerObjectHelper  # noqa: E402


def monkey_patch_varbase():
    """ref: fluid/dygraph/varbase_patch_methods.py — attaches Tensor
    methods (numpy/backward/gradient/detach). Already installed at import
    (tape.monkey_patch_tensor); calling again is idempotent."""
    from .tape import monkey_patch_tensor
    monkey_patch_tensor()


def monkey_patch_math_varbase():
    """ref: fluid/dygraph/math_op_patch.py — math dunders on Tensor;
    installed at import time (see monkey_patch_varbase)."""
    from .tape import monkey_patch_tensor
    monkey_patch_tensor()
